"""The ``gordo`` CLI.

Reference equivalent: ``gordo_components/cli/cli.py`` — the click group
binding container entrypoints to the layers: ``build`` (env-var driven,
one machine per invocation — one Argo pod each), ``run-server``,
``run-watchman``, ``client ...``, ``workflow ...``.

TPU-era addition: ``build-project`` — the whole project in one process via
the fleet engine (buckets of machines as single sharded XLA programs); the
per-machine ``build`` verb is kept verb-for-verb for parity and for
heterogeneous stragglers.
"""

from __future__ import annotations

import json
import logging
import os
import sys
from typing import Any, Dict, Optional

import click
import yaml

import gordo_tpu
from gordo_tpu import telemetry

logger = logging.getLogger(__name__)

_RESUMABLE_EXITS_TOTAL = telemetry.counter(
    "gordo_resumable_exits_total",
    "exit-75 (EX_TEMPFAIL) resumable exits of multi-host build workers, "
    "by stage",
    labels=("stage",),
)


def _parse_config(value: Optional[str], name: str) -> Dict[str, Any]:
    """YAML/JSON text or a path to a YAML file → dict."""
    if not value:
        raise click.ClickException(f"{name} is required (option or env var)")
    if os.path.exists(value):
        with open(value) as f:
            value = f.read()
    loaded = yaml.safe_load(value)
    if not isinstance(loaded, dict):
        raise click.ClickException(f"{name} did not parse to a mapping")
    return loaded


@click.group("gordo")
@click.version_option(version=gordo_tpu.__version__)
@click.option(
    "--log-level",
    type=click.Choice(["CRITICAL", "ERROR", "WARNING", "INFO", "DEBUG"]),
    default="INFO",
    envvar="GORDO_LOG_LEVEL",
    help="Logging level for all gordo components.",
)
def gordo(log_level: str):
    """gordo-tpu: build, serve and fleet-manage per-sensor-tag anomaly
    models on TPU."""
    logging.basicConfig(
        level=getattr(logging, log_level),
        format="%(asctime)s %(levelname)s %(name)s: %(message)s",
    )


# ---------------------------------------------------------------------------
# build (single machine — reference parity verb)
# ---------------------------------------------------------------------------

@gordo.command("build")
@click.argument("output_dir", envvar="OUTPUT_DIR", default="./models")
@click.option("--name", envvar="MACHINE_NAME", default="machine", help="Machine name.")
@click.option("--model-config", envvar="MODEL_CONFIG", help="Model definition (YAML/JSON text or file).")
@click.option("--data-config", envvar="DATA_CONFIG", help="Dataset config (YAML/JSON text or file).")
@click.option("--metadata", envvar="METADATA", default="{}", help="User metadata (YAML/JSON).")
@click.option("--evaluation-config", envvar="EVALUATION_CONFIG", default=None,
              help="Evaluation config, e.g. '{\"cv_mode\": \"full_build\"}'.")
@click.option("--model-register-dir", envvar="MODEL_REGISTER_DIR", default=None,
              help="Config-hash cache registry dir; hits skip training.")
@click.option("--print-cv-scores", is_flag=True, help="Print CV scores to stdout.")
def build(output_dir, name, model_config, data_config, metadata,
          evaluation_config, model_register_dir, print_cv_scores):
    """Build one machine's model into OUTPUT_DIR (reference: the per-pod
    entrypoint of the Argo fan-out)."""
    from gordo_tpu import serializer
    from gordo_tpu.builder.build_model import provide_saved_model
    from gordo_tpu.workflow.config import DEFAULT_MODEL

    model_cfg = (
        _parse_config(model_config, "MODEL_CONFIG")
        if model_config
        else DEFAULT_MODEL
    )
    data_cfg = _parse_config(data_config, "DATA_CONFIG")
    meta = _parse_config(metadata, "METADATA") if metadata else {}
    eval_cfg = (
        _parse_config(evaluation_config, "EVALUATION_CONFIG")
        if evaluation_config
        else None
    )
    path = provide_saved_model(
        name,
        model_cfg,
        data_cfg,
        metadata=meta,
        output_dir=output_dir,
        model_register_dir=model_register_dir,
        evaluation_config=eval_cfg,
    )
    if print_cv_scores:
        build_meta = serializer.load_metadata(path)
        for metric, value in (
            build_meta.get("model", {})
            .get("cross_validation", {})
            .get("scores", {})
            .items()
        ):
            click.echo(f"{metric}: {value}")
    click.echo(path)


# ---------------------------------------------------------------------------
# build-project (fleet engine)
# ---------------------------------------------------------------------------

@gordo.command("build-project")
@click.option("--machine-config", required=True, envvar="MACHINE_CONFIG",
              help="Project YAML (text or file) with machines/globals.")
@click.option("--project-name", envvar="PROJECT_NAME", default="project")
@click.option("--output-dir", envvar="OUTPUT_DIR", default="./models")
@click.option("--model-register-dir", envvar="MODEL_REGISTER_DIR", default=None)
@click.option("--max-bucket-size", default=None, type=int,
              help="Max machines per stacked XLA program. Default: "
                   "per-model-family (512 dense, 256 recurrent — see "
                   "builder.fleet_build.default_bucket_size).")
@click.option("--data-parallel", default=1, show_default=True,
              help="Mesh 'data' axis size (chips per model shard).")
@click.option("--mesh-devices", default=None, envvar="GORDO_MESH_DEVICES",
              help="Fleet-mesh width: 'all'/'auto' (default) spreads the "
                   "models axis over every visible device, '1' forces the "
                   "single-device path, an integer N takes the first N "
                   "devices. Resolved by gordo_tpu.mesh.FleetMesh; env "
                   "equivalent GORDO_MESH_DEVICES.")
@click.option("--data-workers", default=None, show_default="adaptive",
              type=click.IntRange(min=1),
              help="Concurrent data-loader threads feeding the stream. "
                   "Default: sized to the host and the ingest plane "
                   "(BENCH_r23 measured a fixed 8-thread pool slower than "
                   "serial loading on low-core hosts); the resolved count "
                   "lands in the result summary as loader_workers.")
@click.option("--ingest/--no-ingest", "ingest", default=None,
              help="Fleet-vectorized chunk ingest with fingerprint-level "
                   "fetch dedup (gordo_tpu/ingest/). Default: on, env "
                   "GORDO_INGEST=off disables; artifacts are "
                   "byte-identical either way.")
@click.option("--align-lengths", default=None,
              type=click.IntRange(min=2),
              help="Truncate each machine's train rows down to a multiple "
                   "of this (oldest rows drop): ragged projects compile one "
                   "XLA program per DISTINCT row count, so alignment trades "
                   "up to N-1 old rows for ~N-fold fewer compiles.")
@click.option("--pad-lengths", default=None,
              type=click.IntRange(min=2),
              help="Pad each machine's train rows UP to a multiple of this "
                   "with weight-masked rows (zero data loss): one program "
                   "per aligned length, at the cost of fold/batch geometry "
                   "deriving from the padded length. Mutually exclusive "
                   "with --align-lengths.")
@click.option("--machines", "machines_filter", default=None,
              help="Comma-separated machine names: build only this subset "
                   "of the project (partial rebuilds; the unit of work in "
                   "the generated Argo DAG).")
@click.option("--multihost", default=None, envvar="GORDO_MULTIHOST",
              help="'coordinator:port,N,pid': run as process pid of an "
                   "N-process multi-host build (jax.distributed; process 0 "
                   "hosts the coordination service). Each process builds "
                   "its deterministic shard of the machine list into the "
                   "shared --output-dir/--model-register-dir. Env "
                   "equivalents: GORDO_COORDINATOR + GORDO_NUM_PROCESSES + "
                   "GORDO_PROCESS_ID (what the generated Indexed-Job "
                   "manifest sets).")
@click.option("--barrier-timeout", default=None, type=click.FloatRange(min=1),
              help="Seconds before a multi-host barrier declares a peer "
                   "dead; the survivor exits 75 (EX_TEMPFAIL) with its "
                   "shard state resumable. Default 600.")
@click.option("--auto-pad/--no-auto-pad", default=True, show_default=True,
              help="When neither --align-lengths nor --pad-lengths is set "
                   "and the config-level estimate predicts a large ragged "
                   "compile bill, auto-enable --pad-lengths at a computed "
                   "alignment (loudly logged) instead of paying one XLA "
                   "compile per distinct row count.")
@click.option("--artifact-format", default=None,
              type=click.Choice(["v1", "v2"]),
              help="v2 (default): one memory-mapped parameter pack per "
                   "fleet chunk + index (gordo_tpu/artifacts/) — "
                   "O(chunks) files instead of O(machines), zero-copy "
                   "server loads. v1: one directory per machine (the "
                   "compatibility escape hatch, also via "
                   "GORDO_ARTIFACT_FORMAT=v1).")
@click.option("--replace-cache", is_flag=True)
def build_project_cmd(machine_config, project_name, output_dir,
                      model_register_dir, max_bucket_size, data_parallel,
                      mesh_devices, data_workers, ingest, align_lengths,
                      pad_lengths, machines_filter, multihost,
                      barrier_timeout, auto_pad, artifact_format,
                      replace_cache):
    """Build EVERY machine in the project config — homogeneous machines
    train as single mesh-sharded fleet programs (the TPU-native
    replacement for the reference's one-pod-per-machine Argo DAG)."""
    from gordo_tpu.builder.fleet_build import build_project
    from gordo_tpu.workflow.config import NormalizedConfig

    config = NormalizedConfig.from_source(machine_config, project_name)
    machines = config.machines
    if machines_filter:
        wanted = {n.strip() for n in machines_filter.split(",") if n.strip()}
        machines = [m for m in machines if m.name in wanted]
        missing = wanted - {m.name for m in machines}
        if missing:
            raise click.BadParameter(
                f"--machines names not in the project: {sorted(missing)}"
            )

    # ---- multi-host: one process of an N-process sharded build ----
    from gordo_tpu.distributed.runtime import DistributedConfig, parse_multihost_spec

    if multihost:
        try:
            dist_cfg = parse_multihost_spec(multihost)
        except ValueError as exc:
            raise click.BadParameter(str(exc), param_hint="--multihost")
    else:
        dist_cfg = DistributedConfig.from_env()
    if dist_cfg is not None:
        if barrier_timeout:
            dist_cfg.barrier_timeout = barrier_timeout
        _run_multihost_build(
            dist_cfg, machines, output_dir, model_register_dir,
            replace_cache, max_bucket_size, data_parallel, data_workers,
            align_lengths, pad_lengths, auto_pad, artifact_format,
        )
        return

    # ---- single host ----
    from gordo_tpu.mesh import FleetMesh

    try:
        fleet_mesh = FleetMesh.resolve(
            mesh_devices, data_parallel=data_parallel
        )
    except ValueError as exc:
        raise click.BadParameter(str(exc), param_hint="--mesh-devices")
    mesh = fleet_mesh.mesh
    result = build_project(
        machines,
        output_dir,
        model_register_dir=model_register_dir,
        mesh=mesh,
        replace_cache=replace_cache,
        max_bucket_size=max_bucket_size,
        data_workers=data_workers,
        align_lengths=align_lengths,
        pad_lengths=pad_lengths,
        auto_pad=auto_pad,
        artifact_format=artifact_format,
        ingest=ingest,
    )
    click.echo(json.dumps(result.summary()))
    if result.failed:
        sys.exit(1)


def _run_multihost_build(dist_cfg, machines, output_dir, model_register_dir,
                         replace_cache, max_bucket_size, data_parallel,
                         data_workers, align_lengths, pad_lengths, auto_pad,
                         artifact_format=None):
    """One worker of an N-process build: init jax.distributed, build this
    process's shard, barrier at the edges.  A barrier timeout (dead peer)
    exits EXIT_SHARD_RESUMABLE with this shard's state file resumable —
    `os._exit`, because jax.distributed.shutdown() aborts once a peer is
    gone (see distributed/runtime.py)."""
    from gordo_tpu.builder.fleet_build import build_project
    from gordo_tpu.distributed.partition import (
        EXIT_SHARD_RESUMABLE,
        process_shard,
    )
    from gordo_tpu.distributed.runtime import BarrierTimeout, DistributedRuntime

    runtime = DistributedRuntime(dist_cfg)
    runtime.ensure_env()  # before ANY jax backend init
    runtime.initialize()
    n_global = runtime.validate_global_mesh()
    logger.info(
        "multihost build: process %d/%d, %d global devices, mesh validated",
        dist_cfg.process_id, dist_cfg.num_processes, n_global,
    )
    shard = process_shard(
        machines, dist_cfg.num_processes, dist_cfg.process_id,
        output_dir=output_dir,
    )

    def _resumable_exit(stage: str, exc: Exception, result=None) -> None:
        _RESUMABLE_EXITS_TOTAL.inc(1.0, stage)
        telemetry.log_event(
            logger, "resumable_exit",
            stage=stage,
            process_id=dist_cfg.process_id,
            num_processes=dist_cfg.num_processes,
            exit_code=EXIT_SHARD_RESUMABLE,
        )
        if shard.state is not None:
            if not shard.state.machines:
                shard.state.start(shard.names)
            shard.state.mark_resumable(f"{stage}: {exc}")
        # last-gasp shard-local snapshot: the barrier-wait/timeout series
        # this process accumulated must survive the os._exit for the
        # post-mortem merge (`gordo telemetry dump --dir <output_dir>`)
        if telemetry.enabled():
            try:
                telemetry.REGISTRY.write_snapshot(os.path.join(
                    output_dir, telemetry.SNAPSHOT_DIR,
                    f"shard-{dist_cfg.process_id:03d}"
                    f"-of-{dist_cfg.num_processes:03d}.json",
                ))
            except Exception:
                logger.exception("telemetry snapshot write failed")
        doc = result.summary() if result is not None else {}
        doc["resumable"] = {
            "stage": stage,
            "process_id": dist_cfg.process_id,
            "error": str(exc).split("\n")[0][:200],
        }
        click.echo(json.dumps(doc))
        sys.stdout.flush()
        sys.stderr.flush()
        os._exit(EXIT_SHARD_RESUMABLE)

    try:
        runtime.barrier("pre-build")
    except BarrierTimeout as exc:
        _resumable_exit("pre-build", exc)
    result = build_project(
        machines,
        output_dir,
        model_register_dir=model_register_dir,
        mesh=runtime.local_mesh(data_parallel),
        replace_cache=replace_cache,
        max_bucket_size=max_bucket_size,
        data_workers=data_workers,
        align_lengths=align_lengths,
        pad_lengths=pad_lengths,
        auto_pad=auto_pad,
        artifact_format=artifact_format,
        shard=shard,
    )
    try:
        runtime.barrier("post-build")
    except BarrierTimeout as exc:
        # THIS shard may be fully built (its state says so); the exit code
        # still signals "re-run the job" because fleet-wide completion is
        # unconfirmed — the re-run cache-hits everything already on disk
        _resumable_exit("post-build", exc, result)
    runtime.shutdown()
    summary = result.summary()
    summary["multihost"] = {
        "process_id": dist_cfg.process_id,
        "num_processes": dist_cfg.num_processes,
        "global_devices": n_global,
    }
    click.echo(json.dumps(summary))
    if result.failed:
        sys.exit(1)


# ---------------------------------------------------------------------------
# servers
# ---------------------------------------------------------------------------

@gordo.command("run-server")
@click.option("--model-dir", envvar="MODEL_LOCATION", required=True,
              help="One machine's artifact dir, or a project dir of them.")
@click.option("--host", default="0.0.0.0", show_default=True)
@click.option("--port", default=5555, show_default=True)
@click.option("--project", envvar="PROJECT_NAME", default="project")
@click.option("--rescan-interval", default=30.0, show_default=True,
              help="Seconds between artifact-dir rescans picking up newly "
                   "built machines (0 disables).")
@click.option("--coalesce-ms", default=0.0, show_default=True,
              help="Micro-batch concurrent single-machine anomaly requests "
                   "into stacked fleet dispatches (0 disables). The drain "
                   "is continuous; this bounds only the single-rider grace "
                   "wait. Big win under concurrent load; requests below "
                   "--coalesce-min-concurrency bypass and dispatch "
                   "directly, and the coalescer stands down to direct "
                   "dispatch when its saturation signal says batching is "
                   "losing.")
@click.option("--coalesce-min-concurrency", default=2, show_default=True,
              help="Coalesce only when at least this many single-machine "
                   "anomaly requests are in flight; below it requests "
                   "score directly (adaptive bypass).")
@click.option("--coalesce-knee", default=0, show_default=True,
              help="Cap coalesced dispatches at this many machines (the "
                   "throughput knee). 0 = auto-estimate from a short "
                   "warmup sweep on first use.")
@click.option("--model-parallel/--no-model-parallel", default=False,
              show_default=True,
              help="Shard stacked serving dispatches over ALL visible "
                   "devices (the 'models' mesh axis): one server process "
                   "drives a whole slice instead of one chip.")
@click.option("--mesh-devices", default=None, envvar="GORDO_MESH_DEVICES",
              help="Fleet-mesh width for --model-parallel: 'all'/'auto' "
                   "(default) uses every visible device, '1' forces the "
                   "single-device path, an integer N takes the first N "
                   "devices. Default: $GORDO_MESH_DEVICES.")
@click.option("--warmup/--no-warmup", default=False, show_default=True,
              help="Precompile the serving programs in the background at "
                   "startup so the first request doesn't pay jit "
                   "compilation (~20-40s cold on TPU).")
@click.option("--shard", default=None, envvar="GORDO_SERVE_SHARD",
              help="'i/N': serve shard i of an N-replica fleet-sharded "
                   "tier — load, warm, and make device-resident ONLY this "
                   "shard's machines (the same deterministic partition "
                   "the client and watchman compute; docs/serving.md "
                   "'Sharded serving tier'). Default: unsharded.")
@click.option("--reload-watch", default=None, type=float,
              help="Seconds between artifact-generation polls for the "
                   "zero-downtime delta hot reload (one tiny sidecar "
                   "read per poll; a flip re-stacks only the changed "
                   "machines while the old generation keeps serving). "
                   "Default: GORDO_RELOAD_WATCH_SECONDS, else 5; 0 "
                   "disables.")
def run_server_cmd(model_dir, host, port, project, rescan_interval,
                   coalesce_ms, coalesce_min_concurrency, coalesce_knee,
                   model_parallel, mesh_devices, warmup, shard,
                   reload_watch):
    """Serve model(s) over the /gordo/v0/<project>/<machine>/ routes."""
    from gordo_tpu.serve.server import run_server
    from gordo_tpu.serve.shard import ShardSpec

    if shard:
        try:
            shard = ShardSpec.parse(shard)
        except ValueError as exc:
            raise click.BadParameter(str(exc), param_hint="--shard")
    run_server(
        model_dir, host=host, port=port, project=project,
        rescan_interval=rescan_interval,
        coalesce_window_ms=coalesce_ms,
        coalesce_min_concurrency=coalesce_min_concurrency,
        coalesce_knee_batch=coalesce_knee,
        model_parallel=model_parallel,
        mesh_devices=mesh_devices,
        warmup=warmup,
        shard=shard or None,
        reload_watch_interval=reload_watch,
    )


@gordo.command("run-watchman")
@click.option("--project", envvar="PROJECT_NAME", default="project")
@click.option("--machines", default=None,
              help="Comma-separated machine names (or use --machine-config).")
@click.option("--machine-config", default=None,
              help="Project YAML to derive the machine list from.")
@click.option("--target", "targets", multiple=True,
              default=("http://localhost:5555",), show_default=True,
              help="ML-server base URL(s) to poll (repeatable).")
@click.option("--host", default="0.0.0.0", show_default=True)
@click.option("--port", default=5556, show_default=True)
@click.option("--poll-interval", default=30.0, show_default=True)
@click.option("--discover/--no-discover", default=True, show_default=True,
              help="Also discover machines from each target's project "
                   "index (new machines appear without reconfig).")
@click.option("--kube-namespace", default=None,
              help="Discover ml-server Services in this k8s namespace "
                   "(requires the kubernetes client package).")
def run_watchman_cmd(project, machines, machine_config, targets, host, port,
                     poll_interval, discover, kube_namespace):
    """Run the fleet-status aggregation service."""
    from gordo_tpu.watchman.server import run_watchman
    from gordo_tpu.workflow.config import NormalizedConfig

    if machines:
        machine_names = [m.strip() for m in machines.split(",") if m.strip()]
    elif machine_config:
        config = NormalizedConfig.from_source(machine_config, project)
        machine_names = [m.name for m in config.machines]
    elif discover:
        machine_names = []  # discovered from the targets' project indexes
    else:
        raise click.ClickException(
            "Provide --machines or --machine-config (or enable --discover)"
        )
    target_discovery = None
    if kube_namespace:
        from gordo_tpu.watchman.kube import KubeTargetDiscovery

        target_discovery = KubeTargetDiscovery(kube_namespace, project=project)
    run_watchman(
        project, machine_names, list(targets),
        host=host, port=port, poll_interval=poll_interval,
        discover=discover, target_discovery=target_discovery,
    )


# ---------------------------------------------------------------------------
# client
# ---------------------------------------------------------------------------

@gordo.group("client")
@click.option("--project", envvar="PROJECT_NAME", default="project")
@click.option("--host", default="localhost", show_default=True)
@click.option("--port", default=5555, show_default=True)
@click.option("--watchman-url", default=None,
              help="Discover machines from this watchman (healthy only).")
@click.option("--replica-url", "replica_urls", multiple=True,
              help="Fleet-sharded serving tier: replica base URL, ordered "
                   "by shard index (repeatable — give all N). The client "
                   "computes the shard table locally and routes each "
                   "machine's requests straight to its owning replica; "
                   "bulk scoring scatter-gathers across the tier.")
@click.pass_context
def client_group(ctx, project, host, port, watchman_url, replica_urls):
    """Query ML servers: bulk predictions, metadata, model download."""
    ctx.obj = {
        "project": project, "host": host, "port": port,
        "watchman_url": watchman_url,
        "replica_urls": list(replica_urls) or None,
    }


def _make_client(ctx, **kwargs):
    from gordo_tpu.client import Client

    return Client(
        ctx.obj["project"], host=ctx.obj["host"], port=ctx.obj["port"],
        watchman_url=ctx.obj["watchman_url"],
        replica_urls=ctx.obj["replica_urls"], **kwargs
    )


@client_group.command("predict")
@click.argument("start")
@click.argument("end")
@click.option("--machine", "machine_names", multiple=True,
              help="Machine(s) to score; default: every machine.")
@click.option("--output-dir", default=None,
              help="Forward scored frames to this directory.")
@click.option("--parallelism", default=10, show_default=True)
@click.option("--bulk", is_flag=True,
              help="Use the server's stacked bulk route (one vmapped "
                   "dispatch per chunk across all machines).")
@click.pass_context
def client_predict(ctx, start, end, machine_names, output_dir, parallelism,
                   bulk):
    """Score [START, END] for the project's machines."""
    from gordo_tpu.client.forwarders import ForwardPredictionsToDisk

    forwarder = ForwardPredictionsToDisk(output_dir) if output_dir else None
    client = _make_client(
        ctx, prediction_forwarder=forwarder, parallelism=parallelism,
        use_bulk=bulk,
    )
    results = client.predict(start, end, machine_names or None)
    ok = sum(r.ok for r in results)
    for res in results:
        status = "ok" if res.ok else f"FAILED: {'; '.join(res.error_messages)}"
        rows = 0 if res.predictions is None else len(res.predictions)
        click.echo(f"{res.name}: {rows} rows {status}")
    if ok != len(results):
        sys.exit(1)


@client_group.command("metadata")
@click.option("--machine", "machine_names", multiple=True)
@click.option("--output-file", type=click.File("w"), default=None)
@click.pass_context
def client_metadata(ctx, machine_names, output_file):
    """Print (or write) machine metadata JSON."""
    client = _make_client(ctx)
    names = machine_names or client.machine_names()
    meta = {name: client.machine_metadata(name) for name in names}
    out = json.dumps(meta, indent=2, default=str)
    if output_file:
        output_file.write(out)
    else:
        click.echo(out)


@client_group.command("download-model")
@click.argument("output_dir")
@click.option("--machine", "machine_names", multiple=True)
@click.pass_context
def client_download_model(ctx, output_dir, machine_names):
    """Download serialized model(s) into OUTPUT_DIR."""
    from gordo_tpu import serializer

    client = _make_client(ctx)
    names = machine_names or client.machine_names()
    os.makedirs(output_dir, exist_ok=True)
    for name in names:
        model = client.download_model(name)
        serializer.dump(model, os.path.join(output_dir, name))
        click.echo(os.path.join(output_dir, name))


# ---------------------------------------------------------------------------
# warmup (compile plane)
# ---------------------------------------------------------------------------

@gordo.command("warmup")
@click.option("--dir", "model_dir", default=None,
              help="Artifact dir (a machine's, or a project output dir): "
                   "pre-compile its serving programs from the build's "
                   "warmup manifest and print per-program compile seconds. "
                   "Exits non-zero on any compile failure, so an init "
                   "container can gate rollout on it.")
@click.option("--url", "server_url", default=None,
              help="Poll a running server's /healthz until its startup "
                   "warmup reports ready (exit non-zero on timeout or a "
                   "warmup failure) — the remote twin of --dir for pods "
                   "that warm themselves via run-server --warmup.")
@click.option("--rows", "row_sizes", multiple=True, type=int,
              help="Request row bucket(s) to pre-compile for (repeatable); "
                   "default: the manifest's row buckets, else 256 and "
                   "2048.")
@click.option("--shard", default=None, envvar="GORDO_SERVE_SHARD",
              help="--dir mode: 'i/N' — warm only shard i's subset of "
                   "the artifacts (what a sharded replica's init "
                   "container runs: 1/N of the fleet's programs).")
@click.option("--timeout", default=600.0, show_default=True,
              help="--url mode: seconds to wait for the ready state.")
def warmup_cmd(model_dir, server_url, row_sizes, shard, timeout):
    """Pre-compile serving programs (the cold-start gate).

    ``--dir``: AOT-compile every (signature, row bucket) program for the
    artifacts — run it in a kubernetes init container sharing
    ``GORDO_COMPILE_CACHE_DIR`` with the server, and the server's own
    warmup loads every program from the persistent cache in milliseconds.
    ``--url``: wait for a self-warming server to report ready.
    """
    if bool(model_dir) == bool(server_url):
        raise click.UsageError("provide exactly one of --dir or --url")
    if model_dir:
        from gordo_tpu.compile import warmup_collection
        from gordo_tpu.serve.server import ModelCollection
        from gordo_tpu.serve.shard import ShardSpec
        from gordo_tpu.utils.compile_cache import (
            enable_persistent_compile_cache,
        )

        shard_spec = None
        if shard:
            try:
                shard_spec = ShardSpec.parse(shard)
            except ValueError as exc:
                raise click.BadParameter(str(exc), param_hint="--shard")
        enable_persistent_compile_cache()
        try:
            collection = ModelCollection.from_directory(
                model_dir, shard=shard_spec
            )
        except FileNotFoundError as exc:
            raise click.ClickException(str(exc))
        stats = warmup_collection(
            collection, row_sizes=[int(r) for r in row_sizes] or None
        )
        for p in stats["programs"]:
            click.echo(
                f"{p['program']} rows={p['rows']}: {p['seconds']:.3f}s"
                + ("  (cached)" if p["seconds"] == 0.0 else "")
            )
        click.echo(
            f"warmup: {stats['buckets']} bucket(s), "
            f"{len(stats['programs'])} program signature(s), "
            f"dtype={stats.get('dtype', 'float32')}, "
            f"{stats.get('compile_seconds', 0.0):.2f}s compiling, "
            f"{stats['errors']} error(s)"
        )
        if stats["errors"]:
            sys.exit(1)
        return

    # --url: poll /healthz until the server reports ready
    import time as time_mod
    import urllib.error
    import urllib.request

    url = server_url.rstrip("/")
    if not url.endswith("/healthz"):
        url += "/healthz"
    deadline = time_mod.monotonic() + timeout
    last_state = None
    while time_mod.monotonic() < deadline:
        try:
            with urllib.request.urlopen(url, timeout=10) as resp:
                doc = json.loads(resp.read().decode())
        except (urllib.error.URLError, OSError, ValueError):
            doc = None  # not up yet — keep polling
        state = (doc or {}).get("state")
        if state != last_state and state is not None:
            click.echo(f"{url}: {state}", err=True)
            last_state = state
        if state == "ready":
            if doc.get("warmup_error") or doc.get("warmup_errors"):
                raise click.ClickException(
                    "server is ready but its warmup reported errors: "
                    f"{doc.get('warmup_error') or doc.get('warmup_errors')}"
                )
            click.echo("ready")
            return
        time_mod.sleep(1.0)
    raise click.ClickException(
        f"server at {url} did not report ready within {timeout:.0f}s "
        f"(last state: {last_state})"
    )


# ---------------------------------------------------------------------------
# mesh (device placement plane)
# ---------------------------------------------------------------------------

@gordo.group("mesh")
def mesh_group():
    """Device placement plane: inspect the fleet mesh and bucket placement."""


@mesh_group.command("info")
@click.option("--mesh-devices", default=None, envvar="GORDO_MESH_DEVICES",
              help="Fleet-mesh width: the same 'all'/'auto'/'1'/N spec "
                   "run-server and build-project accept. Default: "
                   "$GORDO_MESH_DEVICES, else all visible devices.")
@click.option("--data-parallel", default=1, show_default=True,
              help="Width of the 'data' mesh axis (build-time row "
                   "sharding; serving uses 1).")
@click.option("--model-dir", default=None,
              help="Also print the per-bucket placement plan for these "
                   "artifacts: stacked machines, padded fleet rows, and "
                   "which model slots each device holds.")
@click.option("--shard", default=None, envvar="GORDO_SERVE_SHARD",
              help="--model-dir mode: 'i/N' replica shard to plan for "
                   "(the subset a sharded replica would stack).")
def mesh_info(mesh_devices, data_parallel, model_dir, shard):
    """Print the resolved device mesh (JSON): devices, mesh shape, and —
    with --model-dir — the per-bucket placement plan a server loading
    those artifacts would use."""
    from gordo_tpu.mesh import FleetMesh

    try:
        fm = FleetMesh.resolve(mesh_devices, data_parallel=data_parallel)
    except ValueError as exc:
        raise click.BadParameter(str(exc), param_hint="--mesh-devices")
    doc = fm.describe()
    if model_dir:
        from gordo_tpu.serve.server import ModelCollection
        from gordo_tpu.serve.shard import ShardSpec

        shard_spec = None
        if shard:
            try:
                shard_spec = ShardSpec.parse(shard)
            except ValueError as exc:
                raise click.BadParameter(str(exc), param_hint="--shard")
        try:
            collection = ModelCollection.from_directory(
                model_dir, serve_mesh=fm.mesh, shard=shard_spec
            )
        except FileNotFoundError as exc:
            raise click.ClickException(str(exc))
        plan = []
        for i, bucket in enumerate(collection.fleet_scorer.buckets):
            shards = (
                bucket.mesh.shape["models"] if bucket.mesh is not None else 1
            )
            entry = {
                "bucket": i,
                "machines": len(bucket.names),
                "fleet-rows-padded": bucket.m_pad,
                "model-shards": shards,
                "sharded": bucket.mesh is not None,
            }
            if bucket.mesh is not None:
                per = bucket.m_pad // shards
                # devices grid is (models, data); every device in models
                # row j holds the same model-slot range
                entry["per-device-slots"] = {
                    str(d.id): [j * per, (j + 1) * per]
                    for j in range(shards)
                    for d in bucket.mesh.devices[j].reshape(-1)
                }
            plan.append(entry)
        doc["buckets"] = plan
    click.echo(json.dumps(doc, indent=1))


# ---------------------------------------------------------------------------
# artifacts (format v2 pack tooling)
# ---------------------------------------------------------------------------

@gordo.group("artifacts")
def artifacts_group():
    """Artifact-plane tooling: inspect, repack (v1 → v2), unpack (v2 → v1)."""


@artifacts_group.command("info")
@click.option("--dir", "output_dir", required=True,
              help="A build output dir (either format, or mixed).")
def artifacts_info(output_dir):
    """Print what backs the artifacts under --dir (format, machine and
    pack counts, pack bytes) as JSON."""
    from gordo_tpu import artifacts

    try:
        click.echo(json.dumps(artifacts.store_info(output_dir), indent=1))
    except artifacts.PackError as exc:
        raise click.ClickException(str(exc))


@artifacts_group.command("repack")
@click.option("--dir", "output_dir", required=True,
              help="A v1 (or mixed) build output dir to convert in place.")
@click.option("--max-bucket-size", default=512, show_default=True,
              help="Max machines per pack (the (signature, bucket) chunk "
                   "size).")
@click.option("--keep-dirs", is_flag=True,
              help="Leave the converted per-machine dirs on disk (the pack "
                   "index is authoritative either way).")
def artifacts_repack(output_dir, max_bucket_size, keep_dirs):
    """Convert v1 per-machine dirs to v2 memory-mapped packs in place.
    Machines whose models can't fuse into a stacked serving chain stay
    as v1 dirs — every reader handles the mixed layout."""
    from gordo_tpu import artifacts

    try:
        summary = artifacts.repack(
            output_dir, max_bucket_size=max_bucket_size, keep_dirs=keep_dirs
        )
    except artifacts.PackError as exc:
        raise click.ClickException(str(exc))
    click.echo(json.dumps(
        {"packs": summary["packs"],
         "packed": len(summary["packed"]),
         "kept_as_dirs": summary["kept_as_dirs"]}
    ))


@artifacts_group.command("unpack")
@click.option("--dir", "output_dir", required=True,
              help="A v2 build output dir (its pack index is read).")
@click.option("--dest", required=True,
              help="Directory to write v1 per-machine artifact dirs into.")
def artifacts_unpack(output_dir, dest):
    """Export every packed machine back to v1 per-machine dirs (the
    compatibility direction: external tooling that walks artifact dirs
    keeps working against an export)."""
    from gordo_tpu import artifacts

    try:
        written = artifacts.unpack(output_dir, dest)
    except artifacts.PackError as exc:
        raise click.ClickException(str(exc))
    click.echo(json.dumps({"unpacked": len(written), "dest": dest}))


@artifacts_group.command("gc")
@click.option("--dir", "output_dir", required=True,
              help="A v2 build output dir (its pack index is read).")
@click.option("--keep", default=2, show_default=True,
              help="Generation records to retain (newest first). The "
                   "live generation always survives; retired pack files "
                   "no retained generation references are unlinked.")
def artifacts_gc(output_dir, keep):
    """Prune artifact-generation history and the retired pack files it
    kept reloadable.  Builds and delta writes retire superseded packs
    instead of deleting them (so any retained generation stays loadable
    for rollback); this reclaims the disk once the history is no longer
    wanted.  Refuses --keep 0: the live generation is never collectable.
    Set GORDO_GC_KEEP to auto-prune on every build's generation stamp."""
    from gordo_tpu import artifacts

    try:
        summary = artifacts.gc_generations(output_dir, keep)
    except (artifacts.PackError, ValueError) as exc:
        raise click.ClickException(str(exc))
    click.echo(json.dumps(summary, indent=1))


@artifacts_group.command("flip")
@click.option("--dir", "output_dir", required=True,
              help="A v2 build output dir (its pack index is read).")
def artifacts_flip(output_dir):
    """Force-publish a new artifact generation, republishing every
    machine row.  The operator heal path when pack bytes were restored
    out-of-band (e.g. copied back from a healthy replica): no build
    wrote pending rows, so the ordinary stamp is a no-op, yet serving
    replicas only re-validate — and drop a quarantine — when the
    published generation advances.  A no-op on stores with no machines."""
    from gordo_tpu import artifacts

    try:
        gen = artifacts.stamp_generation(output_dir, force=True)
    except artifacts.PackError as exc:
        raise click.ClickException(str(exc))
    click.echo(json.dumps({"generation": gen}))


@artifacts_group.command("fsck")
@click.option("--dir", "output_dir", required=True,
              help="A build output dir (either format, or mixed).")
@click.option("--repair", is_flag=True,
              help="Fix what is safely fixable: unlink orphaned tmp files "
                   "from dead writers, restamp a stale GENERATION sidecar. "
                   "Corrupt packs are never 'repaired' — they are reported "
                   "(and quarantined by a serving load).")
def artifacts_fsck(output_dir, repair):
    """Verify every artifact invariant under --dir — index rows resolve,
    pack files exist with the recorded size, meta sidecars parse, tensor
    extents stay inside the pack — and report findings as JSON.  The
    server runs this automatically (with repair) at startup; exits
    non-zero when unrepaired findings remain."""
    from gordo_tpu import artifacts

    try:
        report = artifacts.fsck(output_dir, repair=repair)
    except artifacts.PackError as exc:
        raise click.ClickException(str(exc))
    click.echo(json.dumps(report, indent=1))
    if not report["ok"]:
        raise SystemExit(1)


# ---------------------------------------------------------------------------
# scores (score-archive lifecycle tooling)
# ---------------------------------------------------------------------------

@gordo.group("scores")
def scores_group():
    """Score-archive lifecycle: compact, gc, inspect (ls/stat)."""


@scores_group.command("compact")
@click.option("--dir", "archive_dir", required=True,
              help="A backfill output dir (holds .gordo-scores/).")
@click.option("--period", default=None, envvar="GORDO_SCORES_PERIOD",
              help="Time-partition length to merge chunk segments into "
                   "(any pandas Timedelta string). "
                   "[default: GORDO_SCORES_PERIOD or 1d]")
@click.option("--dry-run", is_flag=True,
              help="Report what would merge without writing anything.")
def scores_compact(archive_dir, period, dry_run):
    """Merge small per-chunk score segments into one period file per
    closed time partition.  Crash-safe (write-new-then-flip under the
    index flock): a kill mid-compact never loses a completed period,
    and reads are byte-identical before and after.  Re-run to resume."""
    from gordo_tpu import batch

    try:
        summary = batch.compact_scores(
            archive_dir, period=period, dry_run=dry_run
        )
    except (batch.ArchiveError, ValueError) as exc:
        raise click.ClickException(str(exc))
    click.echo(json.dumps(summary, indent=1))


@scores_group.command("gc")
@click.option("--dir", "archive_dir", required=True,
              help="A backfill output dir (holds .gordo-scores/).")
@click.option("--keep", default=None, type=float,
              envvar="GORDO_SCORES_KEEP",
              help="Days of score history to retain; segments whose "
                   "entire window is older are deleted. Refuses "
                   "--keep < 1. [default: GORDO_SCORES_KEEP or 90]")
def scores_gc(archive_dir, keep):
    """Prune score segments past the retention window, mirroring
    ``gordo artifacts gc``: the index flips before any unlink (readers
    never follow a record to a missing file) and completion records
    survive as ``pruned`` so a backfill resume does not re-score —
    and resurrect — retired windows."""
    from gordo_tpu import batch

    try:
        summary = batch.gc_scores(archive_dir, keep_days=keep)
    except (batch.ArchiveError, ValueError) as exc:
        raise click.ClickException(str(exc))
    click.echo(json.dumps(summary, indent=1))


@scores_group.command("ls")
@click.option("--dir", "archive_dir", required=True,
              help="A backfill output dir (holds .gordo-scores/).")
def scores_ls(archive_dir):
    """List every data segment (chunk and compacted period files) with
    rows and on-disk bytes — what compaction and gc actually did."""
    from gordo_tpu import batch

    try:
        listing = batch.ls_scores(archive_dir)
    except batch.ArchiveError as exc:
        raise click.ClickException(str(exc))
    click.echo(json.dumps(listing, indent=1))


@scores_group.command("stat")
@click.option("--dir", "archive_dir", required=True,
              help="A backfill output dir (holds .gordo-scores/).")
@click.option("--period", default=None, envvar="GORDO_SCORES_PERIOD",
              help="Partition length used to compute pending-compaction."
                   " [default: GORDO_SCORES_PERIOD or 1d]")
def scores_stat(archive_dir, period):
    """One-document archive state: plan, segment/byte totals by kind,
    period coverage, pruned windows, pending compaction work."""
    from gordo_tpu import batch

    try:
        doc = batch.stat_scores(archive_dir, period=period)
    except (batch.ArchiveError, ValueError) as exc:
        raise click.ClickException(str(exc))
    click.echo(json.dumps(doc, indent=1))


# ---------------------------------------------------------------------------
# telemetry
# ---------------------------------------------------------------------------

@gordo.group("telemetry")
def telemetry_group():
    """Observability plane: metric snapshots and scrapes."""


@telemetry_group.command("dump")
@click.option("--dir", "snapshot_dir", default=None,
              help="Merge the shard-local snapshots a project build wrote "
                   "under DIR (a build --output-dir, or its "
                   ".gordo-telemetry/ subdir directly) and print the "
                   "merged result.")
@click.option("--url", "scrape_url", default=None,
              help="Scrape a live server's /metrics (base URL or full "
                   "/metrics URL) and print it.")
@click.option("--format", "output_format",
              type=click.Choice(["prom", "json"]), default="prom",
              show_default=True,
              help="Output format: Prometheus text exposition, or the "
                   "JSON snapshot document (merge-able with "
                   "telemetry.merge_snapshots). A live /metrics scrape "
                   "only speaks prom.")
def telemetry_dump(snapshot_dir, scrape_url, output_format):
    """Print a metrics snapshot.

    Default (no option): this process's own registry — mostly useful under
    ``GORDO_SPAN_LOG``/scripted use.  ``--dir`` merges a (multi-host)
    build's shard-local snapshot files; ``--url`` scrapes a live server.
    ``--format prom`` (default) prints the Prometheus text exposition,
    ``--format json`` the JSON snapshot document.
    """
    if snapshot_dir and scrape_url:
        raise click.UsageError("--dir and --url are mutually exclusive")
    if snapshot_dir:
        candidates = [
            os.path.join(snapshot_dir, telemetry.SNAPSHOT_DIR),
            snapshot_dir,
        ]
        snaps = []
        for directory in candidates:
            snaps = telemetry.load_snapshot_dir(directory)
            if snaps:
                break
        if not snaps:
            raise click.ClickException(
                f"no telemetry snapshots under {candidates}"
            )
        merged = telemetry.merge_snapshots(snaps)
        if output_format == "json":
            click.echo(json.dumps(merged, indent=1, sort_keys=True))
        else:
            click.echo(telemetry.render_snapshot(merged), nl=False)
        return
    if scrape_url:
        if output_format == "json":
            # a /metrics scrape is already-rendered text; recovering the
            # snapshot document from it would be a lossy reparse
            raise click.UsageError(
                "--format json is not available with --url (the scrape "
                "surface speaks Prometheus text); use --dir or the "
                "default registry dump"
            )
        import urllib.request

        url = scrape_url.rstrip("/")
        if not url.endswith("/metrics"):
            url += "/metrics"
        try:
            with urllib.request.urlopen(url, timeout=10) as resp:
                click.echo(resp.read().decode(), nl=False)
        except Exception as exc:
            raise click.ClickException(f"scrape {url} failed: {exc}")
        return
    if output_format == "json":
        click.echo(
            json.dumps(telemetry.REGISTRY.snapshot(), indent=1,
                       sort_keys=True)
        )
        return
    click.echo(telemetry.render(), nl=False)


# ---------------------------------------------------------------------------
# fleet health
# ---------------------------------------------------------------------------

@gordo.command("fleet-health")
@click.option("--url", default=None,
              help="Live surface: an ML-server base URL (the per-replica "
                   "doc; merged fleet-wide when pointed at a watchman) — "
                   "tries /gordo/v0/<project>/fleet-health, then the "
                   "watchman's /fleet-health.")
@click.option("--dir", "rollup_dir", default=None,
              help="File surface: an artifact dir holding the rollup "
                   "JSONL files serving processes append "
                   "(.gordo-fleet-health/); the latest doc per "
                   "process/shard is merged.")
@click.option("--project", envvar="PROJECT_NAME", default="project",
              show_default=True)
@click.option("--top", default=10, show_default=True,
              help="How many machines the drift ranking lists.")
@click.option("--full/--summary", default=False, show_default=True,
              help="--full prints the whole per-machine document "
                   "(sketches included); the default summary prints "
                   "counts by status and the top-drift ranking.")
def fleet_health_cmd(url, rollup_dir, project, top, full):
    """Which machines are drifting, scoring hot, or silent?

    Prints the fleet-health document (docs/observability.md "Fleet
    health"): per-machine live anomaly-score sketches vs their
    training-time baselines, drift scores, and statuses — from a live
    server/watchman (``--url``) or from the rollup files under an
    artifact dir (``--dir``, no HTTP needed).
    """
    if bool(url) == bool(rollup_dir):
        raise click.UsageError("provide exactly one of --url or --dir")
    if rollup_dir:
        doc = telemetry.read_rollups(rollup_dir, top=top)
        if doc is None:
            raise click.ClickException(
                f"no fleet-health rollups under {rollup_dir!r} "
                f"(is the server writing them? GORDO_HEALTH_ROLLUP_SECONDS)"
            )
    else:
        import urllib.error
        import urllib.request

        base = url.rstrip("/")
        candidates = [
            f"{base}/gordo/v0/{project}/fleet-health?top={int(top)}",
            f"{base}/fleet-health?top={int(top)}",  # watchman surface
        ]
        doc = None
        last_err = None
        for candidate in candidates:
            try:
                with urllib.request.urlopen(candidate, timeout=30) as resp:
                    doc = json.loads(resp.read().decode())
                break
            except Exception as exc:  # 404 on a watchman, conn errors
                last_err = exc
        if doc is None:
            raise click.ClickException(
                f"fleet-health fetch failed from {candidates}: {last_err}"
            )
    if full:
        click.echo(json.dumps(doc, indent=1, sort_keys=True))
        return
    by_status: Dict[str, int] = {}
    for entry in (doc.get("machines") or {}).values():
        by_status[entry.get("status", "?")] = (
            by_status.get(entry.get("status", "?"), 0) + 1
        )
    summary = {
        "machines": len(doc.get("machines") or {}),
        "by-status": dict(sorted(by_status.items())),
        "drift-threshold": doc.get("drift-threshold"),
        "top-drift": doc.get("top-drift", []),
    }
    click.echo(json.dumps(summary, indent=1, sort_keys=True))


# ---------------------------------------------------------------------------
# refresh (drift-driven incremental rebuilds)
# ---------------------------------------------------------------------------

@gordo.command("refresh")
@click.option("--machine-config", required=True, envvar="MACHINE_CONFIG",
              help="Project YAML (text or file) with machines/globals — "
                   "the machines this refresh deployment may rebuild.")
@click.option("--project-name", envvar="PROJECT_NAME", default="project")
@click.option("--output-dir", envvar="OUTPUT_DIR", default="./models")
@click.option("--model-register-dir", envvar="MODEL_REGISTER_DIR",
              default=None)
@click.option("--health-url", default=None,
              help="HTTP health surface (server or watchman base URL). "
                   "Default: the rollup JSONL files under --output-dir "
                   "(.gordo-fleet-health/) — no HTTP needed.")
@click.option("--server-url", default=None,
              help="Server base URL to confirm the rebuilt generation "
                   "went live on (client wait_for_generation handshake). "
                   "Default: publish without confirmation.")
@click.option("--once", is_flag=True,
              help="Run exactly one poll→select→rebuild cycle and exit "
                   "(the CronJob face; hysteresis streaks persist under "
                   "<output-dir>/.gordo-refresh/state.json).")
@click.option("--interval", default=None, type=click.FloatRange(min=0),
              help="Seconds between cycles in the continuous loop "
                   "[default: GORDO_REFRESH_INTERVAL or 300].")
@click.option("--hysteresis", default=None, type=click.IntRange(min=1),
              help="Consecutive drifting observations before a machine "
                   "is rebuilt [default: GORDO_REFRESH_HYSTERESIS or 2].")
@click.option("--cooldown-seconds", default=None,
              type=click.FloatRange(min=0),
              help="Per-machine seconds between rebuilds "
                   "[default: GORDO_REFRESH_COOLDOWN_SECONDS or 900].")
@click.option("--wait-timeout", default=120.0, show_default=True,
              type=click.FloatRange(min=1),
              help="Seconds to wait for the generation flip to be "
                   "confirmed live (--server-url).")
def refresh_cmd(machine_config, project_name, output_dir,
                model_register_dir, health_url, server_url, once, interval,
                hysteresis, cooldown_seconds, wait_timeout):
    """Rebuild ONLY the drifting machines, warm-started from the live
    generation — O(drifted) instead of O(fleet).

    Polls fleet health (rollup files or --health-url), selects machines
    observed ``status=drifting`` on K consecutive polls and outside
    their cooldown, warm-starts a subset rebuild from the previous
    generation's params (per-machine cold fallback under the loss-parity
    gate), and publishes through the artifact plane's delta path so live
    servers hot-reload exactly the touched packs.  One summary JSON line
    per cycle on stdout.
    """
    from gordo_tpu.refresh import RefreshConfig, refresh_once
    from gordo_tpu.workflow.config import NormalizedConfig

    config = NormalizedConfig.from_source(machine_config, project_name)
    cfg = RefreshConfig(
        machines=config.machines,
        output_dir=output_dir,
        model_register_dir=model_register_dir,
        project=project_name,
        health_url=health_url,
        server_url=server_url,
        hysteresis=hysteresis,
        cooldown_seconds=cooldown_seconds,
        wait_timeout=wait_timeout,
    )
    if once:
        summary = refresh_once(cfg)
        click.echo(json.dumps(summary, sort_keys=True))
        if summary.get("outcome") == "failed":
            sys.exit(1)
        return

    import time

    from gordo_tpu.refresh.loop import (
        DEFAULT_INTERVAL,
        ENV_INTERVAL,
        DriftSelector,
        state_path,
    )

    if interval is None:
        try:
            interval = float(os.environ.get(ENV_INTERVAL, "")
                             or DEFAULT_INTERVAL)
        except ValueError:
            interval = DEFAULT_INTERVAL
    # one selector for the whole loop: streaks span cycles in-process
    # (run_refresh does the same; inlined here for the per-cycle echo)
    selector = DriftSelector.load(
        state_path(output_dir), hysteresis=hysteresis,
        cooldown_seconds=cooldown_seconds,
    )
    while True:
        summary = refresh_once(cfg, selector=selector)
        click.echo(json.dumps(summary, sort_keys=True))
        time.sleep(interval)


# ---------------------------------------------------------------------------
# backfill (offline historical scoring → columnar archive)
# ---------------------------------------------------------------------------

@gordo.command("backfill")
@click.option("--model-dir", envvar="MODEL_LOCATION", default="./models",
              show_default=True,
              help="Artifact directory holding the fleet's built models "
                   "(the same directory run-server scans).")
@click.option("--archive-dir", envvar="GORDO_BACKFILL_ARCHIVE_DIR",
              default=None,
              help="Archive destination root (scores land under "
                   "<archive-dir>/.gordo-scores/) [default: --model-dir].")
@click.option("--project-name", envvar="PROJECT_NAME", default="project")
@click.option("--start", required=True,
              help="Inclusive start of the historical range (ISO-8601; "
                   "tz-naive is taken as UTC).")
@click.option("--end", required=True,
              help="Exclusive end of the historical range (ISO-8601).")
@click.option("--machines", default=None,
              help="Comma-separated machine subset [default: every "
                   "machine discovered under --model-dir].")
@click.option("--shard", default=None, envvar="GORDO_BACKFILL_SHARD",
              help="'i/N' — score only this shard's deterministic "
                   "partition of the fleet (same partitioner the serving "
                   "tier shards with). Indexed Jobs wire the pair "
                   "GORDO_BACKFILL_SHARD_INDEX/GORDO_BACKFILL_NUM_SHARDS "
                   "instead.")
@click.option("--chunk-rows", default=None, type=click.IntRange(min=1),
              envvar="GORDO_BACKFILL_CHUNK_ROWS",
              help="Rows per staged chunk (the unit of resumability and "
                   "of host→device transfer) [default: "
                   "GORDO_BACKFILL_CHUNK_ROWS or 2048].")
@click.option("--max-chunks", default=None, type=click.IntRange(min=1),
              help="Stop after N chunks this invocation (checkpoint-and-"
                   "yield for preemptible capacity; exits resumable).")
def backfill_cmd(model_dir, archive_dir, project_name, start, end,
                 machines, shard, chunk_rows, max_chunks):
    """Score a historical time range for the whole fleet offline.

    Loads every model from --model-dir (no server, no HTTP), fetches
    each machine's sensor frame from its dataset provider, stages
    fixed-row chunks through the compile plane's fused fleet programs
    at the configured serving dtype, and appends columnar segments to
    the ``.gordo-scores/`` archive.  Completed chunks are durable: a
    killed run re-invoked with the same range resumes from its
    completion records and converges on a byte-identical archive.
    Exits 75 (EX_TEMPFAIL) when progress was archived but the range is
    not finished — supervisors should simply re-run.
    """
    from gordo_tpu.batch import BackfillConfig, BackfillError, run_backfill
    from gordo_tpu.distributed.partition import EXIT_SHARD_RESUMABLE

    machine_list = None
    if machines:
        machine_list = [m.strip() for m in machines.split(",") if m.strip()]
    cfg = BackfillConfig(
        model_dir=model_dir,
        start=start,
        end=end,
        archive_dir=archive_dir,
        project=project_name,
        machines=machine_list,
        shard=shard,
        chunk_rows=chunk_rows,
        max_chunks=max_chunks,
    )
    try:
        summary = run_backfill(cfg)
    except BackfillError as exc:
        # completed chunks are already fsync'd behind their completion
        # records — a re-run resumes, so this is EX_TEMPFAIL, not a crash
        logger.error("backfill interrupted (resumable): %s", exc)
        _RESUMABLE_EXITS_TOTAL.inc(1.0, "backfill")
        sys.exit(EXIT_SHARD_RESUMABLE)
    click.echo(json.dumps(summary, sort_keys=True))
    if summary.get("remaining", 0) > 0:
        # --max-chunks checkpoint-and-yield: archived progress, more to do
        _RESUMABLE_EXITS_TOTAL.inc(1.0, "backfill")
        sys.exit(EXIT_SHARD_RESUMABLE)


# ---------------------------------------------------------------------------
# workflow
# ---------------------------------------------------------------------------

@gordo.group("workflow")
def workflow_group():
    """Project-config driven orchestration documents."""


@workflow_group.command("generate")
@click.option("--machine-config", required=True, envvar="MACHINE_CONFIG")
@click.option("--project-name", envvar="PROJECT_NAME", default="project")
@click.option("--image", default="gordo-tpu", show_default=True)
@click.option("--server-replicas", default=1, show_default=True)
@click.option("--server-arg", "server_args", multiple=True,
              help="Extra 'gordo run-server' flag for the ml-server "
                   "Deployment; repeatable (e.g. --server-arg=--coalesce-ms "
                   "--server-arg=2 --server-arg=--model-parallel).")
@click.option("--format", "fmt", type=click.Choice(["k8s", "argo"]),
              default="k8s", show_default=True,
              help="k8s: builder Job + server/watchman Deployments. argo: "
                   "an argoproj Workflow DAG (one task per fleet chunk) "
                   "plus the serving manifests — for clusters whose "
                   "tooling consumes Argo documents.")
@click.option("--multihost", default=None, type=click.IntRange(min=1),
              help="Emit the builder as an N-process Indexed Job "
                   "(jax.distributed over N pods, GORDO_* env wiring, "
                   "deterministic machine shards). Refused when N exceeds "
                   "the plan's machine-shard count.")
@click.option("--scrape-annotations/--no-scrape-annotations", default=True,
              show_default=True,
              help="Stamp prometheus.io/{scrape,port,path} discovery "
                   "annotations on the server and watchman pod templates "
                   "so their /metrics endpoints are scraped without extra "
                   "cluster config.")
@click.option("--serve-dtype", default=None,
              help="Serving precision (fp32/bf16; int8 needs the "
                   "GORDO_SERVE_INT8 opt-in at runtime): stamps "
                   "GORDO_SERVE_DTYPE on builder AND server pods so the "
                   "warmup manifest, AOT warmup, and request dispatch all "
                   "agree. Only use after the fp32 parity suite passes "
                   "for this project's model family (docs/perf.md).")
@click.option("--serve-shards", default=None, type=click.IntRange(min=1),
              help="Emit the serving tier fleet-sharded across N "
                   "replicas: one Deployment+Service per shard "
                   "(GORDO_SERVE_SHARD=i/N), an HPA per shard driven by "
                   "the coalescer's queue-wait/service-time ratio gauge, "
                   "and per-machine Mappings routed to the owning shard. "
                   "Refused when N exceeds the machine count.")
@click.option("--hpa-max-replicas", default=4, show_default=True,
              type=click.IntRange(min=1),
              help="maxReplicas of each shard's HPA (--serve-shards).")
@click.option("--refresh-cron", default=None,
              help="5-field cron schedule: additionally emit a CronJob "
                   "running 'gordo refresh --once' against the same "
                   "models PVC + project config as the builder — the "
                   "drift-driven incremental rebuild loop. Refused when "
                   "the builder has no models volume to warm-start "
                   "from, or when the schedule is malformed.")
@click.option("--backfill", nargs=2, default=None, metavar="START END",
              help="Additionally emit an Indexed Job running 'gordo "
                   "backfill' over this half-open [START, END) range "
                   "against the builder's models PVC — offline fleet "
                   "scoring into the .gordo-scores/ archive. Refused "
                   "when the range is malformed or the builder has no "
                   "models volume.")
@click.option("--backfill-shards", default=1, show_default=True,
              type=click.IntRange(min=1),
              help="Fan the backfill Job out across N Indexed pods "
                   "(GORDO_BACKFILL_SHARD_INDEX/NUM_SHARDS env wiring; "
                   "deterministic machine partition). Refused when N "
                   "exceeds the machine count.")
@click.option("--output-file", type=click.File("w"), default="-")
def workflow_generate(machine_config, project_name, image, server_replicas,
                      server_args, fmt, multihost, scrape_annotations,
                      serve_dtype, serve_shards, hpa_max_replicas,
                      refresh_cron, backfill, backfill_shards, output_file):
    """Render the kubernetes manifests + fleet build plan (reference:
    the Argo workflow template render)."""
    from gordo_tpu.workflow import (
        NormalizedConfig,
        generate_workflow,
        workflow_to_yaml,
    )

    config = NormalizedConfig.from_source(machine_config, project_name)
    if multihost and fmt == "argo":
        raise click.BadParameter(
            "--multihost applies to the k8s Indexed-Job builder; the argo "
            "format's DAG already fans out one task per fleet chunk",
            param_hint="--multihost",
        )
    try:
        docs = generate_workflow(
            config, image=image, server_replicas=server_replicas,
            server_args=list(server_args), multihost=multihost,
            scrape_annotations=scrape_annotations,
            serve_dtype=serve_dtype,
            serve_shards=serve_shards,
            hpa_max_replicas=hpa_max_replicas,
            refresh_cron=refresh_cron,
            backfill=tuple(backfill) if backfill else None,
            backfill_shards=backfill_shards,
        )
    except ValueError as exc:
        raise click.ClickException(str(exc))
    if fmt == "argo":
        from gordo_tpu.workflow.generator import generate_argo_workflow

        # the Argo Workflow replaces the builder Job; serving manifests
        # (Deployments/Services/Mappings/plan ConfigMap) stay as-is
        try:
            argo = generate_argo_workflow(
                config, image=image, serve_dtype=serve_dtype
            )
        except ValueError as exc:
            raise click.ClickException(str(exc))
        docs = [argo] + [d for d in docs if d.get("kind") != "Job"]
    output_file.write(workflow_to_yaml(docs))


@workflow_group.command("plan")
@click.option("--machine-config", required=True, envvar="MACHINE_CONFIG")
@click.option("--project-name", envvar="PROJECT_NAME", default="project")
@click.option("--max-bucket-size", default=512, show_default=True)
@click.option("--align-lengths", default=None, type=click.IntRange(min=2),
              help="Plan for a build run with this --align-lengths value "
                   "(cache keys include it; silences the ragged-compile "
                   "warning).")
@click.option("--pad-lengths", default=None, type=click.IntRange(min=2),
              help="Plan for a build run with this --pad-lengths value "
                   "(cache keys include it; silences the ragged-compile "
                   "warning).")
def workflow_plan(machine_config, project_name, max_bucket_size,
                  align_lengths, pad_lengths):
    """Print the bucketed fleet build plan as YAML.

    When the configs predict a ragged fleet (multiple distinct train
    lengths per bucket) and neither --align-lengths nor --pad-lengths is
    planned, prints the estimated per-distinct-length compile bill to
    stderr — the dry run is where that cost should surface, not an hour
    into the build."""
    from gordo_tpu.workflow import NormalizedConfig, build_plan

    config = NormalizedConfig.from_source(machine_config, project_name)
    plan = build_plan(
        config, max_bucket_size=max_bucket_size,
        align_lengths=align_lengths, pad_lengths=pad_lengths,
    )
    click.echo(yaml.safe_dump(plan))
    warning = plan.get("ragged_compile_warning")
    if warning:
        click.echo(
            "WARNING: ragged fleet — ~{n} distinct train lengths predicted "
            "→ ~{extra} extra XLA compiles ≈ {secs}s of compile time. "
            "{hint}".format(
                n=warning["estimated_distinct_lengths"],
                extra=warning["estimated_extra_compiles"],
                secs=warning["estimated_extra_compile_seconds"],
                hint=warning["hint"],
            ),
            err=True,
        )


@workflow_group.command("unique-tags")
@click.option("--machine-config", required=True, envvar="MACHINE_CONFIG")
@click.option("--output-file-tag-list", type=click.File("w"), default="-")
def workflow_unique_tags(machine_config, output_file_tag_list):
    """List distinct sensor tags across the project (reference parity)."""
    from gordo_tpu.workflow import NormalizedConfig, unique_tags

    config = NormalizedConfig.from_source(machine_config)
    for tag in unique_tags(config.machines):
        output_file_tag_list.write(f"{tag}\n")


if __name__ == "__main__":
    gordo()
