"""Command-line interface (reference: ``gordo_components/cli/``)."""

from gordo_tpu.cli.cli import gordo  # noqa: F401
