"""Versioned artifact generations: atomic flips over the pack index.

Reference equivalent: TensorFlow Serving's ``AspiredVersionsManager`` —
a servable advances through monotonically numbered versions and the
serving loop loads the new version while the old one keeps answering.
Here the version unit is the whole pack index: pack writes land as
*pending* rows (``gen = active + 1``) without touching the published
``generation``; one :func:`stamp_generation` at the end of a build flips
the id atomically under the index flock (``delta_write`` stamps inside
its own index swap).  The flip is the ONLY reload signal the server
acts on — pack mtimes can tick mid-rewrite, the generation id cannot.

Retention: superseded packs are retired (file kept on disk, entry moved
to the index's ``retired`` table) and each generation record lists the
pack files live at its flip, so any retained generation stays loadable.
:func:`gc_generations` prunes history to the newest ``keep`` records and
unlinks retired files nothing references; it refuses to delete the live
generation, and ``GORDO_GC_KEEP`` makes every stamp auto-prune.
"""

from __future__ import annotations

import os
from typing import Any, Dict, Optional

from gordo_tpu.artifacts.pack import (
    _GENERATIONS_GAUGE,
    _index_path,
    _locked_index_update,
    _prune_generations,
    _read_index,
    _record_generation,
    _write_generation_file,
    GENERATION_FILE,
    packs_dir,
)

__all__ = ["stamp_generation", "read_generation", "gc_generations"]


def read_generation(output_dir: str) -> int:
    """The published generation id, 0 when the project has no packs (or
    predates the generations layer).  Reads the tiny ``GENERATION``
    sidecar first — the cheap per-poll path for the server's watch
    loop — falling back to the index document."""
    directory = packs_dir(output_dir)
    try:
        with open(os.path.join(directory, GENERATION_FILE)) as fh:
            return int(fh.read().strip())
    except (OSError, ValueError):
        pass
    doc = _read_index(directory)
    return int(doc.get("generation", 0)) if doc else 0


def stamp_generation(
    output_dir: str, keep: Optional[int] = None, force: bool = False
) -> int:
    """Publish every pending pack row as ONE new generation.

    Idempotent: when no rows are pending (a fully-cached rebuild, or a
    second stamp) the published generation is returned unchanged — no
    flip, no reload churn downstream.  ``force=True`` flips anyway,
    republishing EVERY machine row: the operator heal path for pack
    bytes restored out-of-band (from a healthy replica, say) — no build
    wrote pending rows, yet serving replicas must be made to re-validate
    and drop their quarantine (``gordo artifacts flip``).  ``keep``
    prunes history to the newest N generations after the flip (the
    ``GORDO_GC_KEEP`` env var does the same on every stamp).  Returns
    the published generation.
    """
    directory = packs_dir(output_dir)
    if not os.path.exists(_index_path(directory)):
        return 0

    def mutate(doc: Dict[str, Any]) -> None:
        current = int(doc.get("generation", 0))
        pending = sorted(
            name for name, row in doc["machines"].items()
            if int(row.get("gen", 0)) > current
        )
        if not pending and force:
            pending = sorted(doc["machines"])
        if pending:
            _record_generation(directory, doc, pending)
        if keep is not None:
            _prune_generations(directory, doc, max(1, int(keep)))
            _GENERATIONS_GAUGE.set(
                float(len(doc.get("generations", {})))
            )

    doc = _locked_index_update(
        directory, mutate,
        # rewriting the sidecar even on a no-op stamp heals a missing /
        # stale GENERATION file (e.g. an index copied without it)
        after=lambda d: _write_generation_file(
            directory, int(d.get("generation", 0))
        ),
    )
    return int(doc.get("generation", 0))


def gc_generations(output_dir: str, keep: int) -> Dict[str, Any]:
    """Prune generation history to the newest ``keep`` records and
    unlink retired pack files no retained generation (nor the live
    index) references.  Refuses ``keep < 1`` — the live generation is
    never collectable.  Returns a summary for the CLI."""
    if int(keep) < 1:
        raise ValueError(
            "refusing to delete the live generation: keep must be >= 1"
        )
    directory = packs_dir(output_dir)
    if not os.path.exists(_index_path(directory)):
        return {
            "generation": 0, "retained": [], "removed-files": [],
            "retired-remaining": 0,
        }
    summary: Dict[str, Any] = {}

    def mutate(doc: Dict[str, Any]) -> None:
        removed = _prune_generations(directory, doc, int(keep))
        _GENERATIONS_GAUGE.set(float(len(doc.get("generations", {}))))
        summary.update(
            {
                "generation": int(doc.get("generation", 0)),
                "retained": sorted(
                    int(g) for g in doc.get("generations", {})
                ),
                "removed-files": sorted(removed),
                "retired-remaining": len(doc.get("retired", {})),
            }
        )

    _locked_index_update(directory, mutate)
    return summary
