"""Crash-safe writer audit: ``gordo artifacts fsck``.

Every write in the v2 pack layout is ``tmp + os.replace`` + dir fsync,
so a crash can leave exactly two classes of debris: orphaned
``*.tmp.<pid>`` files (a writer died between the durable tmp write and
the rename) and a stale ``GENERATION`` sidecar (the sidecar publish
rides the index flock, but a crash between index replace and sidecar
replace leaves the sidecar one generation behind).  Everything else the
format can detect — a truncated pack, an index segment pointing past
EOF, an unreadable meta doc — is a *finding* that quarantine (serve
plane) or a rebuild must address; fsck reports it but never deletes a
referenced file.

:func:`fsck` walks every invariant and returns a report; with
``repair=True`` it sweeps orphan tmp files and re-publishes a lagging
sidecar.  The server runs ``fsck(repair=True)`` at start
(``run_server``), and operators run ``gordo artifacts fsck [--repair]``
— the playbook lives in docs/operations.md.
"""

from __future__ import annotations

import json
import logging
import os
import struct
from typing import Any, Dict, Optional

import numpy as np

from gordo_tpu import telemetry
from gordo_tpu.artifacts.pack import (
    GENERATION_FILE,
    PACK_MAGIC,
    PACK_VERSION,
    PackCorruptError,
    _locked_index_update,
    _read_index,
    _write_generation_file,
    packs_dir,
)

logger = logging.getLogger(__name__)

__all__ = ["fsck"]

_FSCK_FINDINGS = telemetry.counter(
    "gordo_artifact_fsck_findings_total",
    "fsck findings by kind (orphan_tmp | pack | meta | index | sidecar | "
    "machine_row)",
    labels=("kind",),
)


def _finding(
    report: Dict[str, Any], kind: str, detail: str, **extra: Any
) -> None:
    _FSCK_FINDINGS.inc(1.0, kind)
    report["findings"].append({"kind": kind, "detail": detail, **extra})


def _check_pack_entry(
    directory: str, pack_id: str, entry: Dict[str, Any],
    report: Dict[str, Any],
) -> None:
    path = os.path.join(directory, entry["file"])
    try:
        size = os.stat(path).st_size
        with open(path, "rb") as fh:
            header = fh.read(8)
    except OSError as exc:
        _finding(report, "pack", f"pack {pack_id} unreadable: {exc}",
                 pack=pack_id)
        return
    if header[:4] != PACK_MAGIC:
        _finding(report, "pack",
                 f"pack {pack_id} has bad magic {header[:4]!r}", pack=pack_id)
        return
    (version,) = struct.unpack("<I", header[4:8])
    if version != PACK_VERSION:
        _finding(report, "pack",
                 f"pack {pack_id} has version {version}, reader speaks "
                 f"{PACK_VERSION}", pack=pack_id)
    ends = [
        t["offset"]
        + int(np.prod(t["shape"])) * np.dtype(t["dtype"]).itemsize
        for t in entry["tensors"]
    ] + [off + length for off, length in entry["skeletons"]]
    if ends and max(ends) > size:
        _finding(report, "pack",
                 f"pack {pack_id} truncated: index addresses byte "
                 f"{max(ends)} but the file has {size}", pack=pack_id)
    meta_path = os.path.join(directory, entry["meta_file"])
    try:
        with open(meta_path) as fh:
            json.load(fh)
    except FileNotFoundError:
        pass  # meta is optional at read time (defaults apply)
    except (OSError, ValueError) as exc:
        _finding(report, "meta",
                 f"pack {pack_id} metadata unreadable: {exc}", pack=pack_id)


def fsck(output_dir: str, repair: bool = False) -> Dict[str, Any]:
    """Audit (and optionally repair) the pack layout under ``output_dir``.

    Returns a report dict: ``ok`` (no findings), ``findings`` (each with
    a ``kind`` — see the module counter), ``repaired`` (actions taken
    when ``repair=True``), plus counts.  Never raises on corrupt state —
    the whole point is to enumerate it.
    """
    directory = packs_dir(output_dir)
    if not os.path.isdir(directory):
        # also accept the packs dir itself, the way open_store does
        if os.path.exists(os.path.join(output_dir, "index.json")):
            directory = output_dir
        else:
            return {
                "directory": directory, "ok": True, "findings": [],
                "repaired": [], "packs_checked": 0, "machine_rows": 0,
                "note": "no v2 pack index (nothing to check)",
            }

    report: Dict[str, Any] = {
        "directory": directory, "findings": [], "repaired": [],
        "packs_checked": 0, "machine_rows": 0,
    }

    # 1) orphaned tmp files — debris of a writer that died before rename.
    #    tmp names end in the writer's pid; a live writer's files are in
    #    flight, not orphans.
    for fname in sorted(os.listdir(directory)):
        if ".tmp." not in fname:
            continue
        path = os.path.join(directory, fname)
        try:
            writer_pid = int(fname.rsplit(".", 1)[-1])
        except ValueError:
            writer_pid = None
        writer_alive = False
        if writer_pid is not None and writer_pid != os.getpid():
            try:
                os.kill(writer_pid, 0)
                writer_alive = True
            except OSError:
                writer_alive = False
        if writer_alive:
            continue
        _finding(report, "orphan_tmp",
                 f"orphaned tmp file {fname} (writer died before rename)",
                 file=fname)
        if repair:
            try:
                os.unlink(path)
                report["repaired"].append(f"removed {fname}")
            except OSError as exc:
                logger.warning("fsck could not remove %s: %s", path, exc)

    # 2) the index itself
    doc: Optional[Dict[str, Any]] = None
    try:
        doc = _read_index(directory)
    except PackCorruptError as exc:
        _finding(report, "index", str(exc))
    if doc is None:
        if not report["findings"]:
            report["note"] = "no index.json (nothing to check)"
        report["ok"] = not report["findings"]
        return report

    # 3) every pack entry: file present, magic/version, segments in range,
    #    meta readable
    for pack_id, entry in sorted(doc.get("packs", {}).items()):
        report["packs_checked"] += 1
        _check_pack_entry(directory, pack_id, entry, report)

    # 4) machine rows point at live packs and valid slots
    for name, row in sorted(doc.get("machines", {}).items()):
        report["machine_rows"] += 1
        entry = doc["packs"].get(row.get("pack"))
        if entry is None:
            _finding(report, "machine_row",
                     f"machine {name!r} references missing pack "
                     f"{row.get('pack')!r}", machine=name)
        elif not 0 <= int(row.get("slot", -1)) < len(entry["skeletons"]):
            _finding(report, "machine_row",
                     f"machine {name!r} slot {row.get('slot')} outside pack "
                     f"{row['pack']} ({len(entry['skeletons'])} slots)",
                     machine=name)

    # 5) GENERATION sidecar agrees with the index (a crash between the
    #    index replace and the sidecar replace leaves it behind)
    index_gen = int(doc.get("generation", 0))
    sidecar_path = os.path.join(directory, GENERATION_FILE)
    sidecar_gen: Optional[int] = None
    if os.path.exists(sidecar_path):
        try:
            with open(sidecar_path) as fh:
                sidecar_gen = int(fh.read().strip() or 0)
        except (OSError, ValueError) as exc:
            _finding(report, "sidecar",
                     f"GENERATION sidecar unreadable: {exc}")
    if sidecar_gen is not None and sidecar_gen != index_gen:
        _finding(report, "sidecar",
                 f"GENERATION sidecar says {sidecar_gen} but the index is "
                 f"at {index_gen}")
        if repair:
            # re-publish under the index flock, same as a stamp would —
            # the sidecar may never run ahead of the index it summarizes
            try:
                _locked_index_update(
                    directory, lambda d: None,
                    after=lambda d: _write_generation_file(
                        directory, int(d.get("generation", 0))
                    ),
                )
                report["repaired"].append(
                    f"re-published GENERATION sidecar at {index_gen}"
                )
            except Exception as exc:
                logger.warning("fsck sidecar repair failed: %s", exc)

    report["generation"] = index_gen
    report["ok"] = not report["findings"] or (
        repair
        and all(
            f["kind"] in ("orphan_tmp", "sidecar")
            for f in report["findings"]
        )
        and bool(report["repaired"])
    )
    return report
