"""Artifact format v2: memory-mapped bucket packs.

Reference equivalent: none — the reference (and this repo's v1 layout)
ships one directory per machine (``model.pkl`` + ``metadata.json`` +
``definition.yaml``), so a 10k-machine project is ~30k small files that
the build's writer pool must create one by one and the server must
re-deserialize one by one to reassemble what was a single stacked
``(m_pad, ...)`` array on device.  The TensorFlow-serving "one loadable
bundle" pattern and the pjit sharded-checkpoint layout (PAPERS.md) both
point the other way: few large, index-addressed parameter packs.

Layout (under a build output dir)::

    <output_dir>/.gordo-packs/
      index.json            machine -> (pack, slot, cache_key); pack ->
                            tensor/skeleton segment table (the ONE file
                            the disk registry's pack refs resolve through)
      <pack>.pack           raw little-endian tensor segments, each
                            page-aligned (4096), one stacked (M, ...)
                            tensor per array leaf, followed by the
                            per-machine pickled skeletons
      <pack>.meta.json      per-machine build metadata + the chunk's
                            shared definition.yaml text

One pack holds one (signature, bucket) chunk of a fleet build: the
machines share one model structure, so each array leaf stacks across the
machine axis into a single contiguous ``(M, *leaf_shape)`` segment.  A
machine's model is a tiny pickled *skeleton* — the object graph with
every array leaf swapped for a ``(pack-leaf, index)`` persistent id —
and loading it materializes zero-copy ``np.memmap`` views into the
stacked segments.  The serve plane goes further: a whole pack's stacked
tensors ship to the device as ONE :func:`to_device` call (the only
``jax.device_put`` the lint gate permits in this package), so server
start pays one transfer per pack instead of one unpickle per machine.

Delta writes: :func:`delta_write` rewrites only the changed machines'
slot segments in place (O(changed-machines) bytes) plus an atomic index
swap — the primitive incremental rebuilds (ROADMAP item 3) need.

Generations: the index carries a monotonic ``generation`` id, and every
machine row records the generation (``gen``) that last rewrote it.  Pack
writes record rows as *pending* (``gen = active + 1``) without touching
the published generation; one flock-serialized
:func:`~gordo_tpu.artifacts.generations.stamp_generation` at the end of
a build flips the id atomically (``delta_write`` stamps inside its own
index flip).  Readers — the server's delta hot reload above all — never
act on pack mtimes: the generation flip is the ONLY reload signal, and
it happens strictly after the pack bytes it publishes are durable, so a
mid-rewrite pack can never be observed as "new".  Superseded packs are
*retired* (entry moved aside, file retained on disk) rather than
unlinked, so previous generations stay loadable until
:func:`~gordo_tpu.artifacts.generations.gc_generations` prunes them.

Durability matches the registry/round-file convention: every rename is
``tmp + os.replace`` followed by a parent-directory fsync, so an index
can never reference a pack that a crash kept off disk.
"""

from __future__ import annotations

import fcntl
import hashlib
import io
import json
import logging
import os
import pickle
import struct
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np

from gordo_tpu import faults, telemetry
from gordo_tpu.utils.disk_registry import fsync_dir

logger = logging.getLogger(__name__)

#: directory (under a build output dir) holding the pack files + index
PACKS_DIR = ".gordo-packs"
#: pack file magic + format version (little-endian u32 after the magic)
PACK_MAGIC = b"GPK2"
PACK_VERSION = 2
#: tensor segments align to page boundaries so ``np.memmap`` views (and
#: the eventual DMA into device memory) start page-aligned
PAGE = 4096
#: registry values for packed machines: ``pack:<packs_dir>#<machine>``
PACK_REF_PREFIX = "pack:"
#: persistent-id tag marking an extracted array leaf in a skeleton pickle
_LEAF_TAG = "gordo-pack-leaf"

ENV_FORMAT = "GORDO_ARTIFACT_FORMAT"
FORMATS = ("v1", "v2")

#: tiny sidecar holding just the active generation int — the cheap
#: watch-poll target (one small read per poll instead of parsing the
#: whole index); rewritten under the index flock so it can never run
#: ahead of the index it summarizes
GENERATION_FILE = "GENERATION"
#: when set, every generation stamp auto-prunes to the newest N
#: generations (``gordo artifacts gc --keep N`` is the explicit form)
ENV_GC_KEEP = "GORDO_GC_KEEP"

# -- telemetry instruments (docs/observability.md) --------------------------
_PACKS_TOTAL = telemetry.counter(
    "gordo_artifact_packs_total",
    "Pack operations by kind (written | opened | delta | retired | gc)",
    labels=("op",),
)
_GENERATIONS_GAUGE = telemetry.gauge(
    "gordo_artifact_generations",
    "Generation records retained in the pack index (active + history "
    "still reloadable on disk)",
)
_PACK_BYTES_TOTAL = telemetry.counter(
    "gordo_artifact_pack_bytes_total",
    "Bytes written to pack files, by operation (written | delta)",
    labels=("op",),
)
_PACK_DEVICE_PUTS = telemetry.counter(
    "gordo_artifact_pack_device_puts_total",
    "Whole-pack host->device transfers (the v2 load contract: exactly "
    "one per (signature, bucket) pack)",
)
_PACK_LOAD_SECONDS = telemetry.histogram(
    "gordo_artifact_pack_load_seconds",
    "Store open (index validation + memmap) seconds",
)


class PackError(Exception):
    """Base class for v2 artifact failures (always loud, never skipped)."""


class PackCorruptError(PackError):
    """A pack or its index fails validation (truncated segment, offset
    past EOF, bad magic, unreadable index) — refuse to serve from it."""


def resolve_format(fmt: Optional[str] = None) -> str:
    """The artifact format a build writes: an explicit argument wins,
    else ``GORDO_ARTIFACT_FORMAT``, else ``v2`` — memory-mapped bucket
    packs are the library default now that the whole serving tier
    (collection load, fleet prestacking, sharded replicas) consumes
    packs end-to-end.  ``GORDO_ARTIFACT_FORMAT=v1`` is the escape hatch
    for tooling that still walks per-machine directories (or run
    ``gordo artifacts unpack`` to export a v1 view)."""
    fmt = fmt or os.environ.get(ENV_FORMAT, "").strip().lower() or "v2"
    if fmt not in FORMATS:
        raise ValueError(
            f"unknown artifact format {fmt!r}; expected one of {FORMATS}"
        )
    return fmt


def packs_dir(output_dir: str) -> str:
    return os.path.join(output_dir, PACKS_DIR)


def machine_ref(output_dir: str, name: str) -> str:
    """The registry value recorded for a packed machine: the pack index
    is the unit the registry records, so the ref addresses the machine
    THROUGH the index rather than a per-machine path."""
    return f"{PACK_REF_PREFIX}{os.path.abspath(packs_dir(output_dir))}#{name}"


def is_pack_ref(value: str) -> bool:
    return isinstance(value, str) and value.startswith(PACK_REF_PREFIX)


def parse_ref(ref: str) -> Tuple[str, str]:
    """``pack:<packs_dir>#<machine>`` -> (packs_dir, machine)."""
    if not is_pack_ref(ref) or "#" not in ref:
        raise ValueError(f"not a pack ref: {ref!r}")
    body = ref[len(PACK_REF_PREFIX):]
    directory, _, name = body.rpartition("#")
    return directory, name


# ---------------------------------------------------------------------------
# model <-> (skeleton, leaves) flattening
# ---------------------------------------------------------------------------

def flatten_model(model: Any) -> Tuple[bytes, List[np.ndarray]]:
    """Pickle ``model`` with every array leaf swapped for a persistent
    id; returns the skeleton bytes plus the leaves in encounter order.
    Duplicate references to one array collapse to one leaf (and restore
    as one shared view).  jax array leaves pull to host first — packs
    are device-independent, like v1 pickles."""
    leaves: List[np.ndarray] = []
    seen: Dict[int, int] = {}
    keepalive: List[Any] = []  # pin ids against reuse during the dump

    class _Extractor(pickle.Pickler):
        def persistent_id(self, obj):  # noqa: D102
            arr = None
            if isinstance(obj, np.ndarray) and obj.dtype != np.dtype(object):
                arr = obj
            elif isinstance(obj, jax.Array):
                arr = obj
            if arr is None:
                return None
            key = id(arr)
            if key not in seen:
                host = np.ascontiguousarray(
                    np.asarray(jax.device_get(arr))
                    if isinstance(arr, jax.Array) else arr
                )
                if host.dtype.byteorder == ">":
                    host = host.astype(host.dtype.newbyteorder("<"))
                seen[key] = len(leaves)
                leaves.append(host)
                keepalive.append(arr)
            return (_LEAF_TAG, seen[key])

    buf = io.BytesIO()
    _Extractor(buf, protocol=4).dump(model)
    return buf.getvalue(), leaves


class _ViewUnpickler(pickle.Unpickler):
    """Skeleton unpickler: persistent ids resolve to zero-copy views."""

    def __init__(self, data: bytes, resolver: Callable[[int], np.ndarray]):
        super().__init__(io.BytesIO(data))
        self._resolver = resolver

    def persistent_load(self, pid):  # noqa: D102
        if (
            not isinstance(pid, tuple) or len(pid) != 2
            or pid[0] != _LEAF_TAG
        ):
            raise pickle.UnpicklingError(f"unknown persistent id {pid!r}")
        return self._resolver(int(pid[1]))


def _leaf_signature(leaves: Sequence[np.ndarray]) -> List[Tuple]:
    return [(tuple(a.shape), a.dtype.str) for a in leaves]


# ---------------------------------------------------------------------------
# index read/modify/write (flock-serialized: multi-host shards share a dir)
# ---------------------------------------------------------------------------

def _index_path(directory: str) -> str:
    return os.path.join(directory, "index.json")


def _read_index(directory: str) -> Optional[Dict[str, Any]]:
    path = _index_path(directory)
    try:
        with open(path) as fh:
            doc = json.load(fh)
    except FileNotFoundError:
        return None
    except (OSError, ValueError) as exc:
        raise PackCorruptError(f"unreadable pack index {path}: {exc}")
    if doc.get("version") != PACK_VERSION:
        raise PackCorruptError(
            f"pack index {path} has version {doc.get('version')!r}; this "
            f"reader speaks version {PACK_VERSION}"
        )
    return doc


def _locked_index_update(
    directory: str,
    mutate: Callable[[Dict[str, Any]], None],
    after: Optional[Callable[[Dict[str, Any]], None]] = None,
) -> Dict[str, Any]:
    """Read-modify-write the index under an exclusive flock, swapping the
    new index in atomically (tmp + rename + dir fsync).  The lock
    serializes concurrent writers — multi-host build shards write
    disjoint chunks into ONE shared index.  ``after`` runs with the lock
    STILL HELD once the new index is durable (the generation sidecar
    write rides here, so two concurrent stamps can't publish sidecars
    out of order)."""
    os.makedirs(directory, exist_ok=True)
    with open(os.path.join(directory, ".lock"), "a+") as lock:
        fcntl.flock(lock.fileno(), fcntl.LOCK_EX)
        doc = _read_index(directory) or {
            "version": PACK_VERSION, "packs": {}, "machines": {},
        }
        mutate(doc)
        path = _index_path(directory)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as fh:
            json.dump(doc, fh, indent=1, sort_keys=True)
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, path)
        fsync_dir(directory)
        if after is not None:
            after(doc)
        return doc


def _write_generation_file(directory: str, generation: int) -> None:
    """Publish the tiny ``GENERATION`` sidecar (tmp + replace + fsync) —
    what the server's watch loop polls instead of re-parsing the index.
    Callers hold the index flock, so sidecars publish in stamp order."""
    path = os.path.join(directory, GENERATION_FILE)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as fh:
        fh.write(f"{int(generation)}\n")
        fh.flush()
        os.fsync(fh.fileno())
    os.replace(tmp, path)
    fsync_dir(directory)


def _record_generation(
    directory: str, doc: Dict[str, Any], changed: Sequence[str]
) -> int:
    """Flip ``doc`` to the next generation (caller is inside a locked
    index mutate): bump the id, stamp the changed rows, and append a
    generation record carrying the live pack refs — what keeps retired
    pack files reachable (and gc-able) per generation."""
    new_gen = int(doc.get("generation", 0)) + 1
    doc["generation"] = new_gen
    for name in changed:
        row = doc["machines"].get(name)
        if row is not None:
            row["gen"] = new_gen
    doc.setdefault("generations", {})[str(new_gen)] = {
        "created_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "changed_count": len(changed),
        "packs": sorted(
            {e["file"] for e in doc["packs"].values()}
        ),
    }
    keep = os.environ.get(ENV_GC_KEEP, "").strip()
    if keep:
        try:
            _prune_generations(directory, doc, max(1, int(keep)))
        except ValueError:
            logger.warning("ignoring non-integer %s=%r", ENV_GC_KEEP, keep)
    _GENERATIONS_GAUGE.set(float(len(doc.get("generations", {}))))
    return new_gen


def _prune_generations(
    directory: str, doc: Dict[str, Any], keep: int
) -> List[str]:
    """Drop all but the newest ``keep`` generation records and unlink
    retired pack files no retained record (nor the live index)
    references.  Runs inside a locked index mutate; the active
    generation is always retained (``keep >= 1`` is enforced by
    callers).  Returns the file names actually removed."""
    gens = doc.get("generations", {})
    retained = sorted((int(g) for g in gens), reverse=True)[:keep]
    for g in [g for g in gens if int(g) not in retained]:
        del gens[g]
    referenced = {e["file"] for e in doc["packs"].values()}
    referenced |= {e["meta_file"] for e in doc["packs"].values()}
    for rec in gens.values():
        referenced.update(rec.get("packs", ()))
    removed: List[str] = []
    retired = doc.get("retired", {})
    for pack_id in [
        p for p, e in retired.items() if e["file"] not in referenced
    ]:
        entry = retired.pop(pack_id)
        _PACKS_TOTAL.inc(1.0, "gc")
        for key in ("file", "meta_file"):
            if entry.get(key) and entry[key] not in referenced:
                try:
                    os.unlink(os.path.join(directory, entry[key]))
                    removed.append(entry[key])
                except OSError:
                    pass
    return removed


def _gc_dead_packs(directory: str, doc: Dict[str, Any]) -> None:
    """Retire pack entries whose machines were all superseded by newer
    packs: the entry moves to the index's ``retired`` table but the FILE
    stays on disk — a previous generation's packs must remain loadable
    until :func:`~gordo_tpu.artifacts.generations.gc_generations` (or
    the ``GORDO_GC_KEEP`` auto-prune) decides history is deep enough."""
    live: Dict[str, int] = {}
    for row in doc["machines"].values():
        live[row["pack"]] = live.get(row["pack"], 0) + 1
    for pack_id in [p for p in doc["packs"] if not live.get(p)]:
        entry = doc["packs"].pop(pack_id)
        _PACKS_TOTAL.inc(1.0, "retired")
        doc.setdefault("retired", {})[pack_id] = {
            "file": entry["file"],
            "meta_file": entry["meta_file"],
            "bytes": entry.get("bytes", 0),
            "retired_after": int(doc.get("generation", 0)),
        }


# ---------------------------------------------------------------------------
# writing
# ---------------------------------------------------------------------------

def write_pack(
    output_dir: str,
    names: Sequence[str],
    models: Sequence[Any],
    metadatas: Optional[Sequence[Optional[Dict[str, Any]]]] = None,
    definition: Optional[str] = None,
    cache_keys: Optional[Dict[str, str]] = None,
) -> str:
    """Write one (signature, bucket) chunk as a single pack.

    Every model must flatten to the same leaf signature (shapes +
    dtypes) — true by construction for a fleet chunk; a mismatch raises
    :class:`PackError` so the caller can fall back to per-machine v1
    artifacts instead of silently mis-slicing.  Returns the pack id.
    The index update drops any older rows for these machines and
    garbage-collects packs left with no live machines.
    """
    if not names or len(names) != len(models):
        raise PackError(
            f"write_pack needs aligned names/models (got {len(names)} names, "
            f"{len(models)} models)"
        )
    metadatas = list(metadatas) if metadatas is not None else [None] * len(names)
    flat = [flatten_model(m) for m in models]
    sig0 = _leaf_signature(flat[0][1])
    for name, (_, leaves) in zip(names, flat):
        if _leaf_signature(leaves) != sig0:
            raise PackError(
                f"machine {name!r} breaks the chunk's leaf signature — "
                "packs require one shared model structure per chunk"
            )

    directory = packs_dir(output_dir)
    os.makedirs(directory, exist_ok=True)
    # generation-qualify the pack id: a rebuild of the same chunk in a
    # LATER generation must land in a fresh file so the previous
    # generation's bytes survive until gc — same names + same pending
    # generation still collapse to one file (idempotent re-runs)
    try:
        existing = _read_index(directory)
    except PackCorruptError:
        existing = None
    pending_gen = int((existing or {}).get("generation", 0)) + 1
    pack_id = "pack-" + hashlib.md5(
        ",".join(names).encode()
    ).hexdigest()[:12] + f"-g{pending_gen}"
    pack_file = f"{pack_id}.pack"
    meta_file = f"{pack_id}.meta.json"

    tensors: List[Dict[str, Any]] = []
    skeletons: List[Tuple[int, int]] = []
    tmp = os.path.join(directory, f"{pack_file}.tmp.{os.getpid()}")
    with open(tmp, "wb") as fh:
        fh.write(PACK_MAGIC + struct.pack("<I", PACK_VERSION))
        for leaf_idx, (shape, dtype) in enumerate(sig0):
            offset = -(-fh.tell() // PAGE) * PAGE  # next page boundary
            fh.seek(offset)
            for _, leaves in flat:
                fh.write(leaves[leaf_idx].tobytes())
            tensors.append(
                {
                    "offset": offset,
                    "shape": [len(names)] + list(shape),
                    "dtype": dtype,
                }
            )
        for skeleton, _ in flat:
            skeletons.append((fh.tell(), len(skeleton)))
            fh.write(skeleton)
        fh.flush()
        os.fsync(fh.fileno())
        n_bytes = fh.tell()
    # injection seam: "enospc" surfaces as OSError to the caller, "crash"
    # aborts between the durable tmp write and the rename — exactly the
    # torn state `gordo artifacts fsck` must detect and sweep
    faults.check("artifact.write", op="write_pack", file=pack_file)
    os.replace(tmp, os.path.join(directory, pack_file))

    meta_doc = {
        "definition": definition,
        "machines": {
            name: md for name, md in zip(names, metadatas) if md is not None
        },
    }
    tmp = os.path.join(directory, f"{meta_file}.tmp.{os.getpid()}")
    with open(tmp, "w") as fh:
        json.dump(meta_doc, fh, default=str)
        fh.flush()
        os.fsync(fh.fileno())
    os.replace(tmp, os.path.join(directory, meta_file))
    fsync_dir(directory)  # both renames durable before the index names them

    entry = {
        "file": pack_file,
        "meta_file": meta_file,
        "bytes": n_bytes,
        "machines": list(names),
        "tensors": tensors,
        "skeletons": [list(s) for s in skeletons],
        "written_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
    }

    def mutate(doc: Dict[str, Any]) -> None:
        doc["packs"][pack_id] = entry
        # rows land PENDING: gen is one past the published generation,
        # so readers gating on the generation id don't reload mid-build;
        # stamp_generation at build end publishes every pending row in
        # one atomic flip (recomputed under the lock — a stamp that
        # slipped in between makes these rows part of the NEXT flip)
        row_gen = int(doc.get("generation", 0)) + 1
        for slot, name in enumerate(names):
            row: Dict[str, Any] = {
                "pack": pack_id, "slot": slot, "gen": row_gen,
            }
            key = (cache_keys or {}).get(name)
            if key:
                row["cache_key"] = key
            doc["machines"][name] = row
        _gc_dead_packs(directory, doc)

    _locked_index_update(directory, mutate)
    _PACKS_TOTAL.inc(1.0, "written")
    _PACK_BYTES_TOTAL.inc(float(n_bytes), "written")
    return pack_id


def delta_write(
    output_dir: str,
    models: Dict[str, Any],
    metadatas: Optional[Dict[str, Dict[str, Any]]] = None,
) -> List[str]:
    """Rewrite only the named machines inside their existing packs.

    O(changed-machines): each machine's slot segment in every stacked
    tensor is overwritten in place (same shapes/dtypes required — a
    structural change is a rebuild, not a delta), its skeleton is
    appended to the pack tail, and ONE atomic index swap publishes the
    new offsets.  This is the primitive incremental rebuilds compose
    with: changed machines rewrite; the index flip is the generation
    boundary.  Returns the machine names rewritten.
    """
    directory = packs_dir(output_dir)
    faults.check("artifact.write", op="delta_write")
    doc = _read_index(directory)
    if doc is None:
        raise PackError(f"no pack index under {directory}")
    by_pack: Dict[str, List[str]] = {}
    for name in models:
        row = doc["machines"].get(name)
        if row is None:
            raise PackError(f"machine {name!r} is not in the pack index")
        by_pack.setdefault(row["pack"], []).append(name)

    new_skeletons: Dict[str, Dict[int, Tuple[int, int]]] = {}
    delta_bytes = 0
    for pack_id, pack_names in by_pack.items():
        entry = doc["packs"][pack_id]
        sig = [
            (tuple(t["shape"][1:]), t["dtype"]) for t in entry["tensors"]
        ]
        path = os.path.join(directory, entry["file"])
        with open(path, "r+b") as fh:
            for name in pack_names:
                skeleton, leaves = flatten_model(models[name])
                if _leaf_signature(leaves) != sig:
                    raise PackError(
                        f"delta for {name!r} changes the leaf signature — "
                        "structural changes need a full chunk rebuild"
                    )
                slot = doc["machines"][name]["slot"]
                for tensor, leaf in zip(entry["tensors"], leaves):
                    fh.seek(tensor["offset"] + slot * leaf.nbytes)
                    fh.write(leaf.tobytes())
                    delta_bytes += leaf.nbytes
                fh.seek(0, os.SEEK_END)
                new_skeletons.setdefault(pack_id, {})[slot] = (
                    fh.tell(), len(skeleton),
                )
                fh.write(skeleton)
                delta_bytes += len(skeleton)
            fh.flush()
            os.fsync(fh.fileno())
            entry["bytes"] = fh.seek(0, os.SEEK_END)

        if metadatas:
            meta_path = os.path.join(directory, entry["meta_file"])
            try:
                with open(meta_path) as fh:
                    meta_doc = json.load(fh)
            except (OSError, ValueError):
                meta_doc = {"definition": None, "machines": {}}
            for name in pack_names:
                if name in metadatas:
                    meta_doc["machines"][name] = metadatas[name]
            tmp = f"{meta_path}.tmp.{os.getpid()}"
            with open(tmp, "w") as fh:
                json.dump(meta_doc, fh, default=str)
                fh.flush()
                os.fsync(fh.fileno())
            os.replace(tmp, meta_path)

    def mutate(idx: Dict[str, Any]) -> None:
        for pack_id, slots in new_skeletons.items():
            entry = idx["packs"].get(pack_id)
            if entry is None:
                raise PackError(
                    f"pack {pack_id} vanished during delta_write"
                )
            entry["bytes"] = doc["packs"][pack_id]["bytes"]
            for slot, (offset, length) in slots.items():
                entry["skeletons"][slot] = [offset, length]
        # a delta IS a generation: the pack bytes above are already
        # durable (fsync'd before this flip), so stamping here makes the
        # index swap the one atomic publish — readers gating reloads on
        # the generation can never observe the rewrite half-done
        _record_generation(directory, idx, sorted(models))

    _locked_index_update(
        directory, mutate,
        after=lambda idx: _write_generation_file(
            directory, int(idx["generation"])
        ),
    )
    _PACKS_TOTAL.inc(float(len(by_pack)), "delta")
    _PACK_BYTES_TOTAL.inc(float(delta_bytes), "delta")
    return sorted(models)


# ---------------------------------------------------------------------------
# reading
# ---------------------------------------------------------------------------

class PackStore:
    """Read surface over one ``.gordo-packs/`` directory.

    Opening validates every pack eagerly — magic, version, and that each
    recorded segment lies inside the file — so corruption (a truncated
    pack, an index offset past EOF) fails LOUDLY at open instead of
    serving garbage views later.  All reads after that are zero-copy:
    one ``np.memmap`` per pack, ``np.ndarray`` views into it per tensor
    and per machine slot.

    ``quarantine=True`` (the serving path) records a failing pack in
    ``quarantined_packs``/``quarantined_machines`` instead of raising:
    the rest of the store stays readable, and the collection layer
    serves 503 ``quarantined`` for only the affected machines.  The
    default stays loud — registry/CLI callers want corruption to stop
    them, not shrink results silently.
    """

    def __init__(self, directory: str, quarantine: bool = False):
        t0 = time.monotonic()
        self.directory = directory
        doc = _read_index(directory)
        if doc is None:
            raise FileNotFoundError(f"no pack index under {directory}")
        self.packs: Dict[str, Dict[str, Any]] = doc["packs"]
        self.machines: Dict[str, Dict[str, Any]] = doc["machines"]
        #: published generation id at open (0 for a pre-generations
        #: index) — the value the server's project index republishes
        self.generation: int = int(doc.get("generation", 0))
        #: retained generation records (newest last), for store_info/gc
        self.generations: Dict[str, Dict[str, Any]] = dict(
            doc.get("generations", {})
        )
        try:
            st = os.stat(_index_path(directory))
            self.index_stat = (st.st_mtime, st.st_size)
        except OSError:
            self.index_stat = (0.0, -1)
        self._mmaps: Dict[str, np.memmap] = {}
        self._stacked: Dict[str, List[np.ndarray]] = {}
        self._meta: Dict[str, Dict[str, Any]] = {}
        self._slot_views: Dict[Tuple[str, int, int], np.ndarray] = {}
        #: id(view or stacked tensor) -> (pack_id, leaf_idx); lets the
        #: fleet scorer map a reconstructed model's array leaves back to
        #: their stacked pack tensors without copying anything
        self._leaf_ids: Dict[int, Tuple[str, int]] = {}
        #: packs that failed open-validation, {pack_id: error} (always
        #: empty without ``quarantine`` — failures raise instead)
        self.quarantined_packs: Dict[str, str] = {}
        #: machines whose pack is quarantined, {name: error}
        self.quarantined_machines: Dict[str, str] = {}
        for pack_id, entry in self.packs.items():
            try:
                self._validate(pack_id, entry)
            except PackCorruptError as exc:
                if not quarantine:
                    raise
                logger.error("quarantining pack %s: %s", pack_id, exc)
                self.quarantined_packs[pack_id] = str(exc)
        if self.quarantined_packs:
            self.quarantined_machines = {
                name: self.quarantined_packs[row["pack"]]
                for name, row in self.machines.items()
                if row["pack"] in self.quarantined_packs
            }
        _PACKS_TOTAL.inc(float(len(self.packs)), "opened")
        _PACK_LOAD_SECONDS.observe(time.monotonic() - t0)

    # -- validation ---------------------------------------------------------
    def _validate(self, pack_id: str, entry: Dict[str, Any]) -> None:
        path = os.path.join(self.directory, entry["file"])
        try:
            faults.check("pack.open", pack=pack_id, path=path)
            size = os.stat(path).st_size
            with open(path, "rb") as fh:
                header = fh.read(8)
        except faults.InjectedFault as exc:
            raise PackCorruptError(f"pack {pack_id}: {exc}")
        except OSError as exc:
            raise PackCorruptError(f"pack {pack_id} unreadable: {exc}")
        if header[:4] != PACK_MAGIC:
            raise PackCorruptError(
                f"pack {pack_id} has bad magic {header[:4]!r}"
            )
        ends = [
            t["offset"]
            + int(np.prod(t["shape"])) * np.dtype(t["dtype"]).itemsize
            for t in entry["tensors"]
        ] + [off + length for off, length in entry["skeletons"]]
        if ends and max(ends) > size:
            raise PackCorruptError(
                f"pack {pack_id} is truncated: index addresses byte "
                f"{max(ends)} but the file has {size}"
            )

    # -- zero-copy views ----------------------------------------------------
    def _mmap(self, pack_id: str) -> np.memmap:
        mm = self._mmaps.get(pack_id)
        if mm is None:
            path = os.path.join(
                self.directory, self.packs[pack_id]["file"]
            )
            mm = self._mmaps[pack_id] = np.memmap(
                path, dtype=np.uint8, mode="r"
            )
        return mm

    def stacked(self, pack_id: str) -> List[np.ndarray]:
        """The pack's stacked ``(M, *leaf_shape)`` tensors as memmap
        views — what ships to the device in one :func:`to_device`."""
        out = self._stacked.get(pack_id)
        if out is None:
            mm = self._mmap(pack_id)
            out = []
            for leaf_idx, t in enumerate(self.packs[pack_id]["tensors"]):
                dt = np.dtype(t["dtype"])
                n = int(np.prod(t["shape"])) * dt.itemsize
                view = (
                    mm[t["offset"]: t["offset"] + n]
                    .view(dt)
                    .reshape(t["shape"])
                )
                self._leaf_ids[id(view)] = (pack_id, leaf_idx)
                out.append(view)
            self._stacked[pack_id] = out
        return out

    def _slot_view(self, pack_id: str, slot: int, leaf_idx: int) -> np.ndarray:
        key = (pack_id, slot, leaf_idx)
        view = self._slot_views.get(key)
        if view is None:
            view = self.stacked(pack_id)[leaf_idx][slot]
            self._slot_views[key] = view
            self._leaf_ids[id(view)] = (pack_id, leaf_idx)
        return view

    def leaf_of(self, array: Any) -> Optional[Tuple[str, int]]:
        """(pack_id, leaf_idx) when ``array`` is a view this store handed
        out (per-slot or stacked), else None."""
        return self._leaf_ids.get(id(array))

    # -- per-machine surface ------------------------------------------------
    def names(self) -> List[str]:
        """Loadable machine names (quarantined packs' machines excluded —
        the collection layer reports those separately)."""
        if not self.quarantined_machines:
            return sorted(self.machines)
        return sorted(
            n for n in self.machines if n not in self.quarantined_machines
        )

    def __contains__(self, name: str) -> bool:
        return name in self.machines

    def location(self, name: str) -> Tuple[str, int]:
        row = self.machines[name]
        return row["pack"], row["slot"]

    def cache_key(self, name: str) -> Optional[str]:
        row = self.machines.get(name)
        return row.get("cache_key") if row else None

    def machines_of(self, pack_id: str) -> List[str]:
        """Live machines of a pack in slot order (superseded slots —
        machines a newer pack took over — are skipped)."""
        return [
            n for n in self.packs[pack_id]["machines"]
            if self.machines.get(n, {}).get("pack") == pack_id
        ]

    def load_model(self, name: str) -> Any:
        """Reconstruct one machine's model: unpickle its tiny skeleton,
        resolving each array leaf to a zero-copy view of the stacked
        memmap — no per-machine file opens, no array copies."""
        if name in self.quarantined_machines:
            raise PackCorruptError(
                f"machine {name!r} is quarantined: "
                f"{self.quarantined_machines[name]}"
            )
        pack_id, slot = self.location(name)
        try:
            faults.check("pack.read", pack=pack_id, machine=name)
        except (faults.InjectedFault, OSError) as exc:
            raise PackCorruptError(f"machine {name!r}: {exc}")
        offset, length = self.packs[pack_id]["skeletons"][slot]
        data = bytes(self._mmap(pack_id)[offset: offset + length])
        try:
            return _ViewUnpickler(
                data, lambda leaf: self._slot_view(pack_id, slot, leaf)
            ).load()
        except PackError:
            raise
        except Exception as exc:
            raise PackCorruptError(
                f"machine {name!r} skeleton in pack {pack_id} failed to "
                f"load: {exc}"
            )

    def _meta_doc(self, pack_id: str) -> Dict[str, Any]:
        doc = self._meta.get(pack_id)
        if doc is None:
            path = os.path.join(
                self.directory, self.packs[pack_id]["meta_file"]
            )
            try:
                with open(path) as fh:
                    doc = json.load(fh)
            except FileNotFoundError:
                doc = {"definition": None, "machines": {}}
            except (OSError, ValueError) as exc:
                raise PackCorruptError(
                    f"pack {pack_id} metadata unreadable: {exc}"
                )
            self._meta[pack_id] = doc
        return doc

    def load_metadata(self, name: str) -> Dict[str, Any]:
        pack_id, _ = self.location(name)
        return self._meta_doc(pack_id)["machines"].get(name, {})

    def definition(self, name: str) -> Optional[str]:
        pack_id, _ = self.location(name)
        return self._meta_doc(pack_id).get("definition")

    def row_generation(self, name: str) -> int:
        """The generation that last (re)wrote this machine's slot —
        what the server's delta reload compares against its own
        generation to build the changed-machine set.  0 for rows written
        before the generations layer existed."""
        row = self.machines.get(name)
        return int(row.get("gen", 0)) if row else 0

    def changed_since(self, generation: int) -> List[str]:
        """Machines whose rows were rewritten after ``generation`` —
        the O(changed) set a delta hot reload re-stacks."""
        return sorted(
            name for name, row in self.machines.items()
            if int(row.get("gen", 0)) > int(generation)
        )

    def stat(self, name: str) -> Tuple[float, int]:
        """(mtime, size) of the machine's pack file.  Historical reload
        signal, kept for v1-parity surfaces only: a ``delta_write``
        mutates the pack in place, so mtime can tick while the rewrite
        is still torn — rescan gates pack reloads on
        :meth:`row_generation` instead."""
        pack_id, _ = self.location(name)
        try:
            st = os.stat(
                os.path.join(self.directory, self.packs[pack_id]["file"])
            )
            return st.st_mtime, st.st_size
        except OSError:
            return 0.0, -1

    def total_bytes(self) -> int:
        return sum(int(e.get("bytes", 0)) for e in self.packs.values())


def to_device(host_tree: Any, shardings: Any = None, dtype: Any = None) -> Any:
    """ONE whole-pack host→device transfer (counted; the v2 load contract
    is exactly one of these per (signature, bucket) pack — the lint gate
    keeps ``device_put`` out of everywhere else in this package).

    ``dtype``: optional storage dtype (the serving-precision plane —
    ``gordo_tpu/serve/precision.py``): float leaves are cast host-side
    before the transfer, so a bf16 serving configuration ships HALF the
    pack bytes over the wire and resides at half the device footprint.
    ``None`` (the fp32 default) preserves the zero-copy memmap path —
    a cast necessarily materializes a host copy, so it only happens when
    reduced precision was explicitly configured.
    """
    _PACK_DEVICE_PUTS.inc(1.0)
    if dtype is not None:
        dt = np.dtype(dtype)
        host_tree = jax.tree.map(
            lambda a: (
                a.astype(dt)
                if getattr(getattr(a, "dtype", None), "kind", "") == "f"
                else a
            ),
            host_tree,
        )
    if shardings is None:
        return jax.device_put(host_tree)
    return jax.device_put(host_tree, shardings)


def device_put_count() -> float:
    """Current value of the pack-transfer counter (telemetry attestation
    for tests and the artifact_io bench)."""
    return _PACK_DEVICE_PUTS.value()
