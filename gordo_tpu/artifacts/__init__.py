"""Artifact plane: the ONE loading/writing API over both artifact formats.

v1 (compatibility): one directory per machine — ``model.pkl`` +
``metadata.json`` + ``definition.yaml`` (``gordo_tpu.serializer``).
v2: memory-mapped bucket packs — one page-aligned tensor pack per
(signature, bucket) chunk plus a JSON index (``gordo_tpu.artifacts.pack``).

Everything that touches artifacts on disk goes through here: the build
writer stage (:func:`pack.write_pack` per chunk, or per-machine v1
dumps), the server's collection load (:func:`discover`), the registry's
cache lookups (:func:`resolve_cached`), and the conversion tools
(:func:`repack` / :func:`unpack`).  ``scripts/lint.py`` rejects direct
per-machine artifact path construction outside this package and the
serializer/builder write path, so new call sites can't silently grow a
third layout.
"""

from __future__ import annotations

import logging
import os
import shutil
from typing import Any, Dict, List, Optional, Set, Tuple

from gordo_tpu import serializer
from gordo_tpu.artifacts.fsck import fsck  # noqa: F401
from gordo_tpu.artifacts.generations import (  # noqa: F401
    gc_generations,
    read_generation,
    stamp_generation,
)
from gordo_tpu.artifacts.pack import (  # noqa: F401
    ENV_FORMAT,
    ENV_GC_KEEP,
    FORMATS,
    GENERATION_FILE,
    PACK_REF_PREFIX,
    PACKS_DIR,
    PackCorruptError,
    PackError,
    PackStore,
    delta_write,
    device_put_count,
    flatten_model,
    is_pack_ref,
    machine_ref,
    packs_dir,
    parse_ref,
    resolve_format,
    to_device,
    write_pack,
)

logger = logging.getLogger(__name__)

__all__ = [
    "ENV_FORMAT", "ENV_GC_KEEP", "FORMATS", "GENERATION_FILE",
    "PACKS_DIR", "PACK_REF_PREFIX",
    "PackError", "PackCorruptError", "PackStore",
    "ArtifactRef", "discover", "open_store", "is_artifact_dir",
    "machines_on_disk", "resolve_cached", "resolve_format",
    "machine_ref", "parse_ref", "is_pack_ref",
    "write_pack", "delta_write", "flatten_model", "to_device",
    "device_put_count", "repack", "unpack", "store_info", "packs_dir",
    "stamp_generation", "read_generation", "gc_generations", "fsck",
]


def is_artifact_dir(path: str) -> bool:
    """True when ``path`` is a v1 per-machine artifact directory."""
    return os.path.exists(os.path.join(path, serializer.MODEL_FILE))


class ArtifactRef:
    """One machine's artifact behind a format-independent handle.

    ``kind`` is ``"pack"`` (a slot of a v2 pack) or ``"dir"`` (a v1
    per-machine directory); ``ref`` is the addressable location (the
    pack ref string, or the directory path).  ``stat()`` returns the
    (mtime, size) reload signal the server's rescan compares.
    """

    def __init__(self, name: str, kind: str, ref: str,
                 store: Optional[PackStore] = None, directory: str = ""):
        self.name = name
        self.kind = kind
        self.ref = ref
        self._store = store
        self._directory = directory

    def load_model(self) -> Any:
        if self.kind == "pack":
            return self._store.load_model(self.name)
        return serializer.load(self._directory)

    def load_metadata(self) -> Dict[str, Any]:
        if self.kind == "pack":
            return self._store.load_metadata(self.name)
        return serializer.load_metadata(self._directory)

    def stat(self) -> Tuple[float, int]:
        if self.kind == "pack":
            return self._store.stat(self.name)
        try:
            st = os.stat(
                os.path.join(self._directory, serializer.MODEL_FILE)
            )
            return st.st_mtime, st.st_size
        except OSError:
            return 0.0, -1


def open_store(path: str, quarantine: bool = False) -> Optional[PackStore]:
    """The :class:`PackStore` for ``path`` (a build output dir, or its
    ``.gordo-packs/`` directly); None when no v2 index exists.  A present
    but corrupt index raises :class:`PackCorruptError` — loudly.
    ``quarantine`` records corrupt PACKS on the store instead of raising
    (the serving path; see :class:`PackStore`)."""
    candidates = [path, packs_dir(path)]
    for directory in candidates:
        if os.path.exists(os.path.join(directory, "index.json")):
            return PackStore(directory, quarantine=quarantine)
    return None


def discover(
    path: str, quarantine: bool = False
) -> Tuple[Optional[PackStore], List[ArtifactRef]]:
    """Every machine artifact under ``path``, both formats unified.

    v2 pack machines come from the index; v1 per-machine dirs fill in
    anything not packed (a mixed output dir — fleet chunks packed,
    non-fleetable singles as dirs — is the normal v2 build result).  A
    machine present in both resolves to its pack entry: the index is
    authoritative, leftovers are stale.  ``path`` may also be a single
    machine's artifact dir (the v1 single-machine serve case).
    ``quarantine`` opens the store in quarantine mode (corrupt packs
    recorded on it, their machines absent from the refs).
    """
    refs: List[ArtifactRef] = []
    store = open_store(path, quarantine=quarantine)
    packed: Set[str] = set()
    if store is not None:
        for name in store.names():
            refs.append(ArtifactRef(name, "pack", machine_ref(path, name),
                                    store=store))
            packed.add(name)
    if os.path.isdir(path):
        if is_artifact_dir(path):
            name = os.path.basename(os.path.normpath(path))
            refs.append(ArtifactRef(name, "dir", path, directory=path))
        else:
            for child in sorted(os.listdir(path)):
                sub = os.path.join(path, child)
                if child not in packed and is_artifact_dir(sub):
                    refs.append(
                        ArtifactRef(child, "dir", sub, directory=sub)
                    )
    return store, refs


def machines_on_disk(path: str) -> Set[str]:
    """Machine names with a live artifact under ``path`` (pack index rows
    plus v1 dirs) — what the warmup-manifest pruning checks rows
    against, so stale (signature, bucket) rows drop when a partial
    rebuild shrinks a bucket."""
    try:
        _, refs = discover(path)
    except PackError:
        logger.exception("machines_on_disk: unreadable pack index in %s", path)
        return set()
    return {r.name for r in refs}


#: memoized stores for registry lookups — ``resolve_cached`` runs once per
#: machine on a cached re-run (10k+ calls), and each open re-validates
#: every pack.  Keyed by packs dir, invalidated on index (mtime, size).
_STORE_CACHE: Dict[str, Tuple[Tuple[float, int], PackStore]] = {}


def _cached_store(directory: str) -> Optional[PackStore]:
    index_path = os.path.join(directory, "index.json")
    try:
        st = os.stat(index_path)
        stamp = (st.st_mtime, st.st_size)
    except OSError:
        _STORE_CACHE.pop(directory, None)
        return None
    hit = _STORE_CACHE.get(directory)
    if hit is not None and hit[0] == stamp:
        return hit[1]
    store = PackStore(directory)
    _STORE_CACHE[directory] = (stamp, store)
    return store


def resolve_cached(ref: str, cache_key: str) -> Optional[str]:
    """Registry-lookup verification for a pack ref: the machine must
    still be in the index, its recorded cache key must match, and its
    pack must validate.  Returns the ref on a hit, None on any miss —
    the same contract ``lookup_cached_artifact`` applies to v1 dirs."""
    try:
        directory, name = parse_ref(ref)
        store = _cached_store(directory)
    except (ValueError, PackError, OSError) as exc:
        logger.warning("pack ref %s failed to resolve: %s", ref, exc)
        return None
    if store is None or name not in store:
        return None
    stored = store.cache_key(name)
    if stored is not None and stored != cache_key:
        logger.warning(
            "pack slot for %s was overwritten by a different build "
            "(stored key %s != %s); treating as cache miss",
            name, stored, cache_key,
        )
        return None
    return ref


# ---------------------------------------------------------------------------
# conversion (both directions — the parity suite round-trips through these)
# ---------------------------------------------------------------------------

def repack(
    output_dir: str,
    max_bucket_size: int = 512,
    keep_dirs: bool = False,
) -> Dict[str, Any]:
    """Convert a v1 output dir to v2 packs in place.

    Machines whose models share a serving-chain signature group into
    (signature, bucket) chunks of at most ``max_bucket_size`` and pack
    together; machines the chain extractor can't fuse stay as v1 dirs
    (the mixed layout every v2 reader handles).  Converted dirs are
    removed unless ``keep_dirs`` — the index is authoritative either
    way.  Returns a summary dict.
    """
    # serve.scorer imports artifacts' sibling modules; import lazily to
    # keep this package import-light
    import jax

    from gordo_tpu.serve.scorer import _extract_chain

    store, refs = discover(output_dir)
    groups: Dict[Any, List[Tuple[str, Any, Dict, Optional[str]]]] = {}
    skipped: List[str] = []
    for ref in refs:
        if ref.kind != "dir":
            continue
        model = ref.load_model()
        metadata = ref.load_metadata()
        chain = _extract_chain(model)
        if chain is None:
            skipped.append(ref.name)
            continue
        sig = (
            type(model).__name__,
            tuple(type(cls).__name__ for cls, _ in chain["scalers"]),
            chain["mode"], chain["lookback"],
            tuple(
                tuple(a.shape) for a in jax.tree.leaves(chain["params"])
            ),
        )
        definition = None
        def_path = os.path.join(ref.ref, serializer.DEFINITION_FILE)
        if os.path.exists(def_path):
            with open(def_path) as fh:
                definition = fh.read()
        groups.setdefault(sig, []).append(
            (ref.name, model, metadata, definition)
        )

    n_packs, packed = 0, []
    for members in groups.values():
        for start in range(0, len(members), max_bucket_size):
            chunk = members[start: start + max_bucket_size]
            names = [m[0] for m in chunk]
            write_pack(
                output_dir,
                names,
                [m[1] for m in chunk],
                [m[2] for m in chunk],
                definition=chunk[0][3],
                cache_keys={
                    m[0]: m[2].get("cache_key")
                    for m in chunk if m[2].get("cache_key")
                },
            )
            n_packs += 1
            packed.extend(names)
    if not keep_dirs:
        for name in packed:
            shutil.rmtree(os.path.join(output_dir, name), ignore_errors=True)
    return {
        "packed": sorted(packed), "packs": n_packs,
        "kept_as_dirs": sorted(skipped),
    }


def unpack(output_dir: str, dest_dir: str) -> List[str]:
    """Export every packed machine back to v1 per-machine dirs under
    ``dest_dir`` (the compatibility direction of the parity contract:
    pack → dirs → load must score bit-identically)."""
    store = open_store(output_dir)
    if store is None:
        raise PackError(f"no pack index under {output_dir}")
    written = []
    for name in store.names():
        serializer.dump(
            store.load_model(name),
            os.path.join(dest_dir, name),
            metadata=store.load_metadata(name) or None,
            definition=store.definition(name),
        )
        written.append(name)
    return written


def store_info(path: str) -> Dict[str, Any]:
    """Human/CLI summary of the artifacts under ``path``."""
    store, refs = discover(path)
    info: Dict[str, Any] = {
        "format": "v2-packs" if store is not None else "v1-dirs",
        "machines": len(refs),
        "dir_machines": sum(1 for r in refs if r.kind == "dir"),
    }
    if store is not None:
        info.update(
            packs=len(store.packs),
            packed_machines=len(store.machines),
            pack_bytes=store.total_bytes(),
            generation=store.generation,
            generations_retained=len(store.generations),
        )
    return info
