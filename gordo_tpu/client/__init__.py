"""Prediction client & forwarding.

Reference equivalent: ``gordo_components/client/`` — the bulk-scoring
client (``Client.predict``) that discovers machine endpoints, fetches raw
sensor data itself, POSTs chunks concurrently, and optionally forwards
prediction frames to a sink.
"""

from gordo_tpu.client.client import Client, PredictionResult  # noqa: F401
from gordo_tpu.client.forwarders import (  # noqa: F401
    ForwardPredictionsIntoInflux,
    ForwardPredictionsToDisk,
    PredictionForwarder,
)
