"""Prediction forwarders — sinks for scored frames.

Reference equivalent: ``gordo_components/client/forwarders.py`` —
``PredictionForwarder`` contract + ``ForwardPredictionsIntoInflux`` (batch
writes of prediction/anomaly frames into InfluxDB measurements).

The Influx forwarder is import-gated (no influxdb client in this image);
``ForwardPredictionsToDisk`` is the always-available sink (parquet/CSV per
machine), which doubles as the test backend.
"""

from __future__ import annotations

import abc
import logging
import os
from typing import Optional

import pandas as pd

logger = logging.getLogger(__name__)


class PredictionForwarder(abc.ABC):
    """Callable sink: ``forward(predictions, machine_name, metadata)``."""

    @abc.abstractmethod
    def forward(
        self,
        predictions: pd.DataFrame,
        machine_name: str,
        metadata: Optional[dict] = None,
    ) -> None:
        ...

    def __call__(self, predictions, machine_name, metadata=None):
        return self.forward(predictions, machine_name, metadata)


class ForwardPredictionsToDisk(PredictionForwarder):
    """Append scored frames under ``{base_dir}/{machine}/`` as parquet (or
    CSV when parquet engines are unavailable)."""

    def __init__(self, base_dir: str, fmt: str = "parquet"):
        self.base_dir = base_dir
        self.fmt = fmt

    def forward(self, predictions, machine_name, metadata=None):
        dest = os.path.join(self.base_dir, machine_name)
        os.makedirs(dest, exist_ok=True)
        start = predictions.index[0] if len(predictions) else "empty"
        stamp = str(start).replace(":", "-").replace(" ", "T")
        path = os.path.join(dest, f"predictions-{stamp}.{self.fmt}")
        if self.fmt == "parquet":
            try:
                predictions.to_parquet(path)
                return
            except ImportError:  # no parquet engine — fall through to CSV
                path = path[: -len("parquet")] + "csv"
        predictions.to_csv(path)


class ForwardPredictionsIntoInflux(PredictionForwarder):
    """Write prediction/anomaly frames into InfluxDB measurements in
    batches (reference parity).  Requires the ``influxdb`` client package,
    which is not baked into this image — construction raises a clear
    ImportError when absent."""

    def __init__(
        self,
        destination_influx_uri: Optional[str] = None,
        destination_influx_api_key: Optional[str] = None,
        destination_influx_recreate: bool = False,
        n_retries: int = 5,
    ):
        try:
            import influxdb  # noqa: F401
        except ImportError as exc:
            raise ImportError(
                "ForwardPredictionsIntoInflux requires the 'influxdb' package, "
                "which is not installed in this environment. Use "
                "ForwardPredictionsToDisk or a custom PredictionForwarder."
            ) from exc
        from influxdb import DataFrameClient

        self.n_retries = n_retries
        uri = destination_influx_uri or ""
        # uri format (reference): <host>:<port>/<user>:<password>/<dbname>
        parts = uri.split("/")
        if len(parts) != 3 or ":" not in parts[0] or ":" not in parts[1]:
            raise ValueError(
                "destination_influx_uri must look like "
                f"'<host>:<port>/<user>:<password>/<dbname>', got {uri!r}"
            )
        host_port, user_pass, dbname = parts
        host, port = host_port.rsplit(":", 1)   # IPv6-safe
        user, password = user_pass.split(":", 1)  # ':' allowed in password
        self.client = DataFrameClient(
            host=host,
            port=int(port),
            username=user,
            password=password,
            database=dbname,
            headers=(
                {"Authorization": destination_influx_api_key}
                if destination_influx_api_key
                else None
            ),
        )
        if destination_influx_recreate:
            self.client.drop_database(dbname)
            self.client.create_database(dbname)

    def forward(self, predictions, machine_name, metadata=None):
        # Flatten multi-level columns into per-measurement frames:
        # top level (model-output / tag-anomaly-scores / ...) = measurement.
        for top in predictions.columns.get_level_values(0).unique():
            sub = predictions[top]
            if isinstance(sub, pd.Series):
                sub = sub.to_frame(name=top)
            sub = sub.copy()
            sub.columns = [str(c) if str(c) else top for c in sub.columns]
            for attempt in range(self.n_retries):
                try:
                    self.client.write_points(
                        sub,
                        measurement=str(top),
                        tags={"machine": machine_name},
                        batch_size=10_000,
                    )
                    break
                except Exception:
                    if attempt == self.n_retries - 1:
                        raise
                    logger.warning(
                        "Influx write retry %d for %s/%s",
                        attempt + 1, machine_name, top,
                    )
