"""Bulk-scoring prediction client.

Reference equivalent: ``gordo_components/client/client.py`` — ``Client``:
discovers a project's machine endpoints, **fetches the raw sensor data
itself** (dataset layer, using each machine's recorded dataset config),
splits the time range into chunks, POSTs them concurrently under an asyncio
semaphore with retry/revival, returns per-machine ``PredictionResult``s and
optionally forwards frames to a sink.

TPU-era differences: endpoints are discovered from the ML server's project
index route (one server hosts many machines) rather than a watchman k8s
query, and responses come from the fused jitted scorer — the wire contract
is unchanged.
"""

from __future__ import annotations

import asyncio
import functools
import logging
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import aiohttp
import numpy as np
import pandas as pd

from gordo_tpu import faults, telemetry
from gordo_tpu.client.forwarders import PredictionForwarder
from gordo_tpu.client.io import (
    HttpUnprocessableEntity,
    bulk_rows_budget,
    get_json,
    post_bulk,
    post_json,
)
from gordo_tpu.dataset.data_provider.base import GordoBaseDataProvider
from gordo_tpu.dataset.datasets import dataset_from_metadata

logger = logging.getLogger(__name__)

API_PREFIX = "/gordo/v0"

_FAILOVER_TOTAL = telemetry.counter(
    "gordo_client_failover_total",
    "Bulk sub-requests retried against an alternate replica, by outcome "
    "(attempt | recovered | exhausted)",
    labels=("outcome",),
)
_HEDGES_TOTAL = telemetry.counter(
    "gordo_client_hedges_total",
    "Tail sub-requests hedged to an alternate replica",
)


def _check_scatter_fault(base: str) -> None:
    """``replica.scatter`` injection seam: mode ``dead`` makes a replica
    look SIGKILLed from this client's seat (connection refused), driving
    the real failover path in ``post_shard``."""
    if not faults.enabled():
        return
    try:
        faults.check("replica.scatter", replica=base)
    except faults.InjectedFault as exc:
        if exc.mode == "dead":
            raise aiohttp.ClientConnectionError(
                f"replica {base} is dead: {exc}"
            ) from None
        raise


#: response-key classes — frame building and the frame-free arrays path
#: dispatch on NAME, never shape: a 1-D per-tag constant is
#: indistinguishable from a per-row series whenever a chunk's row count
#: happens to equal the tag count, so shape-sniffing is only a fallback
#: for keys this schema doesn't know
PER_TAG_CONSTANT = {"tag-anomaly-thresholds"}
PER_ROW_SERIES = {"total-anomaly-score", "anomaly-confidence"}
SCALAR = {"total-anomaly-threshold"}


class LazyFrame:
    """Deferred view over one machine's bulk response chunks.

    The bulk path stores each round's decoded response dict AS IS —
    zero-copy block views when the columnar wire answered — and builds
    the reference MultiIndex frame only on first :attr:`frame` access
    (then caches it).  :meth:`column` hands back the raw concatenated
    arrays for one response key without ever constructing a frame:
    BENCH_r18 measured eager per-chunk frame construction at ~35x the
    transport cost of the bulk path, so consumers that only need the
    arrays should never pay it.
    """

    __slots__ = ("_tags", "_chunks", "_frame")

    def __init__(self, tags: Sequence[str]):
        self._tags = [str(t) for t in tags]
        #: (round index, decoded response dict, locally-attached index)
        self._chunks: List[Tuple[int, Dict[str, Any], pd.Index]] = []
        self._frame: Optional[pd.DataFrame] = None

    def add_chunk(
        self, round_idx: int, data: Dict[str, Any], index: pd.Index
    ) -> None:
        self._chunks.append((round_idx, data, index))
        self._frame = None

    def __len__(self) -> int:
        return len(self._chunks)

    def _ordered(self) -> List[Tuple[int, Dict[str, Any], pd.Index]]:
        # deterministic row order regardless of round COMPLETION order
        return sorted(self._chunks, key=lambda c: c[0])

    def column(self, key: str) -> Any:
        """The response key's values concatenated across chunks in round
        order — raw arrays (or a python float for scalar keys), no frame."""
        parts = [np.asarray(data[key]) for _, data, _ in self._ordered()]
        if not parts:
            raise KeyError(key)
        if parts[0].ndim == 0:  # per-machine scalar (e.g. agg threshold)
            return float(parts[0])
        return parts[0] if len(parts) == 1 else np.concatenate(parts)

    @property
    def frame(self) -> pd.DataFrame:
        """The reference MultiIndex-column frame (``score_history``
        column parity), materialized on first access and cached."""
        if self._frame is None:
            self._frame = pd.concat(
                [
                    _frame_from_payload(data, self._tags, index)
                    for _, data, index in self._ordered()
                ]
            ).sort_index()
        return self._frame


class PredictionResult:
    """Per-machine outcome (reference: ``client/utils.py::PredictionResult``).

    ``predictions`` stays reference-compatible — a MultiIndex-column
    frame, or None.  When the bulk path handed back a :class:`LazyFrame`
    the frame is materialized on FIRST ``predictions`` access and
    cached; the consume-the-arrays path (:attr:`raw` / :meth:`arrays`)
    reads the decoded response arrays directly and never builds one.
    """

    __slots__ = ("name", "error_messages", "_predictions")

    def __init__(
        self,
        name: str,
        predictions: Any = None,
        error_messages: Optional[List[str]] = None,
    ):
        self.name = name
        self._predictions = predictions
        self.error_messages: List[str] = (
            list(error_messages) if error_messages is not None else []
        )

    @property
    def ok(self) -> bool:
        return not self.error_messages

    @property
    def raw(self) -> Optional[LazyFrame]:
        """The lazy chunk view when this result came off the bulk path,
        else None — access never materializes a frame."""
        if isinstance(self._predictions, LazyFrame):
            return self._predictions
        return None

    def arrays(self, key: str) -> Any:
        """Raw concatenated values for one response key (frame-free on
        the bulk path; sliced out of the frame otherwise)."""
        lazy = self.raw
        if lazy is not None:
            return lazy.column(key)
        if self._predictions is None:
            raise KeyError(f"no predictions for machine {self.name!r}")
        values = self._predictions[key].to_numpy()
        if key in PER_ROW_SERIES and values.ndim == 2 and values.shape[1] == 1:
            return values[:, 0]
        if key in SCALAR:
            return float(values.ravel()[0])
        return values

    @property
    def predictions(self) -> Optional[pd.DataFrame]:
        if isinstance(self._predictions, LazyFrame):
            return self._predictions.frame
        return self._predictions

    def __repr__(self) -> str:  # pragma: no cover - debugging nicety
        kind = (
            "lazy"
            if isinstance(self._predictions, LazyFrame)
            else type(self._predictions).__name__
        )
        return (
            f"PredictionResult(name={self.name!r}, predictions={kind}, "
            f"errors={len(self.error_messages)})"
        )


def _frame_from_payload(
    data: Dict[str, Any], tags: List[str], index: pd.Index
) -> pd.DataFrame:
    """Response ``data`` dict → MultiIndex-column frame aligned to ``index``.

    Mirrors the column layout of ``DiffBasedAnomalyDetector.anomaly`` /
    ``make_base_dataframe`` so forwarders and user code see one schema
    whether frames came from a local model or over HTTP.
    """
    n = None
    for key in ("model-output", "total-anomaly-score"):
        if key in data:
            n = len(data[key])
            break
    if n is None:
        raise ValueError(f"Response has no recognised outputs: {sorted(data)}")
    # Server-returned time info wins over the locally-reattached index
    # (reference parity: responses carry per-row start/end when the request
    # rode with timestamps).
    data = dict(data)
    start = data.pop("start", None)
    end = data.pop("end", None)
    if start is not None and len(start) == n:
        idx = pd.DatetimeIndex(pd.to_datetime(start, utc=True), name="start")
    else:
        idx = index[-n:] if len(index) >= n else pd.RangeIndex(n)

    # Known response keys dispatch on NAME, never shape — see the
    # module-level PER_TAG_CONSTANT / PER_ROW_SERIES / SCALAR classes.
    columns: Dict[Tuple[str, str], Any] = {}

    def tag_names(width: int) -> List[str]:
        return (
            [str(t) for t in tags]
            if width == len(tags)
            else [str(i) for i in range(width)]
        )

    for key, value in data.items():
        arr = np.asarray(value)
        if key in SCALAR or (key not in PER_TAG_CONSTANT and arr.ndim == 0):
            columns[(key, "")] = np.full(n, float(arr))
        elif key in PER_TAG_CONSTANT and arr.ndim == 1:
            for j, tag in enumerate(tag_names(arr.shape[0])):
                columns[(key, tag)] = np.full(n, arr[j])
        elif key in PER_ROW_SERIES and arr.ndim == 1:
            columns[(key, "")] = arr
        elif arr.ndim == 2 and arr.shape[0] == n:
            for j, tag in enumerate(tag_names(arr.shape[1])):
                columns[(key, tag)] = arr[:, j]
        elif arr.ndim == 1 and arr.shape[0] == n:  # unknown key: per-row guess
            columns[(key, "")] = arr
        elif arr.ndim == 1:  # unknown key, wrong length: per-tag constant guess
            for j, tag in enumerate(tag_names(arr.shape[0])):
                columns[(key, tag)] = np.full(n, arr[j])
    frame = pd.DataFrame(columns, index=idx)
    if end is not None and len(end) == n:
        frame[("end", "")] = pd.to_datetime(end, utc=True)
    frame.columns = pd.MultiIndex.from_tuples(frame.columns)
    return frame


class Client:
    """Score a project's machines over a time range.

    Parameters (reference-compatible where meaningful):

    - ``project``: project name (URL path segment).
    - ``host``/``port``/``scheme`` or ``base_url``: where the ML server runs.
    - ``batch_size``: max rows per POST (reference default 1000).
    - ``parallelism``: concurrent in-flight requests (semaphore bound).
    - ``forward_resampled_sensors``: unsupported reference flag, accepted
      and ignored for config compatibility.
    - ``data_provider``: override the provider recorded in each machine's
      metadata (the reference requires this for providers needing creds).
    - ``prediction_forwarder``: ``PredictionForwarder`` sink for scored
      frames.
    """

    def __init__(
        self,
        project: str,
        host: str = "localhost",
        port: int = 5555,
        scheme: str = "http",
        base_url: Optional[str] = None,
        metadata: Optional[dict] = None,
        data_provider: Optional[GordoBaseDataProvider] = None,
        prediction_forwarder: Optional[PredictionForwarder] = None,
        batch_size: int = 1000,
        parallelism: int = 10,
        forward_resampled_sensors: bool = False,
        n_retries: int = 3,
        use_anomaly: bool = True,
        use_bulk: bool = False,
        use_msgpack: bool = True,
        use_columnar: bool = True,
        watchman_url: Optional[str] = None,
        timeout: float = 120.0,
        replica_urls: Optional[Sequence[str]] = None,
        deadline_s: Optional[float] = None,
        hedge_after_p99: Optional[Any] = None,
    ):
        self.project = project
        #: fleet-sharded serving tier: base URLs ordered by shard index
        #: (url i serves shard i/N).  The client computes the SAME
        #: deterministic shard partition the servers loaded with
        #: (gordo_tpu.serve.shard), so every single-machine request goes
        #: straight to its owning replica — no lookup hop, no redirect —
        #: and bulk rounds scatter per shard and gather back in machine
        #: order.  None/1-element = the unsharded single-server behavior.
        self.replica_urls = list(replica_urls) if replica_urls else None
        self._router = None  # built lazily from the fleet machine list
        if base_url is None and self.replica_urls:
            base_url = self.replica_urls[0]
        self.base_url = base_url or f"{scheme}://{host}:{port}"
        self.metadata = metadata or {}
        self.data_provider = data_provider
        self.prediction_forwarder = prediction_forwarder
        self.batch_size = int(batch_size)
        self.parallelism = int(parallelism)
        self.n_retries = int(n_retries)
        self.use_anomaly = use_anomaly
        self.use_bulk = use_bulk
        #: bulk requests/responses ride msgpack (raw array buffers) instead
        #: of JSON — ~100x codec rate against the bundled server.  Set False
        #: when bulk-scoring against a server without msgpack support.
        self.use_msgpack = use_msgpack
        #: bulk responses negotiate the GSB1 columnar wire on top of
        #: msgpack (``Accept: application/x-gordo-columnar,
        #: application/x-msgpack``): stacked results arrive as contiguous
        #: blocks decoded into zero-copy views and frames materialize
        #: lazily.  Safe against old servers — they simply answer the
        #: msgpack fallback in the same header.  Set False to pin plain
        #: msgpack (parity tooling, wire comparisons).
        self.use_columnar = use_columnar
        self.watchman_url = watchman_url
        self.timeout = timeout
        #: end-to-end budget for one predict() call, retries included:
        #: each request restamps the remaining millis into the
        #: X-Gordo-Deadline-Ms header, so the server (and its coalescer)
        #: drops work this client has already given up on
        self.deadline_s = float(deadline_s) if deadline_s else None
        #: tail hedging: after this many seconds (a float), or after the
        #: client's own observed p99 sub-request latency (``True``), a
        #: still-running bulk sub-request is duplicated against an
        #: alternate replica and the first success wins
        self.hedge_after_p99 = hedge_after_p99
        #: recent successful sub-request latencies (seconds) backing the
        #: ``hedge_after_p99=True`` threshold
        self._latencies: List[float] = []
        #: replica base urls watchman currently marks ``down`` — skipped
        #: as first-choice routes and as failover candidates
        self._down_bases: set = set()

    # -- URLs ----------------------------------------------------------------
    def _project_url(self, base: Optional[str] = None) -> str:
        return f"{base or self.base_url}{API_PREFIX}/{self.project}/"

    def _machine_url(self, machine: str) -> str:
        base = self.base_url
        if self._router is not None:
            try:
                base = self._router.url_for(machine)
            except KeyError:
                pass  # unknown to the fleet list: let the server answer
        return f"{base}{API_PREFIX}/{self.project}/{machine}"

    def _note_down_targets(self, body: Dict[str, Any]) -> None:
        """Record which replica bases watchman marks ``down`` (failed
        ``GORDO_WATCHMAN_EVICT_AFTER`` consecutive scrapes): they stop
        being first-choice routes and failover candidates."""
        self._down_bases = {
            base for base, entry in (body.get("targets") or {}).items()
            if entry.get("down")
        }

    def _note_latency(self, seconds: float) -> None:
        """Record a successful sub-request latency for the tracked-p99
        hedge threshold (bounded window — last 512 samples)."""
        self._latencies.append(seconds)
        if len(self._latencies) > 512:
            del self._latencies[: len(self._latencies) - 512]

    def _hedge_delay(self) -> Optional[float]:
        """Seconds to wait before duplicating a sub-request, or None when
        hedging is off / can't be computed yet.  A float configures a
        fixed threshold; ``True`` tracks the client's own p99 over recent
        successful sub-requests (needs >= 20 samples to engage)."""
        if not self.hedge_after_p99:
            return None
        if self.hedge_after_p99 is not True:
            return float(self.hedge_after_p99)
        if len(self._latencies) < 20:
            return None
        ordered = sorted(self._latencies)
        return ordered[min(len(ordered) - 1, int(len(ordered) * 0.99))]

    async def _post_with_hedge(
        self, do_post, base: str, alternates: List[str]
    ) -> Dict[str, Any]:
        """POST to ``base``; when the hedge threshold elapses first, race
        a duplicate against the next alternate and take the first
        success.  Both failing re-raises the primary's error."""
        delay = self._hedge_delay()
        alternates = [a for a in alternates if a not in self._down_bases]
        if delay is None or not alternates:
            return await do_post(base)
        primary = asyncio.ensure_future(do_post(base))
        try:
            return await asyncio.wait_for(asyncio.shield(primary), delay)
        except asyncio.TimeoutError:
            pass  # threshold hit with the primary still running: hedge
        except Exception:
            primary.cancel()
            raise
        _HEDGES_TOTAL.inc()
        hedge = asyncio.ensure_future(do_post(alternates[0]))
        pending = {primary, hedge}
        try:
            while pending:
                done, pending = await asyncio.wait(
                    pending, return_when=asyncio.FIRST_COMPLETED
                )
                for task in done:
                    if task.exception() is None:
                        return task.result()
            raise primary.exception()  # both failed: surface the primary's
        finally:
            for task in (primary, hedge):
                if not task.done():
                    task.cancel()

    @staticmethod
    def _replicas_from_topology(
        topology: Dict[str, Dict[str, Any]], down: set
    ) -> Optional[List[str]]:
        """Order watchman's target roster into a replica-urls list.

        Sharded targets order by shard index — and a down target is only
        excluded when another target covers its shard, because the shard
        TABLE is positional and a hole would shift every later machine.
        Unsharded tiers (every replica serves the full fleet) simply drop
        down targets."""
        sharded = {
            b: e for b, e in topology.items() if "shard-index" in e
        }
        if sharded:
            count = max(
                int(e.get("shard-count", 1)) for e in sharded.values()
            )
            by_idx: Dict[int, str] = {}
            for base, e in sorted(sharded.items()):
                idx = int(e["shard-index"])
                if idx not in by_idx or by_idx[idx] in down:
                    by_idx[idx] = base
            if set(by_idx) == set(range(count)) and count >= 2:
                return [by_idx[i] for i in range(count)]
            return None
        bases = sorted(b for b in topology if b not in down)
        return bases if len(bases) >= 2 else None

    async def _ensure_router(self, session: aiohttp.ClientSession):
        """Build the shard router once per client: the table derives from
        the FULL fleet machine list (watchman's endpoint roster, or a
        replica's reported ``fleet-machines``), never from a request's
        machine subset — the partition is defined over the whole fleet."""
        if self._router is not None:
            return self._router
        body: Optional[Dict[str, Any]] = None
        if self.watchman_url:
            body = await get_json(
                session, self.watchman_url.rstrip("/") + "/",
                retries=self.n_retries, timeout=self.timeout,
            )
            self._note_down_targets(body)
            if self.replica_urls is None:
                # bootstrap the replica roster from watchman's serve
                # topology; targets marked down are excluded (unsharded)
                # or replaced per shard slot when coverage allows
                bootstrapped = self._replicas_from_topology(
                    body.get("serve-topology") or {}, self._down_bases
                )
                if bootstrapped:
                    self.replica_urls = bootstrapped
        if self.replica_urls is None or len(self.replica_urls) < 2:
            return None
        from gordo_tpu.serve.shard import ShardRouter

        fleet: List[str] = []
        if body is not None:
            # ALL endpoints, healthy or not: an unhealthy machine still
            # owns its shard slot, and dropping it would shift every
            # machine after it onto the wrong replica
            fleet = [
                ep["target-name"] for ep in body.get("endpoints", [])
                if ep.get("target-name")
            ]
        if not fleet:
            # ask the replicas: each reports the full fleet list when
            # sharded; union of served machines covers the unsharded case
            last_exc: Optional[Exception] = None
            served: List[str] = []
            for base in self.replica_urls:
                try:
                    body = await get_json(
                        session, self._project_url(base),
                        retries=self.n_retries, timeout=self.timeout,
                    )
                except Exception as exc:
                    last_exc = exc
                    continue
                if body.get("fleet-machines"):
                    fleet = list(body["fleet-machines"])
                    break
                for name in body.get("machines", []):
                    if name not in served:
                        served.append(name)
            if not fleet:
                fleet = served
            if not fleet:
                raise RuntimeError(
                    "could not discover the fleet machine list from any "
                    f"replica of {self.replica_urls}"
                ) from last_exc
        self._router = ShardRouter(fleet, self.replica_urls)
        return self._router

    # -- discovery / metadata ------------------------------------------------
    async def machine_names_async(self, session: aiohttp.ClientSession) -> List[str]:
        """Discover machines: from the watchman status document when
        ``watchman_url`` is configured (reference behavior — only healthy
        endpoints are scored), else from the ML server's project index."""
        if self.watchman_url:
            body = await get_json(
                session, self.watchman_url.rstrip("/") + "/",
                retries=self.n_retries, timeout=self.timeout,
            )
            names = []
            for ep in body.get("endpoints", []):
                if ep.get("healthy"):
                    names.append(ep["target-name"])
                else:
                    logger.warning(
                        "Skipping unhealthy endpoint %s", ep.get("target-name")
                    )
            return names
        if self.replica_urls and len(self.replica_urls) > 1:
            # sharded tier: each replica serves (and lists) its shard;
            # the project's machine roster is their union
            names: List[str] = []
            for base in self.replica_urls:
                body = await get_json(
                    session, self._project_url(base),
                    retries=self.n_retries, timeout=self.timeout,
                )
                for name in body.get("machines", []):
                    if name not in names:
                        names.append(name)
            return names
        body = await get_json(
            session, self._project_url(), retries=self.n_retries, timeout=self.timeout
        )
        return list(body.get("machines", []))

    async def artifact_info_async(
        self, session: aiohttp.ClientSession
    ) -> Dict[str, Any]:
        """What backs the server's collection — ``artifact-format``
        (``v2-packs`` | ``v1-dirs``) plus pack count/bytes when packed.
        Lets operators confirm a rollout actually serves from the new
        pack format without shelling into the pod."""
        body = await get_json(
            session, self._project_url(), retries=self.n_retries,
            timeout=self.timeout,
        )
        return {
            k: v for k, v in body.items()
            if k.startswith("artifact-") or k == "fleet-generation"
        }

    def artifact_info(self) -> Dict[str, Any]:
        return _run(self._with_session(self.artifact_info_async))

    async def fleet_generation_async(
        self, session: aiohttp.ClientSession
    ) -> Dict[str, int]:
        """The artifact generation each replica currently serves, keyed
        by replica base URL (one entry against an unsharded server)."""
        bases = (
            self.replica_urls
            if self.replica_urls and len(self.replica_urls) > 1
            else [self.base_url]
        )
        out: Dict[str, int] = {}
        for base in bases:
            body = await get_json(
                session, self._project_url(base),
                retries=self.n_retries, timeout=self.timeout,
            )
            out[base] = int(body.get("fleet-generation", 0))
        return out

    async def wait_for_generation_async(
        self,
        session: aiohttp.ClientSession,
        generation: int,
        timeout: float = 120.0,
        poll_interval: float = 0.5,
    ) -> Dict[str, int]:
        """Block until EVERY replica reports ``fleet-generation >=
        generation`` — the rollout handshake after a build publishes a
        new artifact generation: stamp, then wait here before flipping
        traffic expectations.  Replicas that error mid-poll (rolling
        restarts) are retried until the deadline.  Returns the final
        per-replica generation map; raises :class:`TimeoutError` when
        the deadline passes first."""
        deadline = time.monotonic() + float(timeout)
        last: Dict[str, int] = {}
        while True:
            try:
                last = await self.fleet_generation_async(session)
            except Exception as exc:
                logger.debug("generation poll failed: %s", exc)
            if last and all(
                g >= int(generation) for g in last.values()
            ):
                return last
            if time.monotonic() >= deadline:
                raise TimeoutError(
                    f"fleet did not reach generation {generation} within "
                    f"{timeout}s (last seen: {last or 'unreachable'})"
                )
            await asyncio.sleep(poll_interval)

    def wait_for_generation(
        self,
        generation: int,
        timeout: float = 120.0,
        poll_interval: float = 0.5,
    ) -> Dict[str, int]:
        return _run(self._with_session(
            self.wait_for_generation_async, generation, timeout,
            poll_interval,
        ))

    def fleet_generation(self) -> Dict[str, int]:
        return _run(self._with_session(self.fleet_generation_async))

    async def fleet_health_async(
        self, session: aiohttp.ClientSession, top: Optional[int] = None
    ) -> Dict[str, Any]:
        """The fleet-health document: per-machine live score sketches,
        build-time baselines, drift scores and statuses.

        Against a sharded tier (``replica_urls``) every replica's doc is
        fetched and merged client-side (sketches merge exactly), so the
        caller sees ONE fleet view identical to what an unsharded server
        would report.  ``top`` bounds the drift ranking."""
        from gordo_tpu import telemetry

        bases = (
            self.replica_urls
            if self.replica_urls and len(self.replica_urls) > 1
            else [self.base_url]
        )
        query = f"?top={int(top)}" if top is not None else ""
        docs: List[Dict[str, Any]] = []
        for base in bases:
            docs.append(await get_json(
                session,
                f"{self._project_url(base)}fleet-health{query}",
                retries=self.n_retries, timeout=self.timeout,
            ))
        if len(docs) == 1:
            return docs[0]
        merged = telemetry.merge_health_docs(docs, top=top)
        merged["project-name"] = self.project
        merged["instances"] = list(bases)
        return merged

    def fleet_health(self, top: Optional[int] = None) -> Dict[str, Any]:
        return _run(self._with_session(self.fleet_health_async, top))

    async def score_summary_async(
        self,
        session: aiohttp.ClientSession,
        machines: Optional[Sequence[str]] = None,
        start: Any = None,
        end: Any = None,
        stats: Optional[Sequence[str]] = None,
        period: Any = None,
        threshold: Optional[float] = None,
    ) -> Dict[str, Any]:
        """Per-machine, per-period score summaries from the server's
        ``GET /scores/aggregate`` pushdown — the dashboard-query
        counterpart of :meth:`score_history`: instead of shipping every
        archived sample and aggregating client-side, the server scans
        its mmap archive columns and returns kilobytes of summaries
        (count / mean / max / threshold exceedance / half-octave sketch
        percentiles like ``p99``), riding the GSB1 columnar wire when
        the server speaks it (one contiguous block per stat; old
        servers answer the msgpack fallback in the same Accept header).

        Returns the server document: ``machines``, ``periods`` (UTC
        period starts), and ``data[machine][stat]`` — one value per
        period.  All parameters optional; server defaults are the full
        roster, the archive plan's span, the standard stat set, ``1d``
        periods and threshold 1.0."""
        from urllib.parse import urlencode

        from gordo_tpu.serve import codec

        params = {}
        if machines:
            params["machines"] = ",".join(machines)
        if start is not None:
            params["start"] = str(start)
        if end is not None:
            params["end"] = str(end)
        if stats:
            params["stats"] = ",".join(stats)
        if period is not None:
            params["period"] = str(period)
        if threshold is not None:
            params["threshold"] = repr(float(threshold))
        query = f"?{urlencode(params)}" if params else ""
        accept = (
            f"{codec.COLUMNAR_CONTENT_TYPE}, {codec.MSGPACK_CONTENT_TYPE}"
            if self.use_columnar
            else codec.MSGPACK_CONTENT_TYPE
        )
        return await get_json(
            session,
            f"{self._project_url()}scores/aggregate{query}",
            retries=self.n_retries,
            timeout=self.timeout,
            headers={"Accept": accept},
        )

    def score_summary(
        self,
        machines: Optional[Sequence[str]] = None,
        start: Any = None,
        end: Any = None,
        stats: Optional[Sequence[str]] = None,
        period: Any = None,
        threshold: Optional[float] = None,
    ) -> Dict[str, Any]:
        return _run(self._with_session(
            self.score_summary_async, machines, start, end, stats,
            period, threshold,
        ))

    async def machine_metadata_async(
        self, session: aiohttp.ClientSession, machine: str
    ) -> Dict[str, Any]:
        body = await get_json(
            session,
            f"{self._machine_url(machine)}/metadata",
            retries=self.n_retries,
            timeout=self.timeout,
        )
        return body.get("metadata", {})

    def machine_names(self) -> List[str]:
        return _run(self._with_session(self.machine_names_async))

    def machine_metadata(self, machine: str) -> Dict[str, Any]:
        return _run(
            self._with_session(self.machine_metadata_async, machine)
        )

    async def download_model_async(
        self, session: aiohttp.ClientSession, machine: str
    ) -> Any:
        from gordo_tpu import serializer

        async with session.get(
            f"{self._machine_url(machine)}/download-model",
            timeout=aiohttp.ClientTimeout(total=self.timeout),
        ) as resp:
            resp.raise_for_status()
            return serializer.loads(await resp.read())

    def download_model(self, machine: str) -> Any:
        return _run(self._with_session(self.download_model_async, machine))

    # -- scoring -------------------------------------------------------------
    def predict(
        self,
        start: Any,
        end: Any,
        machine_names: Optional[Sequence[str]] = None,
    ) -> List[PredictionResult]:
        """Fetch data for ``[start, end]``, score every machine, return one
        ``PredictionResult`` per machine (reference: ``Client.predict``)."""
        return _run(self.predict_async(start, end, machine_names))

    async def predict_async(
        self,
        start: Any,
        end: Any,
        machine_names: Optional[Sequence[str]] = None,
    ) -> List[PredictionResult]:
        sem = asyncio.Semaphore(self.parallelism)
        async with aiohttp.ClientSession() as session:
            await self._ensure_router(session)
            names = (
                list(machine_names)
                if machine_names
                else await self.machine_names_async(session)
            )
            if self.use_bulk and not self.use_anomaly:
                logger.warning(
                    "use_bulk=True requires use_anomaly=True (the bulk route "
                    "is anomaly-only); falling back to per-machine requests"
                )
            if self.use_bulk and self.use_anomaly:
                return await self._predict_bulk(session, sem, names, start, end)
            tasks = [
                self._predict_machine(session, sem, name, start, end)
                for name in names
            ]
            return list(await asyncio.gather(*tasks))

    async def _predict_bulk(
        self,
        session: aiohttp.ClientSession,
        sem: asyncio.Semaphore,
        names: List[str],
        start: Any,
        end: Any,
    ) -> List[PredictionResult]:
        """Score via the server's stacked bulk route: the i-th request
        carries every machine's i-th chunk, so the server dispatches one
        vmapped program per chunk instead of ``machines x chunks`` singles."""
        loop = asyncio.get_running_loop()
        deadline = (
            time.monotonic() + self.deadline_s if self.deadline_s else None
        )

        async def fetch(name: str):
            meta = await self.machine_metadata_async(session, name)
            X = await loop.run_in_executor(
                None, self._fetch_data, meta.get("dataset", {}), start, end
            )
            return name, meta, X

        data: Dict[str, pd.DataFrame] = {}
        metas: Dict[str, Dict] = {}
        errors: Dict[str, List[str]] = {name: [] for name in names}
        fetched = await asyncio.gather(
            *(fetch(n) for n in names), return_exceptions=True
        )
        for name, res in zip(names, fetched):
            if isinstance(res, BaseException):
                logger.error("Data fetch failed for %s: %s", name, res)
                errors[name].append(f"data: {res}")
            else:
                data[res[0]], metas[res[0]] = res[2], res[1]

        # a bulk round spans every machine, so its payload is rows x
        # SUM(machine columns): over a long time range the row slice
        # shrinks to the max-samples budget (keeps codec memory bounded
        # — GORDO_CLIENT_MAX_BULK_SAMPLES), never beyond batch_size
        rows_per_round = bulk_rows_budget(
            sum(X.shape[1] for X in data.values()), self.batch_size
        )
        n_chunks = {
            name: -(-len(X) // rows_per_round) for name, X in data.items()
        }
        # raw decoded chunks land in a LazyFrame per machine: zero-copy
        # columnar views (or msgpack arrays) held as-is, the MultiIndex
        # frame built only if the consumer actually asks for one
        lazies: Dict[str, LazyFrame] = {
            name: LazyFrame([str(c) for c in X.columns])
            for name, X in data.items()
        }

        async def score_round(idx: int):
            payload_X = {}
            payload_index: Dict[str, List[str]] = {}
            chunk_index: Dict[str, pd.Index] = {}
            # machines typically share one fetch window, so their chunk
            # indices are equal — serialize the ISO list ONCE per round,
            # not once per machine (at fleet width the per-machine loop
            # was the client's single hottest line)
            iso_index: Optional[pd.DatetimeIndex] = None
            iso_list: Optional[List[str]] = None
            for name, X in data.items():
                if idx < n_chunks[name]:
                    chunk = X.iloc[idx * rows_per_round : (idx + 1) * rows_per_round]
                    arr = chunk.to_numpy(np.float32)
                    payload_X[name] = arr if self.use_msgpack else arr.tolist()
                    chunk_index[name] = chunk.index
                    if isinstance(chunk.index, pd.DatetimeIndex):
                        if iso_index is None or not chunk.index.equals(
                            iso_index
                        ):
                            iso_index = chunk.index
                            iso_list = [
                                t.isoformat() for t in chunk.index
                            ]
                        payload_index[name] = iso_list
            if not payload_X:
                return
            # scatter: one sub-request per owning replica, computed with
            # the shared shard function (unsharded degenerates to one).
            # Machines outside the fleet list fall to the default base —
            # the server reports them unknown in-slot, same as before.
            plan: Dict[str, List[str]] = {}
            for name in payload_X:
                base = self.base_url
                if self._router is not None:
                    try:
                        base = self._router.url_for(name)
                    except KeyError:
                        pass
                plan.setdefault(base, []).append(name)
            poster = (
                functools.partial(post_bulk, columnar=self.use_columnar)
                if self.use_msgpack
                else post_json
            )

            async def post_shard(
                base: str, members: List[str]
            ) -> Dict[str, Any]:
                payload: Dict[str, Any] = {
                    "X": {m: payload_X[m] for m in members}
                }
                sub_index = {
                    m: payload_index[m]
                    for m in members if m in payload_index
                }
                if sub_index:
                    payload["index"] = sub_index

                async def do_post(b: str) -> Dict[str, Any]:
                    _check_scatter_fault(b)
                    url = (
                        f"{b}{API_PREFIX}/{self.project}"
                        "/_bulk/anomaly/prediction"
                    )
                    async with sem:
                        return await poster(
                            session, url, payload,
                            retries=self.n_retries, timeout=self.timeout,
                            deadline=deadline,
                        )

                # failover order: the owning replica first (unless
                # watchman marks it down), then every other replica not
                # marked down.  An alternate that doesn't host a member
                # reports it unknown in-slot — a per-machine error, never
                # a torn response.
                candidates = [base] + [
                    alt for alt in (self.replica_urls or [])
                    if alt != base and alt not in self._down_bases
                ]
                if base in self._down_bases and len(candidates) > 1:
                    candidates = candidates[1:] + [base]
                body: Optional[Dict[str, Any]] = None
                last_exc: Optional[Exception] = None
                t0 = time.monotonic()
                for n_try, b in enumerate(candidates):
                    try:
                        if n_try == 0:
                            body = await self._post_with_hedge(
                                do_post, b, candidates[1:]
                            )
                        else:
                            _FAILOVER_TOTAL.inc(1.0, "attempt")
                            body = await do_post(b)
                            _FAILOVER_TOTAL.inc(1.0, "recovered")
                        break
                    except HttpUnprocessableEntity:
                        raise
                    except Exception as exc:
                        last_exc = exc
                        logger.warning(
                            "bulk sub-request to %s failed (chunk %d): %s",
                            b, idx, exc,
                        )
                if body is None:
                    # every candidate failed: the machines whose chunks
                    # rode in this sub-request error out; other replicas'
                    # machines (and other rounds) stay ok
                    _FAILOVER_TOTAL.inc(1.0, "exhausted")
                    for name in members:
                        errors[name].append(f"chunk {idx}: {last_exc}")
                    return {}
                self._note_latency(time.monotonic() - t0)
                return body["data"]

            parts = await asyncio.gather(
                *(post_shard(b, ms) for b, ms in plan.items())
            )
            gathered: Dict[str, Any] = {}
            for part in parts:
                gathered.update(part)
            # reassemble in the round's ORIGINAL machine order — which
            # replica answered a machine must never reorder results.  The
            # decoded chunk is stored RAW (no per-machine frame here: the
            # r18 35x materialization wall); LazyFrame defers that work
            # to first .frame access, in round order, bit-identical to
            # the old eager concat.
            for name in payload_X:
                res = gathered.get(name)
                if res is None:
                    continue
                if "error" in res:
                    errors[name].append(str(res["error"]))
                    continue
                lazies[name].add_chunk(idx, res, chunk_index[name])

        rounds = max(n_chunks.values(), default=0)
        await asyncio.gather(*(score_round(i) for i in range(rounds)))

        async def finish(name: str) -> PredictionResult:
            lazy = lazies.get(name)
            predictions = lazy if lazy is not None and len(lazy) else None
            if self.prediction_forwarder is not None and predictions is not None:
                # forwarders consume frames: materialize once here; the
                # LazyFrame caches it, so a later .predictions access on
                # the result reuses the same frame
                await self._forward(
                    predictions.frame, name, metas.get(name), errors[name]
                )
            return PredictionResult(name, predictions, errors[name])

        return list(await asyncio.gather(*(finish(n) for n in names)))

    async def _forward(
        self,
        predictions: Optional[pd.DataFrame],
        machine: str,
        meta: Optional[Dict],
        errors: List[str],
    ) -> None:
        """Push a scored frame to the configured sink; a sink failure is a
        per-machine error, never an exception."""
        if predictions is None or self.prediction_forwarder is None:
            return
        loop = asyncio.get_running_loop()
        try:
            await loop.run_in_executor(
                None, self.prediction_forwarder, predictions, machine, meta
            )
        except Exception as exc:
            logger.exception("Forwarding failed for %s", machine)
            errors.append(f"forwarder: {exc}")

    async def _predict_machine(
        self,
        session: aiohttp.ClientSession,
        sem: asyncio.Semaphore,
        machine: str,
        start: Any,
        end: Any,
    ) -> PredictionResult:
        loop = asyncio.get_running_loop()
        deadline = (
            time.monotonic() + self.deadline_s if self.deadline_s else None
        )
        try:
            meta = await self.machine_metadata_async(session, machine)
            X = await loop.run_in_executor(
                None, self._fetch_data, meta.get("dataset", {}), start, end
            )
        except Exception as exc:
            logger.exception("Data fetch failed for %s", machine)
            return PredictionResult(machine, None, [f"data: {exc}"])

        route = "anomaly/prediction" if self.use_anomaly else "prediction"
        chunks = [
            X.iloc[i : i + self.batch_size]
            for i in range(0, len(X), self.batch_size)
        ]
        tags = [str(c) for c in X.columns]

        async def score_chunk(chunk: pd.DataFrame):
            payload = {"X": chunk.to_numpy(dtype=np.float32).tolist()}
            if isinstance(chunk.index, pd.DatetimeIndex):
                payload["index"] = [t.isoformat() for t in chunk.index]
            url = f"{self._machine_url(machine)}/{route}"
            async with sem:
                try:
                    body = await post_json(
                        session, url, payload,
                        retries=self.n_retries, timeout=self.timeout,
                        deadline=deadline,
                    )
                except HttpUnprocessableEntity:
                    # not an anomaly model — retry on the plain route
                    body = await post_json(
                        session,
                        f"{self._machine_url(machine)}/prediction",
                        payload,
                        retries=self.n_retries,
                        timeout=self.timeout,
                        deadline=deadline,
                    )
            return _frame_from_payload(body["data"], tags, chunk.index)

        frames: List[pd.DataFrame] = []
        errors: List[str] = []
        results = await asyncio.gather(
            *(score_chunk(c) for c in chunks if len(c)), return_exceptions=True
        )
        for res in results:
            if isinstance(res, BaseException):
                errors.append(str(res))
            else:
                frames.append(res)

        predictions = pd.concat(frames).sort_index() if frames else None
        await self._forward(predictions, machine, meta, errors)
        return PredictionResult(machine, predictions, errors)

    # -- streaming (push-based verdicts; serve/stream.py) --------------------
    def _stream_groups(
        self, machines: Optional[Sequence[str]]
    ) -> Dict[str, Optional[List[str]]]:
        """Shard-aware subscription routing: machine verdicts originate
        on the replica that OWNS the machine, so subscriptions split by
        the same shard function requests route with — one upstream
        connection per owning replica, never a fan-in hop through a
        replica that would just 421."""
        if machines:
            groups: Dict[str, Optional[List[str]]] = {}
            for name in machines:
                base = self.base_url
                if self._router is not None:
                    try:
                        base = self._router.url_for(name)
                    except KeyError:
                        pass
                groups.setdefault(base, []).append(name)  # type: ignore[union-attr]
            return groups
        bases = (
            self.replica_urls
            if self.replica_urls and len(self.replica_urls) > 1
            else [self.base_url]
        )
        return {base: None for base in bases}

    def _stream_url(self, base: str, members: Optional[List[str]]) -> str:
        url = f"{base}{API_PREFIX}/{self.project}/stream"
        if members:
            from urllib.parse import urlencode

            url += "?" + urlencode({"machines": ",".join(members)})
        return url

    async def stream_events_async(
        self,
        session: aiohttp.ClientSession,
        machines: Optional[Sequence[str]] = None,
        after: Optional[int] = None,
    ):
        """Async iterator over pushed stream events (``verdict`` /
        ``threshold`` / ``drift``) for ``machines`` (None = the whole
        fleet).  Rides :func:`gordo_tpu.client.io.sse_events`: reconnect
        with ``Last-Event-ID`` resume is automatic, so a dropped
        connection loses and duplicates nothing the server still holds
        in its replay ring.  Against a sharded tier one SSE connection
        runs per owning replica and events merge in arrival order; event
        ids are then per-replica cursors, and ``after`` (which seeds
        every connection) is only meaningful single-replica."""
        from gordo_tpu.client.io import sse_events

        await self._ensure_router(session)
        groups = self._stream_groups(machines)
        if len(groups) == 1:
            ((base, members),) = groups.items()
            async for ev in sse_events(
                session, self._stream_url(base, members),
                last_event_id=after, retries=self.n_retries,
            ):
                yield ev
            return

        queue: "asyncio.Queue" = asyncio.Queue()

        async def pump(base: str, members: Optional[List[str]]):
            try:
                async for ev in sse_events(
                    session, self._stream_url(base, members),
                    last_event_id=after, retries=self.n_retries,
                ):
                    await queue.put(ev)
            except BaseException as exc:  # surfaced on the consumer side
                await queue.put(exc)
                raise

        tasks = [
            asyncio.ensure_future(pump(b, m)) for b, m in groups.items()
        ]
        try:
            while True:
                item = await queue.get()
                if isinstance(item, BaseException):
                    raise item
                yield item
        finally:
            for task in tasks:
                task.cancel()

    def stream(
        self,
        machines: Optional[Sequence[str]] = None,
        after: Optional[int] = None,
        max_events: Optional[int] = None,
    ):
        """Sync generator over pushed stream events — the reference-shaped
        surface (``for ev in client.stream([...])``) around
        :meth:`stream_events_async`.  ``max_events`` bounds the iteration
        (None streams until the consumer breaks or the server goes
        unreachable past the retry budget)."""
        loop = asyncio.new_event_loop()
        session = loop.run_until_complete(self._open_session())
        gen = self.stream_events_async(session, machines, after)
        try:
            n = 0
            while max_events is None or n < max_events:
                try:
                    ev = loop.run_until_complete(gen.__anext__())
                except StopAsyncIteration:
                    break
                yield ev
                n += 1
        finally:
            loop.run_until_complete(gen.aclose())
            loop.run_until_complete(session.close())
            loop.close()

    async def _open_session(self) -> aiohttp.ClientSession:
        return aiohttp.ClientSession()

    async def stream_ingest_async(
        self,
        session: aiohttp.ClientSession,
        X: Dict[str, Any],
    ) -> Dict[str, Any]:
        """POST arriving rows to the streaming ingest route, shard-routed:
        ``X`` maps machine name -> rows (list or ndarray).  Returns the
        merged ``{"accepted", "events"}`` accounting."""
        await self._ensure_router(session)
        plan: Dict[str, Dict[str, Any]] = {}
        for name, rows in X.items():
            base = self.base_url
            if self._router is not None:
                try:
                    base = self._router.url_for(name)
                except KeyError:
                    pass
            rows = np.asarray(rows, np.float32)
            plan.setdefault(base, {})[name] = rows.tolist()
        accepted = 0
        events = 0
        for base, sub in plan.items():
            body = await post_json(
                session,
                f"{base}{API_PREFIX}/{self.project}/stream/ingest",
                {"X": sub},
                retries=self.n_retries, timeout=self.timeout,
            )
            accepted += int(body.get("accepted", 0))
            events += int(body.get("events", 0))
        return {"accepted": accepted, "events": events}

    def stream_ingest(self, X: Dict[str, Any]) -> Dict[str, Any]:
        return _run(self._with_session(self.stream_ingest_async, X))

    # -- data fetch (host-side, reference behavior: client refetches raw) ----
    def _fetch_data(
        self, dataset_meta: Dict[str, Any], start: Any, end: Any
    ) -> pd.DataFrame:
        dataset = dataset_from_metadata(
            dataset_meta, start, end, data_provider=self.data_provider
        )
        X, _ = dataset.get_data()
        return X

    # -- archived history (the backfill plane's read side) -------------------
    def score_history(
        self,
        machines: Optional[Sequence[str]] = None,
        *,
        archive_dir: str,
        start: Any = None,
        end: Any = None,
    ) -> Dict[str, pd.DataFrame]:
        """Archived backfill scores as one frame per machine — months of
        history without a single server round-trip.

        Reads the columnar ``.gordo-scores/`` archive a ``gordo
        backfill`` run wrote under ``archive_dir`` (a shared volume, an
        artifact dir checkout, ...).  Each frame carries a UTC
        ``DatetimeIndex`` of the scored rows, a ``total-anomaly-score``
        column, and one ``tag-anomaly-score-<tag>`` column per tag —
        the archive analogue of a bulk anomaly response.  Machines with
        no archived rows (or outside ``machines``) are omitted.
        ``start``/``end`` clip to ``[start, end)``."""
        from gordo_tpu.batch.archive import ScoreArchive

        arch = ScoreArchive(archive_dir)
        names = list(machines) if machines else arch.machines()
        out: Dict[str, pd.DataFrame] = {}
        for name in names:
            rec = arch.read_machine(name, start=start, end=end)
            if rec is None or rec["total-anomaly-score"].size == 0:
                continue
            index = pd.DatetimeIndex(
                np.asarray(rec["index-ns"]).view("datetime64[ns]"),
                name="time",
            ).tz_localize("UTC")
            tags = list(rec["tags"]) or [
                str(i) for i in range(rec["tag-anomaly-scores"].shape[1])
            ]
            frame = pd.DataFrame(
                rec["tag-anomaly-scores"],
                index=index,
                columns=[f"tag-anomaly-score-{t}" for t in tags],
            )
            frame.insert(
                0, "total-anomaly-score", rec["total-anomaly-score"]
            )
            out[name] = frame
        return out

    # -- plumbing ------------------------------------------------------------
    async def _with_session(self, fn, *args):
        async with aiohttp.ClientSession() as session:
            return await fn(session, *args)


def _run(coro):
    """Run a coroutine from sync code (error out inside a running loop)."""
    try:
        asyncio.get_running_loop()
    except RuntimeError:
        return asyncio.run(coro)
    raise RuntimeError(
        "Client sync methods cannot be called from inside a running event "
        "loop; use the *_async variants"
    )
