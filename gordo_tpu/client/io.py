"""HTTP coroutines with typed errors and retry.

Reference equivalent: ``gordo_components/client/io.py`` — thin aiohttp
wrappers (``fetch_json``/``post_json``) raising ``HttpUnprocessableEntity``
/ ``ResourceGone``-style typed errors so the client loop can distinguish
"model can't do that" from "endpoint is down".
"""

from __future__ import annotations

import asyncio
import os
import random
import time
from typing import Any, Dict, Optional

import aiohttp

from gordo_tpu import faults, telemetry

#: max samples (rows x total machine-columns) one bulk round may carry.
#: A bulk round's payload spans EVERY machine — ``batch_size`` alone
#: bounds only the row axis, so a long-time-range request against a 10k-
#: machine fleet used to pack ``batch_size x machines x tags`` floats
#: into ONE body (gigabytes through the codec; the backfill archive's
#: device-limited chunks made the contrast visible).  Rounds now shrink
#: their row slice so no payload exceeds this budget.
ENV_MAX_BULK_SAMPLES = "GORDO_CLIENT_MAX_BULK_SAMPLES"
DEFAULT_MAX_BULK_SAMPLES = 2_000_000


def max_bulk_samples() -> int:
    try:
        value = int(
            os.environ.get(ENV_MAX_BULK_SAMPLES, "")
            or DEFAULT_MAX_BULK_SAMPLES
        )
    except ValueError:
        return DEFAULT_MAX_BULK_SAMPLES
    return value if value > 0 else DEFAULT_MAX_BULK_SAMPLES


def bulk_rows_budget(total_columns: int, batch_size: int) -> int:
    """Rows one bulk round may carry across ``total_columns`` summed
    machine-columns without exceeding :func:`max_bulk_samples` — never
    more than ``batch_size`` (the row-axis contract stands), never less
    than 1 (progress is always possible)."""
    if total_columns <= 0:
        return max(1, int(batch_size))
    return max(1, min(int(batch_size), max_bulk_samples() // total_columns))


class HttpUnprocessableEntity(Exception):
    """422 — the endpoint understood the request but the model refuses it
    (e.g. anomaly route on a non-anomaly model)."""


class BadGordoRequest(Exception):
    """4xx — permanent client-side error; retrying cannot help."""


class DeadlineExceeded(Exception):
    """The caller's deadline ran out (locally, or the server answered 504
    after dropping the rider) — retrying inside the same deadline is
    pointless by definition."""


class BadGordoResponse(Exception):
    """5xx / non-JSON — endpoint-side failure; retry may help.

    ``retry_after``: the response's ``Retry-After`` delay in seconds when
    the endpoint sent one (429 overload shedding, 503 warmup), else None
    — the retry loop sleeps THAT instead of its exponential guess."""

    retry_after: Optional[float] = None


#: statuses worth retrying (transient by convention)
_RETRYABLE_STATUSES = {408, 425, 429, 500, 502, 503, 504}


def _parse_retry_after(value: Optional[str]) -> Optional[float]:
    """``Retry-After`` seconds form → float (the HTTP-date form is not
    spoken here — the bundled server always sends seconds)."""
    if not value:
        return None
    try:
        seconds = float(value.strip())
    except (TypeError, ValueError):
        return None
    return seconds if seconds >= 0 else None


async def request_json(
    session: aiohttp.ClientSession,
    method: str,
    url: str,
    *,
    json: Optional[Dict[str, Any]] = None,
    data: Optional[bytes] = None,
    headers: Optional[Dict[str, str]] = None,
    retries: int = 3,
    backoff: float = 0.5,
    timeout: float = 120.0,
    deadline: Optional[float] = None,
) -> Dict[str, Any]:
    """``method url`` → parsed body with jittered exponential-backoff retry.

    Responses decode by content type: ``application/x-gordo-columnar``
    through the GSB1 block codec (array leaves come back as ZERO-COPY
    ``np.frombuffer`` views into the response body — no per-machine
    splitting or copying), ``application/x-msgpack`` through the binary
    codec (array leaves come back as ndarrays), anything else as JSON.

    Every request carries the context's trace id in the
    ``X-Gordo-Trace-Id`` header (minted here when the caller hasn't bound
    one): the server tags its handler/coalescer/scorer spans with it and
    echoes it on the response, so one id stitches a request's timeline
    from this client through the whole serving stack.

    ``deadline`` (a ``time.monotonic()`` timestamp) bounds the WHOLE
    call, retries included: each attempt restamps the remaining budget
    into the ``X-Gordo-Deadline-Ms`` header (the server drops riders
    whose budget expired before dispatch), the per-attempt timeout
    shrinks to the remaining budget, and an exhausted budget raises
    :class:`DeadlineExceeded` instead of sleeping into a retry that
    cannot answer in time."""
    headers = dict(headers or {})
    headers.setdefault(telemetry.TRACE_HEADER, telemetry.ensure_trace_id())
    last_exc: Optional[Exception] = None
    for attempt in range(retries + 1):
        attempt_timeout = timeout
        if deadline is not None:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise DeadlineExceeded(
                    f"{method} {url}: deadline exhausted after "
                    f"{attempt} attempt(s)"
                ) from last_exc
            attempt_timeout = min(timeout, remaining)
            headers[telemetry.DEADLINE_HEADER] = str(
                max(1, int(remaining * 1000))
            )
        try:
            _check_http_fault(method, url)
            async with session.request(
                method,
                url,
                json=json,
                data=data,
                headers=headers,
                timeout=aiohttp.ClientTimeout(total=attempt_timeout),
            ) as resp:
                if resp.status == 422:
                    raise HttpUnprocessableEntity(await resp.text())
                if 400 <= resp.status < 500 and resp.status not in _RETRYABLE_STATUSES:
                    raise BadGordoRequest(
                        f"{method} {url} -> {resp.status}: {await resp.text()}"
                    )
                if resp.status >= 400:
                    exc = BadGordoResponse(
                        f"{method} {url} -> {resp.status}: {await resp.text()}"
                    )
                    # 429/503 shedding rides a Retry-After: the server
                    # KNOWS its queue horizon; honor it over the blind
                    # exponential schedule (capped below)
                    exc.retry_after = _parse_retry_after(
                        resp.headers.get("Retry-After")
                    )
                    raise exc
                from gordo_tpu.serve import codec

                if resp.content_type == codec.COLUMNAR_CONTENT_TYPE:
                    return codec.decode_columnar(await resp.read())
                if resp.content_type == codec.MSGPACK_CONTENT_TYPE:
                    return codec.unpackb(await resp.read())
                return await resp.json()
        except (HttpUnprocessableEntity, BadGordoRequest, DeadlineExceeded):
            raise
        except (aiohttp.ClientError, asyncio.TimeoutError, BadGordoResponse) as exc:
            last_exc = exc
            if attempt < retries:
                # FULL jitter: uniform over [0, backoff * 2^attempt].  A
                # deterministic schedule synchronizes every client that
                # failed together, so they thundering-herd the replica
                # the moment it recovers; jitter decorrelates the wave.
                delay = random.uniform(0.0, backoff * (2 ** attempt))
                retry_after = getattr(exc, "retry_after", None)
                if retry_after is not None:
                    # server-stated delay wins over the schedule, capped
                    # at the schedule's own maximum sleep so a confused
                    # endpoint can't park the client for minutes
                    delay = min(
                        retry_after, backoff * (2 ** max(retries - 1, 0))
                    )
                if deadline is not None:
                    # never sleep past the deadline: the remaining budget
                    # caps total retry wall-clock, and a budget too small
                    # to retry in fails NOW with the real cause attached
                    remaining = deadline - time.monotonic()
                    if remaining <= delay:
                        raise DeadlineExceeded(
                            f"{method} {url}: deadline exhausted after "
                            f"{attempt + 1} attempt(s)"
                        ) from exc
                await asyncio.sleep(delay)
    raise BadGordoResponse(f"{method} {url} failed after {retries + 1} attempts") from last_exc


async def sse_events(
    session: aiohttp.ClientSession,
    url: str,
    *,
    headers: Optional[Dict[str, str]] = None,
    last_event_id: Optional[int] = None,
    retries: int = 5,
    backoff: float = 0.5,
    deadline: Optional[float] = None,
    read_timeout: float = 60.0,
):
    """Consume a server-sent-event stream, yielding parsed
    ``{"id", "type", "data"}`` events with automatic reconnect.

    The resume contract: the yielded id becomes the cursor, every
    (re)connect carries it as ``Last-Event-ID``, and the server replays
    what the ring still holds past it — so a dropped connection (or a
    slow-consumer disconnect) loses nothing, and the ``id > cursor``
    guard below drops any overlap, so nothing duplicates either.  A
    torn frame (disconnect mid-event) never reaches the blank-line
    dispatch and is discarded wholesale on reconnect.

    Retry accounting matches :func:`request_json` in spirit: full-jitter
    exponential backoff between connect attempts, ``retries`` bounding
    CONSECUTIVE failed connects (any delivered event resets the count —
    an SSE session is long-lived, so a per-session cap would just decide
    when a healthy stream is eventually killed), permanent 4xx raising
    immediately, and ``deadline`` bounding the whole session.
    ``read_timeout`` bounds the gap between frames; the server's
    keepalive comments (default 15s) tick well inside it.
    """
    headers = dict(headers or {})
    headers.setdefault(telemetry.TRACE_HEADER, telemetry.ensure_trace_id())
    cursor = last_event_id
    attempt = 0
    while True:
        if deadline is not None and deadline - time.monotonic() <= 0:
            raise DeadlineExceeded(f"GET {url}: stream deadline exhausted")
        hdrs = dict(headers)
        if cursor is not None:
            hdrs["Last-Event-ID"] = str(cursor)
        try:
            _check_http_fault("GET", url)
            async with session.get(
                url,
                headers=hdrs,
                timeout=aiohttp.ClientTimeout(
                    total=None, sock_read=read_timeout
                ),
            ) as resp:
                if resp.status == 422:
                    raise HttpUnprocessableEntity(await resp.text())
                if (
                    400 <= resp.status < 500
                    and resp.status not in _RETRYABLE_STATUSES
                ):
                    raise BadGordoRequest(
                        f"GET {url} -> {resp.status}: {await resp.text()}"
                    )
                if resp.status >= 400:
                    raise BadGordoResponse(
                        f"GET {url} -> {resp.status}: {await resp.text()}"
                    )
                import json as _json

                fields: Dict[str, Any] = {}
                data_lines: list = []
                async for raw in resp.content:
                    line = raw.decode("utf-8", "replace").rstrip("\r\n")
                    if not line:
                        if (
                            fields.get("id") is not None
                            and fields.get("type")
                            and data_lines
                        ):
                            eid = fields["id"]
                            if cursor is None or eid > cursor:
                                cursor = eid
                                attempt = 0
                                yield {
                                    "id": eid,
                                    "type": fields["type"],
                                    "data": _json.loads("\n".join(data_lines)),
                                }
                        fields, data_lines = {}, []
                    elif line.startswith(":"):
                        continue  # keepalive / replay-gap comment
                    elif line.startswith("id:"):
                        fields["id"] = int(line[3:].strip())
                    elif line.startswith("event:"):
                        fields["type"] = line[6:].strip()
                    elif line.startswith("data:"):
                        data_lines.append(line[5:].strip())
            # server closed the stream (slow-consumer disconnect, replica
            # restart): fall through to the reconnect accounting below —
            # a clean close that never delivers still can't loop forever
            raise aiohttp.ClientConnectionError(f"GET {url}: stream closed")
        except (HttpUnprocessableEntity, BadGordoRequest, DeadlineExceeded):
            raise
        except (
            aiohttp.ClientError, asyncio.TimeoutError, BadGordoResponse
        ) as exc:
            if attempt >= retries:
                raise BadGordoResponse(
                    f"GET {url}: stream failed after {retries + 1} "
                    "consecutive connect attempts"
                ) from exc
            delay = random.uniform(0.0, backoff * (2 ** attempt))
            attempt += 1
            if deadline is not None and deadline - time.monotonic() <= delay:
                raise DeadlineExceeded(
                    f"GET {url}: stream deadline exhausted"
                ) from exc
            await asyncio.sleep(delay)


def _check_http_fault(method: str, url: str) -> None:
    """``http.request`` injection seam, translated to the wire-level
    failures this module's retry loop already classifies."""
    if not faults.enabled():
        return
    try:
        faults.check("http.request", method=method, url=url)
    except faults.InjectedFault as exc:
        if exc.mode == "blackhole":
            raise asyncio.TimeoutError(str(exc)) from None
        if exc.mode == "reset":
            raise aiohttp.ClientConnectionError(str(exc)) from None
        if exc.mode in ("http_500", "http_503"):
            raise BadGordoResponse(
                f"{method} {url} -> {exc.mode[-3:]}: {exc}"
            ) from None
        raise


async def get_json(session: aiohttp.ClientSession, url: str, **kw) -> Dict[str, Any]:
    return await request_json(session, "GET", url, **kw)


async def post_json(
    session: aiohttp.ClientSession, url: str, payload: Dict[str, Any], **kw
) -> Dict[str, Any]:
    return await request_json(session, "POST", url, json=payload, **kw)


async def post_msgpack(
    session: aiohttp.ClientSession, url: str, payload: Dict[str, Any], **kw
) -> Dict[str, Any]:
    """POST a msgpack body (ndarray leaves ride as raw buffers) and ask for
    a msgpack response — the bulk-scoring fast path between the bundled
    client and server (~100x the JSON codec rate; see ``serve/codec.py``)."""
    from gordo_tpu.serve import codec

    return await request_json(
        session,
        "POST",
        url,
        data=codec.packb(payload),
        headers={
            "Content-Type": codec.MSGPACK_CONTENT_TYPE,
            "Accept": codec.MSGPACK_CONTENT_TYPE,
        },
        **kw,
    )


async def post_bulk(
    session: aiohttp.ClientSession,
    url: str,
    payload: Dict[str, Any],
    *,
    columnar: bool = True,
    **kw,
) -> Dict[str, Any]:
    """POST a msgpack body and negotiate the GSB1 columnar response
    (``Accept: application/x-gordo-columnar, application/x-msgpack``):
    stacked bulk results arrive as contiguous blocks decoded into
    zero-copy views — the ~35x frame-materialization gap BENCH_r18
    measured lived in the per-machine split/copy this skips.  Servers
    that predate the block codec match the msgpack fallback in the same
    header, so the round degrades transparently; ``columnar=False``
    pins plain msgpack (parity tooling, old-wire comparisons)."""
    from gordo_tpu.serve import codec

    accept = (
        f"{codec.COLUMNAR_CONTENT_TYPE}, {codec.MSGPACK_CONTENT_TYPE}"
        if columnar
        else codec.MSGPACK_CONTENT_TYPE
    )
    return await request_json(
        session,
        "POST",
        url,
        data=codec.packb(payload),
        headers={
            "Content-Type": codec.MSGPACK_CONTENT_TYPE,
            "Accept": accept,
        },
        **kw,
    )
