"""Warmup manifest: the build plane tells the serve plane what to compile.

``builder/fleet_build.py`` records the ``(signature, bucket)`` set each
project build materialized — one entry per fleet chunk, carrying the
machine names and the shape facts (widths, lookback) that determine the
serving program family.  On startup the server pre-compiles from that
manifest (:func:`warmup_collection`) through the compile plane's AOT path
— ``lower(shapes).compile()``, no input data, no execution — and only
then flips ``/healthz`` from ``warming`` to ``ready``, so the first
request is never the compiling request.

Layout mirrors the telemetry snapshots: ``<output_dir>/.gordo-warmup/``
holds one JSON per build shard (multi-host shards each write their own
file; a re-run overwrites only its own), and the reader merges them.
A collection without a manifest still warms — the fleet scorer derives
every bucket from the loaded models; the manifest adds the row-bucket
hints and the per-program accounting the ``gordo warmup`` gate prints.
"""

from __future__ import annotations

import json
import logging
import os
import time
from typing import Any, Dict, List, Optional, Sequence

logger = logging.getLogger(__name__)

#: directory (under a build's output dir) where per-shard warmup
#: manifests land
WARMUP_DIR = ".gordo-warmup"

#: request row buckets pre-compiled by default: the smallest serving
#: bucket and the replayed-stream request shape (serve.scorer.MIN_BUCKET
#: and the 2048-row bench/replay size)
DEFAULT_ROW_BUCKETS = (256, 2048)

#: v2 manifests carry the build-time serving dtype (the serving-precision
#: plane): what the builder resolved ``GORDO_SERVE_DTYPE`` to is what the
#: server's warmup must compile for — a bf16 build warms bf16
#: executables, never fp32 ones.  v1 manifests (no ``dtype``) read as
#: float32.
MANIFEST_VERSION = 2


def _shard_path(output_dir: str, shard) -> str:
    pid, n = shard or (0, 1)
    return os.path.join(
        output_dir, WARMUP_DIR, f"shard-{pid:03d}-of-{n:03d}.json"
    )


def write_warmup_manifest(
    output_dir: str,
    entries: List[Dict[str, Any]],
    shard=None,
    row_buckets: Optional[Sequence[int]] = None,
    live_machines: Optional[set] = None,
    serve_dtype: Optional[str] = None,
    mesh=None,
) -> Optional[str]:
    """Write (merge) this build's warmup manifest shard file.

    ``entries``: one dict per fleet chunk —
    ``{"signature", "machines", "n_machines", "n_features", "n_outputs",
    "lookback"}``.  Entries already on disk for machines NOT rebuilt this
    run are kept (a partial rebuild must not unlearn the rest of the
    project); entries overlapping the new machine set are replaced.

    ``serve_dtype``: the serving precision this build was configured for
    (``None`` resolves ``GORDO_SERVE_DTYPE`` here, at write time) —
    recorded doc-level so the serve plane warms, and defaults to serving,
    the same precision; a rewrite (latest build) wins over merged rows'
    older dtype.

    ``mesh``: the device mesh this build's fleet programs compiled over
    (a ``jax.sharding.Mesh``, or ``None`` for single-device) — recorded
    doc-level as ``{"device_count", "shape"}`` so the serve plane can see
    what placement the build warmed for.  v2 manifests without the key
    (older builds) read back as ``mesh=None``.

    ``live_machines``: when given, kept rows PRUNE to it — machines no
    longer present in the build output drop out of their rows, and rows
    left empty drop entirely.  Without pruning, a partial rebuild that
    shrinks a bucket union-merges stale (signature, bucket) rows forever
    and warmup keeps compiling for machines that no longer exist.

    Returns the path written, or None when there was nothing to record
    (a fully-cached re-run keeps the existing manifest untouched).
    """
    if not entries:
        return None
    path = _shard_path(output_dir, shard)
    rebuilt = {name for e in entries for name in e.get("machines", ())}
    kept: List[Dict[str, Any]] = []
    try:
        with open(path) as fh:
            doc = json.load(fh)
        for e in doc.get("programs", ()):
            if rebuilt.intersection(e.get("machines", ())):
                continue
            if live_machines is not None:
                machines = [
                    m for m in e.get("machines", ()) if m in live_machines
                ]
                if not machines:
                    continue  # the whole row went stale — drop it
                if len(machines) != len(e.get("machines", ())):
                    e = dict(e)
                    e["machines"] = machines
                    e["n_machines"] = len(machines)
            kept.append(e)
    except (OSError, ValueError):
        pass
    # lazy import: gordo_tpu.compile initializes before the serve package
    from gordo_tpu.serve.precision import canonical, serve_dtype as _resolve

    doc = {
        "version": MANIFEST_VERSION,
        "dtype": canonical(serve_dtype) if serve_dtype else _resolve(),
        "row_buckets": sorted(
            set(int(r) for r in (row_buckets or DEFAULT_ROW_BUCKETS))
        ),
        "programs": kept + list(entries),
    }
    if mesh is not None:
        doc["mesh"] = {
            "device_count": int(mesh.devices.size),
            "shape": {str(k): int(v) for k, v in mesh.shape.items()},
        }
    os.makedirs(os.path.dirname(path), exist_ok=True)
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        with open(tmp, "w") as fh:
            json.dump(doc, fh, indent=1)
        os.replace(tmp, path)
    except OSError:
        logger.exception("warmup manifest write failed: %s", path)
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return None
    return path


def load_warmup_manifest(path: str) -> Optional[Dict[str, Any]]:
    """Merge every shard manifest under ``path`` (a build output dir, or
    its ``.gordo-warmup/`` subdir directly).  Returns
    ``{"dtype": ..., "row_buckets": [...], "programs": [...]}`` or None
    when no manifest exists.  ``dtype`` is the build-time serving
    precision when every shard agrees (v1 shards read as float32);
    disagreeing shards — a half-finished precision migration — yield
    ``None`` with a warning, and the serve plane falls back to its env
    resolution rather than guessing."""
    candidates = [os.path.join(path, WARMUP_DIR), path]
    directory = next((d for d in candidates if os.path.isdir(d)), None)
    if directory is None:
        return None
    row_buckets: set = set()
    programs: List[Dict[str, Any]] = []
    dtypes: set = set()
    meshes: List[Optional[Dict[str, Any]]] = []
    for name in sorted(os.listdir(directory)):
        if not name.endswith(".json"):
            continue
        try:
            with open(os.path.join(directory, name)) as fh:
                doc = json.load(fh)
        except (OSError, ValueError):
            logger.warning("unreadable warmup manifest: %s", name)
            continue
        row_buckets.update(int(r) for r in doc.get("row_buckets", ()))
        programs.extend(doc.get("programs", ()))
        dtypes.add(str(doc.get("dtype", "float32")))
        meshes.append(doc.get("mesh"))
    if not programs and not row_buckets:
        return None
    dtype: Optional[str] = None
    if len(dtypes) == 1:
        dtype = next(iter(dtypes))
    elif len(dtypes) > 1:
        logger.warning(
            "warmup manifest shards disagree on serving dtype (%s); "
            "ignoring the manifest dtype", sorted(dtypes),
        )
    # placement plane: shards of one build agree on the mesh; mixed or
    # absent (pre-r22) manifests read back as None and the serve plane
    # resolves its own mesh as before
    distinct = {json.dumps(m, sort_keys=True) for m in meshes}
    mesh = meshes[0] if len(distinct) == 1 else None
    return {
        "dtype": dtype,
        "row_buckets": sorted(row_buckets) or list(DEFAULT_ROW_BUCKETS),
        "programs": programs,
        "mesh": mesh,
    }


def filter_manifest(
    manifest: Optional[Dict[str, Any]], machines
) -> Optional[Dict[str, Any]]:
    """Restrict a merged warmup manifest to a machine subset — what a
    fleet-sharded replica (``GORDO_SERVE_SHARD=i/N``) warms: only the
    (signature, bucket) rows that intersect ITS machines, with each kept
    row's machine list pruned to the subset.  Row-bucket hints are
    shape facts, not machine facts, and pass through unchanged.  N
    replicas therefore each AOT-compile ~1/N of the fleet's program
    signatures instead of all of them — warmup wall-clock (and the
    ``gordo warmup --dir --shard`` init-container gate) scales with the
    shard, not the project."""
    if manifest is None:
        return None
    wanted = set(machines)
    programs: List[Dict[str, Any]] = []
    for entry in manifest.get("programs", ()):
        kept = [m for m in entry.get("machines", ()) if m in wanted]
        if not kept:
            continue
        if len(kept) != len(entry.get("machines", ())):
            entry = dict(entry)
            entry["machines"] = kept
            entry["n_machines"] = len(kept)
        programs.append(entry)
    out = dict(manifest)
    out["programs"] = programs
    return out


def warmup_collection(
    collection,
    row_sizes: Optional[Sequence[int]] = None,
    manifest: Optional[Dict[str, Any]] = None,
) -> Dict[str, Any]:
    """Pre-compile a model collection's serving programs AOT.

    Per structural bucket, per row bucket: the full stacked dispatch (the
    ``_bulk`` route's program), the 1-machine subset dispatch (the
    coalescer's common case), and the per-machine fused program — all via
    ``Program.warm`` (lower+compile from shape structs; nothing
    executes).  Returns stats including a ``programs`` list of
    ``{"program", "rows", "seconds"}`` — the per-program compile accounting
    the ``gordo warmup`` CLI prints — with ``seconds == 0.0`` marking a
    signature that was already compiled (in-process or via the persistent
    cache the XLA layer consults underneath).

    Errors are counted, logged, and carried in ``stats["errors"]``; they
    never raise — a warmup failure must not take down server startup
    (the CLI gate turns the count into its exit code instead).
    """
    from gordo_tpu.serve.scorer import MIN_BUCKET, _bucket_rows

    t0 = time.monotonic()
    stats: Dict[str, Any] = {
        "buckets": 0, "fallbacks": 0, "errors": 0, "programs": [],
    }
    if manifest is None and getattr(collection, "source_dir", None):
        manifest = load_warmup_manifest(collection.source_dir)
    if getattr(collection, "shard", None) is not None:
        # a sharded replica warms only ITS manifest subset; the buckets
        # below already reflect the shard (the collection loaded only its
        # machines), this keeps the manifest-derived accounting honest
        manifest = filter_manifest(manifest, collection.entries)
        stats["shard"] = str(collection.shard)
    if not row_sizes:
        row_sizes = (manifest or {}).get("row_buckets") or [MIN_BUCKET, 2048]
    try:
        fleet = collection.fleet_scorer
    except Exception:
        logger.exception("Warmup: fleet scorer construction failed")
        stats["errors"] += 1
        return stats
    # the serving precision actually warmed (bucket program prefixes carry
    # it; a bf16 manifest/collection warms bf16 executables, never fp32)
    stats["dtype"] = getattr(fleet, "dtype", "float32")
    # the placement the warmed executables were compiled for: sharded
    # buckets AOT-compile with NamedSharding-annotated shape structs, so
    # a mesh-N warmup lands mesh-N executables, never single-device ones
    serve_mesh = getattr(collection, "serve_mesh", None)
    stats["model_shards"] = (
        int(serve_mesh.shape.get("models", 1)) if serve_mesh is not None
        else 1
    )

    for bucket in fleet.buckets:
        ok = True
        rows_set = sorted(
            {_bucket_rows(max(int(r), bucket.lookback + 1)) for r in row_sizes}
        )
        try:
            for label, rows, secs in bucket.warm_programs(rows_set):
                stats["programs"].append(
                    {"program": label, "rows": rows, "seconds": round(secs, 3)}
                )
        except Exception:
            logger.exception(
                "Warmup failed for bucket %s", bucket.names[:3]
            )
            stats["errors"] += 1
            ok = False
        # one per-machine fused program warms every machine sharing the
        # architecture (flax modules hash structurally)
        entry = collection.get(bucket.names[0])
        if entry is not None and entry.scorer.fused:
            n_feat = bucket.n_features or 1
            for rows in rows_set:
                try:
                    for label, secs in entry.scorer.warm_programs(
                        rows, n_feat
                    ):
                        stats["programs"].append(
                            {
                                "program": label,
                                "rows": rows,
                                "seconds": round(secs, 3),
                            }
                        )
                except Exception:
                    logger.exception(
                        "Warmup failed for machine program %s rows=%d",
                        bucket.names[0], rows,
                    )
                    stats["errors"] += 1
                    ok = False
            # the streaming plane's incremental step program (one per
            # chain signature — every machine in the bucket shares it);
            # [] when the model can't stream, which is not an error
            try:
                from gordo_tpu.serve import stream as stream_mod

                for label, secs in stream_mod.warm_stream_program(
                    entry.scorer, n_feat
                ):
                    stats["programs"].append(
                        {"program": label, "rows": 1, "seconds": round(secs, 3)}
                    )
            except Exception:
                logger.exception(
                    "Warmup failed for stream program %s", bucket.names[0]
                )
                stats["errors"] += 1
                ok = False
            # one EXECUTED dispatch at the smallest row bucket: the AOT
            # compiles above land the executables, but the first real
            # dispatch still pays one-time runtime costs (backend thread
            # pools, buffer paths) — ~30ms measured on CPU — that must
            # not land on the first request either
            if entry.scorer.is_anomaly:
                try:
                    import numpy as np

                    entry.scorer.anomaly_arrays(
                        np.zeros((rows_set[0], n_feat), np.float32)
                    )
                except Exception:
                    logger.debug(
                        "Warmup exercise skipped for %s",
                        bucket.names[0], exc_info=True,
                    )
        if ok:
            stats["buckets"] += 1

    # fallback (non-fused) machines have no AOT program; executing their
    # own scoring path once still lands whatever jit compiles it needs
    for name in fleet.fallbacks:
        entry = collection.get(name)
        if entry is None:
            continue
        try:
            import numpy as np

            rows = max(MIN_BUCKET, getattr(entry.scorer, "offset", 0) + 1)
            n_feat = len(entry.tags) or 1
            X = np.zeros((rows, n_feat), np.float32)
            if entry.scorer.is_anomaly:
                entry.scorer.anomaly_arrays(X)
            else:
                entry.scorer.predict(X)
            stats["fallbacks"] += 1
        except Exception:
            # fallback models often fail on zeros (e.g. missing thresholds
            # raise by design) — debug-level, not an operational error
            logger.debug("Warmup skipped fallback %s", name, exc_info=True)

    stats["seconds"] = round(time.monotonic() - t0, 2)
    stats["compile_seconds"] = round(
        sum(p["seconds"] for p in stats["programs"]), 3
    )
    logger.info(
        "Compile-plane warmup: %d bucket(s), %d program signature(s), "
        "%.2fs compiling, %d error(s)",
        stats["buckets"], len(stats["programs"]),
        stats["compile_seconds"], stats["errors"],
    )
    return stats
