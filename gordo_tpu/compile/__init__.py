"""Compile plane: AOT program registry, warmup manifest, cold-start tools.

See :mod:`gordo_tpu.compile.registry` for the design.  Every jitted
program in the stack registers here (``scripts/lint.py`` rejects bare
``jax.jit`` outside this package); the serving dispatch family
additionally compiles ahead-of-time through :func:`program` so startup
warmup — driven by the manifest ``builder/fleet_build.py`` writes — can
pre-compile before the first request arrives.
"""

from gordo_tpu.compile.registry import (  # noqa: F401
    REGISTRY,
    ClosureProgram,
    CompileRegistry,
    Program,
    cached_closure,
    closure_program,
    install_persistent_cache_counters,
    jit,
    program,
    set_warming,
    warming,
)
from gordo_tpu.compile.warmup import (  # noqa: F401
    WARMUP_DIR,
    filter_manifest,
    load_warmup_manifest,
    warmup_collection,
    write_warmup_manifest,
)

__all__ = [
    "REGISTRY",
    "ClosureProgram",
    "CompileRegistry",
    "Program",
    "WARMUP_DIR",
    "cached_closure",
    "closure_program",
    "filter_manifest",
    "install_persistent_cache_counters",
    "jit",
    "load_warmup_manifest",
    "program",
    "set_warming",
    "warming",
    "warmup_collection",
    "write_warmup_manifest",
]
