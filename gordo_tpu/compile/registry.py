"""The compile plane: one registry owning every jitted program in the stack.

Reference status: absent upstream — the reference's Keras models had no
compile step to manage.  Here every serving request and every fleet build
runs through an XLA executable, and before this module each call site
managed its own compilation implicitly: ``jax.jit`` traced-and-compiled on
the first unlucky call (ambushing the request path with a multi-second
stall), ``parallel/anomaly.py`` kept its own closure LRU, and nothing
counted compiles or cache reuse.  Both pjit-era training systems and the
AOT-compilation line of work treat compile-once-run-many as a first-class
system concern; this registry makes it one:

- :class:`Program` — an explicitly registered jitted program whose
  compiled executables are cached HERE, keyed by
  ``(program, static args, input signature, sharding)``.  Compilation goes
  through ``jit(...).lower(shapes).compile()`` (the jax AOT API), so it is
  schedulable: :meth:`Program.warm` compiles from shape structs alone —
  no input data, no execution — which is what the server's startup warmup
  and the ``gordo warmup`` init-container hook run off the serving thread.
  A call that misses compiles inline (counted + timed); a call that hits
  dispatches the cached executable (~15µs over jit's C++ fast path,
  noise next to a device dispatch).  Anything the AOT path cannot express
  (tracer inputs, exotic shardings) falls back to the plain jitted
  function — behavior, results, and numerics are identical either way.
- :func:`cached_closure` — the ONE LRU for per-configuration jitted
  closures (the fleet CV+fit programs of ``parallel/anomaly.py``), so the
  builder and the serving plane share a single eviction policy and one
  ``gordo_compiled_programs`` gauge instead of ad-hoc caches.
- :func:`jit` — a registered passthrough to ``jax.jit`` for programs that
  run inside other traced code (where AOT signature capture is
  meaningless).  Keeps ``scripts/lint.py``'s "no bare jax.jit outside
  gordo_tpu/compile/" gate honest: every program in the stack is at least
  *known* to the plane.
- warming state — the server's startup warmup flips
  :func:`set_warming`; ``/healthz`` reports ``warming`` vs ``ready`` and
  the coalescer queues new riders behind the warmup instead of letting
  each executor thread block on its own cold compile.
- persistent-cache counters — when jax's on-disk compilation cache is
  active (``utils/compile_cache.py``), a ``jax.monitoring`` listener maps
  its hit/miss events onto ``gordo_compile_cache_hits_total`` /
  ``misses_total{cache="persistent"}`` so cross-process reuse (server
  restarts, forked multi-host workers) is attestable in a scrape.

Kill switch: ``GORDO_COMPILE_PLANE=off`` routes every :class:`Program`
call straight through the plain jitted function (today's pre-plane
behavior, bit for bit); the registry then only counts.
"""

from __future__ import annotations

import inspect
import logging
import os
import threading
import time
from collections import OrderedDict
from typing import Any, Callable, Dict, List, Optional, Tuple

from gordo_tpu import telemetry

logger = logging.getLogger(__name__)

# -- telemetry instruments (docs/observability.md "Compile plane") ----------
_COMPILE_SECONDS = telemetry.histogram(
    "gordo_compile_seconds",
    "Wall seconds spent lowering+compiling one program signature",
    labels=("program",),
)
_CACHE_HITS = telemetry.counter(
    "gordo_compile_cache_hits_total",
    "Compile-cache hits by cache layer "
    "(programs: in-process executable registry; persistent: jax's "
    "on-disk compilation cache)",
    labels=("cache",),
)
_CACHE_MISSES = telemetry.counter(
    "gordo_compile_cache_misses_total",
    "Compile-cache misses by cache layer",
    labels=("cache",),
)
_PROGRAMS_GAUGE = telemetry.gauge(
    "gordo_compiled_programs",
    "Programs resident in the compile-plane caches, by kind "
    "(aot: compiled executables; closure: jitted builder closures)",
    labels=("kind",),
)
_WARMING_GAUGE = telemetry.gauge(
    "gordo_compile_warming",
    "1 while a startup warmup is pre-compiling serving programs",
)

#: executable-cache bound: power-of-two request buckets keep distinct
#: serving signatures log-few, so 256 covers a large project's full
#: program family with room for transient shapes
MAX_EXECUTABLES = int(os.environ.get("GORDO_COMPILE_PROGRAMS_MAX", "256"))
#: closure-cache bound — matches the historical _EXACT_PROGRAMS LRU of
#: parallel/anomaly.py it replaces
MAX_CLOSURES = 128


def _plane_enabled() -> bool:
    return os.environ.get("GORDO_COMPILE_PLANE", "on").strip().lower() not in (
        "off", "0", "false",
    )


def _sharding_token(leaf: Any) -> Any:
    """Cache-key component for a leaf's placement: only a committed
    mesh sharding distinguishes executables — numpy inputs, shape
    structs, and uncommitted single-device arrays all lower to the same
    program, so they share a token (None)."""
    from gordo_tpu.mesh import NamedSharding

    sharding = getattr(leaf, "sharding", None)
    return sharding if isinstance(sharding, NamedSharding) else None


def _leaf_sig(leaf: Any) -> Tuple:
    shape = getattr(leaf, "shape", None)
    if shape is None:
        return ("py", type(leaf).__name__)
    return (tuple(shape), str(getattr(leaf, "dtype", "?")),
            _sharding_token(leaf))


class BoundProgram:
    """A program pre-resolved to one compiled executable (see
    :meth:`Program.bind`): call with the FULL positional argument list
    (statics included, in signature order) — the statics are already
    baked into the executable and are dropped here by position."""

    __slots__ = ("_exe", "_dyn_idx")

    def __init__(self, exe: Any, dyn_idx: Tuple[int, ...]):
        self._exe = exe
        self._dyn_idx = dyn_idx

    def __call__(self, *ordered):
        return self._exe(*[ordered[i] for i in self._dyn_idx])


class Program:
    """One explicitly registered jitted program with an AOT executable
    cache.

    Call it exactly like the jitted function it wraps — same arguments,
    same results.  The difference is WHERE compilation happens: through
    the shared registry (counted, timed, evictable, pre-compilable via
    :meth:`warm`) instead of inside jit's opaque first-call path.
    """

    def __init__(
        self,
        name: str,
        fn: Callable,
        static_argnames: Tuple[str, ...] = (),
        registry: Optional["CompileRegistry"] = None,
    ):
        self.name = name
        self._fn = fn
        self._static = frozenset(static_argnames)
        import jax

        self._jitted = jax.jit(fn, static_argnames=tuple(static_argnames))
        self._signature = inspect.signature(fn)
        self._registry = registry or REGISTRY
        self._aot_broken = False  # one loud failure, then jit-only
        self._registry._register_program(self)

    # -- signature machinery -------------------------------------------------
    def _normalize(self, args: Tuple, kwargs: Dict) -> List[Any]:
        """Every call form → the full positional argument list (defaults
        applied), so cache keys and lowered calling conventions agree no
        matter how the caller spelled the invocation."""
        bound = self._signature.bind(*args, **kwargs)
        bound.apply_defaults()
        return [bound.arguments[p] for p in self._signature.parameters]

    def _split(self, ordered: List[Any]) -> Tuple[Tuple, List[Any]]:
        statics, dynamics = [], []
        for pname, value in zip(self._signature.parameters, ordered):
            if pname in self._static:
                statics.append((pname, value))
            else:
                dynamics.append(value)
        return tuple(statics), dynamics

    def _key(self, statics: Tuple, dynamics: List[Any]):
        import jax

        flat, treedef = jax.tree.flatten(dynamics)
        if any(isinstance(leaf, jax.core.Tracer) for leaf in flat):
            return None, None  # inside another trace: jit path only
        sig = tuple(_leaf_sig(leaf) for leaf in flat)
        return (self.name, statics, treedef, sig), flat

    # -- dispatch ------------------------------------------------------------
    def __call__(self, *args, **kwargs):
        if self._aot_broken or not _plane_enabled():
            return self._jitted(*args, **kwargs)
        try:
            ordered = self._normalize(args, kwargs)
            statics, dynamics = self._split(ordered)
            key, _ = self._key(statics, dynamics)
        except Exception:  # unbindable/unhashable: jit can still judge it
            return self._jitted(*args, **kwargs)
        if key is None:
            return self._jitted(*args, **kwargs)
        exe = self._registry._get_executable(key)
        if exe is None:
            _CACHE_MISSES.inc(1.0, "programs")
            exe = self._compile(key, ordered)
            if exe is None:  # AOT couldn't express it — jit fallback
                return self._jitted(*args, **kwargs)
        else:
            _CACHE_HITS.inc(1.0, "programs")
        try:
            return exe(*dynamics)
        except Exception:
            # a cached executable that stopped matching (device change,
            # sharding drift) must degrade, not 500 the request
            logger.exception(
                "compiled executable for %s failed; falling back to jit",
                self.name,
            )
            self._registry._drop_executable(key)
            return self._jitted(*args, **kwargs)

    def _compile(self, key, ordered: List[Any]):
        """Lower+compile one signature through the registry (timed)."""
        t0 = time.perf_counter()
        try:
            exe = self._jitted.lower(*ordered).compile()
        except Exception as exc:
            if not self._aot_broken:
                self._aot_broken = True
                logger.warning(
                    "AOT compile unavailable for program %s (%s); "
                    "dispatching through jit for this process",
                    self.name, exc,
                )
            return None
        _COMPILE_SECONDS.observe(time.perf_counter() - t0, self.name)
        self._registry._put_executable(key, exe)
        return exe

    def bind(self, *args, **kwargs) -> Optional["BoundProgram"]:
        """Resolve THIS call signature to its compiled executable once
        and return a :class:`BoundProgram` — the fixed-shape hot-loop
        fast path (the streaming step dispatches through one of these
        per arrival), skipping the per-call normalize/split/key work
        that dominates sub-millisecond dispatches.

        The binding is only valid while every subsequent call repeats
        the SAME static values and dynamic shapes/dtypes; callers must
        re-bind when either changes (a mismatched call raises from the
        executable rather than miscomputing).  Returns None when the
        AOT plane is off or cannot express the call — fall back to
        normal ``__call__`` dispatch then.
        """
        if self._aot_broken or not _plane_enabled():
            return None
        try:
            ordered = self._normalize(args, kwargs)
            statics, dynamics = self._split(ordered)
            key, _ = self._key(statics, dynamics)
        except Exception:
            return None
        if key is None:
            return None
        exe = self._registry._get_executable(key)
        if exe is None:
            _CACHE_MISSES.inc(1.0, "programs")
            exe = self._compile(key, ordered)
            if exe is None:
                return None
        dyn_idx = tuple(
            i for i, pname in enumerate(self._signature.parameters)
            if pname not in self._static
        )
        return BoundProgram(exe, dyn_idx)

    def warm(self, *args, **kwargs) -> float:
        """Pre-compile this program for the given argument shapes without
        executing it.  Dynamic arguments may be real arrays OR
        ``jax.ShapeDtypeStruct``s — warmup needs no input data.  Returns
        the compile seconds (0.0 when the signature was already cached).
        Raises on compile failure so warmup gates (CLI exit codes, k8s
        init containers) can fail loudly.
        """
        ordered = self._normalize(args, kwargs)
        statics, dynamics = self._split(ordered)
        key, _ = self._key(statics, dynamics)
        if key is None:
            raise ValueError(f"cannot warm {self.name} with tracer inputs")
        if self._registry._get_executable(key) is not None:
            return 0.0
        _CACHE_MISSES.inc(1.0, "programs")
        t0 = time.perf_counter()
        exe = self._jitted.lower(*ordered).compile()
        dt = time.perf_counter() - t0
        _COMPILE_SECONDS.observe(dt, self.name)
        self._registry._put_executable(key, exe)
        return dt


class ClosureProgram:
    """A per-configuration jitted CLOSURE with the :class:`Program`
    warm/bind surface.

    The fleet build programs of ``parallel/anomaly.py`` are closures over
    their configuration (module, fold layout, scaler options), built on
    demand and cached in the registry's closure LRU — they cannot be
    top-level :class:`Program`\\ s because the closure itself is part of
    the identity.  Wrapping each closure in a ``ClosureProgram`` gives the
    build plane the same two properties the serve plane gets from
    ``Program``: :meth:`warm` pre-compiles a signature from
    ``jax.ShapeDtypeStruct``\\ s alone (no data, no execution — schedulable
    before the first chunk's arrays exist), and a call whose signature was
    warmed dispatches the AOT executable directly instead of re-entering
    jit's trace-cache path.  A call whose signature was never warmed (the
    common cold-build case) falls through to the plain jitted closure —
    behavior and numerics identical either way, and near-zero overhead:
    the fallthrough is one attribute check while the executable dict is
    empty.

    Executables live on the instance, so they are evicted together with
    the closure when the registry's closure LRU drops it.
    """

    __slots__ = ("name", "_jitted", "_exes", "_lock", "_aot_broken")

    def __init__(self, fn: Callable, name: str = "closure", **jit_kwargs):
        import jax

        self.name = name
        self._jitted = jax.jit(fn, **jit_kwargs)
        with REGISTRY._lock:
            REGISTRY._jits[name] = self._jitted
        self._exes: Dict[Any, Any] = {}
        self._lock = threading.Lock()
        self._aot_broken = False

    def _sig(self, args: Tuple):
        import jax

        flat, treedef = jax.tree.flatten(args)
        if any(isinstance(leaf, jax.core.Tracer) for leaf in flat):
            return None
        return (treedef, tuple(_leaf_sig(leaf) for leaf in flat))

    def warm(self, *args) -> float:
        """Pre-compile this closure for the given argument shapes without
        executing it (arguments may be real arrays or
        ``jax.ShapeDtypeStruct``\\ s, shardings included).  Returns compile
        seconds, 0.0 on a cache hit.  Raises on tracer inputs or compile
        failure so warmup gates fail loudly."""
        key = self._sig(args)
        if key is None:
            raise ValueError(f"cannot warm {self.name} with tracer inputs")
        with self._lock:
            if key in self._exes:
                return 0.0
        if not _plane_enabled():
            return 0.0
        _CACHE_MISSES.inc(1.0, "programs")
        t0 = time.perf_counter()
        exe = self._jitted.lower(*args).compile()
        dt = time.perf_counter() - t0
        _COMPILE_SECONDS.observe(dt, self.name)
        with self._lock:
            self._exes[key] = exe
        return dt

    def bind(self, *args):
        """Resolve this signature to its compiled executable (compiling if
        needed) and return it, or None when the AOT path is off or cannot
        express the call.  The executable is only valid while calls repeat
        the same shapes/dtypes/shardings."""
        if self._aot_broken or not _plane_enabled():
            return None
        key = self._sig(args)
        if key is None:
            return None
        with self._lock:
            exe = self._exes.get(key)
        if exe is not None:
            return exe
        try:
            self.warm(*args)
        except Exception as exc:
            self._aot_broken = True
            logger.warning(
                "AOT compile unavailable for closure %s (%s); "
                "dispatching through jit",
                self.name, exc,
            )
            return None
        with self._lock:
            return self._exes.get(key)

    def __call__(self, *args):
        # empty-dict check first: a never-warmed closure (the common cold
        # build) pays one truthiness test, not a tree flatten
        if self._aot_broken or not self._exes or not _plane_enabled():
            return self._jitted(*args)
        key = self._sig(args)
        if key is None:
            return self._jitted(*args)
        with self._lock:
            exe = self._exes.get(key)
        if exe is None:
            return self._jitted(*args)
        _CACHE_HITS.inc(1.0, "programs")
        try:
            return exe(*args)
        except Exception:
            logger.exception(
                "compiled executable for closure %s failed; "
                "falling back to jit", self.name,
            )
            with self._lock:
                self._exes.pop(key, None)
            return self._jitted(*args)


class CompileRegistry:
    """Process-wide compile-plane state: the AOT executable cache, the
    builder closure cache, the registered-program index, and the warming
    flag.  One instance (:data:`REGISTRY`) serves the whole process."""

    def __init__(
        self,
        max_executables: int = MAX_EXECUTABLES,
        max_closures: int = MAX_CLOSURES,
    ):
        self._lock = threading.Lock()
        self._executables: "OrderedDict[Any, Any]" = OrderedDict()
        self._closures: "OrderedDict[Any, Any]" = OrderedDict()
        self._programs: Dict[str, Program] = {}
        self._jits: Dict[str, Any] = {}
        self.max_executables = max_executables
        self.max_closures = max_closures
        self._warming = False

    # -- program index -------------------------------------------------------
    def _register_program(self, program: Program) -> None:
        with self._lock:
            self._programs[program.name] = program

    def programs(self) -> Dict[str, Program]:
        with self._lock:
            return dict(self._programs)

    # -- AOT executable cache ------------------------------------------------
    def _get_executable(self, key):
        with self._lock:
            exe = self._executables.get(key)
            if exe is not None:
                self._executables.move_to_end(key)
            return exe

    def _put_executable(self, key, exe) -> None:
        with self._lock:
            self._executables[key] = exe
            self._executables.move_to_end(key)
            while len(self._executables) > self.max_executables:
                self._executables.popitem(last=False)
            _PROGRAMS_GAUGE.set(float(len(self._executables)), "aot")

    def _drop_executable(self, key) -> None:
        with self._lock:
            self._executables.pop(key, None)
            _PROGRAMS_GAUGE.set(float(len(self._executables)), "aot")

    def n_executables(self) -> int:
        with self._lock:
            return len(self._executables)

    # -- closure cache (the unified _EXACT_PROGRAMS successor) --------------
    def cached_closure(self, key, factory: Callable[[], Any]):
        """Get-or-build a jitted closure under the shared LRU.  ``key``
        must capture everything the closure's trace depends on — the same
        contract the builder's old private cache had, now with ONE
        eviction policy and a gauge for the whole plane."""
        with self._lock:
            cached = self._closures.get(key)
            if cached is not None:
                self._closures.move_to_end(key)
                _CACHE_HITS.inc(1.0, "closures")
                return cached
        _CACHE_MISSES.inc(1.0, "closures")
        built = factory()
        with self._lock:
            self._closures[key] = built
            self._closures.move_to_end(key)
            while len(self._closures) > self.max_closures:
                self._closures.popitem(last=False)
            _PROGRAMS_GAUGE.set(float(len(self._closures)), "closure")
        return built

    def clear(self) -> None:
        """Drop every cached executable and closure (tests; device swaps)."""
        with self._lock:
            self._executables.clear()
            self._closures.clear()
            _PROGRAMS_GAUGE.set(0.0, "aot")
            _PROGRAMS_GAUGE.set(0.0, "closure")

    # -- warming state -------------------------------------------------------
    def set_warming(self, warming: bool) -> None:
        with self._lock:
            self._warming = bool(warming)
        _WARMING_GAUGE.set(1.0 if warming else 0.0)

    def warming(self) -> bool:
        with self._lock:
            return self._warming


#: the process's compile plane
REGISTRY = CompileRegistry()


def program(
    name: str, fn: Callable, static_argnames: Tuple[str, ...] = ()
) -> Program:
    """Register ``fn`` as a compile-plane :class:`Program` (the AOT path).
    Use for top-level programs called with concrete inputs — the serving
    dispatch family."""
    return Program(name, fn, static_argnames=static_argnames)


def jit(fn: Optional[Callable] = None, *, name: Optional[str] = None, **kwargs):
    """Registered passthrough to ``jax.jit`` for programs that run inside
    other traces or need jit-only features (donation, shardings) — the
    compile plane knows them by name; dispatch is jax's unchanged.
    Usable bare (``compile.jit(fn)``) or parameterized
    (``compile.jit(static_argnames=...)(fn)``)."""
    import jax

    def wrap(f: Callable):
        jitted = jax.jit(f, **kwargs)
        label = name or getattr(f, "__qualname__", getattr(f, "__name__", "jit"))
        with REGISTRY._lock:
            REGISTRY._jits[label] = jitted
        return jitted

    if fn is not None:
        return wrap(fn)
    return wrap


def cached_closure(key, factory: Callable[[], Any]):
    """Module-level convenience for :meth:`CompileRegistry.cached_closure`
    on the process registry."""
    return REGISTRY.cached_closure(key, factory)


def closure_program(
    fn: Callable, *, name: str = "closure", **jit_kwargs
) -> ClosureProgram:
    """Wrap a per-configuration closure as a :class:`ClosureProgram`
    (warm/bind-capable jitted closure).  Pair with :func:`cached_closure`
    so the wrapper shares the closure LRU's eviction."""
    return ClosureProgram(fn, name=name, **jit_kwargs)


def warming() -> bool:
    return REGISTRY.warming()


def set_warming(value: bool) -> None:
    REGISTRY.set_warming(value)


# ---------------------------------------------------------------------------
# persistent-cache counter bridge
# ---------------------------------------------------------------------------

_MONITORING_INSTALLED = False
_PERSISTENT_EVENTS = {
    "/jax/compilation_cache/cache_hits": ("hits", "persistent"),
    "/jax/compilation_cache/cache_misses": ("misses", "persistent"),
}


def install_persistent_cache_counters() -> bool:
    """Map jax's on-disk compilation-cache hit/miss monitoring events onto
    the ``gordo_compile_cache_*_total{cache="persistent"}`` counters, so a
    ``/metrics`` scrape attests cross-process compile reuse.  Idempotent;
    returns True when the listener is installed.  Never raises — an old
    jax without the monitoring surface just leaves the counters at 0."""
    global _MONITORING_INSTALLED
    if _MONITORING_INSTALLED:
        return True
    try:
        from jax import monitoring

        def _listener(event: str, **kw) -> None:
            mapped = _PERSISTENT_EVENTS.get(event)
            if mapped is None:
                return
            which, cache = mapped
            (_CACHE_HITS if which == "hits" else _CACHE_MISSES).inc(1.0, cache)

        monitoring.register_event_listener(_listener)
        _MONITORING_INSTALLED = True
        return True
    except Exception as exc:
        logger.debug("persistent-cache counters unavailable: %s", exc)
        return False
