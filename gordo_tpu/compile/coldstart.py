"""Cold-start measurement child: one fresh process, one first request.

The quantity under test — what a request pays when it is the FIRST to hit
an uncompiled serving program — only exists in a process whose jit and
compile-plane caches are empty, so ``bench.py --stage cold_start`` (and
the slow-lane smoke test) fork this module instead of measuring in-process:

    python -m gordo_tpu.compile.coldstart --artifacts DIR --mode cold|warm

``cold``: load the artifact collection and immediately score — the first
request eats the compile (today's no-warmup behavior).  ``warm``: run the
compile-plane warmup (manifest-driven AOT pre-compiles) first, then score
— the first request should cost dispatch only.  Either way the child
prints ONE JSON line with ``time_to_ready_s`` (process start → able to
serve), ``first_request_s``, ``second_request_s``, and the
``gordo_compile_*`` counter lines from the telemetry exposition (the same
text ``/metrics`` serves), so the parent can attest compile-cache hits.

Persistent-cache runs are driven by the parent via the normal env
contract (``GORDO_COMPILE_CACHE=force`` + ``GORDO_COMPILE_CACHE_DIR``):
back-to-back children on one machine populate then reuse the on-disk
cache, measuring cached-restart time-to-ready against the cold one.
"""

from __future__ import annotations

import time

_T0 = time.monotonic()  # as close to process start as a module can get

import argparse  # noqa: E402
import json  # noqa: E402
import sys  # noqa: E402


def _compile_metric_lines(scrape: str) -> list:
    return [
        line
        for line in scrape.splitlines()
        if not line.startswith("#")
        and line.startswith((
            "gordo_compile_cache_", "gordo_compile_seconds_count",
            "gordo_compiled_programs",
        ))
    ]


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--artifacts", required=True,
                        help="Project artifact dir (build_project output)")
    parser.add_argument("--mode", choices=("cold", "warm"), required=True)
    parser.add_argument("--rows", type=int, default=256,
                        help="Request row count for the measured requests")
    args = parser.parse_args(argv)

    import numpy as np

    from gordo_tpu import telemetry
    from gordo_tpu.serve.server import ModelCollection
    from gordo_tpu.utils.compile_cache import enable_persistent_compile_cache

    persistent = enable_persistent_compile_cache()
    collection = ModelCollection.from_directory(args.artifacts)

    warm_stats = None
    if args.mode == "warm":
        from gordo_tpu.compile import warmup_collection

        warm_stats = warmup_collection(collection)
        if warm_stats["errors"]:
            print(json.dumps({"error": "warmup failed", **warm_stats}))
            return 1
    time_to_ready = time.monotonic() - _T0

    # the measured request: the per-machine anomaly route's scoring path
    name = sorted(collection.entries)[0]
    entry = collection.get(name)
    n_feat = len(entry.tags) or 1
    rng = np.random.default_rng(0)
    X = rng.standard_normal((args.rows, n_feat)).astype(np.float32)

    t0 = time.perf_counter()
    entry.scorer.anomaly_arrays(X)
    first_request = time.perf_counter() - t0
    t0 = time.perf_counter()
    entry.scorer.anomaly_arrays(X)
    second_request = time.perf_counter() - t0

    doc = {
        "mode": args.mode,
        "persistent_cache": bool(persistent),
        "time_to_ready_s": round(time_to_ready, 4),
        "first_request_s": round(first_request, 4),
        "second_request_s": round(second_request, 4),
        "warmup": warm_stats and {
            "buckets": warm_stats["buckets"],
            "programs": len(warm_stats["programs"]),
            "compile_seconds": warm_stats["compile_seconds"],
        },
        "compile_metrics": _compile_metric_lines(telemetry.render()),
    }
    print(json.dumps(doc))
    return 0


if __name__ == "__main__":
    sys.exit(main())
