"""Sharding construction + the one counted ``device_put`` seam.

Everything that ships host data to devices outside the artifact plane's
``to_device`` goes through :func:`place` here, and every
``jax.sharding.NamedSharding`` in the stack is built by this module —
``scripts/lint.py`` rejects raw ``jax.device_put`` / ``jax.sharding.*``
construction anywhere else, the same single-owner contract the shard
function and the compile plane already enforce.

Placement layout for a fleet-stacked program: every operand with a leading
``models`` axis (params, opt-state, X/y/w stacks, thresholds) shards that
axis over the mesh fleet axis and replicates the rest; scalars replicate.
The shardings are donation-compatible — a donated input buffer and its
matching output share a layout, so the compile plane's ``donate_argnums``
keep working unchanged on the sharded path.
"""

from __future__ import annotations

from typing import Any, Iterable, Optional

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from gordo_tpu.telemetry import metrics as telemetry

from .fleet import DATA_AXIS, MODEL_AXIS, FleetMesh

_PLACEMENTS = telemetry.counter(
    "gordo_fleet_placements_total",
    "Fleet-stack device placements by kind (sharded mesh vs single device)",
    labels=("kind",),
)
_DEVICE_TRANSFERS = telemetry.counter(
    "gordo_mesh_device_transfers_total",
    "Array leaves transferred to each device by the placement plane",
    labels=("device",),
)


def model_sharding(mesh: Mesh, extra_dims: int = 0) -> NamedSharding:
    """Sharding placing a leading ``models`` axis over the mesh fleet axis."""
    return NamedSharding(mesh, P(MODEL_AXIS, *([None] * extra_dims)))


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def data_sharding(mesh: Mesh, extra_dims: int = 0) -> NamedSharding:
    """Sharding placing a leading rows axis over the mesh ``data`` axis
    (the data-parallel single-model fit path)."""
    return NamedSharding(mesh, P(DATA_AXIS, *([None] * extra_dims)))


class PlacementSpec:
    """The sharding plan for one fleet-stacked program's operands.

    Wraps an optional mesh (a raw :class:`Mesh`, a :class:`FleetMesh`, or
    ``None``) and answers "what sharding does THIS operand get".  With no
    mesh every method returns ``None`` — which ``jax.device_put`` and the
    compile plane both read as "default single-device placement", keeping
    the degenerate case today's code path exactly.
    """

    __slots__ = ("mesh",)

    def __init__(self, mesh: Optional[Any] = None):
        if isinstance(mesh, FleetMesh):
            mesh = mesh.mesh
        self.mesh: Optional[Mesh] = mesh

    @property
    def is_sharded(self) -> bool:
        return self.mesh is not None

    def stacked(self, extra_dims: int = 0) -> Optional[NamedSharding]:
        """Leading ``models`` axis sharded, ``extra_dims`` trailing axes
        replicated (params/opt-state/X/y/w stacks)."""
        if self.mesh is None:
            return None
        return model_sharding(self.mesh, extra_dims)

    def replicated(self) -> Optional[NamedSharding]:
        """Fully replicated (scalars, shared configuration arrays)."""
        if self.mesh is None:
            return None
        return replicated_sharding(self.mesh)

    def leaf(self, a: Any) -> Optional[NamedSharding]:
        """The stacked sharding matched to ``a``'s rank (leading axis is
        the fleet axis, everything after replicates)."""
        if self.mesh is None:
            return None
        ndim = getattr(a, "ndim", 0)
        return model_sharding(self.mesh, max(int(ndim) - 1, 0))

    def tree(self, host_tree: Any) -> Optional[Any]:
        """Per-leaf stacked shardings for a whole pytree (params stacks)."""
        if self.mesh is None:
            return None
        return jax.tree_util.tree_map(self.leaf, host_tree)


def _iter_sharding_devices(sharding: Any) -> Iterable[jax.Device]:
    """Union of devices named by ``sharding`` (a Sharding or a pytree of
    them); empty for ``None`` / non-sharding leaves."""
    seen = set()
    for s in jax.tree_util.tree_leaves(sharding):
        device_set = getattr(s, "device_set", None)
        if device_set:
            for d in device_set:
                if d not in seen:
                    seen.add(d)
                    yield d


def place(tree: Any, sharding: Any = None) -> Any:
    """THE device transfer of the placement plane.

    ``sharding`` may be ``None`` (default single-device placement — the
    degenerate path), one sharding broadcast over the tree, or a pytree of
    shardings matching ``tree``.  Counts one placement per call
    (``gordo_fleet_placements_total{kind}``) and the per-device leaf
    transfers (``gordo_mesh_device_transfers_total{device}``).
    """
    if sharding is None:
        out = jax.device_put(tree)
    else:
        out = jax.device_put(tree, sharding)
    if telemetry.enabled():
        devices = list(_iter_sharding_devices(sharding))
        sharded = len(devices) > 1
        _PLACEMENTS.inc(1.0, "sharded" if sharded else "single")
        n_leaves = len(jax.tree_util.tree_leaves(tree))
        if not devices:
            devices = jax.devices()[:1]
        for d in devices:
            _DEVICE_TRANSFERS.inc(float(n_leaves), str(d.id))
    return out
