"""The placement plane: the ONE owner of device meshes and shardings.

Everything mesh- or sharding-shaped lives here (or is re-exported from
here): :class:`FleetMesh` resolves which devices participate
(``GORDO_MESH_DEVICES`` / ``--mesh-devices`` / auto), :class:`PlacementSpec`
decides what sharding each operand gets, and :func:`place` is the single
``jax.device_put`` seam outside the artifact plane's ``to_device``.
``scripts/lint.py`` bans raw ``jax.device_put`` / ``jax.sharding.*``
construction everywhere else, so the rest of the stack imports the
``Mesh`` / ``NamedSharding`` / ``PartitionSpec`` types from HERE when it
needs them for annotations or cache keys.
"""

from jax.sharding import Mesh, NamedSharding, PartitionSpec

from gordo_tpu.mesh.fleet import (
    DATA_AXIS,
    ENV_MESH_DEVICES,
    MODEL_AXIS,
    FleetMesh,
    fleet_mesh,
    global_fleet_mesh,
    pad_to_multiple,
)
from gordo_tpu.mesh.placement import (
    PlacementSpec,
    data_sharding,
    model_sharding,
    place,
    replicated_sharding,
)

__all__ = [
    "DATA_AXIS",
    "ENV_MESH_DEVICES",
    "MODEL_AXIS",
    "Mesh",
    "NamedSharding",
    "PartitionSpec",
    "FleetMesh",
    "PlacementSpec",
    "data_sharding",
    "fleet_mesh",
    "global_fleet_mesh",
    "model_sharding",
    "pad_to_multiple",
    "place",
    "replicated_sharding",
]
