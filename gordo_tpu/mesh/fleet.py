"""Fleet device-mesh construction — the placement plane's mesh owner.

The framework's canonical mesh has two axes:

- ``"models"`` — the fleet axis: independent machines' stacked models.  This
  replaces the reference's Argo pod-per-machine fan-out; collectives never
  cross it (pure map), so XLA partitions it for free.
- ``"data"`` — batch/row axis for data-parallel fitting of a single larger
  model (all-reduce of grads rides ICI).

:class:`FleetMesh` wraps device discovery + mesh construction behind ONE
resolution path (``GORDO_MESH_DEVICES`` env var / ``--mesh-devices`` CLI
flag / auto = every visible device), with the single-device case degenerating
to ``mesh=None`` — exactly the sentinel every existing call site already
treats as "today's one-device path", so 1 device is bit-for-bit unchanged.

On a v5e-64 slice the default is all 64 chips on ``"models"``; a single-chip
dev box gets ``mesh=None`` and every program still compiles identically.
This module (and its sibling :mod:`gordo_tpu.mesh.placement`) is the only
place in the stack allowed to construct ``jax.sharding`` objects — enforced
by ``scripts/lint.py`` the same way the compile plane owns ``jax.jit``.
"""

from __future__ import annotations

import os
from typing import Any, Dict, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

from gordo_tpu.telemetry import metrics as telemetry

MODEL_AXIS = "models"
DATA_AXIS = "data"

#: mesh width resolution env var: unset/"auto"/"all" = every visible
#: device, "1" = force the single-device degenerate path, an integer N =
#: the first N devices (error if fewer are visible)
ENV_MESH_DEVICES = "GORDO_MESH_DEVICES"

_MESH_DEVICES_GAUGE = telemetry.gauge(
    "gordo_mesh_devices",
    "Device count of the most recently constructed fleet mesh",
)


def fleet_mesh(
    devices: Optional[Sequence[jax.Device]] = None,
    data_parallel: int = 1,
) -> Mesh:
    """Build the canonical ``("models", "data")`` mesh over ``devices``.

    ``data_parallel`` chips are grouped per model-shard; the rest of the
    devices spread the fleet axis.
    """
    devices = list(devices) if devices is not None else jax.devices()
    n = len(devices)
    if n % data_parallel != 0:
        raise ValueError(
            f"data_parallel={data_parallel} does not divide device count {n}"
        )
    grid = np.asarray(devices).reshape(n // data_parallel, data_parallel)
    return Mesh(grid, (MODEL_AXIS, DATA_AXIS))


def global_fleet_mesh(data_parallel: int = 1) -> Mesh:
    """The canonical mesh over EVERY process's devices — the multi-host
    form of :func:`fleet_mesh` (``gordo_tpu.distributed.runtime``).

    Devices order by ``(process_index, device id)`` so each host's local
    devices are CONTIGUOUS along the ``"models"`` axis: a host feeds its
    shard of a stacked fleet array with one contiguous
    ``make_array_from_process_local_data`` block, and a per-host slice of
    the machine list maps onto a per-host slice of the mesh.  Requires a
    uniform local device count (true of any TPU slice and of the
    simulated launcher); raises otherwise rather than building a mesh
    whose process boundaries fall mid-row.
    """
    import collections

    devices = sorted(jax.devices(), key=lambda d: (d.process_index, d.id))
    per_proc = collections.Counter(d.process_index for d in devices)
    counts = set(per_proc.values())
    if len(counts) > 1:
        raise ValueError(
            "global_fleet_mesh needs a uniform local device count per "
            f"process, got {dict(per_proc)}"
        )
    if data_parallel > 1 and min(counts) % data_parallel != 0:
        # keep every ("models" row x "data" group) within one host: the
        # data axis carries grad all-reduces, which should ride ICI, not
        # straddle the host boundary onto DCN
        raise ValueError(
            f"data_parallel={data_parallel} does not divide the per-process "
            f"device count {min(counts)}; a data group must not span hosts"
        )
    return fleet_mesh(devices, data_parallel=data_parallel)


def pad_to_multiple(m: int, k: int) -> int:
    """Smallest multiple of ``k`` that is >= ``m``."""
    return -(-m // k) * k


def _parse_device_spec(spec: Any) -> Optional[int]:
    """``GORDO_MESH_DEVICES`` / ``--mesh-devices`` value → requested device
    count, or ``None`` for "all visible devices"."""
    if spec is None:
        return None
    s = str(spec).strip().lower()
    if s in ("", "auto", "all"):
        return None
    try:
        n = int(s)
    except ValueError:
        raise ValueError(
            f"mesh device spec {spec!r} is not an integer, 'all', or 'auto' "
            f"(set via --mesh-devices or ${ENV_MESH_DEVICES})"
        ) from None
    if n < 1:
        raise ValueError(f"mesh device spec must be >= 1, got {n}")
    return n


class FleetMesh:
    """The resolved placement decision: which devices, what mesh.

    ``.mesh`` is the canonical ``("models", "data")`` :class:`Mesh` when
    more than one device participates, and ``None`` for the single-device
    degenerate case — the exact sentinel the fleet fit/scoring call sites
    already branch on, so one device stays today's path bit-for-bit.
    """

    __slots__ = ("devices", "data_parallel", "mesh")

    def __init__(
        self,
        devices: Optional[Sequence[jax.Device]] = None,
        data_parallel: int = 1,
    ):
        self.devices = tuple(devices) if devices is not None else tuple(
            jax.devices()
        )
        self.data_parallel = int(data_parallel)
        self.mesh: Optional[Mesh] = (
            fleet_mesh(self.devices, data_parallel=self.data_parallel)
            if len(self.devices) > 1
            else None
        )
        _MESH_DEVICES_GAUGE.set(float(len(self.devices)))

    # -- resolution ---------------------------------------------------------
    @classmethod
    def from_devices(
        cls,
        devices: Optional[Sequence[jax.Device]] = None,
        data_parallel: int = 1,
    ) -> "FleetMesh":
        return cls(devices, data_parallel=data_parallel)

    @classmethod
    def resolve(
        cls,
        spec: Any = None,
        data_parallel: int = 1,
        devices: Optional[Sequence[jax.Device]] = None,
    ) -> "FleetMesh":
        """Resolve the mesh width: explicit ``spec`` (the ``--mesh-devices``
        flag) wins, else ``$GORDO_MESH_DEVICES``, else all visible devices.
        """
        if spec is None:
            spec = os.environ.get(ENV_MESH_DEVICES)
        want = _parse_device_spec(spec)
        pool = list(devices) if devices is not None else jax.devices()
        if want is not None:
            if want > len(pool):
                raise ValueError(
                    f"mesh device spec asks for {want} devices but only "
                    f"{len(pool)} are visible (XLA_FLAGS="
                    f"--xla_force_host_platform_device_count=N forces more "
                    "on CPU)"
                )
            pool = pool[:want]
        return cls(pool, data_parallel=data_parallel)

    # -- introspection ------------------------------------------------------
    @property
    def n_devices(self) -> int:
        return len(self.devices)

    @property
    def n_model_shards(self) -> int:
        """Width of the fleet axis: how many ways a stacked bucket splits."""
        if self.mesh is None:
            return 1
        return self.mesh.shape[MODEL_AXIS]

    @property
    def is_sharded(self) -> bool:
        return self.mesh is not None

    def pad(self, m: int) -> int:
        """Fleet size ``m`` padded up to the mesh divisibility requirement
        (the pad-to-mesh policy: ragged buckets round up, never truncate).
        """
        return pad_to_multiple(m, self.n_model_shards)

    def describe(self) -> Dict[str, Any]:
        """JSON-able summary for ``gordo mesh info`` and the project index."""
        return {
            "n_devices": self.n_devices,
            "devices": [str(d) for d in self.devices],
            "platform": self.devices[0].platform if self.devices else None,
            "mesh_shape": (
                {k: int(v) for k, v in self.mesh.shape.items()}
                if self.mesh is not None
                else None
            ),
            "model_shards": self.n_model_shards,
            "data_parallel": self.data_parallel,
            "sharded": self.is_sharded,
        }

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        return (
            f"FleetMesh(n_devices={self.n_devices}, "
            f"model_shards={self.n_model_shards}, "
            f"data_parallel={self.data_parallel})"
        )
