"""Definition-dict ⇄ live object interpreter.

Behavior-compatible with the reference's
``gordo_components/serializer/pipeline_from_definition.py`` and
``pipeline_into_definition.py`` — the heart of config-driven model
construction.  A model definition is a nested YAML/dict structure where:

- a **string** is a dotted import path instantiated with no kwargs,
- a **single-key dict** ``{"pkg.mod.Class": {kwargs}}`` is a class + kwargs,
- kwargs are **recursed**: nested single-key dicts with dotted keys become
  objects; lists are recursed elementwise,
- ``Pipeline`` steps / ``FeatureUnion`` transformer lists are lists of step
  definitions.

TPU-native twist: dotted paths from the reference era
(``sklearn.preprocessing.MinMaxScaler``,
``gordo_components.model.models.KerasAutoEncoder`` ...) are rewritten through
:data:`gordo_tpu.registry.ALIASES` onto this framework's functional JAX
components, so an existing gordo-components project YAML builds a TPU model
unchanged.  Imports are restricted to an allowlist — the definition dict is
user config, not arbitrary code.
"""

from __future__ import annotations

import copy
import importlib
from typing import Any, Mapping

from gordo_tpu.registry import ALLOWED_IMPORT_PREFIXES, resolve_alias


def _looks_like_import_path(key: str) -> bool:
    return isinstance(key, str) and "." in key and not key.startswith(".")


def import_locate(dotted: str) -> Any:
    """Import ``pkg.mod.attr`` (after alias rewriting), allowlist-enforced."""
    dotted = resolve_alias(dotted)
    if not dotted.startswith(ALLOWED_IMPORT_PREFIXES):
        raise ValueError(
            f"Refusing to import {dotted!r}: not under allowed prefixes "
            f"{ALLOWED_IMPORT_PREFIXES}"
        )
    module_path, _, attr = dotted.rpartition(".")
    try:
        module = importlib.import_module(module_path)
    except ImportError as exc:
        raise ImportError(f"Cannot import module {module_path!r} for {dotted!r}: {exc}")
    try:
        return getattr(module, attr)
    except AttributeError:
        raise ImportError(f"Module {module_path!r} has no attribute {attr!r}")


def from_definition(definition: Any) -> Any:
    """Recursively turn a definition structure into live objects.

    Reference equivalent: ``serializer.pipeline_from_definition``.
    """
    if isinstance(definition, str):
        if _looks_like_import_path(definition):
            target = import_locate(definition)
            return target() if isinstance(target, type) else target
        return definition

    if isinstance(definition, Mapping):
        if len(definition) == 1:
            (key, value), = definition.items()
            if _looks_like_import_path(key):
                target = import_locate(key)
                if value is None:
                    return target() if isinstance(target, type) else target
                if isinstance(value, Mapping):
                    kwargs = {k: _recurse_value(v) for k, v in value.items()}
                    return target(**kwargs)
                # list/scalar positional payload (e.g. Pipeline: [steps...])
                return target(_recurse_value(value))
        return {k: _recurse_value(v) for k, v in definition.items()}

    if isinstance(definition, (list, tuple)):
        return [from_definition(item) for item in definition]

    return definition


def _recurse_value(value: Any) -> Any:
    """Recurse into a kwarg value, instantiating nested definitions."""
    if isinstance(value, Mapping):
        if len(value) == 1 and _looks_like_import_path(next(iter(value))):
            return from_definition(value)
        return {k: _recurse_value(v) for k, v in value.items()}
    if isinstance(value, (list, tuple)):
        return [_recurse_value(v) for v in value]
    if isinstance(value, str) and _looks_like_import_path(value):
        # Strings that are import paths stay strings unless they resolve to a
        # known component; this mirrors the reference's permissiveness for
        # e.g. transformer_funcs referenced by dotted path.
        try:
            target = import_locate(value)
        except (ValueError, ImportError):
            return value
        return target() if isinstance(target, type) else target
    return value


def into_definition(obj: Any) -> Any:
    """Inverse of :func:`from_definition` for fitted/unfitted components.

    Reference equivalent: ``serializer.pipeline_into_definition``.  Relies on
    components exposing ``get_params()`` (the gordo/sklearn contract).
    """
    if obj is None or isinstance(obj, (int, float, bool, str)):
        return obj
    if isinstance(obj, Mapping):
        return {k: into_definition(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [into_definition(v) for v in obj]
    if hasattr(obj, "get_params"):
        cls = type(obj)
        path = f"{cls.__module__}.{cls.__qualname__}"
        params = {
            k: into_definition(v)
            for k, v in obj.get_params(deep=False).items()
            if v is not None
        }
        return {path: params}
    if callable(obj):
        return f"{obj.__module__}.{obj.__qualname__}"
    return copy.deepcopy(obj)


# Parity-named wrappers (the reference exports these names).
pipeline_from_definition = from_definition
pipeline_into_definition = into_definition
