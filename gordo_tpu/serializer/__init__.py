"""Model artifact serialization.

Reference equivalent: ``gordo_components/serializer/__init__.py`` —
``dump(model, dir, metadata=...)`` / ``load(dir)`` / ``load_metadata(dir)``.

The reference walks the sklearn pipeline into nested ``n_step=..._class=...``
directories of pickles with Keras weights riding on HDF5 ``__getstate__``.
Here the artifact layout is flat and TPU-native:

``````
<dir>/
  metadata.json      build + dataset + CV metadata (primary observability)
  definition.yaml    into_definition() of the model (config round-trip)
  model.pkl          pickled component graph; array leaves are host numpy
``````

Components implement ``__getstate__``/``__setstate__`` so jax arrays are
pulled to host numpy before pickling (see ``gordo_tpu.utils.trees.to_host``),
keeping artifacts device-independent: a model built on TPU loads on CPU and
vice versa.
"""

from __future__ import annotations

import json
import os
import pickle
from typing import Any, Optional

import yaml

from gordo_tpu.serializer.definition import (  # noqa: F401
    from_definition,
    into_definition,
    pipeline_from_definition,
    pipeline_into_definition,
)

METADATA_FILE = "metadata.json"
DEFINITION_FILE = "definition.yaml"
MODEL_FILE = "model.pkl"


def dump(
    model: Any,
    dest_dir: str,
    metadata: Optional[dict] = None,
    definition: Optional[str] = None,
) -> str:
    """Serialize ``model`` (+ metadata) into ``dest_dir``; returns the dir.

    ``definition``: pre-serialized ``definition.yaml`` text (see
    :func:`render_definition`) written verbatim instead of re-deriving it
    — the fleet writer pool computes it once per homogeneous chunk
    (machines in a chunk share one model config, so the bytes are
    identical by construction) rather than walking the same config
    hundreds of times.
    """
    os.makedirs(dest_dir, exist_ok=True)
    with open(os.path.join(dest_dir, MODEL_FILE), "wb") as f:
        pickle.dump(model, f)
    if definition is None:
        definition = render_definition(model)
    if definition is not None:
        with open(os.path.join(dest_dir, DEFINITION_FILE), "w") as f:
            f.write(definition)
    if metadata is not None:
        with open(os.path.join(dest_dir, METADATA_FILE), "w") as f:
            json.dump(metadata, f, indent=2, default=str)
    return dest_dir


def render_definition(model: Any) -> Optional[str]:
    """The ``definition.yaml`` text for ``model``, or None when the model
    doesn't round-trip (best-effort convenience, as before)."""
    try:
        return yaml.safe_dump(into_definition(model), sort_keys=False)
    except Exception:
        return None


def load(source_dir: str) -> Any:
    """Load a model serialized by :func:`dump`."""
    with open(os.path.join(source_dir, MODEL_FILE), "rb") as f:
        return pickle.load(f)


def load_metadata(source_dir: str) -> dict:
    """Load the metadata JSON written next to the model artifact."""
    path = os.path.join(source_dir, METADATA_FILE)
    if not os.path.exists(path):
        return {}
    with open(path) as f:
        return json.load(f)


def dumps(model: Any) -> bytes:
    """In-memory serialization (reference: ``serializer.dumps``)."""
    return pickle.dumps(model)


def loads(data: bytes) -> Any:
    return pickle.loads(data)
