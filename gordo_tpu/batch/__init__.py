"""Backfill plane: offline fleet-scale historical scoring.

A device-saturating bulk path over the server's exact fused programs —
no HTTP anywhere in this package (lint-gated): models from the artifact
plane, data from dataset providers, scores into the columnar
``.gordo-scores/`` archive.  See ``docs/batch.md``.
"""

from gordo_tpu.batch.archive import (  # noqa: F401
    AGGREGATE_STATS,
    ARCHIVE_DIR,
    ArchiveError,
    ArchivePlanError,
    ScoreArchive,
    archive_root,
)
from gordo_tpu.batch.compact import (  # noqa: F401
    compact_scores,
    gc_scores,
    ls_scores,
    plan_compaction,
    stat_scores,
)
from gordo_tpu.batch.runner import (  # noqa: F401
    BackfillConfig,
    BackfillError,
    chunk_windows,
    resolve_shard,
    run_backfill,
)

__all__ = [
    "AGGREGATE_STATS",
    "ARCHIVE_DIR",
    "ArchiveError",
    "ArchivePlanError",
    "ScoreArchive",
    "archive_root",
    "compact_scores",
    "gc_scores",
    "ls_scores",
    "plan_compaction",
    "stat_scores",
    "BackfillConfig",
    "BackfillError",
    "chunk_windows",
    "resolve_shard",
    "run_backfill",
]
