"""Columnar per-machine score archive — the backfill plane's output.

Reference status: absent upstream — the reference stack had no offline
scoring product at all; every score it ever produced was an HTTP
response body that evaporated with the connection.  The archive is what
makes backfill a *workload* instead of a loop over requests: months of
per-machine anomaly scores land as mmap-able columnar segments that
``client.score_history`` and
``telemetry.fleet_health.baselines_from_archive`` read without a server.

Format (under ``<root>/.gordo-scores/``), borrowing the artifact plane's
pack durability idioms (magic + version header, page alignment, tmp +
``os.replace`` + dir fsync, one flock-serialized JSON index):

- ``index.json`` — the archive plan (project, period, resolution,
  chunking geometry, machine roster) plus one completion record per
  written ``(chunk, shard)``.  Rewritten atomically under ``.lock``, so
  shards of one backfill job share it safely.
- ``chunk-<c>-s<s>.seg`` — one segment per (time-chunk, shard):
  ``GSA1`` magic, u32 header length, a JSON header mapping machine →
  column table, zero padding to a 4096 boundary, then the raw column
  payloads (64-byte aligned) for every machine the shard scored in that
  window.  Columns per machine: ``index-ns`` (int64 UTC nanoseconds of
  each scored row), ``total-anomaly-score`` (float32 ``[rows]``) and
  ``tag-anomaly-scores`` (float32 ``[rows, n_tags]``).

Resumability contract: a chunk either has a completion record (its
segment is fully durable — the record is written only after the segment
fsyncs) or it does not exist.  A re-run lists the records, skips what is
done, and recomputes the rest; the deterministic chunk plan makes the
result byte-identical to an uninterrupted run (pinned by test).

This module is host-side I/O only: no jax, no HTTP (the batch-plane
lint gate bans server/client imports from the whole package).
"""

from __future__ import annotations

import fcntl
import json
import os
import struct
import time
from typing import Any, Callable, Dict, Iterable, List, Optional, Tuple

import numpy as np

from gordo_tpu.utils.disk_registry import fsync_dir

#: archive directory under the output root (sits next to the artifact
#: plane's sidecars: ``.gordo-telemetry``, ``.gordo-fleet-health``, ...)
ARCHIVE_DIR = ".gordo-scores"

INDEX_FILE = "index.json"
LOCK_FILE = ".lock"

SEGMENT_MAGIC = b"GSA1"
SEGMENT_VERSION = 1
ARCHIVE_VERSION = 1

#: page size segments align their payload base to (mmap granularity)
PAGE = 4096
#: per-column alignment inside the payload (cacheline-friendly slices)
ALIGN = 64

#: the three columns every machine entry carries, in layout order
COLUMNS = ("index-ns", "total-anomaly-score", "tag-anomaly-scores")


class ArchiveError(RuntimeError):
    """Corrupt or unreadable archive state."""


class ArchivePlanError(ValueError):
    """A resume attempted with a plan incompatible with the existing
    archive (different period / resolution / chunk geometry): scoring
    into it would silently mix windows, so it is refused."""


def archive_root(root: str) -> str:
    return os.path.join(root, ARCHIVE_DIR)


def _segment_name(chunk: int, shard: int) -> str:
    return f"chunk-{chunk:05d}-s{shard:02d}.seg"


def _chunk_key(chunk: int, shard: int) -> str:
    return f"{chunk}/{shard}"


# ---------------------------------------------------------------------------
# index read/modify/write (flock-serialized, like the artifact pack index)
# ---------------------------------------------------------------------------

def _read_index(directory: str) -> Optional[Dict[str, Any]]:
    path = os.path.join(directory, INDEX_FILE)
    try:
        with open(path) as fh:
            doc = json.load(fh)
    except FileNotFoundError:
        return None
    except (OSError, ValueError) as exc:
        raise ArchiveError(f"unreadable score-archive index {path}: {exc}")
    if doc.get("version") != ARCHIVE_VERSION:
        raise ArchiveError(
            f"score-archive index {path} has version {doc.get('version')!r};"
            f" this reader speaks version {ARCHIVE_VERSION}"
        )
    return doc


def _locked_index_update(
    directory: str, mutate: Callable[[Dict[str, Any]], None]
) -> Dict[str, Any]:
    """Read-modify-write ``index.json`` under an exclusive flock, swapping
    the new index in atomically (tmp + rename + dir fsync) — concurrent
    shards of one backfill job write disjoint completion records into
    ONE shared index."""
    os.makedirs(directory, exist_ok=True)
    with open(os.path.join(directory, LOCK_FILE), "a+") as lock:
        fcntl.flock(lock.fileno(), fcntl.LOCK_EX)
        doc = _read_index(directory) or {
            "version": ARCHIVE_VERSION,
            "machines": [],
            "chunks": {},
        }
        mutate(doc)
        path = os.path.join(directory, INDEX_FILE)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as fh:
            json.dump(doc, fh, indent=1, sort_keys=True)
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, path)
        fsync_dir(directory)
        return doc


# ---------------------------------------------------------------------------
# segment encode/decode
# ---------------------------------------------------------------------------

def _encode_segment(
    chunk: int,
    shard: int,
    per_machine: Dict[str, Dict[str, Any]],
) -> Tuple[bytes, Dict[str, Any]]:
    """Serialize one chunk's machine columns: returns ``(bytes, header)``.

    ``per_machine[name]`` carries the three COLUMNS arrays plus ``tags``
    (the column names of the tag-anomaly matrix, for self-describing
    reads)."""
    header: Dict[str, Any] = {
        "gordo-score-segment": SEGMENT_VERSION,
        "chunk": int(chunk),
        "shard": int(shard),
        "machines": {},
    }
    layout: List[Tuple[int, np.ndarray]] = []
    pos = 0
    for name in sorted(per_machine):
        rec = per_machine[name]
        entry: Dict[str, Any] = {
            "tags": list(rec.get("tags") or ()),
            "columns": {},
        }
        for col in COLUMNS:
            arr = np.ascontiguousarray(rec[col])
            pos = (pos + ALIGN - 1) // ALIGN * ALIGN
            entry["columns"][col] = {
                "offset": pos,
                "dtype": str(arr.dtype),
                "shape": list(arr.shape),
            }
            layout.append((pos, arr))
            pos += arr.nbytes
        entry["rows"] = int(np.asarray(rec["index-ns"]).shape[0])
        header["machines"][name] = entry

    head = json.dumps(header, sort_keys=True).encode()
    prefix = SEGMENT_MAGIC + struct.pack("<I", len(head)) + head
    payload_base = (len(prefix) + PAGE - 1) // PAGE * PAGE
    buf = bytearray(payload_base + pos)
    buf[: len(prefix)] = prefix
    for off, arr in layout:
        raw = arr.tobytes()
        buf[payload_base + off: payload_base + off + len(raw)] = raw
    return bytes(buf), header


def _read_segment_header(path: str) -> Tuple[Dict[str, Any], int]:
    """``(header, payload_base)`` of a segment file."""
    with open(path, "rb") as fh:
        magic = fh.read(4)
        if magic != SEGMENT_MAGIC:
            raise ArchiveError(f"{path}: bad segment magic {magic!r}")
        (hlen,) = struct.unpack("<I", fh.read(4))
        header = json.loads(fh.read(hlen).decode())
    if header.get("gordo-score-segment") != SEGMENT_VERSION:
        raise ArchiveError(
            f"{path}: segment version {header.get('gordo-score-segment')!r}"
            f" != {SEGMENT_VERSION}"
        )
    payload_base = (8 + hlen + PAGE - 1) // PAGE * PAGE
    return header, payload_base


def _mmap_column(path: str, payload_base: int, col: Dict[str, Any]):
    return np.memmap(
        path,
        dtype=np.dtype(col["dtype"]),
        mode="r",
        offset=payload_base + int(col["offset"]),
        shape=tuple(col["shape"]),
    )


# ---------------------------------------------------------------------------
# the archive object
# ---------------------------------------------------------------------------

class ScoreArchive:
    """One backfill's score archive under ``<root>/.gordo-scores/``."""

    def __init__(self, root: str):
        self.root = root
        self.directory = archive_root(root)

    # -- plan / creation -----------------------------------------------------

    @classmethod
    def create(
        cls,
        root: str,
        *,
        project: str,
        start: str,
        end: str,
        resolution: str,
        chunk_rows: int,
        n_chunks: int,
        dtype: str,
        machines: Iterable[str],
        shard: Tuple[int, int] = (0, 1),
    ) -> "ScoreArchive":
        """Create (or compatibly resume) the archive plan.

        Idempotent under the index flock: the first caller writes the
        plan, later callers (re-runs, sibling shards) verify theirs
        matches and merge their machine roster in.  A mismatched plan
        raises :class:`ArchivePlanError` — never silently mixes runs."""
        arch = cls(root)
        plan = {
            "project": project,
            "start": str(start),
            "end": str(end),
            "resolution": str(resolution),
            "chunk-rows": int(chunk_rows),
            "n-chunks": int(n_chunks),
            "dtype": str(dtype),
        }

        def mutate(doc: Dict[str, Any]) -> None:
            existing = doc.get("plan")
            if existing is not None and existing != plan:
                diff = {
                    k: (existing.get(k), plan[k])
                    for k in plan
                    if existing.get(k) != plan[k]
                }
                raise ArchivePlanError(
                    f"score archive at {arch.directory} was written with a "
                    f"different plan; differing fields (have, want): {diff}."
                    " Point --archive-dir somewhere fresh or delete the old"
                    " archive."
                )
            doc["plan"] = plan
            doc["machines"] = sorted(
                set(doc.get("machines") or ()) | set(machines)
            )
            shards = doc.setdefault("shards", {})
            shards[str(shard[0])] = {"of": int(shard[1])}

        _locked_index_update(arch.directory, mutate)
        return arch

    def index(self) -> Optional[Dict[str, Any]]:
        return _read_index(self.directory)

    def plan(self) -> Optional[Dict[str, Any]]:
        doc = self.index()
        return doc.get("plan") if doc else None

    def machines(self) -> List[str]:
        doc = self.index()
        return list(doc.get("machines") or ()) if doc else []

    # -- completion records --------------------------------------------------

    def chunk_records(self) -> Dict[str, Dict[str, Any]]:
        doc = self.index()
        return dict(doc.get("chunks") or {}) if doc else {}

    def completed_chunks(self, shard: int = 0) -> set:
        """Chunk indices this shard has durable completion records for."""
        done = set()
        for key, rec in self.chunk_records().items():
            c, s = key.split("/")
            if int(s) == int(shard):
                done.add(int(c))
        return done

    # -- writing -------------------------------------------------------------

    def write_chunk(
        self,
        chunk: int,
        per_machine: Dict[str, Dict[str, Any]],
        shard: int = 0,
        meta: Optional[Dict[str, Any]] = None,
    ) -> Optional[str]:
        """Durably write one chunk's columns, then its completion record.

        Ordering is the resumability contract: segment bytes fsync
        BEFORE the record lands in the index, so a record's existence
        proves its segment is whole.  An empty chunk (no machine had
        rows in the window) records completion with no segment."""
        os.makedirs(self.directory, exist_ok=True)
        fname: Optional[str] = None
        rows = 0
        if per_machine:
            fname = _segment_name(chunk, shard)
            blob, header = _encode_segment(chunk, shard, per_machine)
            rows = sum(
                e["rows"] for e in header["machines"].values()
            )
            path = os.path.join(self.directory, fname)
            tmp = f"{path}.tmp.{os.getpid()}"
            with open(tmp, "wb") as fh:
                fh.write(blob)
                fh.flush()
                os.fsync(fh.fileno())
            os.replace(tmp, path)
            fsync_dir(self.directory)

        record = {
            "segment": fname,
            "machines": len(per_machine),
            "rows": int(rows),
            "written-at": time.time(),
        }
        if meta:
            record.update(meta)

        def mutate(doc: Dict[str, Any]) -> None:
            doc.setdefault("chunks", {})[_chunk_key(chunk, shard)] = record

        _locked_index_update(self.directory, mutate)
        return fname

    # -- reading -------------------------------------------------------------

    def _completed_segments(self) -> List[Tuple[int, int, str]]:
        """``(chunk, shard, path)`` of every recorded segment, in chunk
        order (shard as tiebreak) — concatenation order for reads."""
        out = []
        for key, rec in self.chunk_records().items():
            if not rec.get("segment"):
                continue
            c, s = key.split("/")
            out.append(
                (int(c), int(s), os.path.join(self.directory, rec["segment"]))
            )
        return sorted(out)

    def read_machine(
        self,
        name: str,
        start: Optional[Any] = None,
        end: Optional[Any] = None,
    ) -> Optional[Dict[str, Any]]:
        """One machine's scored history across every completed chunk.

        Returns ``{"index-ns", "total-anomaly-score",
        "tag-anomaly-scores", "tags"}`` with rows concatenated in time
        order, optionally clipped to ``[start, end)`` (anything
        ``pd.Timestamp`` accepts), or None when the archive holds no
        rows for the machine."""
        idx_parts: List[np.ndarray] = []
        tot_parts: List[np.ndarray] = []
        tag_parts: List[np.ndarray] = []
        tags: List[str] = []
        for _c, _s, path in self._completed_segments():
            try:
                header, base = _read_segment_header(path)
            except FileNotFoundError:
                raise ArchiveError(
                    f"{path}: completion record exists but segment is "
                    "missing — archive is torn; delete and re-run"
                )
            entry = header["machines"].get(name)
            if entry is None:
                continue
            cols = entry["columns"]
            idx_parts.append(
                np.asarray(_mmap_column(path, base, cols["index-ns"]))
            )
            tot_parts.append(
                np.asarray(
                    _mmap_column(path, base, cols["total-anomaly-score"])
                )
            )
            tag_parts.append(
                np.asarray(
                    _mmap_column(path, base, cols["tag-anomaly-scores"])
                )
            )
            tags = tags or list(entry.get("tags") or ())
        if not idx_parts:
            return None
        index_ns = np.concatenate(idx_parts)
        total = np.concatenate(tot_parts)
        tag_scores = np.concatenate(tag_parts)
        if start is not None or end is not None:
            import pandas as pd

            lo = (
                -np.inf if start is None
                else pd.Timestamp(start).tz_localize("UTC").value
                if pd.Timestamp(start).tzinfo is None
                else pd.Timestamp(start).value
            )
            hi = (
                np.inf if end is None
                else pd.Timestamp(end).tz_localize("UTC").value
                if pd.Timestamp(end).tzinfo is None
                else pd.Timestamp(end).value
            )
            keep = (index_ns >= lo) & (index_ns < hi)
            index_ns, total, tag_scores = (
                index_ns[keep], total[keep], tag_scores[keep]
            )
        return {
            "index-ns": index_ns,
            "total-anomaly-score": total,
            "tag-anomaly-scores": tag_scores,
            "tags": tags,
        }

    def summary(self) -> Dict[str, Any]:
        doc = self.index() or {}
        chunks = doc.get("chunks") or {}
        return {
            "directory": self.directory,
            "plan": doc.get("plan"),
            "machines": len(doc.get("machines") or ()),
            "chunks-completed": len(chunks),
            "rows": sum(int(r.get("rows", 0)) for r in chunks.values()),
            "segments": sum(1 for r in chunks.values() if r.get("segment")),
        }
