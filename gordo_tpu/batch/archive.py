"""Columnar per-machine score archive — the backfill plane's output.

Reference status: absent upstream — the reference stack had no offline
scoring product at all; every score it ever produced was an HTTP
response body that evaporated with the connection.  The archive is what
makes backfill a *workload* instead of a loop over requests: months of
per-machine anomaly scores land as mmap-able columnar segments that
``client.score_history`` and
``telemetry.fleet_health.baselines_from_archive`` read without a server.

Format (under ``<root>/.gordo-scores/``), borrowing the artifact plane's
pack durability idioms (magic + version header, page alignment, tmp +
``os.replace`` + dir fsync, one flock-serialized JSON index):

- ``index.json`` — the archive plan (project, period, resolution,
  chunking geometry, machine roster) plus one completion record per
  written ``(chunk, shard)``.  Rewritten atomically under ``.lock``, so
  shards of one backfill job share it safely.
- ``chunk-<c>-s<s>.seg`` — one segment per (time-chunk, shard):
  ``GSA1`` magic, u32 header length, a JSON header mapping machine →
  column table, zero padding to a 4096 boundary, then the raw column
  payloads (64-byte aligned) for every machine the shard scored in that
  window.  Columns per machine: ``index-ns`` (int64 UTC nanoseconds of
  each scored row), ``total-anomaly-score`` (float32 ``[rows]``) and
  ``tag-anomaly-scores`` (float32 ``[rows, n_tags]``).
- ``period-<YYYYmmddTHHMMSS>.seg`` — a compacted period file (same GSA1
  layout, one per time partition): ``gordo scores compact``
  (:mod:`gordo_tpu.batch.compact`) merges every chunk segment whose
  window starts inside the partition into one segment, across shards,
  with each machine's rows concatenated in chunk order — so reads are
  byte-identical pre/post compaction.  The index's ``periods`` table
  maps partition key → {segment, chunks, rows}; merged chunk records
  keep their completion entry (the resume ledger) with ``segment``
  nulled and ``period`` pointing at the partition that absorbed them.

Resumability contract: a chunk either has a completion record (its
segment is fully durable — the record is written only after the segment
fsyncs) or it does not exist.  A re-run lists the records, skips what is
done, and recomputes the rest; the deterministic chunk plan makes the
result byte-identical to an uninterrupted run (pinned by test).
Compaction extends the contract: a period file is fsynced and flipped
into the index before the chunk segments it replaces are unlinked, so a
kill mid-compact never loses a completed period (chaos-pinned).

This module is host-side I/O only: no jax, no HTTP (the batch-plane
lint gate bans server/client imports from the whole package).
"""

from __future__ import annotations

import fcntl
import json
import os
import struct
import threading
import time
from collections import OrderedDict
from typing import Any, Callable, Dict, Iterable, List, Optional, Tuple

import numpy as np

from gordo_tpu.utils.disk_registry import fsync_dir

#: archive directory under the output root (sits next to the artifact
#: plane's sidecars: ``.gordo-telemetry``, ``.gordo-fleet-health``, ...)
ARCHIVE_DIR = ".gordo-scores"

INDEX_FILE = "index.json"
LOCK_FILE = ".lock"

SEGMENT_MAGIC = b"GSA1"
SEGMENT_VERSION = 1
ARCHIVE_VERSION = 1

#: page size segments align their payload base to (mmap granularity)
PAGE = 4096
#: per-column alignment inside the payload (cacheline-friendly slices)
ALIGN = 64

#: the three columns every machine entry carries, in layout order
COLUMNS = ("index-ns", "total-anomaly-score", "tag-anomaly-scores")

#: default stat set of :meth:`ScoreArchive.aggregate` (any ``pNN``
#: percentile in 1..99 is accepted beyond these)
AGGREGATE_STATS = ("count", "mean", "max", "p50", "p90", "p99", "exceed")


def _quantile_q(stat: str) -> Optional[float]:
    """``"p99" -> 0.99`` for percentile stat names, else None."""
    if len(stat) >= 2 and stat[0] == "p" and stat[1:].isdigit():
        n = int(stat[1:])
        if 1 <= n <= 99:
            return n / 100.0
    return None


class ArchiveError(RuntimeError):
    """Corrupt or unreadable archive state."""


class ArchivePlanError(ValueError):
    """A resume attempted with a plan incompatible with the existing
    archive (different period / resolution / chunk geometry): scoring
    into it would silently mix windows, so it is refused."""


def archive_root(root: str) -> str:
    return os.path.join(root, ARCHIVE_DIR)


def _segment_name(chunk: int, shard: int) -> str:
    return f"chunk-{chunk:05d}-s{shard:02d}.seg"


def _chunk_key(chunk: int, shard: int) -> str:
    return f"{chunk}/{shard}"


def _period_name(key: str) -> str:
    """File name of a compacted period partition (key = the partition's
    UTC start stamped ``YYYYmmddTHHMMSS`` — lexical order IS time
    order)."""
    return f"period-{key}.seg"


def _ts_ns(value: Any) -> int:
    """UTC nanoseconds of anything ``pd.Timestamp`` accepts (naive
    values are taken as UTC, matching ``read_machine``'s clip)."""
    import pandas as pd

    ts = pd.Timestamp(value)
    if ts.tzinfo is None:
        ts = ts.tz_localize("UTC")
    return int(ts.value)


# ---------------------------------------------------------------------------
# index read/modify/write (flock-serialized, like the artifact pack index)
# ---------------------------------------------------------------------------

def _read_index(directory: str) -> Optional[Dict[str, Any]]:
    path = os.path.join(directory, INDEX_FILE)
    try:
        with open(path) as fh:
            doc = json.load(fh)
    except FileNotFoundError:
        return None
    except (OSError, ValueError) as exc:
        raise ArchiveError(f"unreadable score-archive index {path}: {exc}")
    if doc.get("version") != ARCHIVE_VERSION:
        raise ArchiveError(
            f"score-archive index {path} has version {doc.get('version')!r};"
            f" this reader speaks version {ARCHIVE_VERSION}"
        )
    return doc


def _locked_index_update(
    directory: str, mutate: Callable[[Dict[str, Any]], None]
) -> Dict[str, Any]:
    """Read-modify-write ``index.json`` under an exclusive flock, swapping
    the new index in atomically (tmp + rename + dir fsync) — concurrent
    shards of one backfill job write disjoint completion records into
    ONE shared index."""
    os.makedirs(directory, exist_ok=True)
    with open(os.path.join(directory, LOCK_FILE), "a+") as lock:
        fcntl.flock(lock.fileno(), fcntl.LOCK_EX)
        doc = _read_index(directory) or {
            "version": ARCHIVE_VERSION,
            "machines": [],
            "chunks": {},
        }
        mutate(doc)
        path = os.path.join(directory, INDEX_FILE)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as fh:
            json.dump(doc, fh, indent=1, sort_keys=True)
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, path)
        fsync_dir(directory)
        return doc


# ---------------------------------------------------------------------------
# segment encode/decode
# ---------------------------------------------------------------------------

def _segment_layout(
    chunk: int,
    shard: int,
    machines_meta: Dict[str, Dict[str, Any]],
    extra: Optional[Dict[str, Any]] = None,
) -> Tuple[Dict[str, Any], bytes, int, int]:
    """Header + byte layout of a segment WITHOUT touching column data.

    ``machines_meta[name]`` is ``{"tags": [...], "columns": {col:
    (dtype_str, shape_tuple)}}``.  Returns ``(header, prefix,
    payload_base, payload_bytes)`` — the single source of truth for
    column placement, shared by the in-memory chunk encoder and the
    streaming period writer so both produce identical bytes."""
    header: Dict[str, Any] = {
        "gordo-score-segment": SEGMENT_VERSION,
        "chunk": int(chunk),
        "shard": int(shard),
        "machines": {},
    }
    if extra:
        header.update(extra)
    pos = 0
    for name in sorted(machines_meta):
        rec = machines_meta[name]
        entry: Dict[str, Any] = {
            "tags": list(rec.get("tags") or ()),
            "columns": {},
        }
        for col in COLUMNS:
            dtype_str, shape = rec["columns"][col]
            pos = (pos + ALIGN - 1) // ALIGN * ALIGN
            entry["columns"][col] = {
                "offset": pos,
                "dtype": dtype_str,
                "shape": list(shape),
            }
            pos += int(
                np.dtype(dtype_str).itemsize
                * np.prod(shape, dtype=np.int64)
            )
        entry["rows"] = int(rec["columns"]["index-ns"][1][0])
        header["machines"][name] = entry

    head = json.dumps(header, sort_keys=True).encode()
    prefix = SEGMENT_MAGIC + struct.pack("<I", len(head)) + head
    payload_base = (len(prefix) + PAGE - 1) // PAGE * PAGE
    return header, prefix, payload_base, pos


def _encode_segment(
    chunk: int,
    shard: int,
    per_machine: Dict[str, Dict[str, Any]],
    extra: Optional[Dict[str, Any]] = None,
) -> Tuple[bytes, Dict[str, Any]]:
    """Serialize one chunk's machine columns: returns ``(bytes, header)``.

    ``per_machine[name]`` carries the three COLUMNS arrays plus ``tags``
    (the column names of the tag-anomaly matrix, for self-describing
    reads).  ``extra`` merges additional header fields (compaction
    stamps the period key and merged chunk list)."""
    arrays = {
        name: {
            col: np.ascontiguousarray(rec[col]) for col in COLUMNS
        }
        for name, rec in per_machine.items()
    }
    meta = {
        name: {
            "tags": per_machine[name].get("tags"),
            "columns": {
                col: (str(a.dtype), a.shape)
                for col, a in cols.items()
            },
        }
        for name, cols in arrays.items()
    }
    header, prefix, payload_base, payload = _segment_layout(
        chunk, shard, meta, extra
    )
    buf = bytearray(payload_base + payload)
    buf[: len(prefix)] = prefix
    for name, cols in arrays.items():
        entry = header["machines"][name]["columns"]
        for col, arr in cols.items():
            off = payload_base + int(entry[col]["offset"])
            buf[off: off + arr.nbytes] = arr.tobytes()
    return bytes(buf), header


def _read_segment_header(path: str) -> Tuple[Dict[str, Any], int]:
    """``(header, payload_base)`` of a segment file."""
    with open(path, "rb") as fh:
        magic = fh.read(4)
        if magic != SEGMENT_MAGIC:
            raise ArchiveError(f"{path}: bad segment magic {magic!r}")
        (hlen,) = struct.unpack("<I", fh.read(4))
        header = json.loads(fh.read(hlen).decode())
    if header.get("gordo-score-segment") != SEGMENT_VERSION:
        raise ArchiveError(
            f"{path}: segment version {header.get('gordo-score-segment')!r}"
            f" != {SEGMENT_VERSION}"
        )
    payload_base = (8 + hlen + PAGE - 1) // PAGE * PAGE
    return header, payload_base


def _mmap_column(path: str, payload_base: int, col: Dict[str, Any]):
    return np.memmap(
        path,
        dtype=np.dtype(col["dtype"]),
        mode="r",
        offset=payload_base + int(col["offset"]),
        shape=tuple(col["shape"]),
    )


#: parsed segment headers keyed by (dev, inode, size, mtime_ns).
#: Segments are IMMUTABLE once visible — writers publish with
#: os.replace (fresh inode, fresh mtime), so a matching key proves the
#: cached parse is current.  Bounded LRU: a long-lived server watching
#: a compacting archive must not pin headers of long-unlinked segment
#: files forever.
_HEADER_CACHE: "OrderedDict[Tuple[int, int, int, int], Tuple[Dict[str, Any], int]]" = (  # noqa: E501
    OrderedDict()
)
_HEADER_CACHE_MAX = 512
_HEADER_CACHE_LOCK = threading.Lock()


def _segment_header(path: str) -> Tuple[Dict[str, Any], int]:
    """``(header, payload_base)`` of a segment via the immutability cache.

    Fleet-scale reads (aggregate / read_machine over N machines) touch
    every segment once per MACHINE, and the header JSON itself grows
    with the roster — re-parsing it per touch makes the scan quadratic
    in fleet size (measured r20: 74% of a 512-machine aggregate was
    header re-parsing).  The cache turns that into one parse per
    segment per generation."""
    st = os.stat(path)
    key = (st.st_dev, st.st_ino, st.st_size, st.st_mtime_ns)
    with _HEADER_CACHE_LOCK:
        hit = _HEADER_CACHE.get(key)
        if hit is not None:
            _HEADER_CACHE.move_to_end(key)
            return hit
    parsed = _read_segment_header(path)
    with _HEADER_CACHE_LOCK:
        _HEADER_CACHE[key] = parsed
        _HEADER_CACHE.move_to_end(key)
        while len(_HEADER_CACHE) > _HEADER_CACHE_MAX:
            _HEADER_CACHE.popitem(last=False)
    return parsed


def _segment_buffer(path: str) -> np.ndarray:
    """The whole segment mmapped once as raw bytes.  Fleet-scale scans
    slice per-machine column views out of this with :func:`_column_view`
    instead of paying an open+mmap syscall pair per (machine, column) —
    ~45µs each, the second quadratic term after header parsing.

    Returned as a PLAIN ndarray view (the mmap stays alive through
    ``.base``): ufuncs and ``np.concatenate`` drop into subclass-safe
    slow paths when any operand is an ``np.memmap``, measured 6.6x
    slower than the same copy through a base-class view."""
    return np.asarray(np.memmap(path, dtype=np.uint8, mode="r"))


def _column_view(
    buf: np.ndarray, payload_base: int, col: Dict[str, Any]
) -> np.ndarray:
    """Zero-copy ndarray view of one column inside a segment buffer."""
    dtype = np.dtype(col["dtype"])
    shape = tuple(col["shape"])
    start = payload_base + int(col["offset"])
    n = dtype.itemsize
    for dim in shape:
        n *= int(dim)
    return buf[start: start + n].view(dtype).reshape(shape)


# ---------------------------------------------------------------------------
# the archive object
# ---------------------------------------------------------------------------

class ScoreArchive:
    """One backfill's score archive under ``<root>/.gordo-scores/``."""

    def __init__(self, root: str):
        self.root = root
        self.directory = archive_root(root)

    # -- plan / creation -----------------------------------------------------

    @classmethod
    def create(
        cls,
        root: str,
        *,
        project: str,
        start: str,
        end: str,
        resolution: str,
        chunk_rows: int,
        n_chunks: int,
        dtype: str,
        machines: Iterable[str],
        shard: Tuple[int, int] = (0, 1),
    ) -> "ScoreArchive":
        """Create (or compatibly resume) the archive plan.

        Idempotent under the index flock: the first caller writes the
        plan, later callers (re-runs, sibling shards) verify theirs
        matches and merge their machine roster in.  A mismatched plan
        raises :class:`ArchivePlanError` — never silently mixes runs."""
        arch = cls(root)
        plan = {
            "project": project,
            "start": str(start),
            "end": str(end),
            "resolution": str(resolution),
            "chunk-rows": int(chunk_rows),
            "n-chunks": int(n_chunks),
            "dtype": str(dtype),
        }

        def mutate(doc: Dict[str, Any]) -> None:
            existing = doc.get("plan")
            if existing is not None and existing != plan:
                diff = {
                    k: (existing.get(k), plan[k])
                    for k in plan
                    if existing.get(k) != plan[k]
                }
                raise ArchivePlanError(
                    f"score archive at {arch.directory} was written with a "
                    f"different plan; differing fields (have, want): {diff}."
                    " Point --archive-dir somewhere fresh or delete the old"
                    " archive."
                )
            doc["plan"] = plan
            doc["machines"] = sorted(
                set(doc.get("machines") or ()) | set(machines)
            )
            shards = doc.setdefault("shards", {})
            shards[str(shard[0])] = {"of": int(shard[1])}

        _locked_index_update(arch.directory, mutate)
        return arch

    def index(self) -> Optional[Dict[str, Any]]:
        return _read_index(self.directory)

    def plan(self) -> Optional[Dict[str, Any]]:
        doc = self.index()
        return doc.get("plan") if doc else None

    def machines(self) -> List[str]:
        doc = self.index()
        return list(doc.get("machines") or ()) if doc else []

    # -- completion records --------------------------------------------------

    def chunk_records(self) -> Dict[str, Dict[str, Any]]:
        doc = self.index()
        return dict(doc.get("chunks") or {}) if doc else {}

    def completed_chunks(self, shard: int = 0) -> set:
        """Chunk indices this shard has durable completion records for."""
        done = set()
        for key, rec in self.chunk_records().items():
            c, s = key.split("/")
            if int(s) == int(shard):
                done.add(int(c))
        return done

    # -- writing -------------------------------------------------------------

    def write_chunk(
        self,
        chunk: int,
        per_machine: Dict[str, Dict[str, Any]],
        shard: int = 0,
        meta: Optional[Dict[str, Any]] = None,
    ) -> Optional[str]:
        """Durably write one chunk's columns, then its completion record.

        Ordering is the resumability contract: segment bytes fsync
        BEFORE the record lands in the index, so a record's existence
        proves its segment is whole.  An empty chunk (no machine had
        rows in the window) records completion with no segment."""
        os.makedirs(self.directory, exist_ok=True)
        fname: Optional[str] = None
        rows = 0
        if per_machine:
            fname = _segment_name(chunk, shard)
            blob, header = _encode_segment(chunk, shard, per_machine)
            rows = sum(
                e["rows"] for e in header["machines"].values()
            )
            path = os.path.join(self.directory, fname)
            tmp = f"{path}.tmp.{os.getpid()}"
            with open(tmp, "wb") as fh:
                fh.write(blob)
                fh.flush()
                os.fsync(fh.fileno())
            os.replace(tmp, path)
            fsync_dir(self.directory)

        record = {
            "segment": fname,
            "machines": len(per_machine),
            "rows": int(rows),
            "written-at": time.time(),
        }
        if meta:
            record.update(meta)

        def mutate(doc: Dict[str, Any]) -> None:
            doc.setdefault("chunks", {})[_chunk_key(chunk, shard)] = record

        _locked_index_update(self.directory, mutate)
        return fname

    # -- reading -------------------------------------------------------------

    def periods(self) -> Dict[str, Dict[str, Any]]:
        """The compaction table: partition key → {segment, chunks, rows}."""
        doc = self.index()
        return dict(doc.get("periods") or {}) if doc else {}

    def _completed_segments(self) -> List[Tuple[int, int, str]]:
        """``(chunk, shard, path)`` of every recorded chunk segment, in
        chunk order (shard as tiebreak)."""
        out = []
        for key, rec in self.chunk_records().items():
            if not rec.get("segment"):
                continue
            c, s = key.split("/")
            out.append(
                (int(c), int(s), os.path.join(self.directory, rec["segment"]))
            )
        return sorted(out)

    def _data_segments(self) -> List[str]:
        """Every data segment (chunk files AND compacted period files)
        in time order — the concatenation order for reads.  A period
        file sorts at its first merged chunk; its chunks are contiguous
        and disjoint from every surviving chunk segment (compaction only
        absorbs whole periods), so interleaving by (first-chunk, shard)
        reproduces the uncompacted concatenation order exactly — the
        byte-consistency contract."""
        doc = self.index() or {}
        out: List[Tuple[Tuple[int, int], str]] = []
        for key, rec in (doc.get("chunks") or {}).items():
            if not rec.get("segment"):
                continue
            c, s = key.split("/")
            out.append(
                ((int(c), int(s)),
                 os.path.join(self.directory, rec["segment"]))
            )
        for rec in (doc.get("periods") or {}).values():
            first = min(int(c) for c in rec["chunks"])
            out.append(
                ((first, -1), os.path.join(self.directory, rec["segment"]))
            )
        return [path for _key, path in sorted(out)]

    def read_machine(
        self,
        name: str,
        start: Optional[Any] = None,
        end: Optional[Any] = None,
    ) -> Optional[Dict[str, Any]]:
        """One machine's scored history across every completed chunk.

        Returns ``{"index-ns", "total-anomaly-score",
        "tag-anomaly-scores", "tags"}`` with rows concatenated in time
        order, optionally clipped to ``[start, end)`` (anything
        ``pd.Timestamp`` accepts), or None when the archive holds no
        rows for the machine."""
        idx_parts: List[np.ndarray] = []
        tot_parts: List[np.ndarray] = []
        tag_parts: List[np.ndarray] = []
        tags: List[str] = []
        buffers: Dict[str, np.ndarray] = {}
        for path in self._data_segments():
            try:
                header, base = _segment_header(path)
            except FileNotFoundError:
                raise ArchiveError(
                    f"{path}: completion record exists but segment is "
                    "missing — archive is torn; delete and re-run"
                )
            entry = header["machines"].get(name)
            if entry is None:
                continue
            buf = buffers.get(path)
            if buf is None:
                buf = buffers[path] = _segment_buffer(path)
            cols = entry["columns"]
            idx_parts.append(_column_view(buf, base, cols["index-ns"]))
            tot_parts.append(
                _column_view(buf, base, cols["total-anomaly-score"])
            )
            tag_parts.append(
                _column_view(buf, base, cols["tag-anomaly-scores"])
            )
            tags = tags or list(entry.get("tags") or ())
        if not idx_parts:
            return None
        index_ns = np.concatenate(idx_parts)
        total = np.concatenate(tot_parts)
        tag_scores = np.concatenate(tag_parts)
        if start is not None or end is not None:
            import pandas as pd

            lo = (
                -np.inf if start is None
                else pd.Timestamp(start).tz_localize("UTC").value
                if pd.Timestamp(start).tzinfo is None
                else pd.Timestamp(start).value
            )
            hi = (
                np.inf if end is None
                else pd.Timestamp(end).tz_localize("UTC").value
                if pd.Timestamp(end).tzinfo is None
                else pd.Timestamp(end).value
            )
            keep = (index_ns >= lo) & (index_ns < hi)
            index_ns, total, tag_scores = (
                index_ns[keep], total[keep], tag_scores[keep]
            )
        return {
            "index-ns": index_ns,
            "total-anomaly-score": total,
            "tag-anomaly-scores": tag_scores,
            "tags": tags,
        }

    def _machine_series(
        self,
        name: str,
        lo_ns: Optional[int] = None,
        hi_ns: Optional[int] = None,
        segments: Optional[List[str]] = None,
        buffers: Optional[Dict[str, np.ndarray]] = None,
    ) -> Optional[Tuple[np.ndarray, np.ndarray]]:
        """``(index-ns, total-anomaly-score)`` for one machine, clipped
        to ``[lo_ns, hi_ns)`` — the aggregation scan.  Touches ONLY the
        two scalar columns' pages (the tag matrix, ~80% of segment
        bytes, is never faulted in), which is what makes pushdown run at
        mmap scan speed instead of full-archive read speed.

        ``segments`` / ``buffers`` let a fleet-wide caller (aggregate)
        resolve the segment list once and share one mmap per segment
        across every machine instead of re-reading index.json and
        re-mapping per machine."""
        idx_parts: List[np.ndarray] = []
        tot_parts: List[np.ndarray] = []
        if segments is None:
            segments = self._data_segments()
        if buffers is None:
            buffers = {}
        for path in segments:
            try:
                header, base = _segment_header(path)
            except FileNotFoundError:
                raise ArchiveError(
                    f"{path}: completion record exists but segment is "
                    "missing — archive is torn; delete and re-run"
                )
            entry = header["machines"].get(name)
            if entry is None:
                continue
            buf = buffers.get(path)
            if buf is None:
                buf = buffers[path] = _segment_buffer(path)
            cols = entry["columns"]
            idx_parts.append(_column_view(buf, base, cols["index-ns"]))
            tot_parts.append(
                _column_view(buf, base, cols["total-anomaly-score"])
            )
        if not idx_parts:
            return None
        index_ns = np.concatenate(idx_parts)
        total = np.concatenate(tot_parts)
        if lo_ns is not None or hi_ns is not None:
            lo = -(2 ** 63) if lo_ns is None else int(lo_ns)
            hi = 2 ** 63 - 1 if hi_ns is None else int(hi_ns)
            keep = (index_ns >= lo) & (index_ns < hi)
            index_ns, total = index_ns[keep], total[keep]
        return index_ns, total

    def aggregate(
        self,
        machines: Optional[Iterable[str]] = None,
        start: Optional[Any] = None,
        end: Optional[Any] = None,
        *,
        stats: Optional[Iterable[str]] = None,
        period: Any = "1d",
        threshold: float = 1.0,
    ) -> Dict[str, Any]:
        """Per-machine, per-period summary statistics scanned straight
        off the mmap columns — the aggregation pushdown.

        ``period`` is any ``pd.Timedelta`` string (default ``"1d"``);
        periods are epoch-aligned ``[k*period, (k+1)*period)`` windows
        covering ``[start, end)`` (default: the archive plan's span).
        ``stats`` picks from ``count`` / ``mean`` / ``max`` / ``exceed``
        (rows with score strictly above ``threshold``) / ``pNN``
        (N in 1..99).  Percentiles are sketch-resolution upper bounds:
        rows bin into the r14 fleet-health half-octave histogram
        (bit-extraction binning, identical to ``ScoreSketch.observe``)
        and ``pNN`` reports the upper edge of the bucket holding the
        N-th percentile — at most one half-octave above the exact
        sample percentile, and exactly mergeable, so results are
        byte-identical pre/post compaction (rows concatenate in the
        same order either way; pinned by test and bench).

        Returns ``{"machines", "periods", "period", "period-ns",
        "threshold", "start", "end", "stats": {name: [n_machines,
        n_periods] array}}``.  Empty (machine, period) cells read 0 for
        count/exceed and NaN for mean/max/percentiles."""
        import pandas as pd

        from gordo_tpu.telemetry import fleet_health as _sketch

        doc = self.index()
        if not doc or not doc.get("plan"):
            raise ArchiveError(
                f"{self.directory}: no score archive to aggregate"
            )
        plan = doc["plan"]
        wanted = tuple(stats) if stats else AGGREGATE_STATS
        quantiles = {}
        for s in wanted:
            if s in ("count", "mean", "max", "exceed"):
                continue
            q = _quantile_q(s)
            if q is None:
                raise ValueError(
                    f"unknown aggregate stat {s!r}; supported: count,"
                    " mean, max, exceed, p1..p99"
                )
            quantiles[s] = q
        period_ns = int(pd.Timedelta(period).value)
        if period_ns <= 0:
            raise ValueError(
                f"aggregation period must be positive, got {period!r}"
            )
        lo_ns = _ts_ns(plan["start"] if start is None else start)
        hi_ns = _ts_ns(plan["end"] if end is None else end)
        names = (
            list(machines) if machines is not None
            else list(doc.get("machines") or ())
        )
        p_lo = lo_ns // period_ns
        n_p = (
            (hi_ns - 1) // period_ns - p_lo + 1 if hi_ns > lo_ns else 0
        )
        n_m = len(names)

        count = np.zeros((n_m, n_p), dtype=np.int64)
        sums = np.zeros((n_m, n_p), dtype=np.float64)
        maxs = np.full((n_m, n_p), np.nan, dtype=np.float32)
        exceed = np.zeros((n_m, n_p), dtype=np.int64)
        hist = (
            np.zeros((n_m, n_p, _sketch.N_SLOTS), dtype=np.int64)
            if quantiles and n_p else None
        )
        thr = float(threshold)
        segments = self._data_segments() if n_m and n_p else []
        buffers: Dict[str, np.ndarray] = {}
        for i, name in enumerate(names):
            if not n_p:
                break
            series = self._machine_series(
                name, lo_ns, hi_ns, segments=segments, buffers=buffers
            )
            if series is None:
                continue
            ns, total = series
            if ns.size == 0:
                continue
            # rows are time-sorted (chunk plan order, preserved by
            # compaction), so period ids are non-decreasing: per-period
            # reductions are reduceat over the run boundaries — one
            # O(rows) pass, no sort
            pid = ns // period_ns - p_lo
            uniq, starts = np.unique(pid, return_index=True)
            count[i, uniq] = np.diff(np.append(starts, ns.size))
            sums[i, uniq] = np.add.reduceat(
                total.astype(np.float64), starts
            )
            maxs[i, uniq] = np.maximum.reduceat(total, starts)
            exceed[i, uniq] = np.add.reduceat(
                (total > thr).astype(np.int64), starts
            )
            if hist is not None:
                f32 = np.ascontiguousarray(total, dtype=np.float32)
                slot = (
                    (f32.view(np.int32) >> 22) - (_sketch._RAW_LO - 1)
                ).astype(np.int64)
                np.clip(slot, 0, _sketch.N_SLOTS - 1, out=slot)
                hist[i] = np.bincount(
                    pid * _sketch.N_SLOTS + slot,
                    minlength=n_p * _sketch.N_SLOTS,
                ).reshape(n_p, _sketch.N_SLOTS)

        out_stats: Dict[str, np.ndarray] = {}
        cum = hist.cumsum(axis=2) if hist is not None else None
        # slot → value: the bucket's UPPER edge (underflow reads the
        # lowest edge, overflow +inf) — a guaranteed upper bound
        upper = np.concatenate(
            [_sketch.EDGES[:1], _sketch.EDGES[1:], [np.inf]]
        ).astype(np.float32)
        for s in wanted:
            if s == "count":
                out_stats[s] = count
            elif s == "exceed":
                out_stats[s] = exceed
            elif s == "max":
                out_stats[s] = maxs
            elif s == "mean":
                mean = np.full((n_m, n_p), np.nan, dtype=np.float64)
                np.divide(sums, count, out=mean, where=count > 0)
                out_stats[s] = mean
            else:
                vals = np.full((n_m, n_p), np.nan, dtype=np.float32)
                if cum is not None:
                    k = np.maximum(
                        np.ceil(quantiles[s] * count), 1
                    ).astype(np.int64)
                    slot_idx = (cum < k[..., None]).sum(axis=2)
                    np.clip(slot_idx, 0, _sketch.N_SLOTS - 1,
                            out=slot_idx)
                    vals = upper[slot_idx]
                    vals[count == 0] = np.nan
                out_stats[s] = vals

        return {
            "machines": [str(n) for n in names],
            "periods": [
                pd.Timestamp((p_lo + j) * period_ns, tz="UTC").isoformat()
                for j in range(n_p)
            ],
            "period": str(period),
            "period-ns": period_ns,
            "threshold": thr,
            "start": pd.Timestamp(lo_ns, tz="UTC").isoformat(),
            "end": pd.Timestamp(hi_ns, tz="UTC").isoformat(),
            "stats": out_stats,
        }

    def summary(self) -> Dict[str, Any]:
        doc = self.index() or {}
        chunks = doc.get("chunks") or {}
        periods = doc.get("periods") or {}
        return {
            "directory": self.directory,
            "plan": doc.get("plan"),
            "machines": len(doc.get("machines") or ()),
            "chunks-completed": len(chunks),
            "rows": sum(int(r.get("rows", 0)) for r in chunks.values()),
            "segments": (
                sum(1 for r in chunks.values() if r.get("segment"))
                + len(periods)
            ),
            "periods-compacted": len(periods),
        }
