"""The backfill runner: fleet-scale historical scoring, no HTTP anywhere.

Reference status: absent upstream — the reference could only score
history by replaying requests through the latency-bound server.  This
plane is the Podracer-style decoupling (PAPERS.md): a dedicated bulk
path that drives the SAME fused, compile-plane-registered programs the
server dispatches, at the configured serving dtype, but feeds them
device-saturating stacked chunks instead of request payloads — large-
batch offline inference is where the hardware earns its keep (the
Gemma-on-TPU comparison, PAPERS.md).

Pipeline per chunk (the ``parallel/fleet`` stage/dispatch discipline —
host work for chunk N overlaps device work for chunk N+1):

1. dataset providers → per-machine frames over the backfill period
   (one fetch per distinct dataset fingerprint: replicated fleets share
   tags, so the host cost scales with distinct datasets, not machines);
2. time-windowed chunk slicing (``chunk_rows`` resolution steps per
   chunk — the deterministic plan resumability depends on);
3. ``FleetScorer.dispatch_all`` — the server's exact stacked bucket
   geometry, pack-backed staging, and jit registry, so archive bytes
   are fp32-identical to the online fused path over the same windows
   (pinned by test).  Dispatches run under
   ``telemetry.FLEET_HEALTH.suspended()``: historical scores must not
   masquerade as live traffic in the drift sketches;
4. while the device computes chunk N, chunk N-1 assembles and lands in
   the :class:`~gordo_tpu.batch.archive.ScoreArchive` (columnar mmap
   segments + completion records under ``.gordo-scores/``).

Resumability: completed chunks are skipped on re-run (the archive's
completion records are the ledger); a mid-run kill therefore costs one
chunk of work.  Sharding rides ``distributed.partition``'s one shard
function — ``--shard i/N`` (or the Indexed-Job env pair) scores a
disjoint machine subset into the same flock-shared archive.

Plane boundary (lint-gated): this package never imports
``serve.server``, the HTTP client, or any HTTP machinery — models load
straight from the artifact plane, data from providers, scores to disk.
"""

from __future__ import annotations

import dataclasses
import logging
import os
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np
import pandas as pd

from gordo_tpu import artifacts, telemetry
from gordo_tpu.batch.archive import ScoreArchive
from gordo_tpu.compile import load_warmup_manifest
from gordo_tpu.dataset import dataset_from_metadata
from gordo_tpu.ingest.fingerprint import provider_fingerprint
from gordo_tpu.serve import precision
from gordo_tpu.serve.shard import shard_slices
from gordo_tpu.serve.fleet_scorer import FleetScorer

logger = logging.getLogger(__name__)

# -- knobs (docs/configuration.md "Backfill plane") -------------------------
ENV_CHUNK_ROWS = "GORDO_BACKFILL_CHUNK_ROWS"
DEFAULT_CHUNK_ROWS = 2048
ENV_SHARD = "GORDO_BACKFILL_SHARD"
#: the Indexed-Job spelling: the generator maps JOB_COMPLETION_INDEX
#: into the index half, the shard count rides the job spec
ENV_SHARD_INDEX = "GORDO_BACKFILL_SHARD_INDEX"
ENV_NUM_SHARDS = "GORDO_BACKFILL_NUM_SHARDS"

# -- telemetry instruments (docs/observability.md) --------------------------
_CHUNKS_TOTAL = telemetry.counter(
    "gordo_backfill_chunks_total",
    "Backfill chunks handled, by outcome",
    labels=("outcome",),  # ok | skipped | empty | failed
)
_ROWS_TOTAL = telemetry.counter(
    "gordo_backfill_rows_total",
    "Scored rows written to the score archive",
)
_SAMPLES_TOTAL = telemetry.counter(
    "gordo_backfill_samples_total",
    "Scored samples (rows x tags) written to the score archive",
)
_SAMPLES_PER_SECOND = telemetry.gauge(
    "gordo_backfill_samples_per_second",
    "End-to-end archive-path scoring rate of the last backfill run",
)
_DEVICE_TRANSFERS = telemetry.counter(
    "gordo_backfill_device_transfers_total",
    "Stacked host->device chunk dispatches (one per bucket program per "
    "chunk — the device-transfer attestation bench reads)",
)
_CHUNK_OCCUPANCY = telemetry.histogram(
    "gordo_backfill_chunk_occupancy",
    "Fraction of a chunk's row window each machine actually had data "
    "for (1.0 = fully dense history)",
    buckets=(0.1, 0.25, 0.5, 0.75, 0.9, 1.0),
)
_MACHINES = telemetry.gauge(
    "gordo_backfill_machines",
    "Machines scored by the last backfill run (this shard)",
)


class BackfillError(RuntimeError):
    """A chunk failed mid-run.  The archive keeps every completed chunk's
    record, so a re-run resumes — the CLI maps this onto the shared
    resumable exit code (75)."""


def resolve_shard(spec: Optional[str] = None) -> Tuple[int, int]:
    """``(index, count)`` from an ``i/N`` spec, ``GORDO_BACKFILL_SHARD``,
    or the Indexed-Job env pair; ``(0, 1)`` unsharded."""
    spec = spec or os.environ.get(ENV_SHARD) or ""
    if not spec:
        n = os.environ.get(ENV_NUM_SHARDS, "")
        if n:
            spec = f"{os.environ.get(ENV_SHARD_INDEX, '0') or '0'}/{n}"
    if not spec:
        return (0, 1)
    idx_s, sep, n_s = spec.partition("/")
    try:
        idx, n = int(idx_s), int(n_s)
    except ValueError:
        raise ValueError(f"shard spec must be i/N, got {spec!r}")
    if not sep or not 0 <= idx < n:
        raise ValueError(f"shard spec must satisfy 0 <= i < N, got {spec!r}")
    return (idx, n)


@dataclasses.dataclass
class BackfillConfig:
    """One backfill invocation's wiring."""

    model_dir: str
    start: Any
    end: Any
    #: archive destination root; defaults to ``model_dir`` (the archive
    #: lands next to the artifacts it was scored with)
    archive_dir: Optional[str] = None
    project: str = "project"
    #: machine-name subset (None = every discovered machine)
    machines: Optional[Sequence[str]] = None
    #: ``i/N`` spec; None resolves env (Indexed Job) then unsharded
    shard: Optional[str] = None
    #: resolution steps per chunk; None resolves GORDO_BACKFILL_CHUNK_ROWS
    chunk_rows: Optional[int] = None
    #: stop after scoring this many NEW chunks (bounded runs / tests —
    #: remaining chunks stay resumable)
    max_chunks: Optional[int] = None
    mesh: Any = None


def _to_utc(value: Any) -> pd.Timestamp:
    ts = pd.Timestamp(value)
    return ts.tz_localize("UTC") if ts.tzinfo is None else ts


def chunk_windows(
    start: Any, end: Any, resolution: str, chunk_rows: int
) -> List[Tuple[pd.Timestamp, pd.Timestamp]]:
    """The deterministic chunk plan: half-open ``[t0, t1)`` windows of
    ``chunk_rows`` resolution steps covering ``[start, end)``.  Pure
    arithmetic over the period — every shard and every re-run computes
    the identical plan, which is what completion records key on."""
    start, end = _to_utc(start), _to_utc(end)
    if start >= end:
        raise ValueError(f"backfill start {start} must precede end {end}")
    step = pd.tseries.frequencies.to_offset(resolution).nanos * chunk_rows
    windows = []
    t = start.value
    while t < end.value:
        t1 = min(t + step, end.value)
        windows.append((
            pd.Timestamp(t, unit="ns", tz="UTC"),
            pd.Timestamp(t1, unit="ns", tz="UTC"),
        ))
        t = t1
    return windows


# Frames are shareable iff tags + resolution + provider match —
# replicated fleets collapse to one provider fetch.  The fingerprint
# definition was hoisted into the shared ingest plane (r24) so the
# builder, refresh, and batch planes cannot drift on what "same data"
# means.
_dataset_fingerprint = provider_fingerprint


def _load_fleet(
    cfg: BackfillConfig, shard: Tuple[int, int]
) -> Tuple[Any, List[Any]]:
    """Discover artifacts, filter to the requested subset, take this
    shard's slice with the ONE shard function (``serve.shard`` wrapping
    ``distributed.partition`` — so a backfill shard owns exactly the
    machines the same-index serving shard would)."""
    store, refs = artifacts.discover(cfg.model_dir, quarantine=True)
    if not refs:
        raise BackfillError(f"no artifacts under {cfg.model_dir}")
    if cfg.machines:
        wanted = set(cfg.machines)
        missing = wanted - {r.name for r in refs}
        if missing:
            raise BackfillError(
                f"machines not in the artifact fleet: {sorted(missing)}"
            )
        refs = [r for r in refs if r.name in wanted]
    refs = sorted(refs, key=lambda r: r.name)
    if shard[1] > 1:
        owned = set(
            shard_slices([r.name for r in refs], shard[1])[shard[0]]
        )
        refs = [r for r in refs if r.name in owned]
    return store, refs


def run_backfill(cfg: BackfillConfig) -> Dict[str, Any]:
    """Score ``[start, end)`` for this shard's fleet into the archive.

    Returns a summary dict (the CLI prints it as JSON).  ``remaining``
    > 0 means the run is resumable rather than complete (``max_chunks``
    bound hit); a chunk failure raises :class:`BackfillError` and leaves
    every completed chunk's record durable."""
    t_run = time.perf_counter()
    shard = resolve_shard(cfg.shard)
    chunk_rows = int(
        cfg.chunk_rows
        if cfg.chunk_rows is not None
        else os.environ.get(ENV_CHUNK_ROWS, "") or DEFAULT_CHUNK_ROWS
    )
    if chunk_rows < 1:
        raise ValueError(f"chunk_rows must be >= 1, got {chunk_rows}")

    store, refs = _load_fleet(cfg, shard)
    names = [r.name for r in refs]
    _MACHINES.set(float(len(names)))
    logger.info(
        "backfill shard %d/%d: %d machine(s), %s -> %s",
        shard[0], shard[1], len(names), cfg.start, cfg.end,
    )

    # models + metadata at the serving precision (the server's exact
    # resolution order: env > warmup-manifest dtype > float32)
    models = {r.name: r.load_model() for r in refs}
    metas = {r.name: (r.load_metadata() or {}) for r in refs}
    manifest_dtype = (load_warmup_manifest(cfg.model_dir) or {}).get("dtype")
    dtype = precision.serve_dtype(default=manifest_dtype)
    scorer = FleetScorer.from_models(
        models, mesh=cfg.mesh, pack_store=store, dtype=dtype
    )

    # one provider fetch per distinct dataset fingerprint
    frames: Dict[str, pd.DataFrame] = {}
    by_fp: Dict[str, pd.DataFrame] = {}
    tags_of: Dict[str, List[str]] = {}
    resolutions: Dict[str, int] = {}
    for name in names:
        dataset_meta = metas[name].get("dataset") or {}
        fp = _dataset_fingerprint(dataset_meta)
        if fp not in by_fp:
            dataset = dataset_from_metadata(dataset_meta, cfg.start, cfg.end)
            X, _ = dataset.get_data()
            by_fp[fp] = X
        frames[name] = by_fp[fp]
        tags_of[name] = list(frames[name].columns)
        res = dataset_meta.get("resolution", "10min")
        resolutions[res] = resolutions.get(res, 0) + 1
    # the plan resolution: the fleet's most common (ties break stably);
    # machines at other resolutions still slice correctly by timestamp,
    # their occupancy just reads off-unity
    resolution = max(sorted(resolutions), key=lambda r: resolutions[r])

    windows = chunk_windows(cfg.start, cfg.end, resolution, chunk_rows)
    archive = ScoreArchive.create(
        cfg.archive_dir or cfg.model_dir,
        project=cfg.project,
        start=str(_to_utc(cfg.start)),
        end=str(_to_utc(cfg.end)),
        resolution=resolution,
        chunk_rows=chunk_rows,
        n_chunks=len(windows),
        dtype=dtype,
        machines=names,
        shard=shard,
    )
    done = archive.completed_chunks(shard[0])

    counts = {"ok": 0, "skipped": 0, "empty": 0, "short": 0}
    rows_written = 0
    samples = 0
    transfers = 0

    def finalize(ci: int, disp, idx_by: Dict[str, pd.Index]) -> None:
        nonlocal rows_written, samples
        with telemetry.FLEET_HEALTH.suspended():
            results = disp.assemble()
        per_machine: Dict[str, Dict[str, Any]] = {}
        for name, res in results.items():
            if "error" in res:
                # short windows (rows <= the model's lookback offset)
                # are a property of the chunk boundary, not a failure
                counts["short"] += 1
                continue
            total = np.asarray(res["total-anomaly-score"], np.float32)
            tag_scores = np.asarray(res["tag-anomaly-scores"], np.float32)
            idx = idx_by[name]
            # scored rows = input rows - the model's lookback offset;
            # derive from output length so the two can never diverge
            ts = idx[len(idx) - len(total):]
            per_machine[name] = {
                "index-ns": ts.as_unit("ns").asi8
                if ts.unit != "ns" else ts.asi8,
                "total-anomaly-score": total,
                "tag-anomaly-scores": tag_scores,
                "tags": tags_of[name],
            }
            rows_written += len(total)
            samples += int(tag_scores.size)
            _CHUNK_OCCUPANCY.observe(min(1.0, len(idx) / chunk_rows))
        archive.write_chunk(ci, per_machine, shard=shard[0])
        _ROWS_TOTAL.inc(float(sum(
            len(r["total-anomaly-score"]) for r in per_machine.values()
        )))
        _CHUNKS_TOTAL.inc(1.0, "ok" if per_machine else "empty")
        counts["ok" if per_machine else "empty"] += 1

    pending: Optional[Tuple[int, Any, Dict[str, pd.Index]]] = None
    scored_new = 0
    remaining = 0
    try:
        for ci, (t0, t1) in enumerate(windows):
            if ci in done:
                _CHUNKS_TOTAL.inc(1.0, "skipped")
                counts["skipped"] += 1
                continue
            if cfg.max_chunks is not None and scored_new >= cfg.max_chunks:
                remaining += 1
                continue
            X_by: Dict[str, np.ndarray] = {}
            idx_by: Dict[str, pd.Index] = {}
            for name, X in frames.items():
                lo = X.index.searchsorted(t0)
                hi = X.index.searchsorted(t1)
                if hi > lo:
                    window = X.iloc[lo:hi]
                    X_by[name] = window.to_numpy(np.float32)
                    idx_by[name] = window.index
            scored_new += 1
            if not X_by:
                archive.write_chunk(ci, {}, shard=shard[0])
                _CHUNKS_TOTAL.inc(1.0, "empty")
                counts["empty"] += 1
                continue
            # dispatch chunk N, then archive chunk N-1 while the device
            # runs — the fleet_stage/fleet_dispatch overlap discipline
            with telemetry.FLEET_HEALTH.suspended():
                disp = scorer.dispatch_all(X_by)
            n_disp = disp.n_device_dispatches
            transfers += n_disp
            _DEVICE_TRANSFERS.inc(float(n_disp))
            if pending is not None:
                finalize(*pending)
            pending = (ci, disp, idx_by)
        if pending is not None:
            finalize(*pending)
            pending = None
    except (ArithmeticError, OSError, RuntimeError, ValueError) as exc:
        _CHUNKS_TOTAL.inc(1.0, "failed")
        raise BackfillError(
            f"backfill failed mid-run ({counts['ok']} chunk(s) archived "
            f"and durable; re-run to resume): {exc}"
        ) from exc

    elapsed = time.perf_counter() - t_run
    rate = samples / elapsed if elapsed > 0 else 0.0
    _SAMPLES_TOTAL.inc(float(samples))
    _SAMPLES_PER_SECOND.set(rate)
    summary = {
        "project": cfg.project,
        "archive": archive.directory,
        "shard": f"{shard[0]}/{shard[1]}",
        "machines": len(names),
        "dtype": dtype,
        "resolution": resolution,
        "chunk-rows": chunk_rows,
        "chunks": len(windows),
        "chunks-ok": counts["ok"],
        "chunks-skipped": counts["skipped"],
        "chunks-empty": counts["empty"],
        "short-windows": counts["short"],
        "remaining": remaining,
        "rows": rows_written,
        "samples": samples,
        "seconds": round(elapsed, 3),
        "samples-per-second": round(rate, 1),
        "device-transfers": transfers,
    }
    logger.info("backfill summary: %s", summary)
    return summary
