"""Score-archive lifecycle: compaction and retention.

Reference status: absent upstream — the reference stack had no score
store at all, let alone a lifecycle for one.  The r18 backfill plane
writes one GSA1 segment per (time-chunk, shard) and never merges or
deletes, so a fleet that scores continuously grows ``.gordo-scores/``
without bound in both bytes and file count.  This module is the
lifecycle half of the archive's production story (the query half is
:meth:`ScoreArchive.aggregate`):

- :func:`compact_scores` (``gordo scores compact``) merges the small
  per-chunk segments of each closed time partition into ONE period file
  (``period-<key>.seg``, same GSA1 layout), across shards, keeping every
  machine's rows in chunk order so reads stay byte-identical.  The
  discipline is write-new-then-flip, borrowed from the artifact plane's
  generation writes: the period file is written to a tmp name, fsynced,
  renamed, and only THEN does the flock-serialized index flip the chunk
  records over to it — after which the absorbed chunk segments are
  unlinked.  A kill at any point loses nothing: pre-flip the chunk
  segments still back every read and the next run rewrites the same
  period bytes (the merge is deterministic); post-flip the period file
  is durable and leftovers are swept.  The ``scores.compact`` fault
  point fires between the tmp fsync and the rename — the chaos suite's
  kill-mid-compact seam.
- :func:`gc_scores` (``gordo scores gc --keep DAYS``) prunes segments
  whose entire window is older than the cutoff, mirroring the r15
  artifact-generation gc: refuse a keep that would empty the archive,
  mutate the index first (a read never sees a record pointing at an
  unlinked file), unlink after, report a JSON summary.  Completion
  records survive pruning (``pruned: true``) so a backfill resume never
  re-scores — and thereby silently resurrects — retired windows.

Both report through ``gordo_scores_*`` telemetry (segments merged,
bytes written/reclaimed) so fleet dashboards can watch the lifecycle
run.  Host-side I/O only, like the rest of the batch plane (lint-gated:
no server/client/HTTP imports).
"""

from __future__ import annotations

import fcntl
import logging
import os
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from gordo_tpu import faults, telemetry
from gordo_tpu.batch.archive import (
    COLUMNS,
    LOCK_FILE,
    ArchiveError,
    ScoreArchive,
    _column_view,
    _locked_index_update,
    _period_name,
    _read_index,
    _segment_buffer,
    _segment_header,
    _segment_layout,
    _ts_ns,
)
from gordo_tpu.utils.disk_registry import fsync_dir

logger = logging.getLogger(__name__)

#: compaction partition length (any ``pd.Timedelta`` string); the CLI
#: and :func:`compact_scores` default to this env var, then ``"1d"``
ENV_PERIOD = "GORDO_SCORES_PERIOD"
#: retention default for ``gordo scores gc`` (days)
ENV_KEEP = "GORDO_SCORES_KEEP"

DEFAULT_PERIOD = "1d"
DEFAULT_KEEP_DAYS = 90

_PERIODS_COMPACTED = telemetry.counter(
    "gordo_scores_periods_compacted_total",
    "Time partitions merged into period files by score-archive "
    "compaction",
)
_SEGMENTS_MERGED = telemetry.counter(
    "gordo_scores_segments_merged_total",
    "Per-chunk segments absorbed into period files by compaction",
)
_COMPACT_BYTES_WRITTEN = telemetry.counter(
    "gordo_scores_compact_bytes_written_total",
    "Bytes of period files written by score-archive compaction",
)
_COMPACT_BYTES_RECLAIMED = telemetry.counter(
    "gordo_scores_compact_bytes_reclaimed_total",
    "Bytes of absorbed chunk segments unlinked after a period flip",
)
_GC_SEGMENTS = telemetry.counter(
    "gordo_scores_gc_segments_total",
    "Score-archive segments deleted by retention gc",
)
_GC_BYTES_RECLAIMED = telemetry.counter(
    "gordo_scores_gc_bytes_reclaimed_total",
    "Bytes reclaimed by score-archive retention gc",
)


def _resolve_period(period: Optional[Any]) -> Tuple[str, int]:
    """``(spelling, nanoseconds)`` of the compaction partition length
    (arg > ``GORDO_SCORES_PERIOD`` > ``"1d"``)."""
    import pandas as pd

    if period is None:
        period = os.environ.get(ENV_PERIOD, "") or DEFAULT_PERIOD
    ns = int(pd.Timedelta(period).value)
    if ns <= 0:
        raise ValueError(
            f"compaction period must be positive, got {period!r}"
        )
    return str(period), ns


def _chunk_geometry(plan: Dict[str, Any]) -> Tuple[int, int]:
    """``(plan start ns, chunk span ns)`` — chunk ``c`` covers
    ``[start + c*span, start + (c+1)*span)``."""
    import pandas as pd

    step_ns = int(pd.Timedelta(plan["resolution"]).value)
    return _ts_ns(plan["start"]), int(plan["chunk-rows"]) * step_ns


def _period_key(start_ns: int) -> str:
    import pandas as pd

    return pd.Timestamp(start_ns, tz="UTC").strftime("%Y%m%dT%H%M%S")


def _pid_alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except PermissionError:
        return True
    return True


# ---------------------------------------------------------------------------
# compaction
# ---------------------------------------------------------------------------

def plan_compaction(
    root: str, period: Optional[Any] = None
) -> Dict[str, Any]:
    """What ``compact_scores`` would merge: partition key →
    ``{"chunks", "segments", "start-ns"}`` for every ELIGIBLE partition
    — all of its chunks have completion records for every shard of the
    job, it is not already compacted, and it holds at least two segment
    files (merging one is churn, not compaction).  Read-only."""
    arch = ScoreArchive(root)
    doc = arch.index()
    if not doc or not doc.get("plan"):
        raise ArchiveError(f"{arch.directory}: no score archive to compact")
    plan = doc["plan"]
    period_str, period_ns = _resolve_period(period)
    start_ns, span_ns = _chunk_geometry(plan)
    records = doc.get("chunks") or {}
    done = doc.get("periods") or {}
    shard_meta = doc.get("shards") or {}
    n_shards = max(
        [int(v.get("of", 1)) for v in shard_meta.values()] + [1]
    )

    by_period: Dict[int, List[int]] = {}
    for c in range(int(plan["n-chunks"])):
        p = (start_ns + c * span_ns) // period_ns
        by_period.setdefault(p, []).append(c)

    eligible: Dict[str, Dict[str, Any]] = {}
    for p, chunks in sorted(by_period.items()):
        key = _period_key(p * period_ns)
        if key in done:
            continue
        segments: List[Tuple[int, int, str]] = []
        complete = True
        for c in chunks:
            for s in range(n_shards):
                rec = records.get(f"{c}/{s}")
                if rec is None:
                    complete = False
                    break
                if rec.get("segment"):
                    segments.append((c, s, rec["segment"]))
            if not complete:
                break
        if not complete or len(segments) < 2:
            continue
        eligible[key] = {
            "chunks": list(chunks),
            "segments": sorted(segments),
            "start-ns": p * period_ns,
        }
    return {
        "directory": arch.directory,
        "period": period_str,
        "period-ns": period_ns,
        "eligible": eligible,
    }


def _merge_sources(
    directory: str, segments: List[Tuple[int, int, str]]
) -> Tuple[Dict[str, Dict[str, List[np.ndarray]]], Dict[str, List[str]]]:
    """Zero-copy mmap views of every machine's columns across
    ``segments`` in (chunk, shard) order — exactly the order
    ``_data_segments`` reads uncompacted files in, so the merged period
    file is byte-consistent with the segments it replaces.  Returns
    ``(sources, tags)``; nothing is materialized until the views are
    concatenated straight into the period file."""
    sources: Dict[str, Dict[str, List[np.ndarray]]] = {}
    tags: Dict[str, List[str]] = {}
    for _c, _s, fname in segments:
        path = os.path.join(directory, fname)
        try:
            header, base = _segment_header(path)
        except FileNotFoundError:
            raise ArchiveError(
                f"{path}: completion record exists but segment is "
                "missing — archive is torn; delete and re-run"
            )
        buf = _segment_buffer(path)
        for name, entry in header["machines"].items():
            cols = entry["columns"]
            slot = sources.setdefault(name, {col: [] for col in COLUMNS})
            if name not in tags:
                tags[name] = list(entry.get("tags") or ())
            for col in COLUMNS:
                slot[col].append(_column_view(buf, base, cols[col]))
    return sources, tags


def _write_period_file(
    tmp: str,
    key: str,
    chunks: List[int],
    sources: Dict[str, Dict[str, List[np.ndarray]]],
    tags: Dict[str, List[str]],
) -> Tuple[int, Dict[str, Any]]:
    """Stream the merged period segment into ``tmp`` in ONE data pass:
    layout is computed from column metadata alone, the file is sized
    with ftruncate, and each output column is concatenated directly
    into its mmapped destination slice (``np.concatenate(out=...)``) —
    no intermediate merged arrays, no ``tobytes`` staging, no bytearray
    assembly.  r20 measured the staged encoder at 5 memory passes per
    byte (60 MB/s wall); this path is bounded by one memcpy plus the
    fsync.  Returns ``(bytes_written, header)``; the tmp is fsynced but
    NOT renamed — the caller owns the flip."""
    meta = {}
    for name, cols in sources.items():
        colmeta = {}
        for col in COLUMNS:
            parts = cols[col]
            rows = int(sum(p.shape[0] for p in parts))
            shape = (rows,) + tuple(parts[0].shape[1:])
            colmeta[col] = (str(parts[0].dtype), shape)
        meta[name] = {"tags": tags.get(name) or [], "columns": colmeta}
    header, prefix, payload_base, payload = _segment_layout(
        min(chunks), -1, meta,
        extra={"period": key, "chunks": sorted(chunks)},
    )
    total = payload_base + payload
    with open(tmp, "wb") as fh:
        os.ftruncate(fh.fileno(), total)
        dest = np.memmap(tmp, dtype=np.uint8, mode="r+", shape=(total,))
        # copy through a base-class view: concatenate with an np.memmap
        # operand takes a subclass-safe path measured 6.6x slower
        payload_view = np.asarray(dest)
        payload_view[: len(prefix)] = np.frombuffer(prefix, dtype=np.uint8)
        for name, cols in sources.items():
            entry = header["machines"][name]["columns"]
            for col in COLUMNS:
                view = _column_view(payload_view, payload_base, entry[col])
                np.concatenate(cols[col], axis=0, out=view)
        dest.flush()
        del dest
        os.fsync(fh.fileno())
    return total, header


def _compact_one(
    arch: ScoreArchive, key: str, info: Dict[str, Any]
) -> Dict[str, Any]:
    """Merge one partition: write-new, fault seam, flip, unlink."""
    segments: List[Tuple[int, int, str]] = info["segments"]
    sources, tags = _merge_sources(arch.directory, segments)
    fname = _period_name(key)
    path = os.path.join(arch.directory, fname)
    tmp = f"{path}.tmp.{os.getpid()}"
    nbytes, header = _write_period_file(
        tmp, key, info["chunks"], sources, tags
    )
    # the kill-mid-compact seam: a crash between the tmp fsync and the
    # flip loses nothing — every read still resolves to the chunk
    # segments, and the next run deterministically rewrites these bytes
    faults.check("scores.compact", period=key)
    os.replace(tmp, path)
    fsync_dir(arch.directory)

    expected = {f"{c}/{s}": name for c, s, name in segments}
    chunk_set = set(info["chunks"])

    def mutate(doc: Dict[str, Any]) -> None:
        chunks = doc.setdefault("chunks", {})
        for ck, want in expected.items():
            rec = chunks.get(ck)
            if rec is None or rec.get("segment") != want:
                raise ArchiveError(
                    f"score archive changed under compaction "
                    f"(chunk {ck}); re-run"
                )
        for ck, rec in chunks.items():
            if int(ck.split("/")[0]) in chunk_set:
                rec["segment"] = None
                rec["period"] = key
        doc.setdefault("periods", {})[key] = {
            "segment": fname,
            "chunks": sorted(chunk_set),
            "rows": int(sum(
                e["rows"] for e in header["machines"].values()
            )),
            "bytes": nbytes,
            "compacted-at": time.time(),
        }

    _locked_index_update(arch.directory, mutate)

    reclaimed = 0
    for _c, _s, old in segments:
        old_path = os.path.join(arch.directory, old)
        try:
            size = os.path.getsize(old_path)
            os.unlink(old_path)
            reclaimed += size
        except FileNotFoundError:
            pass
    _PERIODS_COMPACTED.inc(1.0)
    _SEGMENTS_MERGED.inc(float(len(segments)))
    _COMPACT_BYTES_WRITTEN.inc(float(nbytes))
    _COMPACT_BYTES_RECLAIMED.inc(float(reclaimed))
    return {
        "period": key,
        "segment": fname,
        "segments-merged": len(segments),
        "bytes-written": nbytes,
        "bytes-reclaimed": reclaimed,
    }


def _sweep_leftovers(directory: str) -> Dict[str, int]:
    """Unlink crash leftovers, under the index flock: dead writers' tmp
    files, and chunk segments whose own record says they were already
    absorbed (``period``) or pruned (``pruned``) — the unlink that a
    kill between an index flip and its cleanup skipped.  Files with no
    index record are left alone: a racing backfill writer owns the gap
    between its segment rename and its completion record."""
    swept = {"files": 0, "bytes": 0}
    with open(os.path.join(directory, LOCK_FILE), "a+") as lock:
        fcntl.flock(lock.fileno(), fcntl.LOCK_EX)
        doc = _read_index(directory) or {}
        # records do not retain the old file name; reconstruct it from
        # the key (the naming rule is deterministic)
        absorbed = set()
        for ck, rec in (doc.get("chunks") or {}).items():
            if rec.get("segment") is None and (
                rec.get("period") or rec.get("pruned")
            ):
                c, s = ck.split("/")
                absorbed.add(f"chunk-{int(c):05d}-s{int(s):02d}.seg")
        for entry in sorted(os.listdir(directory)):
            path = os.path.join(directory, entry)
            if ".tmp." in entry:
                pid = entry.rsplit(".", 1)[-1]
                if pid.isdigit() and _pid_alive(int(pid)):
                    continue
            elif entry not in absorbed:
                continue
            try:
                size = os.path.getsize(path)
                os.unlink(path)
            except FileNotFoundError:
                continue
            swept["files"] += 1
            swept["bytes"] += size
    return swept


def compact_scores(
    root: str,
    period: Optional[Any] = None,
    dry_run: bool = False,
) -> Dict[str, Any]:
    """Merge every eligible time partition's chunk segments into one
    period file each (see module docstring for the crash discipline).
    Re-entrant: an interrupted run resumes by recomputing the same
    deterministic merges; already-compacted partitions are skipped.
    Returns a JSON-ready summary (the CLI prints it verbatim)."""
    cp = plan_compaction(root, period)
    arch = ScoreArchive(root)
    summary: Dict[str, Any] = {
        "directory": cp["directory"],
        "period": cp["period"],
        "periods-compacted": 0,
        "segments-merged": 0,
        "bytes-written": 0,
        "bytes-reclaimed": 0,
        "periods": [],
    }
    if dry_run:
        summary["dry-run"] = True
        summary["eligible"] = {
            key: [name for _c, _s, name in info["segments"]]
            for key, info in cp["eligible"].items()
        }
        return summary
    for key in sorted(cp["eligible"]):
        done = _compact_one(arch, key, cp["eligible"][key])
        summary["periods"].append(done)
        summary["periods-compacted"] += 1
        summary["segments-merged"] += done["segments-merged"]
        summary["bytes-written"] += done["bytes-written"]
        summary["bytes-reclaimed"] += done["bytes-reclaimed"]
    swept = _sweep_leftovers(arch.directory)
    summary["leftovers-swept"] = swept["files"]
    summary["bytes-reclaimed"] += swept["bytes"]
    return summary


# ---------------------------------------------------------------------------
# retention
# ---------------------------------------------------------------------------

def gc_scores(
    root: str,
    keep_days: Optional[float] = None,
    now: Optional[float] = None,
) -> Dict[str, Any]:
    """Delete every segment (chunk or period) whose entire window ended
    more than ``keep_days`` days ago (arg > ``GORDO_SCORES_KEEP`` > 90).

    Mirrors the artifact plane's ``gc_generations``: refuses a keep
    below one day (an archive is never collectable wholesale by
    accident), flips the index BEFORE unlinking (a reader never follows
    a record to a missing file), and keeps completion records — marked
    ``pruned`` — so a backfill resume does not re-score retired windows
    and resurrect the data gc just reclaimed."""
    if keep_days is None:
        keep_days = float(
            os.environ.get(ENV_KEEP, "") or DEFAULT_KEEP_DAYS
        )
    keep_days = float(keep_days)
    if keep_days < 1:
        raise ValueError(
            "refusing to gc the score archive: --keep must be >= 1 day"
        )
    arch = ScoreArchive(root)
    doc = arch.index()
    if not doc or not doc.get("plan"):
        raise ArchiveError(f"{arch.directory}: no score archive to gc")
    start_ns, span_ns = _chunk_geometry(doc["plan"])
    wall = time.time() if now is None else float(now)
    cutoff_ns = int((wall - keep_days * 86400.0) * 1e9)
    victims: List[str] = []
    pruned = {"chunks": 0, "periods": 0}

    def mutate(idx: Dict[str, Any]) -> None:
        chunks = idx.get("chunks") or {}
        periods = idx.get("periods") or {}
        for key in sorted(list(periods)):
            rec = periods[key]
            end_ns = start_ns + (max(rec["chunks"]) + 1) * span_ns
            if end_ns > cutoff_ns:
                continue
            victims.append(rec["segment"])
            retired = set(rec["chunks"])
            for ck, crec in chunks.items():
                if int(ck.split("/")[0]) in retired:
                    crec["pruned"] = True
            del periods[key]
            pruned["periods"] += 1
        for ck, crec in chunks.items():
            c = int(ck.split("/")[0])
            if (
                crec.get("segment")
                and start_ns + (c + 1) * span_ns <= cutoff_ns
            ):
                victims.append(crec["segment"])
                crec["segment"] = None
                crec["pruned"] = True
                pruned["chunks"] += 1

    _locked_index_update(arch.directory, mutate)

    reclaimed = 0
    deleted = 0
    for fname in victims:
        path = os.path.join(arch.directory, fname)
        try:
            size = os.path.getsize(path)
            os.unlink(path)
        except FileNotFoundError:
            continue
        reclaimed += size
        deleted += 1
    _GC_SEGMENTS.inc(float(deleted))
    _GC_BYTES_RECLAIMED.inc(float(reclaimed))
    import pandas as pd

    return {
        "directory": arch.directory,
        "keep-days": keep_days,
        "cutoff": pd.Timestamp(cutoff_ns, tz="UTC").isoformat(),
        "segments-deleted": deleted,
        "bytes-reclaimed": reclaimed,
        "periods-pruned": pruned["periods"],
        "chunks-pruned": pruned["chunks"],
    }


# ---------------------------------------------------------------------------
# inspection (``gordo scores ls`` / ``gordo scores stat``)
# ---------------------------------------------------------------------------

def _file_bytes(directory: str, fname: str) -> Optional[int]:
    try:
        return os.path.getsize(os.path.join(directory, fname))
    except OSError:
        return None


def ls_scores(root: str) -> Dict[str, Any]:
    """Every data segment with its kind, window, rows and on-disk bytes
    — what compaction and gc actually did, file by file."""
    arch = ScoreArchive(root)
    doc = arch.index()
    if not doc:
        raise ArchiveError(f"{arch.directory}: no score archive")
    segments: List[Dict[str, Any]] = []
    for ck in sorted(
        doc.get("chunks") or {}, key=lambda k: tuple(map(int, k.split("/")))
    ):
        rec = (doc.get("chunks") or {})[ck]
        if not rec.get("segment"):
            continue
        c, s = ck.split("/")
        segments.append({
            "segment": rec["segment"],
            "kind": "chunk",
            "chunk": int(c),
            "shard": int(s),
            "rows": int(rec.get("rows", 0)),
            "bytes": _file_bytes(arch.directory, rec["segment"]),
        })
    for key in sorted(doc.get("periods") or {}):
        rec = (doc.get("periods") or {})[key]
        segments.append({
            "segment": rec["segment"],
            "kind": "period",
            "period": key,
            "chunks": list(rec.get("chunks") or ()),
            "rows": int(rec.get("rows", 0)),
            "bytes": _file_bytes(arch.directory, rec["segment"]),
        })
    return {"directory": arch.directory, "segments": segments}


def stat_scores(
    root: str, period: Optional[Any] = None
) -> Dict[str, Any]:
    """One-document archive state: the plan, segment/byte totals by
    partition kind, period coverage, pruned-window count, and how many
    partitions the next ``compact`` would merge."""
    arch = ScoreArchive(root)
    doc = arch.index()
    if not doc or not doc.get("plan"):
        raise ArchiveError(f"{arch.directory}: no score archive")
    listing = ls_scores(root)["segments"]
    by_kind: Dict[str, Dict[str, int]] = {}
    for seg in listing:
        slot = by_kind.setdefault(
            seg["kind"], {"segments": 0, "bytes": 0, "rows": 0}
        )
        slot["segments"] += 1
        slot["bytes"] += int(seg["bytes"] or 0)
        slot["rows"] += seg["rows"]
    chunks = doc.get("chunks") or {}
    out = arch.summary()
    out["by-kind"] = by_kind
    out["chunks-pruned"] = sum(
        1 for r in chunks.values() if r.get("pruned")
    )
    out["periods"] = sorted(doc.get("periods") or {})
    out["pending-compaction"] = len(
        plan_compaction(root, period)["eligible"]
    )
    return out
