"""Component registries.

Two registries live here:

1. ``register_model_builder`` — the model-factory registry, behavior
   compatible with the reference's
   ``gordo_components/model/register.py::register_model_builder``: a
   decorator that files a factory function under ``{model_type: {name: fn}}``
   so estimators can resolve their ``kind`` parameter at fit time.

2. ``ALIASES`` — dotted-path aliases used by the definition-dict interpreter
   (:mod:`gordo_tpu.serializer.definition`) so that *reference* YAML configs
   (``sklearn.pipeline.Pipeline``, ``gordo_components.model.models.
   KerasAutoEncoder`` ...) resolve to this framework's TPU-native classes.
   This is what makes an existing gordo-components project YAML work
   unchanged against gordo_tpu.
"""

from __future__ import annotations

from typing import Callable, Dict

# {model_type: {factory_name: factory_fn}}
FACTORY_REGISTRY: Dict[str, Dict[str, Callable]] = {}


def register_model_builder(type: str) -> Callable:  # noqa: A002 - parity name
    """Decorator registering a model factory under an estimator type.

    Mirrors ``gordo_components.model.register.register_model_builder``::

        @register_model_builder(type="AutoEncoder")
        def my_factory(n_features: int, **kwargs): ...

    The estimator looks the factory up via its ``kind`` parameter.
    """

    def decorator(fn: Callable) -> Callable:
        FACTORY_REGISTRY.setdefault(type, {})[fn.__name__] = fn
        return fn

    return decorator


def lookup_factory(model_type: str, kind: str) -> Callable:
    """Resolve a registered factory; raise with the available names."""
    # Strict per-type resolution, like the reference: a factory registered
    # for another estimator type expects different input ranks and would fail
    # obscurely inside the jitted loss — better to error here with the list.
    by_type = FACTORY_REGISTRY.get(model_type, {})
    if kind in by_type:
        return by_type[kind]
    raise ValueError(
        f"Unknown model factory kind={kind!r} for type={model_type!r}; "
        f"available: {sorted(by_type)}"
    )


# Dotted-path aliases: reference-era paths -> gordo_tpu paths.  Consulted by
# the definition interpreter before importing, so reference YAMLs run as-is.
ALIASES: Dict[str, str] = {
    # sklearn containers -> functional TPU-native pipeline containers
    "sklearn.pipeline.Pipeline": "gordo_tpu.pipeline.Pipeline",
    "sklearn.pipeline.FeatureUnion": "gordo_tpu.pipeline.FeatureUnion",
    "sklearn.compose.TransformedTargetRegressor": "gordo_tpu.pipeline.TransformedTargetRegressor",
    "sklearn.multioutput.MultiOutputRegressor": "gordo_tpu.pipeline.MultiOutputRegressor",
    # sklearn transformers -> jax functional scalers
    "sklearn.preprocessing.MinMaxScaler": "gordo_tpu.ops.scalers.MinMaxScaler",
    "sklearn.preprocessing.data.MinMaxScaler": "gordo_tpu.ops.scalers.MinMaxScaler",
    "sklearn.preprocessing.StandardScaler": "gordo_tpu.ops.scalers.StandardScaler",
    "sklearn.preprocessing.RobustScaler": "gordo_tpu.ops.scalers.RobustScaler",
    "sklearn.preprocessing.QuantileTransformer": "gordo_tpu.ops.scalers.QuantileTransformer",
    "sklearn.preprocessing.FunctionTransformer": "gordo_tpu.ops.scalers.FunctionTransformer",
    "sklearn.impute.SimpleImputer": "gordo_tpu.ops.scalers.SimpleImputer",
    "sklearn.decomposition.PCA": "gordo_tpu.ops.scalers.PCA",
    # reference estimators -> TPU estimators
    "gordo_components.model.models.KerasAutoEncoder": "gordo_tpu.models.estimator.AutoEncoder",
    "gordo_components.model.models.KerasLSTMAutoEncoder": "gordo_tpu.models.estimator.LSTMAutoEncoder",
    "gordo_components.model.models.KerasLSTMForecast": "gordo_tpu.models.estimator.LSTMForecast",
    "gordo_components.model.models.KerasRawModelRegressor": "gordo_tpu.models.estimator.AutoEncoder",
    # anomaly detectors
    "gordo_components.model.anomaly.diff.DiffBasedAnomalyDetector": "gordo_tpu.anomaly.diff.DiffBasedAnomalyDetector",
    # transformer funcs usable inside FunctionTransformer
    "gordo_components.model.transformer_funcs.general.multiplier": "gordo_tpu.ops.transformer_funcs.multiplier",
    # datasets / providers (reference dataset configs name these types)
    "gordo_components.dataset.datasets.TimeSeriesDataset": "gordo_tpu.dataset.datasets.TimeSeriesDataset",
    "gordo_components.dataset.datasets.RandomDataset": "gordo_tpu.dataset.datasets.RandomDataset",
    "gordo_components.dataset.data_provider.providers.RandomDataProvider": "gordo_tpu.dataset.data_provider.providers.RandomDataProvider",
    "gordo_components.dataset.data_provider.providers.InfluxDataProvider": "gordo_tpu.dataset.data_provider.providers.InfluxDataProvider",
    "gordo_components.dataset.data_provider.providers.DataLakeProvider": "gordo_tpu.dataset.data_provider.providers.DataLakeProvider",
}

# Import allowlist for dotted paths in definitions (safety: the definition
# dict is the config-driven extension point; restrict what it may import).
ALLOWED_IMPORT_PREFIXES = (
    "gordo_tpu.",
    "gordo_components.",  # rewritten through ALIASES above
    "sklearn.",           # rewritten through ALIASES above
    "numpy.",
    "optax.",
)


def resolve_alias(dotted: str) -> str:
    return ALIASES.get(dotted, dotted)
