"""gordo_tpu — a TPU-native framework for config-driven building, serving and
fleet management of thousands of small per-sensor-tag autoencoder models for
industrial time-series anomaly detection.

Capability parity target: equinor/gordo-components (``gordo_components/``
upstream layout — see SURVEY.md; the reference mount was empty so citations
are upstream module paths, not file:line).

Design stance (NOT a port):

- Models are Flax modules built by registered factories
  (``feedforward_hourglass``, ``lstm_hourglass``, ...) instead of Keras
  ``Sequential``s (reference: ``gordo_components/model/factories/``).
- Training is a single jitted XLA program: ``lax.scan`` over optimizer steps
  with data resident on device (reference: per-model ``keras Model.fit`` on
  CPU, one Argo pod per model).
- The fleet axis (thousands of independent per-tag models) is a *mesh
  dimension*: stacked per-model params trained by ``shard_map(vmap(step))``
  over a ``("models", "data")`` device mesh with XLA collectives over ICI
  (reference: Argo DAG fan-out across k8s pods).
- The config surface (project YAML, model-definition dicts, metadata schema,
  HTTP routes, CLI verbs) stays behavior-compatible with the reference.
"""

__version__ = "0.1.0"

from gordo_tpu import serializer  # noqa: F401

__all__ = ["__version__", "serializer"]
