from gordo_tpu.anomaly.base import AnomalyDetectorBase  # noqa: F401
from gordo_tpu.anomaly.diff import DiffBasedAnomalyDetector  # noqa: F401
