"""Anomaly-detector contract.

Reference equivalent: ``gordo_components/model/anomaly/base.py`` —
``AnomalyDetectorBase`` adds ``.anomaly(X, y) -> pd.DataFrame`` to the
estimator contract; the server's ``/anomaly/prediction`` route requires it.
"""

from __future__ import annotations

import abc

import pandas as pd

from gordo_tpu.models.base import GordoBase


class AnomalyDetectorBase(GordoBase, abc.ABC):
    @abc.abstractmethod
    def anomaly(self, X, y=None, frequency=None) -> pd.DataFrame:
        """Score ``X`` (optionally against targets ``y``) into the canonical
        anomaly frame (model-input / model-output / tag-anomaly-scores /
        total-anomaly-score [+ thresholds])."""
