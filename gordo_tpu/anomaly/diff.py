"""Diff-based anomaly detection.

Reference equivalent: ``gordo_components/model/anomaly/diff.py::
DiffBasedAnomalyDetector``:

- wraps a base estimator (typically ``Pipeline[scaler, AutoEncoder]``),
- ``cross_validate`` produces out-of-fold predictions and derives **per-tag
  thresholds and an aggregate threshold** from fold-wise error statistics
  (smoothed scaled absolute error maxima, averaged across folds),
- ``anomaly`` returns a frame with per-tag ``tag-anomaly-scores``, a
  ``total-anomaly-score`` (L2 across tags), thresholds, and model in/out.

TPU-native: the entire scoring path — scale targets, scale predictions,
absolute diff, L2 aggregate — is a single jitted pure function of
``(scaler_stats, y, y_pred)`` (:func:`scores_fn`), reused by the serving
scorer; threshold derivation applies the same function per fold.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import jax.numpy as jnp
import numpy as np
import pandas as pd

from gordo_tpu import compile as compile_plane
from gordo_tpu.anomaly.base import AnomalyDetectorBase
from gordo_tpu.models.utils import make_base_dataframe
from gordo_tpu.ops.scalers import BaseTransform, MinMaxScaler
from gordo_tpu.train.cv import cross_validate
from gordo_tpu.utils.args import ParamsMixin, capture_args
from gordo_tpu.utils.trees import to_host

#: smoothing window (samples) applied to error series before taking fold
#: maxima — keeps single-sample spikes from setting thresholds (reference
#: smooths with a short rolling window the same way).
SMOOTHING_WINDOW = 6


@compile_plane.jit(name="anomaly.scores", static_argnames=("scaler_cls",))
def scores_fn(scaler_cls, scaler_stats, y, y_pred):
    """Pure scoring: per-tag scaled |diff| and total L2 score."""
    y_s = scaler_cls.apply(scaler_stats, y)
    p_s = scaler_cls.apply(scaler_stats, y_pred)
    tag_scores = jnp.abs(p_s - y_s)
    total = jnp.linalg.norm(tag_scores, axis=1)
    return tag_scores, total


def _rolling_min_max(arr: np.ndarray, window: int) -> np.ndarray:
    """max over time of the rolling min — a spike-robust maximum."""
    s = pd.DataFrame(arr).rolling(window, min_periods=1).min()
    return s.max(axis=0).to_numpy()


class DiffBasedAnomalyDetector(ParamsMixin, AnomalyDetectorBase):
    @capture_args
    def __init__(
        self,
        base_estimator: Any = None,
        scaler: Optional[BaseTransform] = None,
        require_thresholds: bool = True,
        window: Optional[int] = None,
    ):
        if base_estimator is None:
            from gordo_tpu.models.estimator import AutoEncoder
            from gordo_tpu.pipeline import Pipeline

            base_estimator = Pipeline([MinMaxScaler(), AutoEncoder()])
        self.base_estimator = base_estimator
        self.scaler = scaler if scaler is not None else MinMaxScaler()
        self.require_thresholds = require_thresholds
        self.window = window
        self.feature_thresholds_: Optional[np.ndarray] = None
        self.aggregate_threshold_: Optional[float] = None
        self.cv_metadata_: Dict[str, Any] = {}

    @property
    def offset(self) -> int:
        return getattr(self.base_estimator, "offset", 0)

    # -- estimator surface ---------------------------------------------------
    def fit(self, X, y=None, **kwargs):
        X_arr = np.asarray(X, dtype=np.float32)
        y_arr = X_arr if y is None else np.asarray(y, dtype=np.float32)
        self.scaler.fit(y_arr)
        self.base_estimator.fit(X_arr, y_arr, **kwargs)
        return self

    def predict(self, X):
        return self.base_estimator.predict(X)

    def score(self, X, y=None, sample_weight=None):
        return self.base_estimator.score(X, y, sample_weight)

    # -- cross-validation + thresholds ---------------------------------------
    def cross_validate(self, X, y=None, cv=None) -> Dict[str, Any]:
        """Fold-wise fit/predict; derives thresholds from out-of-fold errors.

        Threshold semantics (reference parity): per fold, the per-tag scaled
        absolute error is smoothed (rolling-min over SMOOTHING_WINDOW) and
        its maximum taken; fold maxima are averaged into
        ``feature_thresholds_``; the same on the L2 total gives
        ``aggregate_threshold_``.
        """
        X_arr = np.asarray(X, dtype=np.float32)
        y_arr = X_arr if y is None else np.asarray(y, dtype=np.float32)
        self.scaler.fit(y_arr)
        stats = to_host(self.scaler.stats_)
        scaler_cls = type(self.scaler)

        results = cross_validate(self.base_estimator, X_arr, y_arr, cv=cv)

        fold_tag_maxima = []
        fold_total_maxima = []
        for _, y_true, y_pred in results["predictions"]:
            tag_scores, total = scores_fn(
                scaler_cls, stats, jnp.asarray(y_true), jnp.asarray(y_pred)
            )
            fold_tag_maxima.append(_rolling_min_max(np.asarray(tag_scores), SMOOTHING_WINDOW))
            fold_total_maxima.append(
                float(_rolling_min_max(np.asarray(total)[:, None], SMOOTHING_WINDOW)[0])
            )

        self.feature_thresholds_ = np.mean(fold_tag_maxima, axis=0)
        self.aggregate_threshold_ = float(np.mean(fold_total_maxima))
        self.cv_metadata_ = {
            "scores": results["scores"],
            "feature_thresholds": [float(v) for v in self.feature_thresholds_],
            "aggregate_threshold": self.aggregate_threshold_,
        }
        return results

    # -- anomaly scoring -----------------------------------------------------
    def anomaly(self, X, y=None, frequency=None) -> pd.DataFrame:
        index = X.index if isinstance(X, pd.DataFrame) else None
        tags = list(X.columns) if isinstance(X, pd.DataFrame) else None
        X_arr = np.asarray(X, dtype=np.float32)
        y_arr = X_arr if y is None else np.asarray(y, dtype=np.float32)

        pred = np.asarray(self.predict(X_arr))
        offset = self.offset
        y_aligned = y_arr[offset:]

        stats = to_host(self.scaler.stats_)
        tag_scores, total = scores_fn(
            type(self.scaler), stats, jnp.asarray(y_aligned), jnp.asarray(pred)
        )
        tag_scores = np.asarray(tag_scores)
        total = np.asarray(total)

        if self.window:
            tag_scores = (
                pd.DataFrame(tag_scores).rolling(self.window, min_periods=1).median().to_numpy()
            )
            total = (
                pd.Series(total).rolling(self.window, min_periods=1).median().to_numpy()
            )

        tags = tags or [f"sensor_{i}" for i in range(X_arr.shape[1])]
        frame = make_base_dataframe(
            tags, X_arr, pred, index=index, frequency=frequency
        )
        n = len(frame)
        for i, tag in enumerate(tags[: tag_scores.shape[1]]):
            frame[("tag-anomaly-scores", str(tag))] = tag_scores[-n:, i]
        frame[("total-anomaly-score", "")] = total[-n:]

        if self.feature_thresholds_ is not None:
            for i, tag in enumerate(tags[: len(self.feature_thresholds_)]):
                frame[("tag-anomaly-thresholds", str(tag))] = self.feature_thresholds_[i]
            frame[("total-anomaly-threshold", "")] = self.aggregate_threshold_
            with np.errstate(divide="ignore", invalid="ignore"):
                confidence = total[-n:] / max(self.aggregate_threshold_, 1e-12)
            frame[("anomaly-confidence", "")] = confidence
        elif self.require_thresholds:
            raise AttributeError(
                "DiffBasedAnomalyDetector.anomaly called with "
                "require_thresholds=True but cross_validate() has not been "
                "run to derive thresholds"
            )
        return frame

    # -- metadata ------------------------------------------------------------
    def get_metadata(self) -> Dict[str, Any]:
        meta = {
            "anomaly_detector": type(self).__name__,
            "scaler": type(self.scaler).__name__,
            "require_thresholds": self.require_thresholds,
        }
        if self.cv_metadata_:
            meta["cross_validation"] = self.cv_metadata_
        if hasattr(self.base_estimator, "get_metadata"):
            meta["base_estimator"] = self.base_estimator.get_metadata()
        return meta
