"""Fleet-wide observability: metrics, Prometheus exposition, trace spans.

The telemetry plane every layer reports through:

- :mod:`gordo_tpu.telemetry.metrics` — process-wide registry of counters,
  gauges and fixed-bucket histograms; Prometheus text exposition
  (``serve/server.py`` mounts it at ``GET /metrics``); JSON snapshots the
  multi-host builder writes per shard and watchman/CLI merge.
- :mod:`gordo_tpu.telemetry.spans` — wall-clock trace spans with a
  context-propagated trace id (``X-Gordo-Trace-Id`` header), layered on
  top of the opt-in ``utils/profiling.trace`` jax-profiler hook.
- :mod:`gordo_tpu.telemetry.fleet_health` — per-machine anomaly-score
  distribution sketches (mergeable log-bucket histograms), build-time
  baselines, and the baseline-vs-live drift signal behind the
  ``gordo_machine_*`` gauges, ``/fleet-health`` docs, and rollup files.

Kill switch: ``GORDO_TELEMETRY=off`` (or :func:`set_enabled`) turns every
record call into a cheap no-op; ``bench.py --stage telemetry_overhead``
attests the instrumented hot path costs <= 2% vs the switch.
"""

from gordo_tpu.telemetry.metrics import (  # noqa: F401
    REGISTRY,
    MetricsRegistry,
    add_instance_label,
    counter,
    enabled,
    gauge,
    histogram,
    load_snapshot_dir,
    log_event,
    merge_expositions,
    merge_snapshots,
    render,
    render_snapshot,
    set_enabled,
)
from gordo_tpu.telemetry.fleet_health import (  # noqa: F401
    FLEET_HEALTH,
    FleetHealth,
    ScoreSketch,
    baselines_from_archive,
    drift_score,
    load_rollups,
    merge_health_docs,
    normalize_health_doc,
    read_rollups,
    sketch_from_scores,
    write_rollup,
)
from gordo_tpu.telemetry.spans import (  # noqa: F401
    DEADLINE_HEADER,
    TRACE_HEADER,
    current_trace_id,
    ensure_trace_id,
    new_trace_id,
    set_trace_id,
    span,
)

#: directory (under a build's output dir) where shard-local metric
#: snapshots land — one file per process of a (multi-host) project build
SNAPSHOT_DIR = ".gordo-telemetry"

__all__ = [
    "FLEET_HEALTH",
    "FleetHealth",
    "REGISTRY",
    "MetricsRegistry",
    "SNAPSHOT_DIR",
    "DEADLINE_HEADER",
    "ScoreSketch",
    "TRACE_HEADER",
    "add_instance_label",
    "counter",
    "drift_score",
    "current_trace_id",
    "enabled",
    "ensure_trace_id",
    "gauge",
    "histogram",
    "load_rollups",
    "load_snapshot_dir",
    "log_event",
    "merge_expositions",
    "merge_health_docs",
    "merge_snapshots",
    "new_trace_id",
    "normalize_health_doc",
    "baselines_from_archive",
    "read_rollups",
    "render",
    "render_snapshot",
    "set_enabled",
    "set_trace_id",
    "sketch_from_scores",
    "span",
    "write_rollup",
]
