"""Wall-clock trace spans with a context-propagated trace id.

This is the request-scoped half of the telemetry plane: where
``telemetry.metrics`` answers "how often / how slow on aggregate",
spans answer "where did THIS request's time go" — client → HTTP header →
server handler → coalescer dispatch → scorer, all stitched by one trace
id riding the ``X-Gordo-Trace-Id`` header.

Layering: spans sit ON TOP of ``utils/profiling.trace`` (the opt-in
``jax.profiler`` hook), not instead of it.  The profiler answers
"what did XLA do inside this section" at Perfetto granularity when
``GORDO_PROFILE_DIR`` is set; spans are always-on wall-clock timing that
feeds the ``gordo_span_seconds`` histogram and (optionally) a JSONL span
log, cheap enough for every request.

Span log: set ``GORDO_SPAN_LOG=/path/spans.jsonl`` and every finished
span appends one JSON line ``{ts, trace, span, seconds, ...attrs}``.
Off by default — the histograms alone carry the aggregate signal.
The file is size-capped: at ``GORDO_SPAN_LOG_MAX_BYTES`` (default
64 MiB) it rotates to ``spans.jsonl.1``, keeping the last 2 files — a
long-lived server under heavy traffic previously grew it unboundedly.
"""

from __future__ import annotations

import contextlib
import contextvars
import json
import logging
import os
import threading
import time
import uuid
from typing import Any, Dict, Iterator, Optional

from gordo_tpu.telemetry import metrics
from gordo_tpu.telemetry.rotate import append_jsonl_line

logger = logging.getLogger(__name__)

#: the propagation header: clients send it, servers echo it back and tag
#: their spans with it; absent on ingress the server mints one so every
#: request is traceable end-to-end regardless of the caller
TRACE_HEADER = "X-Gordo-Trace-Id"

#: deadline propagation: the REMAINING request budget in integer
#: milliseconds, restamped by the client at each send.  The server
#: middleware converts it back to an absolute monotonic deadline and the
#: coalescer drops riders whose budget expired before dispatch — work
#: that is already dead upstream never reaches the device.
DEADLINE_HEADER = "X-Gordo-Deadline-Ms"

ENV_SPAN_LOG = "GORDO_SPAN_LOG"
ENV_SPAN_LOG_MAX_BYTES = "GORDO_SPAN_LOG_MAX_BYTES"

#: span-log rotation threshold (bytes); the crossing line starts the
#: next generation and the previous one survives as ``<path>.1``
DEFAULT_SPAN_LOG_MAX_BYTES = 64 * 1024 * 1024

_trace_id: "contextvars.ContextVar[Optional[str]]" = contextvars.ContextVar(
    "gordo_trace_id", default=None
)

_SPAN_SECONDS = metrics.histogram(
    "gordo_span_seconds",
    "Wall-clock duration of named trace spans",
    labels=("span",),
)

_log_lock = threading.Lock()


def new_trace_id() -> str:
    """16-hex-char trace id (random; uniqueness, not secrecy)."""
    return uuid.uuid4().hex[:16]


def current_trace_id() -> Optional[str]:
    """The trace id bound to this execution context, or None."""
    return _trace_id.get()


def set_trace_id(trace_id: Optional[str]) -> "contextvars.Token":
    """Bind a trace id to the current context (handlers call this on
    ingress); returns the token for symmetric reset."""
    return _trace_id.set(trace_id)


def ensure_trace_id() -> str:
    """Current trace id, minting and binding one if absent."""
    tid = _trace_id.get()
    if tid is None:
        tid = new_trace_id()
        _trace_id.set(tid)
    return tid


def span_log_path() -> Optional[str]:
    return os.environ.get(ENV_SPAN_LOG) or None


def span_log_max_bytes() -> int:
    try:
        return int(
            os.environ.get(ENV_SPAN_LOG_MAX_BYTES, "")
            or DEFAULT_SPAN_LOG_MAX_BYTES
        )
    except ValueError:
        return DEFAULT_SPAN_LOG_MAX_BYTES


def _write_span_line(doc: Dict[str, Any]) -> None:
    path = span_log_path()
    if not path:
        return
    try:
        line = json.dumps(doc)
        with _log_lock:
            # size-capped keep-last-2 rotation: a busy server's span log
            # is bounded at ~2x the cap instead of growing forever
            append_jsonl_line(path, line, max_bytes=span_log_max_bytes())
    except Exception:  # the span log must never break the traced path
        logger.exception("span log append failed")


@contextlib.contextmanager
def span(name: str, trace_id: Optional[str] = None,
         **attrs: Any) -> Iterator[Dict[str, Any]]:
    """Time a section: feeds ``gordo_span_seconds{span=name}`` and (when
    ``GORDO_SPAN_LOG`` is set) appends one JSONL line.  ``name`` is a
    histogram label — keep it a BOUNDED set (route names, stage names);
    per-request values belong in ``attrs``, which only reach the span
    log.  Yields the attrs dict so callers can attach results
    (e.g. batch sizes known only at exit)."""
    if not metrics.enabled():
        yield attrs
        return
    tid = trace_id if trace_id is not None else current_trace_id()
    t0 = time.perf_counter()
    try:
        yield attrs
    finally:
        seconds = time.perf_counter() - t0
        _SPAN_SECONDS.observe(seconds, name)
        if span_log_path():
            doc: Dict[str, Any] = {
                "ts": round(time.time(), 6),
                "span": name,
                "seconds": round(seconds, 6),
            }
            if tid:
                doc["trace"] = tid
            doc.update(attrs)
            _write_span_line(doc)
