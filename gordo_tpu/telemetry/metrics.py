"""Process-wide metrics registry with Prometheus text exposition.

Reference status: absent upstream — the reference recorded build wall-times
into artifact metadata and nothing else (SURVEY.md §6.1); serving and fleet
behavior were unobservable at runtime.  Production ML systems treat
monitoring as a first-class subsystem (the TensorFlow paper ships a whole
metrics plane), and the adaptive machinery this repo grew in r6/r7 (knee
estimation, saturation stand-downs, barrier timeouts, resumable exits) is
exactly the kind of behavior that must be visible while it happens, not
reconstructed from logs afterwards.

Design constraints, in priority order:

- **Hot-path cheap.**  A counter increment on the serve path is a dict
  lookup plus a float add under a per-metric lock (uncontended in
  practice: the GIL serializes the adds and the lock only arbitrates the
  rare first-touch of a new label set).  The ``GORDO_TELEMETRY=off`` kill
  switch turns every record call into one attribute read and a return —
  the bench's ``telemetry_overhead`` stage holds the instrumented path to
  <= 2% of the disabled one.
- **Dependency-free.**  No prometheus_client in the image; the text
  exposition format is simple enough to emit directly, and owning it
  keeps the registry snapshot-able as JSON (the multi-host builder writes
  shard-local snapshots that watchman/CLI merge).
- **One naming convention.**  Every metric name must match
  ``gordo_[a-z_]+`` (enforced here at registration AND statically by
  ``scripts/lint.py``), with the usual Prometheus suffix conventions:
  ``*_total`` for counters, ``*_seconds`` for time histograms.

The module-level :data:`REGISTRY` is the process's default; components
register their instruments at import time via :func:`counter` /
:func:`gauge` / :func:`histogram` (get-or-create, so re-imports and tests
share series instead of colliding).
"""

from __future__ import annotations

import json
import logging
import os
import re
import threading
import time
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

ENV_TELEMETRY = "GORDO_TELEMETRY"

#: the catalog rule: lowercase, underscore-separated, gordo-prefixed.
#: scripts/lint.py enforces the same pattern statically over the repo so a
#: misnamed metric fails CI before it ever registers.
NAME_RE = re.compile(r"^gordo_[a-z_]+$")

#: default latency buckets (seconds): sub-ms device dispatches through
#: multi-second cold compiles.  Histograms are fixed-bucket by design —
#: per-observation cost is one binary search, and fixed buckets merge
#: trivially across shard snapshots and scraped endpoints.
DEFAULT_TIME_BUCKETS = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
    0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
)

#: batch/queue-size buckets (counts, not seconds)
DEFAULT_SIZE_BUCKETS = (1, 2, 4, 8, 16, 32, 64, 128, 256, 512)


def _env_enabled() -> bool:
    return os.environ.get(ENV_TELEMETRY, "").lower() not in (
        "off", "0", "false", "disabled",
    )


def _escape_label(value: str) -> str:
    return (
        str(value)
        .replace("\\", "\\\\")
        .replace("\n", "\\n")
        .replace('"', '\\"')
    )


def _fmt(value: float) -> str:
    """Prometheus sample value: integers render without the trailing .0
    noise, everything else as repr (shortest round-trip)."""
    f = float(value)
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


class _Metric:
    """Shared label-series bookkeeping for the three instrument kinds."""

    kind = "untyped"

    def __init__(self, registry: "MetricsRegistry", name: str, help: str,
                 labels: Sequence[str] = ()):
        if not NAME_RE.match(name):
            raise ValueError(
                f"metric name {name!r} violates the catalog convention "
                f"{NAME_RE.pattern!r} (see docs/observability.md)"
            )
        self.registry = registry
        self.name = name
        self.help = help
        self.label_names = tuple(labels)
        self._lock = threading.Lock()
        self._series: Dict[Tuple[str, ...], Any] = {}

    def _key(self, label_values: Tuple[Any, ...]) -> Tuple[str, ...]:
        if len(label_values) != len(self.label_names):
            raise ValueError(
                f"{self.name} expects {len(self.label_names)} label "
                f"value(s) {self.label_names}, got {label_values!r}"
            )
        return tuple(str(v) for v in label_values)

    def _series_lines(self) -> List[str]:
        raise NotImplementedError

    def render(self) -> List[str]:
        lines = [
            f"# HELP {self.name} {self.help}",
            f"# TYPE {self.name} {self.kind}",
        ]
        lines.extend(self._series_lines())
        return lines

    def reset_series(self) -> None:
        """Drop every label series (values AND label sets).  Exists for
        scrape-time-refreshed bounded-cardinality exports — the fleet
        health plane re-publishes only the current top-K machines per
        scrape, and without a reset a machine rotating OUT of the top-K
        would leave its stale sample on /metrics forever."""
        with self._lock:
            self._series.clear()

    def _label_str(self, key: Tuple[str, ...], extra: str = "") -> str:
        parts = [
            f'{n}="{_escape_label(v)}"'
            for n, v in zip(self.label_names, key)
        ]
        if extra:
            parts.append(extra)
        return "{" + ",".join(parts) + "}" if parts else ""


class Counter(_Metric):
    """Monotonic float counter, optionally labeled."""

    kind = "counter"

    def inc(self, amount: float = 1.0, *label_values: Any) -> None:
        if not self.registry.enabled:
            return
        key = self._key(label_values)
        with self._lock:
            self._series[key] = self._series.get(key, 0.0) + amount

    def value(self, *label_values: Any) -> float:
        return float(self._series.get(self._key(label_values), 0.0))

    def _series_lines(self) -> List[str]:
        with self._lock:
            items = sorted(self._series.items())
        return [
            f"{self.name}{self._label_str(k)} {_fmt(v)}" for k, v in items
        ]


class Gauge(_Metric):
    """Last-write-wins instantaneous value, optionally labeled."""

    kind = "gauge"

    def set(self, value: float, *label_values: Any) -> None:
        if not self.registry.enabled:
            return
        key = self._key(label_values)
        with self._lock:
            self._series[key] = float(value)

    def value(self, *label_values: Any) -> float:
        return float(self._series.get(self._key(label_values), 0.0))

    def _series_lines(self) -> List[str]:
        with self._lock:
            items = sorted(self._series.items())
        return [
            f"{self.name}{self._label_str(k)} {_fmt(v)}" for k, v in items
        ]


class Histogram(_Metric):
    """Fixed-bucket histogram with ``le``-inclusive Prometheus semantics.

    Per-bucket counts are stored non-cumulative (merging shard snapshots
    is then plain addition); exposition renders the cumulative form the
    text format requires.
    """

    kind = "histogram"

    def __init__(self, registry, name, help, labels=(),
                 buckets: Sequence[float] = DEFAULT_TIME_BUCKETS):
        super().__init__(registry, name, help, labels)
        self.buckets = tuple(sorted(float(b) for b in buckets))
        if not self.buckets:
            raise ValueError(f"{name}: histogram needs at least one bucket")

    def observe(self, value: float, *label_values: Any) -> None:
        if not self.registry.enabled:
            return
        key = self._key(label_values)
        with self._lock:
            state = self._series.get(key)
            if state is None:
                # [per-bucket counts..., +Inf count], sum, count
                state = [[0] * (len(self.buckets) + 1), 0.0, 0]
                self._series[key] = state
            counts, _, _ = state
            i = 0
            for i, bound in enumerate(self.buckets):
                if value <= bound:
                    counts[i] += 1
                    break
            else:
                counts[len(self.buckets)] += 1
            state[1] += float(value)
            state[2] += 1

    def snapshot_series(self, *label_values: Any) -> Dict[str, Any]:
        state = self._series.get(self._key(label_values))
        if state is None:
            return {"counts": [0] * (len(self.buckets) + 1),
                    "sum": 0.0, "count": 0}
        return {
            "counts": list(state[0]), "sum": state[1], "count": state[2],
        }

    def _series_lines(self) -> List[str]:
        with self._lock:
            items = sorted(
                (k, [list(v[0]), v[1], v[2]]) for k, v in self._series.items()
            )
        lines: List[str] = []
        for key, (counts, total, count) in items:
            cum = 0
            for bound, c in zip(self.buckets, counts):
                cum += c
                le = 'le="%s"' % _fmt(bound)
                lines.append(
                    f"{self.name}_bucket{self._label_str(key, le)} {cum}"
                )
            cum += counts[-1]
            le_inf = 'le="+Inf"'
            lines.append(
                f"{self.name}_bucket{self._label_str(key, le_inf)} {cum}"
            )
            lines.append(f"{self.name}_sum{self._label_str(key)} {_fmt(total)}")
            lines.append(f"{self.name}_count{self._label_str(key)} {count}")
        return lines


class MetricsRegistry:
    """Get-or-create instrument registry + exposition/snapshot surface."""

    def __init__(self, enabled: Optional[bool] = None):
        self.enabled = _env_enabled() if enabled is None else enabled
        self._lock = threading.Lock()
        self._metrics: Dict[str, _Metric] = {}

    def set_enabled(self, enabled: bool) -> None:
        """Runtime kill switch (env ``GORDO_TELEMETRY=off`` sets the
        initial state; benches toggle it to measure their own overhead).
        Disabling stops recording; registered series keep their values."""
        self.enabled = bool(enabled)

    def _get_or_create(self, cls, name, help, labels, **kw) -> _Metric:
        with self._lock:
            existing = self._metrics.get(name)
            if existing is not None:
                if type(existing) is not cls or (
                    existing.label_names != tuple(labels)
                ):
                    raise ValueError(
                        f"metric {name!r} already registered as "
                        f"{existing.kind} with labels {existing.label_names}"
                    )
                return existing
            metric = cls(self, name, help, labels, **kw)
            self._metrics[name] = metric
            return metric

    def counter(self, name: str, help: str,
                labels: Sequence[str] = ()) -> Counter:
        return self._get_or_create(Counter, name, help, labels)

    def gauge(self, name: str, help: str,
              labels: Sequence[str] = ()) -> Gauge:
        return self._get_or_create(Gauge, name, help, labels)

    def histogram(self, name: str, help: str, labels: Sequence[str] = (),
                  buckets: Sequence[float] = DEFAULT_TIME_BUCKETS) -> Histogram:
        return self._get_or_create(
            Histogram, name, help, labels, buckets=buckets
        )

    def get(self, name: str) -> Optional[_Metric]:
        return self._metrics.get(name)

    def render(self) -> str:
        """The full Prometheus text exposition (``text/plain; version=0.0.4``)."""
        lines: List[str] = []
        for name in sorted(self._metrics):
            lines.extend(self._metrics[name].render())
        return "\n".join(lines) + "\n"

    # -- snapshots (shard-local files the fleet layers merge) ---------------
    def snapshot(self) -> Dict[str, Any]:
        """JSON-able dump of every series.  Counter/histogram series merge
        across snapshots by addition; gauges are last-write (the merge
        keeps the value from the latest snapshot)."""
        out: Dict[str, Any] = {}
        for name, metric in sorted(self._metrics.items()):
            doc: Dict[str, Any] = {
                "kind": metric.kind,
                "help": metric.help,
                "labels": list(metric.label_names),
            }
            if isinstance(metric, Histogram):
                doc["buckets"] = list(metric.buckets)
                doc["series"] = {
                    json.dumps(list(k)): {
                        "counts": list(v[0]), "sum": v[1], "count": v[2],
                    }
                    for k, v in metric._series.items()
                }
            else:
                doc["series"] = {
                    json.dumps(list(k)): v
                    for k, v in metric._series.items()
                }
            out[name] = doc
        return {"gordo_telemetry_snapshot": 1, "time": time.time(),
                "metrics": out}

    def write_snapshot(self, path: str) -> None:
        """Atomic snapshot dump (tmp + rename, like the shard state files:
        a SIGKILL mid-write must not leave a torn document)."""
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(self.snapshot(), f, indent=1)
        os.replace(tmp, path)


def merge_snapshots(snapshots: Iterable[Dict[str, Any]]) -> Dict[str, Any]:
    """Merge snapshot documents (shard-local files from a multi-host
    build, or per-process dumps): counters and histogram series add,
    gauges take the value from the latest-``time`` snapshot."""
    merged: Dict[str, Any] = {}
    merged_time: Dict[str, Dict[str, float]] = {}
    out_time = 0.0
    for snap in snapshots:
        snap_time = float(snap.get("time", 0.0))
        out_time = max(out_time, snap_time)
        for name, doc in snap.get("metrics", {}).items():
            into = merged.get(name)
            if into is None:
                merged[name] = json.loads(json.dumps(doc))  # deep copy
                merged_time[name] = {
                    k: snap_time for k in doc.get("series", {})
                }
                continue
            series_time = merged_time[name]
            for key, value in doc.get("series", {}).items():
                if key not in into["series"]:
                    into["series"][key] = json.loads(json.dumps(value))
                    series_time[key] = snap_time
                elif doc["kind"] == "histogram":
                    tgt = into["series"][key]
                    tgt["counts"] = [
                        a + b for a, b in zip(tgt["counts"], value["counts"])
                    ]
                    tgt["sum"] += value["sum"]
                    tgt["count"] += value["count"]
                elif doc["kind"] == "gauge":
                    if snap_time >= series_time.get(key, 0.0):
                        into["series"][key] = value
                        series_time[key] = snap_time
                else:  # counter
                    into["series"][key] += value
    return {"gordo_telemetry_snapshot": 1, "time": out_time,
            "metrics": merged}


def render_snapshot(snapshot: Dict[str, Any]) -> str:
    """Snapshot document → Prometheus text (loads into a throwaway
    registry so exposition has exactly one implementation)."""
    reg = MetricsRegistry(enabled=True)
    for name, doc in sorted(snapshot.get("metrics", {}).items()):
        labels = doc.get("labels", [])
        if doc["kind"] == "histogram":
            h = reg.histogram(name, doc.get("help", ""), labels,
                              buckets=doc.get("buckets") or DEFAULT_TIME_BUCKETS)
            for key, v in doc.get("series", {}).items():
                h._series[tuple(json.loads(key))] = [
                    list(v["counts"]), v["sum"], v["count"],
                ]
        else:
            m = (reg.counter if doc["kind"] == "counter" else reg.gauge)(
                name, doc.get("help", ""), labels
            )
            for key, v in doc.get("series", {}).items():
                m._series[tuple(json.loads(key))] = float(v)
    return reg.render()


def load_snapshot_dir(directory: str) -> List[Dict[str, Any]]:
    """All snapshot JSONs under ``directory`` (the ``.gordo-telemetry/``
    dir a project build maintains — one file per shard/process)."""
    snaps: List[Dict[str, Any]] = []
    if not os.path.isdir(directory):
        return snaps
    for fname in sorted(os.listdir(directory)):
        if not fname.endswith(".json"):
            continue
        try:
            with open(os.path.join(directory, fname)) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            continue
        if doc.get("gordo_telemetry_snapshot"):
            snaps.append(doc)
    return snaps


def add_instance_label(exposition: str, instance: str) -> str:
    """Inject ``instance="<url>"`` into every sample of a Prometheus text
    exposition — how watchman merges N endpoints' scrapes without
    guessing merge semantics (summing a ``batch_cap`` gauge across
    servers would be a lie; per-instance series are just the truth)."""
    out: List[str] = []
    esc = _escape_label(instance)
    for line in exposition.splitlines():
        if not line or line.startswith("#"):
            out.append(line)
            continue
        name_part, _, value_part = line.rpartition(" ")
        if not name_part:
            out.append(line)
            continue
        if name_part.endswith("}"):
            rewritten = name_part[:-1] + f',instance="{esc}"}}'
        else:
            rewritten = name_part + f'{{instance="{esc}"}}'
        out.append(f"{rewritten} {value_part}")
    return "\n".join(out) + ("\n" if exposition.endswith("\n") else "")


def merge_expositions(pairs: Sequence[Tuple[str, str]]) -> str:
    """Merge N Prometheus text expositions into one, tagging every sample
    with ``instance="<id>"`` (``pairs`` is ``[(instance_id, text), ...]``).

    Families regroup so each metric's samples stay contiguous under one
    HELP/TYPE header — the text format requires all lines of a family in
    a single group, which naive concatenation of per-target scrapes
    violates.  Conflicting HELP strings keep the first seen.
    """
    help_lines: Dict[str, str] = {}
    type_lines: Dict[str, str] = {}
    samples: Dict[str, List[str]] = {}
    for instance, text in pairs:
        labeled = add_instance_label(text, instance)
        family: Optional[str] = None
        for line in labeled.splitlines():
            if line.startswith("# HELP ") or line.startswith("# TYPE "):
                parts = line.split(None, 3)
                if len(parts) < 3:
                    continue
                family = parts[2]
                target = help_lines if parts[1] == "HELP" else type_lines
                target.setdefault(family, line)
            elif line.strip() and not line.startswith("#"):
                # samples attach to the family block they appeared under;
                # a headerless line keys by its own bare metric name
                key = family or line.split("{", 1)[0].split(" ", 1)[0]
                samples.setdefault(key, []).append(line)
    out: List[str] = []
    for name in sorted(set(samples) | set(type_lines)):
        if name in help_lines:
            out.append(help_lines[name])
        if name in type_lines:
            out.append(type_lines[name])
        out.extend(samples.get(name, ()))
    return "\n".join(out) + "\n"


#: the process-wide default registry every component records into
REGISTRY = MetricsRegistry()


def enabled() -> bool:
    return REGISTRY.enabled


def set_enabled(value: bool) -> None:
    REGISTRY.set_enabled(value)


def counter(name: str, help: str, labels: Sequence[str] = ()) -> Counter:
    return REGISTRY.counter(name, help, labels)


def gauge(name: str, help: str, labels: Sequence[str] = ()) -> Gauge:
    return REGISTRY.gauge(name, help, labels)


def histogram(name: str, help: str, labels: Sequence[str] = (),
              buckets: Sequence[float] = DEFAULT_TIME_BUCKETS) -> Histogram:
    return REGISTRY.histogram(name, help, labels, buckets=buckets)


def render() -> str:
    return REGISTRY.render()


#: every structured event increments this, so event rates are queryable
#: even when nobody tails the logs
_EVENTS = REGISTRY.counter(
    "gordo_events_total",
    "Structured operational events by name (see docs/observability.md)",
    labels=("event",),
)


def log_event(target_logger: logging.Logger, event: str,
              level: int = logging.WARNING, **fields: Any) -> None:
    """Count + log one operational event as a SINGLE structured line:
    ``EVENT <name> key=value ...`` — grep-able, parse-able, and exactly
    one line per occurrence (the satellite contract for stand-downs,
    knee estimates, barrier timeouts and resumable exits)."""
    _EVENTS.inc(1.0, event)
    parts = " ".join(f"{k}={v}" for k, v in fields.items())
    target_logger.log(level, "EVENT %s %s", event, parts)
