"""Size-capped JSONL appends with keep-last-2 rotation.

Shared by the span log (``GORDO_SPAN_LOG`` — which previously grew
unboundedly on long-lived servers) and the fleet-health rollup files:
both are append-only operational JSONL streams whose old tail matters
far less than bounding disk use.  Rotation is rename-based (``path`` →
``path.1``, replacing the previous ``.1``), so a reader always sees at
most two files and the live file never exceeds ~max_bytes + one line.
"""

from __future__ import annotations

import os
import threading
from typing import Optional

#: one lock for all rotating appenders in the process: appends are rare
#: (per span / per rollup tick) and a shared lock keeps the
#: check-size → rotate → append sequence atomic across streams sharing
#: a path (two threads rotating the same file concurrently would drop a
#: generation)
_LOCK = threading.Lock()


def rotated_path(path: str) -> str:
    """Where the previous generation lives after a rotation."""
    return path + ".1"


def rotate_if_large(path: str, max_bytes: int) -> bool:
    """Rotate ``path`` to ``path.1`` when it has reached ``max_bytes``
    (the old ``.1`` is replaced — keep-last-2).  Returns True when a
    rotation happened.  Caller holds no lock; this takes the module
    lock itself."""
    with _LOCK:
        return _rotate_locked(path, max_bytes)


def _rotate_locked(path: str, max_bytes: int) -> bool:
    if max_bytes <= 0:
        return False
    try:
        size = os.path.getsize(path)
    except OSError:
        return False  # nothing there yet
    if size < max_bytes:
        return False
    os.replace(path, rotated_path(path))
    return True


def append_jsonl_line(
    path: str, line: str, max_bytes: Optional[int] = None
) -> None:
    """Append one line to ``path`` (creating parent dirs), rotating
    first when the file already holds ``max_bytes`` — the line that
    crosses the cap starts the next generation, so no single append is
    ever split across files."""
    with _LOCK:
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        if max_bytes:
            _rotate_locked(path, max_bytes)
        with open(path, "a") as fh:
            fh.write(line.rstrip("\n") + "\n")
