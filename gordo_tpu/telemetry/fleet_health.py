"""Fleet health plane: per-machine score-distribution sketches and drift.

Reference status: absent upstream — the reference could say whether a
*server* was up (watchman's health poll) but nothing about the *fleet
under observation*: which of 10k machines are drifting away from their
training-time behavior, scoring hot, or silently receiving no traffic.
This module is the observability layer ROADMAP item 3 (drift-driven
incremental rebuilds) is blocked on: scoring feeds a per-machine
streaming sketch, the build plane records the same sketch over the
training residuals, and the distance between the two IS the drift
signal `gordo refresh` will consume.

Design constraints, in priority order:

- **Near-zero hot-path cost.**  Recording accumulates from the response
  arrays the serve path has ALREADY fetched to host (no extra D2H): one
  vectorized ``searchsorted`` + ``bincount`` over the request's total
  anomaly scores, a few float adds, under a per-sketch lock.  The
  ``GORDO_TELEMETRY=off`` kill switch applies, and
  ``bench.py --stage health_overhead`` holds the recording path within
  the existing <= 2% telemetry budget.
- **Exactly mergeable.**  Sketches are fixed log-scale bucket counts
  plus plain sums — shard A + shard B is integer/float addition, so a
  fleet-sharded tier's per-replica health docs merge into the SAME doc
  a single process serving the whole fleet would produce (modulo
  timestamps; the bench pins this byte-equivalence).  Associativity and
  commutativity are pinned by tests.
- **Order-invariant drift.**  The drift score is computed from bucket
  counts only (a Hellinger distance between the normalized baseline and
  live distributions), never from order-sensitive state like the EWMA —
  resorting the request stream cannot change it.

Surfaces: ``gordo_machine_*`` / ``gordo_machine_drift`` gauges (top-K by
drift, so exposition cardinality stays bounded on a 10k-machine fleet),
the full per-machine doc at ``GET /gordo/v0/<project>/fleet-health``,
periodic JSONL rollups under the artifact dir (the file interface a
``gordo refresh`` loop consumes without HTTP), and watchman's
``GET /fleet-health`` merging every shard's doc into one fleet view.
"""

from __future__ import annotations

import contextlib
import json
import logging
import os
import threading
import time
from typing import Any, Dict, Iterable, Iterator, List, Optional, Sequence

import numpy as np

from gordo_tpu.telemetry import metrics
from gordo_tpu.telemetry.rotate import append_jsonl_line

logger = logging.getLogger(__name__)

#: bump when the bucket layout below changes — sketches only merge and
#: only compare within one edges version (a mixed pair raises)
EDGES_VERSION = 1

#: fixed log-scale bucket edges for anomaly scores: HALF-OCTAVE buckets
#: (edges ``2^e`` and ``1.5 * 2^e`` for e in -10..9) spanning ~1e-3 to
#: 1024.  Half-octaves are chosen so the bucket index of a float32
#: score is a pure bit extraction — ``(bits >> 22) - offset`` (the
#: exponent plus the top mantissa bit; the raw bit pattern of a
#: positive float is monotone in its value) — which costs ~10us per
#: 2048-score response where a binary-search ``searchsorted`` cost ~30:
#: the difference between fitting the <= 2% serving budget and not.
#: Bit-extracted indices agree EXACTLY with
#: ``searchsorted(EDGES, x, side="right")`` on these edges (pinned by
#: test), and identical edges everywhere make build-time baselines,
#: live shards, and watchman merges exactly comparable.  Scores are
#: non-negative L2 magnitudes; zeros/denormals land in the underflow
#: slot, NaN/inf (a blown-up model is a distribution shift too) in
#: overflow.
N_BUCKETS = 40
EDGES = np.asarray(
    [v * 2.0 ** e for e in range(-10, 10) for v in (1.0, 1.5)]
    + [2.0 ** 10]
)

#: ``float32 bits >> 22`` of the lowest in-range edge (2^-10): the
#: offset turning raw half-octave indices into count slots
_RAW_LO = (127 - 10) << 1

#: counts layout: [underflow] + N_BUCKETS bins + [overflow]
N_SLOTS = N_BUCKETS + 2

#: EWMA smoothing for the per-machine score level (one update per
#: recorded response, on the response's mean score): recent-window
#: signal for the ``gordo_machine_score_ewma_mean`` gauge.  The drift
#: score NEVER reads it (order-sensitive by construction).
EWMA_ALPHA = 0.1

#: minimum observations BOTH sides need before a drift score is
#: computed: the Hellinger distance between a finite sample and its own
#: source distribution is positively biased ~sqrt(B/8n) (B occupied
#: buckets, n samples), so a 64-row live window against a 2048-row
#: baseline reads ~0.3 of pure sampling noise.  At 128+ scores the bias
#: sits well under the 0.25 flag threshold; until then the doc reports
#: drift=null rather than an arithmetically-true, operationally-false
#: number.
MIN_DRIFT_COUNT = 128

ENV_DRIFT_THRESHOLD = "GORDO_DRIFT_THRESHOLD"
ENV_DRIFT_TOP_K = "GORDO_DRIFT_TOP_K"
ENV_BASELINE = "GORDO_FLEET_BASELINE"
ENV_ROLLUP_MAX_BYTES = "GORDO_HEALTH_ROLLUP_MAX_BYTES"

#: directory (under a build output / artifact dir) where serving
#: processes append their periodic fleet-health rollup lines
ROLLUP_DIR = ".gordo-fleet-health"

#: default rollup file size cap before rotation (keep last 2 files)
DEFAULT_ROLLUP_MAX_BYTES = 16 * 1024 * 1024

#: metadata key the builder records the training-time baseline under
#: (``metadata["fleet-health"]["baseline"]`` = a sketch doc)
METADATA_KEY = "fleet-health"

#: training rows the baseline sketch sees, taken from the TAIL of the
#: training matrix (most recent regime): enough samples for a stable
#: 48-bucket distribution while bounding the builder's extra scoring
#: dispatch — one stacked forward pass per trained chunk, ~a bulk
#: serving round, against epochs of fwd+bwd the chunk just paid
BASELINE_MAX_ROWS = 2048


def drift_threshold() -> float:
    """Drift score above which a machine is flagged ``drifting`` (the
    Hellinger distance is bounded [0, 1]; 0.25 flags a distribution
    whose mass visibly moved across buckets while tolerating sampling
    noise on thin live windows)."""
    try:
        return float(os.environ.get(ENV_DRIFT_THRESHOLD, "") or 0.25)
    except ValueError:
        return 0.25


def drift_top_k() -> int:
    """How many machines the drift gauges export (exposition cardinality
    bound; the full set is always available via ``/fleet-health``)."""
    try:
        return int(os.environ.get(ENV_DRIFT_TOP_K, "") or 10)
    except ValueError:
        return 10


def baselines_enabled() -> bool:
    """``GORDO_FLEET_BASELINE=off`` skips the builder's training-time
    baseline sketch (the drift signal then has nothing to compare
    against — serving still sketches live scores)."""
    return os.environ.get(ENV_BASELINE, "").strip().lower() not in (
        "off", "0", "false", "disabled",
    )


class ScoreSketch:
    """Streaming sketch of one machine's anomaly-score distribution.

    Fixed log-scale bucket counts (mergeable by addition), exact
    count/sum/sum-of-squares (mergeable by addition), an EWMA of
    per-response mean scores (recent-level signal; NOT merged by
    addition — the later-seen side wins), and a last-seen timestamp.
    Thread-safe: serving records from executor threads.
    """

    __slots__ = (
        "counts", "count", "sum", "sum_sq",
        "ewma_mean", "ewma_var", "last_seen", "_lock",
    )

    def __init__(self):
        self.counts = np.zeros(N_SLOTS, dtype=np.int64)
        self.count = 0
        self.sum = 0.0
        self.sum_sq = 0.0
        self.ewma_mean: Optional[float] = None
        self.ewma_var = 0.0
        self.last_seen = 0.0
        self._lock = threading.Lock()

    def observe(self, scores: Any, ts: Optional[float] = None) -> None:
        """Fold one response's total-anomaly-score array in.  Host
        arrays only — the caller already holds the encoded response, so
        this adds no D2H and, for f32 serving outputs, no float copy:
        the bucket index is extracted straight from the float32 bit
        patterns (see EDGES), then one bincount, one f64 sum and one
        BLAS dot.  ~15us per 2048-score response."""
        flat = np.asarray(scores)
        if flat.dtype != np.float32 or not flat.flags.c_contiguous:
            flat = np.ascontiguousarray(flat, dtype=np.float32)
        flat = flat.ravel()
        if flat.size == 0:
            return
        # bin i covers [EDGES[i-1], EDGES[i]) — identical to
        # searchsorted(EDGES, flat, side="right") (pinned by test):
        # positive-float bit patterns are monotone, so exponent + top
        # mantissa bit IS the half-octave index.  Values below 2^-10
        # (incl. 0 and any negative, whose int32 view is negative) clip
        # to the underflow slot; >= 2^10, NaN and inf clip to overflow.
        idx = (flat.view(np.int32) >> 22) - (_RAW_LO - 1)
        np.clip(idx, 0, N_SLOTS - 1, out=idx)
        add = np.bincount(idx, minlength=N_SLOTS)
        total = float(flat.sum(dtype=np.float64))
        batch_mean = total / flat.size
        with self._lock:
            self.counts += add
            self.count += int(flat.size)
            self.sum += total
            self.sum_sq += float(np.dot(flat, flat))
            if self.ewma_mean is None:
                self.ewma_mean = batch_mean
            else:
                prev = self.ewma_mean
                self.ewma_mean = prev + EWMA_ALPHA * (batch_mean - prev)
                self.ewma_var = (1.0 - EWMA_ALPHA) * (
                    self.ewma_var + EWMA_ALPHA * (batch_mean - prev) ** 2
                )
            self.last_seen = time.time() if ts is None else float(ts)

    @property
    def mean(self) -> float:
        return self.sum / self.count if self.count else 0.0

    def merge(self, other: "ScoreSketch") -> None:
        """Fold ``other`` in.  Counts/sums add exactly; the EWMA pair
        combines count-weighted — weights add across merges, so the
        operation is associative AND commutative (A+B == B+A and
        (A+B)+C == A+(B+C), pinned by tests), which is what lets shard
        docs merge in any order.  A machine-affinity-sharded tier never
        actually merges two live sketches of one machine, so the
        weighted EWMA is only ever a tie-break for replayed/overlapping
        streams."""
        with self._lock:
            if other.ewma_mean is not None:
                if self.ewma_mean is None:
                    self.ewma_mean = other.ewma_mean
                    self.ewma_var = other.ewma_var
                else:
                    total = self.count + other.count
                    if total > 0:
                        w_self = self.count / total
                        w_other = other.count / total
                        self.ewma_mean = (
                            w_self * self.ewma_mean
                            + w_other * other.ewma_mean
                        )
                        self.ewma_var = (
                            w_self * self.ewma_var
                            + w_other * other.ewma_var
                        )
            self.counts += other.counts
            self.count += other.count
            self.sum += other.sum
            self.sum_sq += other.sum_sq
            self.last_seen = max(self.last_seen, other.last_seen)

    def to_doc(self) -> Dict[str, Any]:
        with self._lock:
            doc: Dict[str, Any] = {
                "v": 1,
                "edges-version": EDGES_VERSION,
                "counts": [int(c) for c in self.counts],
                "count": int(self.count),
                "sum": float(self.sum),
                "sum-sq": float(self.sum_sq),
                "last-seen": float(self.last_seen),
            }
            if self.ewma_mean is not None:
                doc["ewma-mean"] = float(self.ewma_mean)
                doc["ewma-var"] = float(self.ewma_var)
            return doc

    @classmethod
    def from_doc(cls, doc: Dict[str, Any]) -> "ScoreSketch":
        ver = int(doc.get("edges-version", 0))
        if ver != EDGES_VERSION:
            raise ValueError(
                f"sketch edges-version {ver} != supported {EDGES_VERSION}"
            )
        counts = np.asarray(doc.get("counts", ()), dtype=np.int64)
        if counts.shape != (N_SLOTS,):
            raise ValueError(
                f"sketch has {counts.size} slots, expected {N_SLOTS}"
            )
        sk = cls()
        sk.counts = counts.copy()
        sk.count = int(doc.get("count", 0))
        sk.sum = float(doc.get("sum", 0.0))
        sk.sum_sq = float(doc.get("sum-sq", 0.0))
        if doc.get("ewma-mean") is not None:
            sk.ewma_mean = float(doc["ewma-mean"])
            sk.ewma_var = float(doc.get("ewma-var", 0.0))
        sk.last_seen = float(doc.get("last-seen", 0.0))
        return sk


def sketch_from_scores(scores: Any, ts: Optional[float] = None) -> ScoreSketch:
    """One-shot sketch of an array (the builder's baseline constructor)."""
    sk = ScoreSketch()
    sk.observe(scores, ts=ts)
    return sk


def drift_score(
    baseline: Optional[Dict[str, Any]], live: Optional[Dict[str, Any]]
) -> Optional[float]:
    """Hellinger distance between two sketch docs' normalized bucket
    distributions, in [0, 1] (0 = identical shape, 1 = disjoint
    support).  Computed from counts ONLY, so it is invariant to the
    order scores arrived in and to how the stream was sharded.  None
    when either side has fewer than :data:`MIN_DRIFT_COUNT`
    observations — below that, sampling noise alone reads as drift."""
    if not baseline or not live:
        return None
    for doc in (baseline, live):
        ver = int(doc.get("edges-version", 0))
        if ver != EDGES_VERSION:
            raise ValueError(
                f"sketch edges-version {ver} != supported {EDGES_VERSION}"
            )
    p = np.asarray(baseline.get("counts", ()), dtype=np.float64)
    q = np.asarray(live.get("counts", ()), dtype=np.float64)
    if (
        p.sum() < MIN_DRIFT_COUNT
        or q.sum() < MIN_DRIFT_COUNT
        or p.shape != q.shape
    ):
        return None
    p = p / p.sum()
    q = q / q.sum()
    h = float(
        np.sqrt(0.5 * np.square(np.sqrt(p) - np.sqrt(q)).sum())
    )
    return round(min(1.0, h), 9)


def machine_status(
    baseline: Optional[Dict[str, Any]],
    live: Optional[Dict[str, Any]],
    drift: Optional[float],
    threshold: float,
) -> str:
    """One word per machine: ``drifting`` (distance past the threshold),
    ``silent`` (a baseline exists but NO live scores — the machine the
    fleet forgot), ``no-baseline`` (live traffic but the build recorded
    no residual distribution), else ``ok``."""
    has_live = bool(live and live.get("count"))
    if baseline and not has_live:
        return "silent"
    if drift is not None and drift > threshold:
        return "drifting"
    if not baseline and has_live:
        return "no-baseline"
    return "ok"


# -- telemetry instruments (docs/observability.md "Fleet health") -----------
#: exported for the TOP-K machines by drift only — a 10k-machine fleet
#: must not put 10k series on /metrics; the full set lives in the
#: /fleet-health doc.  Series reset at each export so machines rotating
#: out of the top-K don't leave stale samples behind.
_DRIFT_GAUGE = metrics.gauge(
    "gordo_machine_drift",
    "Baseline-vs-live anomaly-score distribution distance (Hellinger, "
    "0..1) for the top-K drifting machines",
    labels=("machine",),
)
_EWMA_GAUGE = metrics.gauge(
    "gordo_machine_score_ewma_mean",
    "EWMA of per-response mean total anomaly score, top-K machines",
    labels=("machine",),
)
_COUNT_GAUGE = metrics.gauge(
    "gordo_machine_score_count",
    "Live-window anomaly scores sketched per machine, top-K machines",
    labels=("machine",),
)
_STATUS_GAUGE = metrics.gauge(
    "gordo_fleet_health_machines",
    "Machines by fleet-health status (ok / drifting / silent / "
    "no-baseline) as of the latest export",
    labels=("status",),
)


class FleetHealth:
    """Process-wide registry of per-machine live sketches + baselines.

    The module-level :data:`FLEET_HEALTH` is the default every serving
    component records into (mirroring ``telemetry.metrics.REGISTRY``).
    Machines are keyed by name only: a fleet-sharded tier's replicas
    serve disjoint machines, so even two in-process test replicas never
    collide.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._live: Dict[str, ScoreSketch] = {}
        self._baselines: Dict[str, Dict[str, Any]] = {}
        self._suspend = threading.local()

    @contextlib.contextmanager
    def suspended(self) -> Iterator[None]:
        """Recording no-op for this thread while the context holds —
        the builder scores training data through the SAME serving path
        to derive baselines, and those scores must not masquerade as
        live traffic (a build+serve test process would otherwise start
        with its live windows pre-filled)."""
        prev = getattr(self._suspend, "on", False)
        self._suspend.on = True
        try:
            yield
        finally:
            self._suspend.on = prev

    # -- recording (the serve hot path) ---------------------------------
    def record(self, machine: Optional[str], scores: Any) -> None:
        """Fold one scoring response's total-anomaly-score array into
        ``machine``'s live sketch.  The ONE hot-path entry: called by
        ``serve/scorer.py`` (per-machine responses) and
        ``serve/fleet_scorer.py`` (stacked-dispatch assembly), always on
        host arrays already fetched for response encoding.  Honors the
        telemetry kill switch."""
        if machine is None or scores is None or not metrics.enabled():
            return
        if getattr(self._suspend, "on", False):
            return
        with self._lock:
            sk = self._live.get(machine)
            if sk is None:
                sk = self._live[machine] = ScoreSketch()
        sk.observe(scores)

    # -- baselines -------------------------------------------------------
    def set_baseline(
        self, machine: str, doc: Optional[Dict[str, Any]]
    ) -> None:
        with self._lock:
            if doc:
                self._baselines[machine] = dict(doc)
            else:
                self._baselines.pop(machine, None)

    def baseline(self, machine: str) -> Optional[Dict[str, Any]]:
        return self._baselines.get(machine)

    def load_baselines(self, metadata_by_name: Dict[str, Dict]) -> int:
        """Adopt training-time baselines from artifact metadata docs
        (``metadata["fleet-health"]["baseline"]``, what the builder
        records).  Returns how many machines got one."""
        n = 0
        for name, meta in metadata_by_name.items():
            doc = ((meta or {}).get(METADATA_KEY) or {}).get("baseline")
            if doc:
                self.set_baseline(name, doc)
                n += 1
        return n

    # -- lifecycle -------------------------------------------------------
    def clear(self, machines: Optional[Iterable[str]] = None) -> None:
        """Drop live sketches (and baselines) for ``machines`` — or
        everything when None.  Tests and benches phase-separate with
        this; a serving process keeps accumulating across rescans."""
        with self._lock:
            if machines is None:
                self._live.clear()
                self._baselines.clear()
                return
            for m in machines:
                self._live.pop(m, None)
                self._baselines.pop(m, None)

    def tracked(self) -> List[str]:
        with self._lock:
            return sorted(set(self._live) | set(self._baselines))

    # -- documents -------------------------------------------------------
    def doc(
        self,
        machines: Optional[Iterable[str]] = None,
        top: Optional[int] = None,
        threshold: Optional[float] = None,
    ) -> Dict[str, Any]:
        """The fleet-health document: per-machine live/baseline sketches,
        drift score and status, plus the top-K drift ranking.  Machine
        keys are sorted, so two docs over the same state serialize
        identically (the merge-parity gate depends on it)."""
        names = sorted(machines) if machines is not None else self.tracked()
        threshold = drift_threshold() if threshold is None else threshold
        top = drift_top_k() if top is None else int(top)
        out_machines: Dict[str, Any] = {}
        ranking: List[Any] = []
        for name in names:
            sk = self._live.get(name)
            live_doc = sk.to_doc() if sk is not None and sk.count else None
            base_doc = self._baselines.get(name)
            drift = drift_score(base_doc, live_doc)
            status = machine_status(base_doc, live_doc, drift, threshold)
            out_machines[name] = {
                "live": live_doc,
                "baseline": dict(base_doc) if base_doc else None,
                "drift": drift,
                "status": status,
            }
            if drift is not None:
                ranking.append((name, drift))
        ranking.sort(key=lambda item: (-item[1], item[0]))
        return {
            "gordo-fleet-health": 1,
            "time": time.time(),
            "edges-version": EDGES_VERSION,
            "drift-threshold": threshold,
            "top-drift": [
                {"machine": n, "drift": d} for n, d in ranking[:top]
            ],
            "machines": out_machines,
        }

    # -- gauges ----------------------------------------------------------
    def export_gauges(
        self,
        machines: Optional[Iterable[str]] = None,
        top: Optional[int] = None,
    ) -> None:
        """Refresh the ``gordo_machine_*`` gauges for the top-K machines
        by drift (falling back to live volume when no drift is
        computable) and the by-status fleet summary.  Called at scrape
        time — these describe "now", and resetting the series each time
        bounds cardinality at K no matter how the top set rotates."""
        if not metrics.enabled():
            return
        doc = self.doc(machines=machines, top=top)
        k = drift_top_k() if top is None else int(top)
        ranked = sorted(
            doc["machines"].items(),
            key=lambda kv: (
                -(kv[1]["drift"] if kv[1]["drift"] is not None else -1.0),
                -((kv[1]["live"] or {}).get("count", 0)),
                kv[0],
            ),
        )
        for g in (_DRIFT_GAUGE, _EWMA_GAUGE, _COUNT_GAUGE, _STATUS_GAUGE):
            g.reset_series()
        status_counts: Dict[str, int] = {}
        for name, entry in doc["machines"].items():
            status_counts[entry["status"]] = (
                status_counts.get(entry["status"], 0) + 1
            )
        for status, n in status_counts.items():
            _STATUS_GAUGE.set(float(n), status)
        for name, entry in ranked[:k]:
            live = entry["live"] or {}
            if entry["drift"] is not None:
                _DRIFT_GAUGE.set(entry["drift"], name)
            if live.get("ewma-mean") is not None:
                _EWMA_GAUGE.set(float(live["ewma-mean"]), name)
            if live.get("count"):
                _COUNT_GAUGE.set(float(live["count"]), name)


#: the process-wide default registry scoring responses record into
FLEET_HEALTH = FleetHealth()


def merge_health_docs(
    docs: Sequence[Dict[str, Any]],
    top: Optional[int] = None,
    threshold: Optional[float] = None,
) -> Dict[str, Any]:
    """Merge per-shard fleet-health docs into ONE fleet view — what
    watchman serves at ``/fleet-health`` and the CLI's ``--dir`` mode
    computes from rollup files.  Live sketches add exactly (the sketch
    merge contract); a machine seen by several docs keeps the first
    baseline (identical across shards by construction — they all read
    the same artifact metadata).  Drift, status and the top-K ranking
    recompute from the merged counts, so a machine-affinity-sharded
    tier's merged doc equals the single-process doc for the same request
    stream (modulo timestamps; pinned by ``bench --stage
    health_overhead``)."""
    live: Dict[str, ScoreSketch] = {}
    baselines: Dict[str, Dict[str, Any]] = {}
    thresholds: List[float] = []
    for doc in docs:
        if not doc:
            continue
        if doc.get("drift-threshold") is not None:
            thresholds.append(float(doc["drift-threshold"]))
        for name, entry in (doc.get("machines") or {}).items():
            if entry.get("baseline") and name not in baselines:
                baselines[name] = dict(entry["baseline"])
            if entry.get("live"):
                sk = ScoreSketch.from_doc(entry["live"])
                if name in live:
                    live[name].merge(sk)
                else:
                    live[name] = sk
    merged = FleetHealth()
    merged._live = live
    merged._baselines = baselines
    if threshold is None and thresholds:
        threshold = max(thresholds)
    return merged.doc(top=top, threshold=threshold)


def normalize_health_doc(doc: Dict[str, Any]) -> Dict[str, Any]:
    """A health doc with every volatile field removed — wall-clock
    timestamps (``time``, per-sketch ``last-seen``) and per-instance
    identity (``serve-shard``, ``instances``, ``project-name``) — so two
    docs over the same request stream compare byte-for-byte
    (``json.dumps(..., sort_keys=True)``)."""
    drop_top = {"time", "serve-shard", "instances", "project-name",
                "targets-responding"}
    out = {k: v for k, v in doc.items() if k not in drop_top}
    machines = {}
    for name, entry in (out.get("machines") or {}).items():
        entry = dict(entry)
        for key in ("live", "baseline"):
            if entry.get(key):
                entry[key] = {
                    k: v for k, v in entry[key].items() if k != "last-seen"
                }
        machines[name] = entry
    if "machines" in out:
        out["machines"] = machines
    return out


# ---------------------------------------------------------------------------
# training-time baselines (the build plane's half of the drift signal)
# ---------------------------------------------------------------------------

def training_baseline(model: Any, X: Any) -> Optional[Dict[str, Any]]:
    """One machine's training-time residual sketch, or None.

    Scores the TAIL of the training matrix (``BASELINE_MAX_ROWS`` rows)
    through the SAME fused serving scorer the live traffic will run —
    apples-to-apples by construction: any systematic difference between
    the build-time and serve-time scoring paths would read as permanent
    phantom drift.  Timestamps are pinned to 0 (a training artifact has
    no "last seen"), so a rebuilt artifact's bytes depend only on the
    model and data.  Never raises — a baseline is a hint, not a build
    step that may fail the machine."""
    if not baselines_enabled():
        return None
    try:
        from gordo_tpu.serve.scorer import CompiledScorer

        scorer = CompiledScorer(model)
        if not scorer.is_anomaly:
            return None
        Xa = np.asarray(X, np.float32)[-BASELINE_MAX_ROWS:]
        with FLEET_HEALTH.suspended():
            out = scorer.anomaly_arrays(Xa)
        return sketch_from_scores(
            out["total-anomaly-score"], ts=0.0
        ).to_doc()
    except Exception:
        logger.debug("training baseline sketch failed", exc_info=True)
        return None


def training_baselines(
    models: Dict[str, Any], X_by_name: Dict[str, Any],
    prestacked_hint: Optional[Dict[str, Any]] = None,
) -> Dict[str, Dict[str, Any]]:
    """Training-time residual sketches for a whole trained chunk in ONE
    stacked dispatch (the chunk shares a structural signature, so the
    fleet scorer buckets it into a single vmapped program — the builder
    pays ~one bulk serving round per chunk, not one dispatch per
    machine).  ``prestacked_hint``: the chunk's stacked host arrays as
    fetched by the build's collect side (``PendingFleetBuild.prestacked``)
    — the scorer adopts them whole instead of re-stacking per-machine
    views leaf by leaf.  Returns ``{machine: sketch doc}``; machines
    whose scoring failed are simply absent."""
    if not baselines_enabled() or not models:
        return {}
    docs: Dict[str, Dict[str, Any]] = {}
    try:
        from gordo_tpu.serve.fleet_scorer import FleetScorer

        X_by = {
            name: np.asarray(X, np.float32)[-BASELINE_MAX_ROWS:]
            for name, X in X_by_name.items()
            if name in models
        }
        scorer = FleetScorer.from_models(
            {n: models[n] for n in X_by},
            prestacked_hint=prestacked_hint,
        )
        with FLEET_HEALTH.suspended():
            out = scorer.score_all(X_by)
        for name, res in out.items():
            scores = res.get("total-anomaly-score")
            if scores is not None:
                docs[name] = sketch_from_scores(scores, ts=0.0).to_doc()
    except Exception:
        logger.exception(
            "training baseline sketching failed for chunk %s...",
            sorted(models)[:3],
        )
    return docs


# ---------------------------------------------------------------------------
# rollup files (the no-HTTP interface `gordo refresh` consumes)
# ---------------------------------------------------------------------------

def rollup_max_bytes() -> int:
    try:
        return int(
            os.environ.get(ENV_ROLLUP_MAX_BYTES, "")
            or DEFAULT_ROLLUP_MAX_BYTES
        )
    except ValueError:
        return DEFAULT_ROLLUP_MAX_BYTES


def rollup_path(directory: str, shard=None) -> str:
    """This process's rollup file under ``<directory>/.gordo-fleet-health/``.
    Shard-keyed when serving a shard (stable across restarts; replica i
    always appends to the same file), ``rollup-unsharded.jsonl``
    otherwise."""
    if shard is not None:
        name = (
            f"rollup-shard-{int(shard.index):03d}"
            f"-of-{int(shard.count):03d}.jsonl"
        )
    else:
        name = "rollup-unsharded.jsonl"
    return os.path.join(directory, ROLLUP_DIR, name)


def write_rollup(
    directory: str,
    doc: Dict[str, Any],
    shard=None,
    max_bytes: Optional[int] = None,
) -> Optional[str]:
    """Append one health-doc line to this process's rollup JSONL under
    the artifact dir (size-capped, keep-last-2 rotation).  Never raises
    — a full disk must not take down scoring."""
    path = rollup_path(directory, shard=shard)
    try:
        append_jsonl_line(
            path,
            json.dumps(doc, sort_keys=True),
            max_bytes=rollup_max_bytes() if max_bytes is None else max_bytes,
        )
        return path
    except Exception:
        logger.exception("fleet-health rollup write failed: %s", path)
        return None


def load_rollups(directory: str) -> List[Dict[str, Any]]:
    """The latest health doc from every rollup file under ``directory``
    (an artifact dir, or its ``.gordo-fleet-health/`` subdir directly) —
    one doc per serving process/shard, ready for
    :func:`merge_health_docs`."""
    candidates = [os.path.join(directory, ROLLUP_DIR), directory]
    rolldir = next((d for d in candidates if os.path.isdir(d)), None)
    docs: List[Dict[str, Any]] = []
    if rolldir is None:
        return docs
    for fname in sorted(os.listdir(rolldir)):
        if not fname.endswith(".jsonl"):
            continue
        latest: Optional[Dict[str, Any]] = None
        try:
            with open(os.path.join(rolldir, fname)) as fh:
                for line in fh:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        doc = json.loads(line)
                    except ValueError:
                        continue  # torn tail line mid-append
                    if doc.get("gordo-fleet-health"):
                        latest = doc
        except OSError:
            continue
        if latest is not None:
            docs.append(latest)
    return docs


def read_rollups(
    directory: str,
    top: Optional[int] = None,
    threshold: Optional[float] = None,
) -> Optional[Dict[str, Any]]:
    """ONE merged fleet-health doc from the shard-keyed rollup JSONL
    files under ``directory`` (an artifact dir, or its
    ``.gordo-fleet-health/`` directly), or None when no rollups exist.

    The shared file-interface reader: the refresh loop, ``gordo
    fleet-health --dir``, and tests all consume rollups through this —
    none of them needs private knowledge of the file layout, the
    torn-tail skip, or the shard merge algebra
    (:func:`load_rollups` + :func:`merge_health_docs`)."""
    docs = load_rollups(directory)
    if not docs:
        return None
    return merge_health_docs(docs, top=top, threshold=threshold)


def baselines_from_archive(
    directory: str,
    machines: Optional[Sequence[str]] = None,
    apply: bool = False,
) -> Dict[str, Dict[str, Any]]:
    """Per-machine baseline sketch docs regenerated from a backfill
    score archive (``<directory>/.gordo-scores/``) — REAL served-history
    distributions instead of training residuals.

    A baseline built from months of archived scores is the distribution
    the machine actually lives at, so drift measured against it flags
    behavior changes rather than train/serve skew.  Returns
    ``{machine: sketch doc}`` (machines with no archived rows are
    omitted); ``apply=True`` additionally installs each doc as the live
    process's baseline (:meth:`FleetHealth.set_baseline`), the hook a
    server rescan or refresh loop calls after a backfill lands.

    The batch plane import is deferred: telemetry must stay importable
    without the backfill plane's jax surface."""
    from gordo_tpu.batch.archive import ScoreArchive

    arch = ScoreArchive(directory)
    docs: Dict[str, Dict[str, Any]] = {}
    for name in machines if machines is not None else arch.machines():
        rec = arch.read_machine(name)
        if rec is None:
            continue
        scores = rec["total-anomaly-score"]
        if scores.size == 0:
            continue
        docs[name] = sketch_from_scores(scores).to_doc()
        if apply:
            FLEET_HEALTH.set_baseline(name, docs[name])
    return docs
