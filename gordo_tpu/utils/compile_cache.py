"""Persistent XLA compilation cache for cross-process compile reuse.

Reference equivalent: none — the reference's Keras/TF models had no
ahead-of-time compile cost to amortize.  Here every fleet program (CV +
multi-epoch fit, LSTM scans) is an XLA executable that can take tens of
seconds to compile cold; a builder pod that restarts, or a project built
across several CLI invocations, would re-pay every compile.  jax's
persistent compilation cache writes executables to disk keyed by program
fingerprint, so a process-cold build of an already-seen program shape
loads in milliseconds instead.

Enabled by default at the CLI/builder/server entry points — on TPU (and
GPU) backends only.  **XLA:CPU is excluded**: its cached AOT executables
embed the compiling process's detected machine features, and loading an
entry whose feature set disagrees with the current detection crashed the
process in this container (SIGILL-class segfault inside
``compilation_cache.get_executable_and_time`` — the loader itself warns
"could lead to execution errors such as SIGILL").  On CPU the cold
compiles are also far cheaper, so the trade is not worth the risk;
``GORDO_COMPILE_CACHE=force`` overrides for a trusted single-machine
setup.  Opt out entirely with ``GORDO_COMPILE_CACHE=0`` or point the
location via ``GORDO_COMPILE_CACHE_DIR`` (default
``~/.cache/gordo_tpu/xla``).
"""

from __future__ import annotations

import logging
import os

logger = logging.getLogger(__name__)

_ENABLED = False


def enable_persistent_compile_cache(cache_dir: str | None = None) -> bool:
    """Turn on jax's on-disk compilation cache (idempotent; TPU/GPU only
    unless forced — see module docstring for the XLA:CPU hazard).

    Returns True when the cache is active.  Never raises: a read-only
    filesystem or an old jax falls back to in-memory-only compiles.
    """
    global _ENABLED
    if _ENABLED:
        return True
    flag = os.environ.get("GORDO_COMPILE_CACHE", "1")
    if flag in ("0", "false", "no"):
        return False
    try:
        import jax

        if flag != "force" and jax.default_backend() == "cpu":
            logger.debug(
                "Persistent compile cache skipped on CPU backend "
                "(AOT feature-mismatch hazard; GORDO_COMPILE_CACHE=force "
                "overrides)"
            )
            return False
        cache_dir = (
            cache_dir
            or os.environ.get("GORDO_COMPILE_CACHE_DIR")
            or os.path.join(
                os.path.expanduser("~"), ".cache", "gordo_tpu", "xla"
            )
        )
        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        # default min-compile-time (1s) keeps tiny programs out of the
        # cache; the fleet fit/CV programs are seconds-to-minutes.
        # GORDO_COMPILE_CACHE_MIN_SECONDS overrides (the cold-start bench
        # sets 0 so its deliberately small programs exercise the disk
        # round-trip; a serving fleet of sub-second programs may too).
        min_secs = os.environ.get("GORDO_COMPILE_CACHE_MIN_SECONDS")
        if min_secs is not None:
            jax.config.update(
                "jax_persistent_cache_min_compile_time_secs",
                float(min_secs),
            )
        _ENABLED = True
        # hit/miss events from jax's cache land on the compile plane's
        # gordo_compile_cache_*_total{cache="persistent"} counters so a
        # /metrics scrape attests cross-process reuse
        from gordo_tpu.compile import install_persistent_cache_counters

        install_persistent_cache_counters()
        logger.debug("Persistent compile cache at %s", cache_dir)
        return True
    except Exception as exc:
        logger.warning("Persistent compile cache unavailable: %s", exc)
        return False
