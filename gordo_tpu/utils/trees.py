"""Pytree helpers shared across the framework."""

from __future__ import annotations

from typing import Any

import jax
import numpy as np


def to_host(tree: Any) -> Any:
    """Pull every jax array leaf to host numpy (device-independent pickling)."""

    def _leaf(x):
        if isinstance(x, jax.Array):
            return np.asarray(jax.device_get(x))
        return x

    return jax.tree_util.tree_map(_leaf, tree)


def tree_size_bytes(tree: Any) -> int:
    leaves = jax.tree_util.tree_leaves(tree)
    return sum(getattr(l, "nbytes", 0) for l in leaves)


def param_count(tree: Any) -> int:
    leaves = jax.tree_util.tree_leaves(tree)
    return int(sum(getattr(l, "size", 0) for l in leaves))
