"""Profiling & step-metrics hooks.

Reference status (SURVEY.md §6.1): essentially absent — the reference only
records build wall-times into metadata.  The TPU build keeps that
metadata-first design and adds opt-in ``jax.profiler`` tracing: set
``GORDO_PROFILE_DIR`` (or pass ``profile_dir``) and every wrapped section
dumps a Perfetto/TensorBoard-loadable trace.

Since the telemetry plane landed, ``trace`` is no longer a pure no-op
without the profiler: every wrapped section ALWAYS records its wall time
into the ``gordo_profile_section_seconds`` histogram (label = the section
name's leading component, so ``fleet_bucket/512`` and ``fleet_bucket/64``
share a bounded series), and emits a span (``telemetry.spans``) carrying
the full section name.  The jax-profiler dump stays opt-in — it is the
expensive microscope; the histogram is the always-on clock.
"""

from __future__ import annotations

import contextlib
import logging
import os
import time
from typing import Iterator, Optional

from gordo_tpu import telemetry

logger = logging.getLogger(__name__)

ENV_VAR = "GORDO_PROFILE_DIR"

_SECTION_SECONDS = telemetry.histogram(
    "gordo_profile_section_seconds",
    "Wall-clock duration of profiling.trace sections (always recorded; "
    "label is the section name before any '/')",
    labels=("section",),
)


def profile_dir() -> Optional[str]:
    return os.environ.get(ENV_VAR) or None


@contextlib.contextmanager
def trace(section: str, directory: Optional[str] = None) -> Iterator[None]:
    """Wrap a section: wall time always lands in the telemetry histogram;
    additionally, when profiling is enabled (``GORDO_PROFILE_DIR``), a
    ``jax.profiler`` trace dumps to ``<dir>/<section>/`` (one subdir per
    section so repeated builds don't clobber each other)."""
    directory = directory or profile_dir()
    # bounded histogram label: 'fleet_bucket/512' -> 'fleet_bucket'; the
    # exact section name still reaches the span log when enabled
    head = section.split("/", 1)[0]
    t0 = time.perf_counter()
    try:
        with telemetry.span("profile." + head, section=section):
            if not directory:
                yield
                return
            import jax

            dest = os.path.join(directory, section.replace("/", "_"))
            os.makedirs(dest, exist_ok=True)
            logger.info("Profiling %r -> %s", section, dest)
            with jax.profiler.trace(dest):
                yield
    finally:
        _SECTION_SECONDS.observe(time.perf_counter() - t0, head)
