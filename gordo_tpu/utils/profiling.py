"""Profiling & step-metrics hooks.

Reference status (SURVEY.md §6.1): essentially absent — the reference only
records build wall-times into metadata.  The TPU build keeps that
metadata-first design and adds opt-in ``jax.profiler`` tracing: set
``GORDO_PROFILE_DIR`` (or pass ``profile_dir``) and every wrapped section
dumps a Perfetto/TensorBoard-loadable trace.
"""

from __future__ import annotations

import contextlib
import logging
import os
from typing import Iterator, Optional

logger = logging.getLogger(__name__)

ENV_VAR = "GORDO_PROFILE_DIR"


def profile_dir() -> Optional[str]:
    return os.environ.get(ENV_VAR) or None


@contextlib.contextmanager
def trace(section: str, directory: Optional[str] = None) -> Iterator[None]:
    """Wrap a section in a ``jax.profiler`` trace when profiling is enabled,
    else a no-op.  Traces land in ``<dir>/<section>/`` (one subdir per
    section so repeated builds don't clobber each other)."""
    directory = directory or profile_dir()
    if not directory:
        yield
        return
    import jax

    dest = os.path.join(directory, section.replace("/", "_"))
    os.makedirs(dest, exist_ok=True)
    logger.info("Profiling %r -> %s", section, dest)
    with jax.profiler.trace(dest):
        yield


