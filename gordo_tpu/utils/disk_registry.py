"""On-disk key→value registry for built-model caching.

Reference equivalent: ``gordo_components/util/disk_registry.py`` — flat
files ``{registry_dir}/{key}`` whose contents are the cached value (here:
the absolute path of a built model artifact dir).  Load-bearing for the
fleet north star: a re-run project build skips every machine whose config
hash is already registered.
"""

from __future__ import annotations

import logging
import os
import re
from typing import Optional

logger = logging.getLogger(__name__)

_KEY_RE = re.compile(r"^[A-Za-z0-9_.-]+$")


def _key_path(registry_dir: str, key: str) -> str:
    if not _KEY_RE.match(key):
        raise ValueError(f"Invalid registry key {key!r}")
    return os.path.join(registry_dir, key)


def fsync_dir(path: str) -> None:
    """fsync a directory so a rename INTO it survives a crash — an
    ``os.replace`` alone makes the file atomic, not durable: until the
    directory entry itself is flushed, a power cut can roll the rename
    back.  Best-effort on filesystems that refuse directory fds."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def write_key(registry_dir: str, key: str, value: str) -> None:
    os.makedirs(registry_dir, exist_ok=True)
    path = _key_path(registry_dir, key)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        f.write(value)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)  # atomic vs concurrent builders of the same key
    # durability, not just atomicity: the registry entry must not survive
    # a crash that its artifact (pack/model bytes, fsynced before their
    # own rename) did not — same bug class PR 4 fixed for round files
    fsync_dir(registry_dir)


def get_value(registry_dir: str, key: str) -> Optional[str]:
    path = _key_path(registry_dir, key)
    if not os.path.exists(path):
        return None
    with open(path) as f:
        return f.read().strip()


def list_keys(registry_dir: str) -> "list[str]":
    """Every key in the registry, sorted.  Tolerates a missing dir (empty
    registry) and skips in-flight ``.tmp.<pid>`` files from concurrent
    writers — multi-host builds share one registry dir."""
    try:
        names = os.listdir(registry_dir)
    except FileNotFoundError:
        return []
    return sorted(n for n in names if _KEY_RE.match(n))


def merge_registries(source_dirs: "list[str]", dest_dir: str) -> int:
    """Union per-shard registries into ``dest_dir`` (last writer wins on a
    duplicate key, which only happens when two shards built the same
    config — same value either way).  Returns the number of keys written.
    Used when multi-host shards write host-local registries instead of a
    shared one; with a shared dir the merge is implicit."""
    n = 0
    for src in source_dirs:
        for key in list_keys(src):
            value = get_value(src, key)
            if value is not None:
                write_key(dest_dir, key, value)
                n += 1
    return n


def delete_value(registry_dir: str, key: str) -> bool:
    path = _key_path(registry_dir, key)
    if os.path.exists(path):
        os.remove(path)
        return True
    return False
