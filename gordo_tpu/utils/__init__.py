from gordo_tpu.utils.args import capture_args  # noqa: F401
from gordo_tpu.utils.trees import to_host  # noqa: F401
