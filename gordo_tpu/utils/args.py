"""Constructor-argument capture.

Reference equivalent: ``gordo_components/dataset/data_provider/base.py::
capture_args`` — records ``__init__`` arguments on the instance so components
are self-describing: ``get_params()`` round-trips through definition dicts /
metadata JSON without each class hand-writing parameter bookkeeping.
"""

from __future__ import annotations

import functools
import inspect
from typing import Any, Dict


@functools.lru_cache(maxsize=256)
def _cached_signature(init):
    # signature inspection cost ~0.2ms per construction — at fleet-ingest
    # scale (3 constructions x thousands of machines) it was a measurable
    # slice of the load stage; Signature objects are immutable, bind() is
    # per-call
    return inspect.signature(init)


def capture_args(init):
    """Decorator for ``__init__`` storing bound arguments as ``_init_params``."""

    @functools.wraps(init)
    def wrapper(self, *args, **kwargs):
        sig = _cached_signature(init)
        bound = sig.bind(self, *args, **kwargs)
        bound.apply_defaults()
        params: Dict[str, Any] = {
            k: v for k, v in bound.arguments.items() if k != "self"
        }
        for name, p in sig.parameters.items():
            if p.kind is inspect.Parameter.VAR_KEYWORD and name in params:
                params.update(params.pop(name))
            if p.kind is inspect.Parameter.VAR_POSITIONAL and name in params:
                params[name] = list(params[name])
        self._init_params = params
        return init(self, *args, **kwargs)

    return wrapper


class ParamsMixin:
    """sklearn-flavoured ``get_params``/``set_params`` off captured args."""

    _init_params: Dict[str, Any]

    def get_params(self, deep: bool = False) -> Dict[str, Any]:
        return dict(getattr(self, "_init_params", {}))

    def set_params(self, **params):
        new = self.get_params()
        new.update(params)
        self.__init__(**new)  # type: ignore[misc]
        return self

    def clone(self):
        """Fresh unfitted copy with identical construction params."""
        from gordo_tpu.serializer.definition import from_definition, into_definition

        return from_definition(into_definition(self))
