from gordo_tpu.train.fit import (  # noqa: F401
    LOSSES,
    OPTIMIZERS,
    TrainConfig,
    fit as fit_model,
    init_params,
    make_loss_fn,
    make_optimizer,
)
