"""Cross-validation.

Reference equivalent: the sklearn ``TimeSeriesSplit``/``cross_val_predict``
machinery used by ``gordo_components/builder/build_model.py`` and
``model/anomaly/diff.py::DiffBasedAnomalyDetector.cross_validate``.

Fold index generation is host-side numpy (static per dataset length); each
fold's fit runs the jitted training program.  Fold fits of the same shape
reuse the compiled executable; the fleet engine goes further and vmaps folds
(``gordo_tpu.parallel.fleet``).
"""

from __future__ import annotations

from typing import Any, Dict, Iterator, List, Optional, Tuple

import numpy as np

from gordo_tpu.ops import metrics as jmetrics


class TimeSeriesSplit:
    """Expanding-window splitter (sklearn ``TimeSeriesSplit`` semantics):
    fold k trains on the first k blocks and tests on block k+1."""

    def __init__(self, n_splits: int = 3):
        if n_splits < 1:
            raise ValueError("n_splits must be >= 1")
        self.n_splits = n_splits

    def split(self, X, y=None) -> Iterator[Tuple[np.ndarray, np.ndarray]]:
        n = len(X)
        if n < self.n_splits + 1:
            raise ValueError(
                f"Cannot split {n} samples into {self.n_splits} folds"
            )
        fold_size = n // (self.n_splits + 1)
        for k in range(1, self.n_splits + 1):
            train_end = fold_size * k
            test_end = fold_size * (k + 1) if k < self.n_splits else n
            yield (
                np.arange(0, train_end),
                np.arange(train_end, test_end),
            )

    def get_n_splits(self, X=None, y=None) -> int:
        return self.n_splits


class KFold:
    """Contiguous (unshuffled) K-fold."""

    def __init__(self, n_splits: int = 5):
        self.n_splits = n_splits

    def split(self, X, y=None):
        n = len(X)
        indices = np.arange(n)
        for test_idx in np.array_split(indices, self.n_splits):
            train_idx = np.setdiff1d(indices, test_idx)
            yield train_idx, test_idx

    def get_n_splits(self, X=None, y=None) -> int:
        return self.n_splits


SPLITTERS = {"TimeSeriesSplit": TimeSeriesSplit, "KFold": KFold}


def build_splitter(cv: Any) -> Any:
    """Config → splitter: dict ``{"TimeSeriesSplit": {"n_splits": 3}}``,
    a splitter instance, or None (default TimeSeriesSplit(3))."""
    if cv is None:
        return TimeSeriesSplit(3)
    if isinstance(cv, dict):
        (name, kwargs), = cv.items()
        name = name.rsplit(".", 1)[-1]
        if name not in SPLITTERS:
            raise ValueError(f"Unknown CV splitter {name!r}; available: {sorted(SPLITTERS)}")
        return SPLITTERS[name](**(kwargs or {}))
    if hasattr(cv, "split"):
        return cv
    raise ValueError(f"Cannot build CV splitter from {cv!r}")


def cross_validate(
    model,
    X: np.ndarray,
    y: Optional[np.ndarray] = None,
    cv: Any = None,
    metric_names: Tuple[str, ...] = (
        "explained_variance_score",
        "r2_score",
        "mean_squared_error",
        "mean_absolute_error",
    ),
) -> Dict[str, Any]:
    """Out-of-fold predictions + per-fold metrics.

    ``model`` must expose ``clone()`` (unfitted copy), ``fit`` and
    ``predict``.  Returns ``{"folds": [...], "scores": {...},
    "predictions": [(test_index, y_true_aligned, y_pred), ...]}``.
    """
    X = np.asarray(X, dtype=np.float32)
    y_arr = X if y is None else np.asarray(y, dtype=np.float32)
    splitter = build_splitter(cv)

    folds: List[Dict[str, float]] = []
    predictions = []
    for fold_idx, (train_idx, test_idx) in enumerate(splitter.split(X)):
        est = model.clone() if hasattr(model, "clone") else model
        est.fit(X[train_idx], y_arr[train_idx])
        pred = np.asarray(est.predict(X[test_idx]))
        offset = getattr(est, "offset", 0)
        y_true = y_arr[test_idx][offset:]
        fold_scores = {
            name: float(getattr(jmetrics, name)(y_true, pred))
            for name in metric_names
        }
        folds.append(fold_scores)
        predictions.append((test_idx[offset:], y_true, pred))

    scores = {
        name: {
            "folds": [f[name] for f in folds],
            "mean": float(np.mean([f[name] for f in folds])),
            "std": float(np.std([f[name] for f in folds])),
        }
        for name in metric_names
    }
    return {"folds": folds, "scores": scores, "predictions": predictions}
