"""Mid-fit checkpoint / resume.

Reference status (SURVEY.md §6.4): the reference checkpoints only at the
model-artifact level (``serializer.dump`` + the config-hash build cache);
there is no mid-training checkpointing.  The TPU build keeps the artifact
cache (it is load-bearing for fleet re-runs) and adds optional mid-fit
checkpointing for long fits: the epoch loop is chunked, and after each
chunk ``(params, opt_state, history, epochs_done)`` land on disk via Orbax
(pickle fallback when Orbax is unavailable).

Contracts:

- **Determinism**: per-epoch shuffle keys are derived once from the fit
  seed (``jax.random.split(rng, epochs)``) and indexed per chunk, so a
  resumed fit is **bit-identical** to an uninterrupted one
  (tests/test_checkpoint.py).  Resuming with a larger ``cfg.epochs``
  continues the same key sequence (``split(k, n)`` is prefix-stable).
- **Identity**: the checkpoint records a fingerprint of (module, config
  minus epochs, data, seed); a checkpoint that does not match the current
  fit is ignored, never silently reused — a cloned CV fold or a refit on
  new data with the same ``checkpoint_dir`` retrains from scratch.
- **Atomicity**: the whole checkpoint (tree + state + history) is staged
  in a temp dir and ``os.replace``d into place; a crash mid-save loses at
  most the newest chunk, never yields a mixed-epoch state.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import logging
import os
import pickle
import shutil
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from gordo_tpu import compile as compile_plane

from gordo_tpu.train.fit import (
    TrainConfig,
    _pad_batches,
    init_params,
    make_optimizer,
    make_stateful_fit_fn,
)
from gordo_tpu.utils.trees import to_host

logger = logging.getLogger(__name__)

STATE_FILE = "state.json"
PAYLOAD_DIR = "ckpt"


def fit_fingerprint(module, cfg: TrainConfig, X, y, rng: jax.Array) -> str:
    """Identity of one logical fit, *excluding* ``epochs`` (resuming with a
    larger epoch budget is the supported continuation case; everything else
    changing means the checkpoint belongs to a different fit)."""
    h = hashlib.md5()
    h.update(repr(module).encode())
    h.update(repr(dataclasses.replace(cfg, epochs=0)).encode())
    h.update(np.asarray(jax.random.key_data(rng)).tobytes())
    for arr in (X, y):
        arr = np.asarray(arr)
        h.update(str(arr.shape).encode())
        h.update(arr.tobytes())
    return h.hexdigest()


def _save_tree(path: str, tree: Any) -> None:
    try:
        import orbax.checkpoint as ocp

        ocp.PyTreeCheckpointer().save(os.path.abspath(path), to_host(tree))
    except ImportError:
        os.makedirs(path, exist_ok=True)
        with open(os.path.join(path, "tree.pkl"), "wb") as f:
            pickle.dump(to_host(tree), f)


def _load_tree(path: str, target: Any = None) -> Any:
    pkl = os.path.join(path, "tree.pkl")
    if os.path.exists(pkl):
        with open(pkl, "rb") as f:
            return pickle.load(f)
    import orbax.checkpoint as ocp

    # restoring against a concrete target preserves pytree node types
    # (optax opt_states are NamedTuples; a bare restore yields dicts)
    return ocp.PyTreeCheckpointer().restore(
        os.path.abspath(path), item=to_host(target) if target is not None else None
    )


def save_checkpoint(
    ckpt_dir: str,
    params: Any,
    opt_state: Any,
    history: np.ndarray,
    epochs_done: int,
    fingerprint: str = "",
) -> None:
    """Atomically persist the full fit state under ``ckpt_dir/ckpt``."""
    os.makedirs(ckpt_dir, exist_ok=True)
    tmp = os.path.join(ckpt_dir, PAYLOAD_DIR + ".tmp")
    final = os.path.join(ckpt_dir, PAYLOAD_DIR)
    if os.path.exists(tmp):
        shutil.rmtree(tmp)
    os.makedirs(tmp)
    _save_tree(os.path.join(tmp, "tree"), {"params": params, "opt_state": opt_state})
    np.save(os.path.join(tmp, "history.npy"), np.asarray(history, np.float32))
    with open(os.path.join(tmp, STATE_FILE), "w") as f:
        json.dump({"epochs_done": int(epochs_done), "fingerprint": fingerprint}, f)
    # Keep a valid payload on disk at every instant: the previous checkpoint
    # is moved aside (one atomic rename), the new one renamed in, and only
    # then is the old one deleted.  A crash anywhere in between leaves either
    # `ckpt` or `ckpt.old` restorable (load_checkpoint falls back to .old).
    # A stale `.old` (from a crash that left ONLY it behind) must survive
    # until the new payload is in place — never delete it up front.
    old = final + ".old"
    if os.path.exists(final):
        if os.path.exists(old):
            shutil.rmtree(old)
        os.replace(final, old)
    os.replace(tmp, final)
    if os.path.exists(old):
        shutil.rmtree(old)


def load_checkpoint(
    ckpt_dir: str, target: Any = None, fingerprint: Optional[str] = None
) -> Optional[Tuple[Any, Any, np.ndarray, int]]:
    """Restore ``(params, opt_state, history, epochs_done)`` or None.

    ``target``: example ``{"params", "opt_state"}`` tree (fresh init) used
    to restore exact pytree node types.  A ``fingerprint`` mismatch returns
    None — stale checkpoints are never silently reused.
    """
    payload = os.path.join(ckpt_dir, PAYLOAD_DIR)
    if not os.path.exists(os.path.join(payload, STATE_FILE)):
        # a crash mid-save may have left only the moved-aside previous payload
        payload = os.path.join(ckpt_dir, PAYLOAD_DIR + ".old")
    state_path = os.path.join(payload, STATE_FILE)
    if not os.path.exists(state_path):
        return None
    with open(state_path) as f:
        state = json.load(f)
    if fingerprint is not None and state.get("fingerprint") != fingerprint:
        logger.warning(
            "Checkpoint in %s belongs to a different fit "
            "(config/data/seed changed); retraining from scratch", ckpt_dir,
        )
        return None
    tree = _load_tree(os.path.join(payload, "tree"), target)
    history = np.load(os.path.join(payload, "history.npy"))
    return tree["params"], tree["opt_state"], history, int(state["epochs_done"])


# Static-keyed like fit._fit_jit so CV folds / repeat fits with the same
# (module, cfg, shapes) reuse one compiled executable per chunk size.
@compile_plane.jit(
    name="train.stateful_fit",
    static_argnames=("module", "cfg", "steps", "bs"),
)
def _stateful_fit_jit(module, cfg: TrainConfig, steps: int, bs: int,
                      params, opt_state, X, y, w, epoch_keys):
    return make_stateful_fit_fn(module, cfg, steps, bs)(
        params, opt_state, X, y, w, epoch_keys
    )


def fit_checkpointed(
    module,
    X,
    y,
    cfg: TrainConfig,
    ckpt_dir: str,
    checkpoint_every: int = 10,
    rng: Optional[jax.Array] = None,
) -> Tuple[Any, np.ndarray]:
    """Fit with a checkpoint every ``checkpoint_every`` epochs; resumes
    from ``ckpt_dir`` iff it holds a checkpoint of THIS fit.  Same RNG
    derivation as ``train.fit.fit`` → same final params when never
    interrupted."""
    if checkpoint_every < 1:
        raise ValueError(f"checkpoint_every must be >= 1, got {checkpoint_every}")
    rng = rng if rng is not None else jax.random.PRNGKey(0)
    X = jnp.asarray(X, jnp.float32)
    y = jnp.asarray(y, jnp.float32)

    init_rng, fit_rng = jax.random.split(rng)
    epoch_keys = jax.random.split(fit_rng, cfg.epochs)
    Xp, yp, w, steps, bs = _pad_batches(X, y, cfg.batch_size)
    fingerprint = fit_fingerprint(module, cfg, X, y, rng)

    params = init_params(module, init_rng, X[:1])
    opt_state = make_optimizer(cfg).init(params)
    resumed = load_checkpoint(
        ckpt_dir,
        target={"params": params, "opt_state": opt_state},
        fingerprint=fingerprint,
    )
    if resumed is not None and resumed[3] > cfg.epochs:
        # the fingerprint deliberately excludes epochs, so a re-run with a
        # SMALLER epoch budget can match an over-trained checkpoint; using
        # it would break the "same params as an uninterrupted fit" contract
        logger.warning(
            "Checkpoint in %s has %d epochs done > budget %d; "
            "retraining from scratch", ckpt_dir, resumed[3], cfg.epochs,
        )
        resumed = None
    if resumed is not None:
        params, opt_state, hist_arr, epochs_done = resumed
        history = list(np.asarray(hist_arr))
        logger.info("Resuming fit at epoch %d from %s", epochs_done, ckpt_dir)
    else:
        epochs_done, history = 0, []

    while epochs_done < cfg.epochs:
        chunk = min(checkpoint_every, cfg.epochs - epochs_done)
        keys = epoch_keys[epochs_done : epochs_done + chunk]
        params, opt_state, chunk_hist = _stateful_fit_jit(
            module, cfg, steps, bs, params, opt_state, Xp, yp, w, keys
        )
        epochs_done += chunk
        history.extend(np.asarray(chunk_hist).tolist())
        save_checkpoint(
            ckpt_dir, params, opt_state,
            np.asarray(history, np.float32), epochs_done, fingerprint,
        )
    assert len(history) == cfg.epochs, (
        f"history has {len(history)} entries for a {cfg.epochs}-epoch fit"
    )
    return params, np.asarray(history, dtype=np.float32)
