"""Jitted model fitting.

Reference equivalent: the ``keras Model.fit`` hot loop inside
``gordo_components/model/models.py::KerasBaseEstimator.fit`` — the only
compute-bound loop in the reference (single-process CPU TensorFlow).

TPU-native design: the ENTIRE fit — every epoch, every minibatch, the
per-epoch shuffle — is one XLA program: ``lax.scan`` over epochs around
``lax.scan`` over minibatches, with the dataset resident in device memory
(these datasets are tiny: months of 10-minute samples x tens of tags).
One dispatch, zero host↔device traffic inside training.  Shapes are static:
the data is padded to ``steps * batch_size`` rows and a weight vector masks
the padding out of the loss.

The pure pieces (``make_loss_fn``, ``make_optimizer``, ``make_epoch_fn``)
are reused by the fleet engine (``gordo_tpu.parallel.fleet``) which vmaps
them across stacked models and shards them over the device mesh.
"""

from __future__ import annotations

import dataclasses
import warnings
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from gordo_tpu import compile as compile_plane

# _fit_jit donates params/X/y/w.  Only params can alias an output, so XLA
# reports X/y/w as "not usable" donations — donating them is still the
# point: the padded training set frees at its last use inside the program
# instead of surviving until the result fetch.  Silence that advisory.
warnings.filterwarnings(
    "ignore", message="Some donated buffers were not usable"
)

OPTIMIZERS: Dict[str, Callable[..., optax.GradientTransformation]] = {
    "adam": optax.adam,
    "adamw": optax.adamw,
    "sgd": optax.sgd,
    "rmsprop": optax.rmsprop,
    "adagrad": optax.adagrad,
    "nadam": optax.nadam,
    "lamb": optax.lamb,
}


def _mse(pred, target):
    return (pred - target) ** 2


def _mae(pred, target):
    return jnp.abs(pred - target)


def _huber(pred, target, delta: float = 1.0):
    err = pred - target
    abs_err = jnp.abs(err)
    quad = jnp.minimum(abs_err, delta)
    return 0.5 * quad ** 2 + delta * (abs_err - quad)


LOSSES: Dict[str, Callable] = {
    "mse": _mse,
    "mean_squared_error": _mse,
    "mae": _mae,
    "mean_absolute_error": _mae,
    "huber": _huber,
}


@dataclasses.dataclass(frozen=True)
class TrainConfig:
    """Hashable training config (static arg to the jitted fit)."""

    epochs: int = 10
    batch_size: int = 256
    optimizer: str = "adam"
    learning_rate: float = 1e-3
    loss: str = "mse"
    shuffle: bool = True
    optimizer_kwargs: Tuple[Tuple[str, Any], ...] = ()

    @classmethod
    def from_kwargs(cls, kwargs: Dict[str, Any]) -> Tuple["TrainConfig", Dict[str, Any]]:
        """Split estimator kwargs into (train config, factory kwargs)."""
        known = {f.name for f in dataclasses.fields(cls)}
        cfg_kwargs = {}
        rest = {}
        for k, v in kwargs.items():
            if k in known:
                cfg_kwargs[k] = v
            elif k == "optimizer_kwargs" or k == "compile_kwargs":
                cfg_kwargs["optimizer_kwargs"] = tuple(sorted(dict(v).items()))
            else:
                rest[k] = v
        if "optimizer_kwargs" in cfg_kwargs and not isinstance(
            cfg_kwargs["optimizer_kwargs"], tuple
        ):
            cfg_kwargs["optimizer_kwargs"] = tuple(
                sorted(dict(cfg_kwargs["optimizer_kwargs"]).items())
            )
        return cls(**cfg_kwargs), rest


def make_optimizer(cfg: TrainConfig) -> optax.GradientTransformation:
    name = cfg.optimizer.lower()
    if name not in OPTIMIZERS:
        raise ValueError(f"Unknown optimizer {cfg.optimizer!r}; available: {sorted(OPTIMIZERS)}")
    kwargs = dict(cfg.optimizer_kwargs)
    lr = kwargs.pop("learning_rate", cfg.learning_rate)
    return OPTIMIZERS[name](lr, **kwargs)


def make_loss_fn(apply_fn: Callable, loss: str) -> Callable:
    """Weighted scalar loss of (params, x, y, w); w masks padded rows."""
    if loss not in LOSSES:
        raise ValueError(f"Unknown loss {loss!r}; available: {sorted(LOSSES)}")
    elem = LOSSES[loss]

    def loss_fn(params, x, y, w):
        pred = apply_fn({"params": params}, x)
        per_row = jnp.mean(elem(pred, y), axis=tuple(range(1, pred.ndim)))
        return jnp.sum(per_row * w) / jnp.maximum(jnp.sum(w), 1.0)

    return loss_fn


def init_params(module, rng: jax.Array, sample_x: jnp.ndarray):
    return module.init(rng, sample_x)["params"]


def batch_geometry(n: int, batch_size: int) -> Tuple[int, int, int]:
    """Shared minibatch geometry: ``(steps, bs, n_pad)`` for ``n`` rows.

    Single source of truth for the padding arithmetic that the single-model,
    fleet, and data-parallel fits must all agree on (their bit-parity tests
    depend on identical geometry).
    """
    bs = int(min(batch_size, n))
    steps = -(-n // bs)
    return steps, bs, steps * bs - n


def _pad_batches(X, y, batch_size: int):
    """Pad to a whole number of batches; returns (X, y, w, steps, bs)."""
    n = X.shape[0]
    steps, bs, n_pad = batch_geometry(n, batch_size)
    w = jnp.concatenate([jnp.ones((n,), jnp.float32), jnp.zeros((n_pad,), jnp.float32)])
    if n_pad:
        X = jnp.concatenate([X, jnp.zeros((n_pad,) + X.shape[1:], X.dtype)])
        y = jnp.concatenate([y, jnp.zeros((n_pad,) + y.shape[1:], y.dtype)])
    return X, y, w, steps, bs


def make_epoch_fn(loss_fn: Callable, tx: optax.GradientTransformation,
                  steps: int, bs: int, shuffle: bool) -> Callable:
    """One epoch as a pure function — scan over minibatches of padded data."""

    grad_fn = jax.value_and_grad(loss_fn)

    def epoch(carry, key, X, y, w):
        params, opt_state = carry
        n_total = X.shape[0]
        if shuffle:
            perm = jax.random.permutation(key, n_total)
        else:
            perm = jnp.arange(n_total)
        xb = X[perm].reshape((steps, bs) + X.shape[1:])
        yb = y[perm].reshape((steps, bs) + y.shape[1:])
        wb = w[perm].reshape(steps, bs)

        def step(c, batch):
            p, s = c
            bx, by, bw = batch
            loss, grads = grad_fn(p, bx, by, bw)
            updates, s = tx.update(grads, s, p)
            p = optax.apply_updates(p, updates)
            return (p, s), loss * jnp.sum(bw)

        (params, opt_state), losses = jax.lax.scan(step, (params, opt_state), (xb, yb, wb))
        epoch_loss = jnp.sum(losses) / jnp.maximum(jnp.sum(w), 1.0)
        return (params, opt_state), epoch_loss

    return epoch


def make_stateful_fit_fn(module, cfg: TrainConfig, steps: int, bs: int) -> Callable:
    """Resumable fit: ``(params, opt_state, X, y, w, epoch_keys) ->
    (params, opt_state, history)``.

    Unlike :func:`make_fit_fn` the optimizer state flows through, and the
    per-epoch shuffle keys come in as an array — so a fit chunked across
    checkpoints (``gordo_tpu.train.checkpoint``) is bit-identical to the
    uninterrupted run.
    """
    tx = make_optimizer(cfg)
    loss_fn = make_loss_fn(module.apply, cfg.loss)
    epoch = make_epoch_fn(loss_fn, tx, steps, bs, cfg.shuffle)

    def fit_fn(params, opt_state, X, y, w, epoch_keys):
        def body(carry, key):
            return epoch(carry, key, X, y, w)

        (params, opt_state), history = jax.lax.scan(
            body, (params, opt_state), epoch_keys
        )
        return params, opt_state, history

    return fit_fn


def make_fit_fn(module, cfg: TrainConfig, steps: int, bs: int) -> Callable:
    """The whole multi-epoch fit as ONE pure function
    ``(params, X, y, w, rng) -> (params, history)``.

    This is the unit the fleet engine vmaps across stacked models
    (``gordo_tpu.parallel.fleet``) and the single-model path jits directly.
    """
    tx = make_optimizer(cfg)
    loss_fn = make_loss_fn(module.apply, cfg.loss)
    epoch = make_epoch_fn(loss_fn, tx, steps, bs, cfg.shuffle)

    def fit_fn(params, X, y, w, rng):
        opt_state = tx.init(params)
        keys = jax.random.split(rng, cfg.epochs)

        def body(carry, key):
            return epoch(carry, key, X, y, w)

        (params, _), history = jax.lax.scan(body, (params, opt_state), keys)
        return params, history

    return fit_fn


# Static-keyed on the module itself: flax modules are frozen dataclasses, so
# two estimators built from the same factory kwargs produce EQUAL modules and
# hit the same compiled executable (per-instance bound methods would not —
# every CV fold / fleet member would recompile).
# params/X/y/w are DONATED: the fitted params alias the incoming params
# buffers, and the (padded) training set frees at its last device use —
# callers must hand over buffers they no longer need (fit() guarantees
# this for its own callers by copying anything the caller still owns).
def _fit_jit_fn(module, cfg: TrainConfig, steps: int, bs: int,
                params, X, y, w, rng):
    return make_fit_fn(module, cfg, steps, bs)(params, X, y, w, rng)


_fit_jit = compile_plane.jit(
    _fit_jit_fn,
    name="train.fit",
    static_argnames=("module", "cfg", "steps", "bs"),
    donate_argnums=(4, 5, 6, 7),
)


def fit(module, X, y, cfg: TrainConfig,
        rng: Optional[jax.Array] = None,
        params: Optional[Any] = None) -> Tuple[Any, np.ndarray]:
    """Fit ``module`` on (X, y); returns (params, per-epoch loss history).

    The whole multi-epoch loop compiles to a single XLA executable; repeat
    fits with the same shapes/config reuse the compiled program.
    """
    rng = rng if rng is not None else jax.random.PRNGKey(0)
    X_in, y_in, params_in = X, y, params
    X = jnp.asarray(X, jnp.float32)
    y = jnp.asarray(y, jnp.float32)
    if params is None:
        init_rng, rng = jax.random.split(rng)
        params = init_params(module, init_rng, X[:1])
    Xp, yp, w, steps, bs = _pad_batches(X, y, cfg.batch_size)
    # _fit_jit donates params/X/y/w; a donated buffer is deleted, so never
    # hand over one the CALLER may still hold.  jnp.asarray copies host
    # arrays and padding copies device arrays — only an unpadded
    # caller-owned jax array (or caller-supplied params, or y aliasing X)
    # can reach the donated slots, so copy exactly those cases.
    if Xp is X_in:
        Xp = jnp.array(Xp)
    if yp is y_in or yp is Xp:
        yp = jnp.array(yp)
    if params_in is not None:
        params = jax.tree.map(jnp.array, params)
    params, history = _fit_jit(module, cfg, steps, bs, params, Xp, yp, w, rng)
    return params, np.asarray(history)
