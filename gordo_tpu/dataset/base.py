"""Dataset contract + config dispatch.

Reference equivalent: ``gordo_components/dataset/base.py`` —
``GordoBaseDataset.get_data() -> (X, y)``, ``get_metadata()``, and
``from_dict`` config dispatch.
"""

from __future__ import annotations

import abc
from typing import Any, Dict, Tuple

from gordo_tpu.utils.args import ParamsMixin


class GordoBaseDataset(ParamsMixin, abc.ABC):
    @abc.abstractmethod
    def get_data(self) -> Tuple[Any, Any]:
        """Return (X, y) — pandas DataFrames with a shared time index."""

    @abc.abstractmethod
    def get_metadata(self) -> Dict[str, Any]:
        ...

    @classmethod
    def from_dict(cls, config: dict) -> "GordoBaseDataset":
        """Instantiate a dataset from a data-config dict.

        ``type`` selects the dataset class (short name within
        ``gordo_tpu.dataset.datasets`` or a dotted path); everything else is
        constructor kwargs — the reference's dispatch convention.
        """
        from gordo_tpu.serializer.definition import import_locate

        config = dict(config)
        type_path = config.pop("type", "TimeSeriesDataset")
        if "." not in type_path:
            type_path = f"gordo_tpu.dataset.datasets.{type_path}"
        target = import_locate(type_path)
        return target(**config)
