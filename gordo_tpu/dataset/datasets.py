"""Dataset assembly.

Reference equivalent: ``gordo_components/dataset/datasets.py`` —
``TimeSeriesDataset`` (the workhorse: per-tag series → resampled, joined,
row-filtered tag matrix) and ``RandomDataset``.

Host-side by design: this is the I/O + pandas layer (SURVEY.md §4 marks it
I/O-bound, not compute-bound).  It produces contiguous float32 matrices that
the builder moves to device once; nothing here runs under jit.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Tuple, Union

import numpy as np
import pandas as pd

from gordo_tpu.dataset.base import GordoBaseDataset
from gordo_tpu.dataset.data_provider.base import GordoBaseDataProvider
from gordo_tpu.dataset.data_provider.providers import RandomDataProvider
from gordo_tpu.dataset.filter_rows import pandas_filter_rows
from gordo_tpu.dataset.sensor_tag import SensorTag, normalize_sensor_tags
from gordo_tpu.utils.args import capture_args


def _summary_statistics(data: pd.DataFrame) -> Dict[str, Dict[str, float]]:
    """Per-tag mean/std/min/max for dataset metadata.

    One vectorized numpy pass instead of four pandas reductions per
    column: the pandas nanops machinery costs ~2ms per call, which at
    4 stats x tags x thousands of machines made METADATA the largest
    single host cost of a warm project build (measured ~75ms/machine,
    ~80% of warm build wall time).  ddof=1 matches ``Series.std``."""
    return summary_statistics_arrays(
        data.to_numpy(dtype=np.float64, copy=False), list(data.columns)
    )


def summary_statistics_arrays(
    values: np.ndarray, cols: List[Any]
) -> Dict[str, Dict[str, float]]:
    """:func:`_summary_statistics` on a ``(rows, len(cols))`` float64
    matrix — the shared kernel the fleet ingest plane calls column-slice
    by column-slice without materializing per-machine DataFrames."""
    if not cols:
        return {}
    if values.shape[0] == 0:
        nan = float("nan")
        return {
            str(c): {"mean": nan, "std": nan, "min": nan, "max": nan}
            for c in cols
        }
    with np.errstate(all="ignore"):
        import warnings

        with warnings.catch_warnings():
            # all-NaN columns: emit NaN stats like pandas, not warnings
            warnings.simplefilter("ignore", category=RuntimeWarning)
            means = np.nanmean(values, axis=0)
            stds = np.nanstd(values, axis=0, ddof=1)
            mins = np.nanmin(values, axis=0)
            maxs = np.nanmax(values, axis=0)
    return {
        str(c): {
            "mean": float(means[i]),
            "std": float(stds[i]),
            "min": float(mins[i]),
            "max": float(maxs[i]),
        }
        for i, c in enumerate(cols)
    }


def _bin_label_index(
    origin: int, first_bin: int, last_bin: int, nanos: int, name
) -> pd.DatetimeIndex:
    """Resample-output label index, cached: machines in a fleet share the
    train period and resolution, so the (identical) tz-aware label grid was
    being rebuilt per tag per machine — about half of the vectorized
    resample's remaining cost."""
    key = (origin, first_bin, last_bin, nanos, name)
    cached = _bin_label_index._cache.get(key)
    if cached is None:
        label_ns = origin + np.arange(first_bin, last_bin + 1) * nanos
        cached = pd.DatetimeIndex(
            label_ns.view("datetime64[ns]"), name=name
        ).tz_localize("UTC")
        # loader-pool threads share this cache; the lock only guards the
        # bounded-size eviction (reads stay lock-free)
        with _bin_label_index._lock:
            if len(_bin_label_index._cache) >= 32:
                _bin_label_index._cache.pop(
                    next(iter(_bin_label_index._cache)), None
                )
            _bin_label_index._cache[key] = cached
    return cached


_bin_label_index._cache = {}
_bin_label_index._lock = threading.Lock()


#: nanoseconds per day — resample origin is midnight UTC of the first sample
_DAY_NS = 86_400_000_000_000


def resample_prep(
    index: pd.DatetimeIndex, nanos: int
) -> Tuple[np.ndarray, int, np.ndarray, pd.DatetimeIndex]:
    """Binning geometry for a mean-resample of ``index`` at a fixed
    ``nanos``-wide resolution: ``(starts, grid_size, scatter,
    label_index)`` — bin-boundary positions for ``np.add.reduceat``, the
    complete output grid size, the scatter positions of the occupied
    bins, and the (cached) label index.

    The ONE definition of the resample geometry: the per-machine fast
    path (:meth:`TimeSeriesDataset._resample_one_arrays`) and the fleet
    ingest plane's cross-machine columnar pass
    (``gordo_tpu/ingest/plane.py``) both call it, so they cannot drift.
    Assumes a non-empty, monotonic, UTC index."""
    # pandas 2.x indexes may be us/ms-resolution; do the math in ns
    idx = index.asi8 if index.unit == "ns" else index.as_unit("ns").asi8
    # midnight UTC of the first sample as pure integer math
    # (Timestamp.normalize() was a measurable per-tag cost)
    origin = (idx[0] // _DAY_NS) * _DAY_NS
    bins = (idx - origin) // nanos
    starts = np.concatenate([[0], np.flatnonzero(np.diff(bins)) + 1])
    grid_size = int(bins[-1] - bins[0]) + 1
    scatter = (bins[starts] - bins[0]).astype(np.int64)
    label_index = _bin_label_index(
        origin, int(bins[0]), int(bins[-1]), nanos, index.name
    )
    return starts, grid_size, scatter, label_index


def _to_timestamp(value) -> pd.Timestamp:
    ts = pd.Timestamp(value)
    if ts.tzinfo is None:
        ts = ts.tz_localize("UTC")
    return ts


class InsufficientDataError(ValueError):
    pass


class TimeSeriesDataset(GordoBaseDataset):
    """Pull tags from a provider over a train period, resample + join +
    filter into an aligned tag matrix.

    Parameters mirror the reference's config surface:
    ``train_start_date``/``train_end_date``, ``tag_list``,
    ``target_tag_list`` (defaults to ``tag_list`` — autoencoder X == y),
    ``resolution`` (pandas offset, default "10min"), ``row_filter`` (safe
    boolean expression), ``aggregation_methods``, ``row_filter_buffer_size``,
    ``n_samples_threshold``.
    """

    @capture_args
    def __init__(
        self,
        train_start_date: Union[str, pd.Timestamp] = None,
        train_end_date: Union[str, pd.Timestamp] = None,
        tag_list: Optional[List] = None,
        target_tag_list: Optional[List] = None,
        data_provider: Union[GordoBaseDataProvider, dict, None] = None,
        resolution: str = "10min",
        row_filter: Union[str, list, None] = None,
        aggregation_methods: Union[str, List[str]] = "mean",
        row_filter_buffer_size: int = 0,
        n_samples_threshold: int = 0,
        asset: Optional[str] = None,
        tags: Optional[List] = None,
        **_ignored,
    ):
        # project-YAML spelling (reference config uses ``tags:``)
        if tag_list is None and tags is not None:
            tag_list = tags
        if train_start_date is None or train_end_date is None:
            raise ValueError("train_start_date and train_end_date are required")
        self.train_start_date = _to_timestamp(train_start_date)
        self.train_end_date = _to_timestamp(train_end_date)
        if self.train_start_date >= self.train_end_date:
            raise ValueError(
                f"train_start_date {self.train_start_date} must precede "
                f"train_end_date {self.train_end_date}"
            )
        self.asset = asset
        self.tag_list = normalize_sensor_tags(list(tag_list or []), asset=asset)
        self.target_tag_list = (
            normalize_sensor_tags(list(target_tag_list), asset=asset)
            if target_tag_list
            else list(self.tag_list)
        )
        if isinstance(data_provider, dict):
            data_provider = GordoBaseDataProvider.from_dict(data_provider)
        self.data_provider = data_provider or RandomDataProvider()
        self.resolution = resolution
        self.row_filter = row_filter
        self.aggregation_methods = aggregation_methods
        self.row_filter_buffer_size = row_filter_buffer_size
        self.n_samples_threshold = n_samples_threshold
        self._metadata: Dict[str, Any] = {}

    # -- assembly ------------------------------------------------------------

    def _resample_one_arrays(self, series: pd.Series, _memo=None):
        """Vectorized resample of one tag to ``(values, label_index)``, or
        None when only the pandas path applies.

        Mean aggregation of a UTC series over a fixed-width resolution is
        O(n) ``np.add.reduceat`` over bin boundaries — at fleet scale the
        per-tag pandas ``resample().mean()`` dominated project-build wall
        time by ~10x.  Output is bin-for-bin identical to pandas (origin =
        midnight of the first sample's day, left-closed/left-labeled,
        empty bins NaN).  Returning raw arrays (not a Series) lets the
        join build its matrix without ever materializing per-tag pandas
        objects — the Series constructor itself was ~25% of assembly.
        """
        if (
            self.aggregation_methods != "mean"
            or len(series) == 0
            or str(series.index.tz) != "UTC"
        ):
            return None
        try:
            nanos = pd.tseries.frequencies.to_offset(self.resolution).nanos
        except ValueError:  # non-fixed frequency (e.g. months) — pandas path
            return None

        if not series.index.is_monotonic_increasing:
            series = series.sort_index()
        # The binning geometry (ns timestamps, bin boundaries, scatter
        # positions, label index) depends only on the index object — and a
        # provider yields every tag of one machine on ONE shared index, so
        # it is computed once per machine, not once per tag.
        index = series.index
        prep = _memo.get(id(index)) if _memo is not None else None
        if prep is None:
            starts, grid_size, scatter, label_index = resample_prep(
                index, nanos
            )
            # the entry holds the index object itself: the memo is keyed by
            # id(), and letting the index be GC'd could recycle its id for
            # a DIFFERENT index within the same join
            prep = (index, starts, grid_size, scatter, label_index)
            if _memo is not None:
                _memo[id(index)] = prep
        _, starts, grid_size, scatter, label_index = prep
        values = series.to_numpy(dtype=np.float64, copy=False)
        # NaN samples must not poison bucket means (pandas mean skips them)
        nan_mask = np.isnan(values)
        sums = np.add.reduceat(np.where(nan_mask, 0.0, values), starts)
        valid = np.add.reduceat((~nan_mask).astype(np.int64), starts)
        # where= keeps the empty-bin lanes NaN without an errstate guard
        means = np.divide(
            sums, valid, out=np.full(sums.shape, np.nan), where=valid > 0
        )
        # scatter onto the COMPLETE bin grid (empty bins NaN) so length,
        # labels, and metadata match the pandas path exactly
        grid = np.full(grid_size, np.nan)
        grid[scatter] = means
        return grid, label_index

    def _resample_one(self, series: pd.Series) -> pd.Series:
        """Resample a single tag's series to ``self.resolution`` (the
        vectorized path when applicable, else pandas)."""
        fast = self._resample_one_arrays(series)
        if fast is None:
            return series.resample(self.resolution).agg(
                self.aggregation_methods
            )
        grid, label_index = fast
        return pd.Series(grid, index=label_index, name=series.name)

    def _join_timeseries(self, series_iter) -> pd.DataFrame:
        entries = []            # ("fast", name, values, label_index) |
        all_fast = True         # ("slow", aggregated pandas object)
        metadata = {}
        idx_memo: Dict[int, Any] = {}
        for series in series_iter:
            raw_len = len(series)
            fast = (
                self._resample_one_arrays(series, idx_memo)
                if raw_len else None
            )
            if fast is not None:
                grid, label_index = fast
                entries.append(("fast", series.name, grid, label_index))
                n_out = len(grid)
            else:
                all_fast = False
                agg = (
                    series.resample(self.resolution).agg(
                        self.aggregation_methods
                    )
                    if raw_len
                    else series
                )
                if isinstance(agg, pd.DataFrame):  # multi-agg methods
                    agg.columns = [f"{series.name}_{m}" for m in agg.columns]
                else:
                    agg.name = series.name
                entries.append(("slow", agg))
                n_out = len(agg)
            metadata[str(series.name)] = {
                "original_length": int(raw_len),
                "resampled_length": int(n_out),
            }
        self._metadata["tag_loading_metadata"] = metadata

        if all_fast and entries and all(
            e[3] is entries[0][3] or e[3].equals(entries[0][3])
            for e in entries[1:]
        ):
            # all-fast, identical label grids (guaranteed when tags share a
            # provider period and the label-index cache hits): build the
            # matrix directly and drop NaN rows with one vectorized mask —
            # no per-tag Series, no concat alignment, no block dropna
            mat = np.column_stack([e[2] for e in entries])
            keep = ~np.isnan(mat).any(axis=1)
            return pd.DataFrame(
                mat[keep],
                index=entries[0][3][keep],
                columns=[e[1] for e in entries],
            )
        # mixed/slow path: materialize fast columns as Series (original
        # iteration order preserved) and join through pandas
        frames = [
            e[1] if e[0] == "slow"
            else pd.Series(e[2], index=e[3], name=e[1])
            for e in entries
        ]
        if (
            len(frames) > 1
            and all(
                isinstance(f, pd.Series) and f.dtype == np.float64
                for f in frames
            )
            and all(f.index.equals(frames[0].index) for f in frames[1:])
        ):
            joined = pd.DataFrame(
                np.column_stack([f.to_numpy() for f in frames]),
                index=frames[0].index,
                columns=[f.name for f in frames],
            ).dropna()
        else:
            joined = pd.concat(frames, axis=1, join="inner").dropna()
        return joined

    def get_data(self) -> Tuple[pd.DataFrame, pd.DataFrame]:
        all_tags: List[SensorTag] = list(
            dict.fromkeys(self.tag_list + self.target_tag_list)
        )
        series_iter = self.data_provider.load_series(
            self.train_start_date, self.train_end_date, all_tags
        )
        data = self._join_timeseries(series_iter)
        rows_after_join = len(data)

        if self.row_filter:
            data = pandas_filter_rows(
                data, self.row_filter, buffer_size=self.row_filter_buffer_size
            )
        rows_after_filter = len(data)

        if rows_after_filter < max(self.n_samples_threshold, 1):
            raise InsufficientDataError(
                f"Only {rows_after_filter} rows after filtering "
                f"(threshold {self.n_samples_threshold}) for period "
                f"{self.train_start_date} → {self.train_end_date}"
            )

        # Column order follows the config's tag order.  With multiple
        # aggregation methods the columns are "<tag>_<method>" and X spans
        # them all (the reference behaves the same way).
        x_cols = [t.name for t in self.tag_list]
        y_cols = [t.name for t in self.target_tag_list]
        cols = list(data.columns)
        # already in config order (the normal case): skip the listlike
        # reindex, which costs more than the rest of column selection
        # combined on the fleet-build hot path
        if cols == x_cols:
            X = data
        elif all(c in data.columns for c in x_cols):
            X = data[x_cols]
        else:
            X = data
        if y_cols == x_cols:
            # autoencoder default (targets == inputs): reuse X — every
            # consumer treats X and y as read-only (jax conversion copies)
            y = X
        elif all(c in data.columns for c in y_cols):
            y = data[y_cols]
        else:
            y = X.copy()

        self._metadata.update(
            {
                "train_start_date": str(self.train_start_date),
                "train_end_date": str(self.train_end_date),
                "resolution": self.resolution,
                "row_filter": self.row_filter,
                "rows_after_join": int(rows_after_join),
                "rows_after_filter": int(rows_after_filter),
                "filtered_periods": int(rows_after_join - rows_after_filter),
                "tag_list": [t.to_json() for t in self.tag_list],
                "target_tag_list": [t.to_json() for t in self.target_tag_list],
                "data_provider": self.data_provider.to_dict(),
                "summary_statistics": _summary_statistics(data),
            }
        )
        return X, y

    def get_metadata(self) -> Dict[str, Any]:
        return dict(self._metadata)


def dataset_from_metadata(
    dataset_meta: Dict[str, Any],
    start: Any,
    end: Any,
    data_provider: Optional[GordoBaseDataProvider] = None,
) -> TimeSeriesDataset:
    """A scoring-period :class:`TimeSeriesDataset` reconstructed from a
    build's dataset metadata (``metadata["dataset"]`` as the builder
    records it: ``tag_list``, ``resolution``, ``data_provider``).

    The shared refetch recipe: the HTTP client re-pulls raw data for a
    prediction period with it, and the backfill runner drives historical
    windows through the exact same assembly — one definition of "the
    data a machine scores over", not two."""
    tag_list = [
        t["name"] if isinstance(t, dict) else str(t)
        for t in dataset_meta.get("tag_list", [])
    ]
    if not tag_list:
        raise ValueError("Dataset metadata has no tag_list")
    provider = data_provider
    if provider is None:
        dp_cfg = dataset_meta.get("data_provider")
        if not dp_cfg:
            raise ValueError(
                "No data_provider in dataset metadata and none supplied"
            )
        provider = GordoBaseDataProvider.from_dict(dict(dp_cfg))
    return TimeSeriesDataset(
        train_start_date=start,
        train_end_date=end,
        tag_list=tag_list,
        resolution=dataset_meta.get("resolution", "10min"),
        data_provider=provider,
    )


class RandomDataset(TimeSeriesDataset):
    """TimeSeriesDataset preconfigured with the RandomDataProvider
    (reference: ``datasets.RandomDataset``)."""

    @capture_args
    def __init__(
        self,
        train_start_date="2017-12-25 06:00:00Z",
        train_end_date="2017-12-29 06:00:00Z",
        tag_list: Optional[List] = None,
        **kwargs,
    ):
        kwargs.pop("data_provider", None)
        if not tag_list and not kwargs.get("tags"):
            tag_list = ["tag-1", "tag-2", "tag-3"]
        super().__init__(
            train_start_date=train_start_date,
            train_end_date=train_end_date,
            tag_list=tag_list,
            data_provider=RandomDataProvider(),
            **kwargs,
        )
