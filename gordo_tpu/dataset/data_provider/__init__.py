from gordo_tpu.dataset.data_provider.base import GordoBaseDataProvider  # noqa: F401
from gordo_tpu.dataset.data_provider.providers import (  # noqa: F401
    DataLakeProvider,
    FileSystemTagProvider,
    InfluxDataProvider,
    RandomDataProvider,
)
