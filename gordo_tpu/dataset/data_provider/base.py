"""Data-provider contract.

Reference equivalent: ``gordo_components/dataset/data_provider/base.py`` —
``GordoBaseDataProvider`` with the ``load_series`` generator contract,
``can_handle_tag``, and ``capture_args`` so providers round-trip through
metadata JSON (``to_dict``/``from_dict``).
"""

from __future__ import annotations

import abc
from typing import Iterable, List

import pandas as pd

from gordo_tpu.utils.args import ParamsMixin


class GordoBaseDataProvider(ParamsMixin, abc.ABC):
    @abc.abstractmethod
    def load_series(
        self,
        from_ts: pd.Timestamp,
        to_ts: pd.Timestamp,
        tag_list: List,
        dry_run: bool = False,
    ) -> Iterable[pd.Series]:
        """Yield one timezone-aware, time-indexed series per requested tag,
        named after the tag."""

    @abc.abstractmethod
    def can_handle_tag(self, tag) -> bool:
        ...

    def load_arrays(
        self,
        from_ts: pd.Timestamp,
        to_ts: pd.Timestamp,
        tag_list: List,
    ):
        """Optional array-grain fetch for the fleet ingest plane: return
        ``(index, values)`` — ONE shared ``pd.DatetimeIndex`` and a
        float64 ``(len(index), len(tag_list))`` matrix whose columns
        follow ``tag_list`` order and hold bit-identical values to what
        :meth:`load_series` would yield — or None when the provider can
        only speak per-tag Series (the plane then materializes the
        series itself).  Providers whose tags share a sampling grid
        should implement it: per-tag ``pd.Series`` construction was ~40%
        of the fleet build's measured load-stage cost."""
        return None

    def to_dict(self) -> dict:
        """Self-describing config (reference: ``capture_args`` round-trip)."""
        cls = type(self)
        return {
            "type": f"{cls.__module__}.{cls.__qualname__}",
            **{
                k: v
                for k, v in self.get_params().items()
                if isinstance(v, (str, int, float, bool, list, dict, type(None)))
            },
        }

    @classmethod
    def from_dict(cls, config: dict) -> "GordoBaseDataProvider":
        from gordo_tpu.serializer.definition import import_locate

        config = dict(config)
        type_path = config.pop("type", None)
        if type_path is None:
            from gordo_tpu.dataset.data_provider.providers import RandomDataProvider

            return RandomDataProvider(**config)
        target = import_locate(
            type_path
            if "." in type_path
            else f"gordo_tpu.dataset.data_provider.providers.{type_path}"
        )
        return target(**config)
