"""Concrete data providers.

Reference equivalents (``gordo_components/dataset/data_provider/``):

- ``RandomDataProvider`` — the no-external-deps provider that backs every
  integration test and example (SURVEY.md §5 calls it the backbone).
- ``InfluxDataProvider`` — reads tag series from InfluxDB measurements.
  Import-gated: constructing it without the ``influxdb`` client installed
  raises with instructions, mirroring how the reference fails.
- ``DataLakeProvider`` + NCS/IROC readers — data-lake access with the
  walk/dispatch/yearly-file logic implemented against the injectable
  ``lake.TagFileSystem`` interface: ``lake.ADLSGen1FileSystem`` (gated on
  the Azure SDK) in production, ``lake.LocalFileSystem`` for mounted/NFS
  tag archives and tests.  :class:`FileSystemTagProvider` remains the
  simpler flat-layout alternative.
"""

from __future__ import annotations

import glob
import os
import zlib
from typing import Iterable, List, Optional

import numpy as np
import pandas as pd

from gordo_tpu.dataset.data_provider.base import GordoBaseDataProvider
from gordo_tpu.dataset.sensor_tag import SensorTag, normalize_sensor_tags
from gordo_tpu.utils.args import capture_args


class RandomDataProvider(GordoBaseDataProvider):
    """Deterministic pseudo-random series per tag (seeded by tag name).

    Series are emitted on a regular ``frequency`` grid (default denser than
    the dataset layer's default 10-min resolution) so every resample bucket
    is populated and tags align after the inner join — with irregular
    per-tag sampling, `resample → inner-join → dropna` keeps only buckets
    where EVERY tag happens to have a sample, collapsing the matrix.
    ``min_size``/``max_size`` bound the point count for very long ranges.
    """

    @capture_args
    def __init__(
        self,
        min_size: int = 100,
        max_size: int = 50_000,
        seed: int = 0,
        frequency: str = "5min",
    ):
        self.min_size = min_size
        self.max_size = max_size
        self.seed = seed
        self.frequency = frequency

    def can_handle_tag(self, tag) -> bool:
        return True

    def _shared_index(
        self, from_ts: pd.Timestamp, to_ts: pd.Timestamp
    ) -> pd.DatetimeIndex:
        step = pd.tseries.frequencies.to_offset(self.frequency).nanos
        n_grid = int((to_ts - from_ts).value // step) + 1
        n = int(np.clip(n_grid, self.min_size, self.max_size))
        # one shared grid for every tag (identical period/count) — building
        # it per tag made date_range the provider's dominant cost at fleet
        # scale (measured ~40% of load_series)
        # ns unit up front: tz-aware periods-based date_range yields a
        # µs-resolution index, and every downstream resample would pay its
        # own as_unit("ns") conversion per tag
        return pd.date_range(
            start=from_ts, end=to_ts, periods=n, name="time"
        ).as_unit("ns")

    def _tag_values(self, tag_name: str, n: int) -> np.ndarray:
        # Stable digest (Python's hash() is salted per process and would
        # break cross-process reproducibility / the build cache contract).
        rng = np.random.default_rng(
            zlib.crc32(f"{tag_name}:{self.seed}".encode())
        )
        return rng.standard_normal(n).cumsum() * 0.1 + rng.uniform(-1, 1)

    def load_series(
        self,
        from_ts: pd.Timestamp,
        to_ts: pd.Timestamp,
        tag_list: List,
        dry_run: bool = False,
    ) -> Iterable[pd.Series]:
        tags = normalize_sensor_tags(list(tag_list))
        index = self._shared_index(from_ts, to_ts)
        for tag in tags:
            yield pd.Series(
                self._tag_values(tag.name, len(index)),
                index=index, name=tag.name,
            )

    # machines in a fleet share the train window, so the (identical)
    # index grid was being rebuilt per machine by the ingest plane's
    # array fetch; pd indexes are immutable, sharing one is safe.  The
    # per-machine load_series path is left uncached on purpose — it is
    # the bench baseline the ingest plane is measured against.
    _index_cache: dict = {}

    def _shared_index_cached(
        self, from_ts: pd.Timestamp, to_ts: pd.Timestamp
    ) -> pd.DatetimeIndex:
        key = (
            int(from_ts.value), int(to_ts.value), self.frequency,
            self.min_size, self.max_size,
        )
        index = RandomDataProvider._index_cache.get(key)
        if index is None:
            index = self._shared_index(from_ts, to_ts)
            if len(RandomDataProvider._index_cache) >= 32:
                RandomDataProvider._index_cache.pop(
                    next(iter(RandomDataProvider._index_cache)), None
                )
            RandomDataProvider._index_cache[key] = index
        return index

    def load_arrays(
        self,
        from_ts: pd.Timestamp,
        to_ts: pd.Timestamp,
        tag_list: List,
    ):
        """Array-grain fetch for the fleet ingest plane: the same shared
        grid and per-tag generator as :meth:`load_series` (bit-identical
        columns) without 1 ``pd.Series`` construction per tag."""
        tags = normalize_sensor_tags(list(tag_list))
        index = self._shared_index_cached(from_ts, to_ts)
        values = np.empty((len(index), len(tags)), dtype=np.float64)
        for j, tag in enumerate(tags):
            values[:, j] = self._tag_values(tag.name, len(index))
        return index, values


class FileSystemTagProvider(GordoBaseDataProvider):
    """Per-tag CSV/parquet files under an asset-directory convention.

    Layout (the reference's NCS/IROC on-lake conventions, on any mounted
    filesystem)::

        <base_dir>/<asset>/<tag>.csv                 # single file per tag
        <base_dir>/<asset>/<tag>_<year>.parquet      # yearly partitions

    CSV files need columns ``(time, value)`` (header optional); parquet
    needs a datetime index or a ``time`` column.
    """

    @capture_args
    def __init__(self, base_dir: str, asset: Optional[str] = None,
                 file_format: str = "csv"):
        self.base_dir = base_dir
        self.asset = asset
        self.file_format = file_format

    def can_handle_tag(self, tag) -> bool:
        tag = normalize_sensor_tags([tag])[0]
        return bool(self._files_for(tag))

    def _files_for(self, tag: SensorTag) -> List[str]:
        asset = tag.asset or self.asset or ""
        stem = os.path.join(self.base_dir, asset, tag.name)
        return sorted(
            glob.glob(f"{stem}.{self.file_format}")
            + glob.glob(f"{stem}_*.{self.file_format}")
        )

    def _read_one(self, path: str) -> pd.Series:
        if self.file_format == "parquet":
            df = pd.read_parquet(path)
            if "time" in df.columns:
                df = df.set_index("time")
            series = df.iloc[:, 0]
        else:
            df = pd.read_csv(path, header=None, names=["time", "value"],
                             skiprows=self._csv_skiprows(path))
            series = df.set_index("time")["value"]
        series.index = pd.to_datetime(series.index, utc=True)
        return series

    @staticmethod
    def _csv_skiprows(path: str) -> int:
        with open(path) as f:
            first = f.readline().strip().lower()
        return 1 if first.startswith(("time", "timestamp")) else 0

    def load_series(
        self,
        from_ts: pd.Timestamp,
        to_ts: pd.Timestamp,
        tag_list: List,
        dry_run: bool = False,
    ) -> Iterable[pd.Series]:
        tags = normalize_sensor_tags(list(tag_list), asset=self.asset)
        for tag in tags:
            files = self._files_for(tag)
            if not files:
                raise FileNotFoundError(
                    f"No {self.file_format} files for tag {tag.name!r} "
                    f"(asset {tag.asset or self.asset!r}) under {self.base_dir}"
                )
            series = pd.concat([self._read_one(p) for p in files]).sort_index()
            series = series[(series.index >= from_ts) & (series.index < to_ts)]
            series.name = tag.name
            yield series


class IrocBundleProvider(GordoBaseDataProvider):
    """Bundle-CSV reader (reference: ``iroc_reader.IrocReader``).

    The IROC on-lake layout stores MANY tags per CSV — rows of
    ``tag,timestamp,value`` — instead of one file per tag.  This provider
    reads every ``*.csv`` under ``base_dir`` (or an explicit file list),
    filters to the requested window, and yields one series per tag.

    Column names are matched case-insensitively against
    ``(tag, timestamp|time, value)``; headerless files are assumed to be in
    that order.
    """

    @capture_args
    def __init__(self, base_dir: str, files: Optional[List[str]] = None):
        self.base_dir = base_dir
        self.files = files

    def _bundle_files(self) -> List[str]:
        if self.files:
            return [os.path.join(self.base_dir, f) for f in self.files]
        return sorted(glob.glob(os.path.join(self.base_dir, "*.csv")))

    def can_handle_tag(self, tag) -> bool:
        return bool(self._bundle_files())

    @staticmethod
    def _read_bundle(path) -> pd.DataFrame:
        """``path`` may be a filesystem path or a seekable file-like (the
        lake reader hands in downloaded bytes)."""
        head = pd.read_csv(path, nrows=0)
        if hasattr(path, "seek"):
            path.seek(0)
        cols = [c.strip().lower() for c in head.columns]
        if "tag" in cols and "value" in cols:
            df = pd.read_csv(path)
            df.columns = [c.strip().lower() for c in df.columns]
            time_candidates = [
                c for c in ("timestamp", "time", "datetime") if c in df.columns
            ]
            if not time_candidates:
                raise ValueError(
                    f"Bundle CSV {path!r} has no recognized time column "
                    f"(expected one of timestamp/time/datetime, got {cols})"
                )
            time_col = time_candidates[0]
        else:  # headerless: tag,timestamp,value order
            df = pd.read_csv(path, header=None, names=["tag", "timestamp", "value"])
            time_col = "timestamp"
        df = df.rename(columns={time_col: "time"})[["tag", "time", "value"]]
        df["time"] = pd.to_datetime(df["time"], utc=True)
        return df

    def load_series(
        self,
        from_ts: pd.Timestamp,
        to_ts: pd.Timestamp,
        tag_list: List,
        dry_run: bool = False,
    ) -> Iterable[pd.Series]:
        files = self._bundle_files()
        if not files:
            raise FileNotFoundError(f"No bundle CSVs under {self.base_dir!r}")
        bundle = pd.concat([self._read_bundle(p) for p in files])
        known_tags = set(bundle["tag"].unique())
        bundle = bundle[(bundle["time"] >= from_ts) & (bundle["time"] < to_ts)]
        by_tag = dict(tuple(bundle.groupby("tag")))
        for tag in normalize_sensor_tags(list(tag_list)):
            if tag.name not in known_tags:
                raise KeyError(
                    f"Tag {tag.name!r} not present in IROC bundles under "
                    f"{self.base_dir!r} (have: {sorted(known_tags)[:10]}...)"
                )
            if tag.name not in by_tag:
                # tag exists but had no samples in the window: yield empty so
                # the dataset layer reports the data gap, not a missing tag
                yield pd.Series(
                    dtype=float,
                    index=pd.DatetimeIndex([], tz="UTC", name="time"),
                    name=tag.name,
                )
                continue
            group = by_tag[tag.name].sort_values("time")
            series = group.set_index("time")["value"].astype(float)
            series.name = tag.name
            yield series


class InfluxDataProvider(GordoBaseDataProvider):
    """InfluxDB-measurement provider (reference: ``InfluxDataProvider``).

    Gated on the ``influxdb`` client package, which is not part of the
    TPU image; constructing without it raises ImportError with context.
    """

    @capture_args
    def __init__(self, measurement: str = "sensors", value_name: str = "Value",
                 api_key: Optional[str] = None, api_key_header: Optional[str] = None,
                 uri: Optional[str] = None, **influx_kwargs):
        try:
            import influxdb  # noqa: F401
        except ImportError as exc:
            raise ImportError(
                "InfluxDataProvider requires the 'influxdb' client package, "
                "which is not installed in this environment"
            ) from exc
        self.measurement = measurement
        self.value_name = value_name
        self.uri = uri
        self.influx_kwargs = influx_kwargs
        self._client = influxdb.DataFrameClient(**self._parse_uri(uri, influx_kwargs))

    @staticmethod
    def _parse_uri(uri: Optional[str], kwargs: dict) -> dict:
        if not uri:
            return kwargs
        # format: <host>:<port>/<username>/<password>/<database>
        host_port, username, password, database = uri.split("/", 3)
        host, _, port = host_port.partition(":")
        return {
            "host": host,
            "port": int(port or 8086),
            "username": username,
            "password": password,
            "database": database,
            **kwargs,
        }

    def can_handle_tag(self, tag) -> bool:
        return True

    @staticmethod
    def _esc_ident(name: str) -> str:
        """Escape an InfluxQL double-quoted identifier."""
        return name.replace("\\", "\\\\").replace('"', '\\"')

    @staticmethod
    def _esc_str(value: str) -> str:
        """Escape an InfluxQL single-quoted string literal — a tag name
        containing ``'`` must not break (or rewrite) the query."""
        return value.replace("\\", "\\\\").replace("'", "\\'")

    def load_series(self, from_ts, to_ts, tag_list, dry_run=False):
        for tag in normalize_sensor_tags(list(tag_list)):
            query = (
                f'SELECT "{self._esc_ident(self.value_name)}" '
                f'FROM "{self._esc_ident(self.measurement)}" '
                f"WHERE time >= '{from_ts.isoformat()}' "
                f"AND time < '{to_ts.isoformat()}' "
                f"AND \"tag\" = '{self._esc_str(tag.name)}'"
            )
            result = self._client.query(query)
            frame = result.get(self.measurement, pd.DataFrame())
            series = (
                frame[self.value_name]
                if not frame.empty
                else pd.Series(dtype=float)
            )
            series.name = tag.name
            yield series

    def __getstate__(self):
        state = dict(self.__dict__)
        state["_client"] = None
        return state


class DataLakeProvider(GordoBaseDataProvider):
    """Data-lake provider dispatching per-tag reads to sub-readers
    (reference: ``DataLakeProvider`` + ``azure_utils``/``ncs_reader``/
    ``iroc_reader``).

    The filesystem is injectable (``lake.TagFileSystem``): production wires
    ``lake.ADLSGen1FileSystem`` (import-gated on the Azure SDK, same auth
    modes as the reference), tests and mounted archives use
    ``lake.LocalFileSystem`` — exactly the reference's own test strategy of
    mocking the adls filesystem object (SURVEY.md §5).

    Dispatch: each tag goes to the first sub-reader whose
    ``can_handle_tag`` accepts it — :class:`lake.NcsReader` (per-asset
    per-tag yearly files, year-window pruned) then
    :class:`lake.IrocLakeReader` (bundle CSVs).  Reads fan out over a
    thread pool; store round-trips, not CPU, dominate lake access.
    """

    @capture_args
    def __init__(
        self,
        filesystem=None,
        base_dir: str = "/raw/plant",
        iroc_base_dir: Optional[str] = None,
        interactive: bool = False,
        storename: str = "dataplatformdlsprod",
        dl_service_auth_str: Optional[str] = None,
        max_workers: int = 8,
        **kwargs,
    ):
        self.base_dir = base_dir
        self.iroc_base_dir = iroc_base_dir or base_dir
        self.interactive = interactive
        self.storename = storename
        self.dl_service_auth_str = dl_service_auth_str
        self.max_workers = max_workers
        self.kwargs = kwargs
        # config-driven (YAML) use passes a string spec: "local:<root>"
        # mounts an on-disk archive; a TagFileSystem instance is injected
        # directly by tests/library callers.  The spec is kept so a pickled
        # provider re-wires the SAME filesystem, never silently retargeting
        # the ADLS default.
        self._fs_spec: Optional[str] = None
        self._had_injected_fs = False
        if isinstance(filesystem, str):
            self._fs_spec = filesystem
            filesystem = self._fs_from_spec(filesystem)
        elif filesystem is not None:
            self._had_injected_fs = True
        self._fs = filesystem
        self._readers = None

    @staticmethod
    def _fs_from_spec(spec: str):
        if spec.startswith("local:"):
            from gordo_tpu.dataset.data_provider.lake import LocalFileSystem

            return LocalFileSystem(spec[len("local:"):] or "/")
        raise ValueError(
            f"Unknown filesystem spec {spec!r}; expected 'local:<root>' "
            "or a TagFileSystem instance"
        )

    # -- lazily wired filesystem + sub-readers ------------------------------
    @property
    def filesystem(self):
        if self._fs is None:
            if self._fs_spec is not None:
                self._fs = self._fs_from_spec(self._fs_spec)
            elif self._had_injected_fs:
                raise RuntimeError(
                    "This DataLakeProvider was built around an injected "
                    "filesystem object that did not survive pickling; "
                    "re-inject one (or construct with a 'local:<root>' spec, "
                    "which round-trips)"
                )
            else:
                from gordo_tpu.dataset.data_provider.lake import (
                    ADLSGen1FileSystem,
                )

                # import-gated: raises with the LocalFileSystem alternative
                # when the Azure SDK is absent (not part of the TPU image)
                self._fs = ADLSGen1FileSystem(
                    store_name=self.storename,
                    interactive=self.interactive,
                    dl_service_auth_str=self.dl_service_auth_str,
                )
        return self._fs

    @property
    def readers(self):
        if self._readers is None:
            from gordo_tpu.dataset.data_provider.lake import (
                IrocLakeReader,
                NcsReader,
            )

            self._readers = [
                NcsReader(self.filesystem, self.base_dir),
                IrocLakeReader(self.filesystem, self.iroc_base_dir),
            ]
        return self._readers

    def _reader_for(self, tag: SensorTag):
        for reader in self.readers:
            if reader.can_handle_tag(tag):
                return reader
        raise ValueError(
            f"No lake reader can handle tag {tag.name!r} "
            f"(asset {tag.asset!r}) under {self.base_dir!r}"
        )

    def can_handle_tag(self, tag) -> bool:
        tag = normalize_sensor_tags([tag])[0]
        if tag.asset is None:
            return False
        return any(reader.can_handle_tag(tag) for reader in self.readers)

    def load_series(
        self,
        from_ts: pd.Timestamp,
        to_ts: pd.Timestamp,
        tag_list: List,
        dry_run: bool = False,
    ) -> Iterable[pd.Series]:
        from gordo_tpu.dataset.data_provider.lake import read_tags_concurrently

        tags = normalize_sensor_tags(list(tag_list))
        missing = [t.name for t in tags if t.asset is None]
        if missing:
            raise ValueError(
                f"DataLakeProvider needs an asset for every tag; missing for "
                f"{missing}"
            )
        if dry_run:
            for tag in tags:  # existence probe only, no reads
                self._reader_for(tag)
            return
        yield from read_tags_concurrently(
            self._reader_for, tags, from_ts, to_ts, self.max_workers
        )

    def __getstate__(self):
        # the filesystem handle (SDK session / open fds) never rides in
        # metadata round-trips; it re-wires lazily on the other side
        state = dict(self.__dict__)
        state["_fs"] = None
        state["_readers"] = None
        return state
