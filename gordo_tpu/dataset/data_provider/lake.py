"""Data-lake tag readers over an injectable filesystem.

Reference equivalents (``gordo_components/dataset/data_provider/``):

- ``azure_utils.py`` — wraps Azure Data Lake gen1 auth + file walking/open.
  Here that surface is the :class:`TagFileSystem` protocol with two
  implementations: :class:`LocalFileSystem` (mounted/NFS archives, also the
  test double — the reference's own tests mock the adls filesystem object
  the same way, SURVEY.md §5) and :class:`ADLSGen1FileSystem`
  (import-gated on the ``azure-datalake-store`` SDK).
- ``ncs_reader.py`` — Norwegian-Continental-Shelf per-tag yearly files
  under an asset directory convention → :class:`NcsReader`, including the
  year-window file pruning (only files whose year overlaps
  ``[from_ts, to_ts]`` are opened).
- ``iroc_reader.py`` — bundle CSVs (many tags per file) → the separate
  :class:`~gordo_tpu.dataset.data_provider.providers.IrocBundleProvider`;
  :class:`IrocLakeReader` adapts the same parsing to a
  :class:`TagFileSystem` so ``DataLakeProvider`` can dispatch to it.

The dispatching provider itself lives in ``providers.DataLakeProvider``.
"""

from __future__ import annotations

import fnmatch
import io
import logging
import os
import posixpath
import re
import threading
from concurrent.futures import ThreadPoolExecutor
from typing import IO, Iterable, List, Optional, Sequence

import pandas as pd

from gordo_tpu.dataset.sensor_tag import SensorTag

logger = logging.getLogger(__name__)


class TagFileSystem:
    """Minimal filesystem surface the lake readers need (ADLS-shaped).

    Paths are POSIX-style strings relative to the filesystem root.
    """

    def ls(self, path: str) -> List[str]:  # pragma: no cover - interface
        """Entry names (not full paths) under ``path``; [] if missing."""
        raise NotImplementedError

    def exists(self, path: str) -> bool:  # pragma: no cover - interface
        raise NotImplementedError

    def isdir(self, path: str) -> bool:  # pragma: no cover - interface
        raise NotImplementedError

    def open(self, path: str, mode: str = "rb") -> IO:  # pragma: no cover
        raise NotImplementedError

    # -- shared helpers ------------------------------------------------------
    def glob(self, path: str, pattern: str) -> List[str]:
        """Full paths of entries under ``path`` matching ``pattern``."""
        return [
            posixpath.join(path, name)
            for name in sorted(self.ls(path))
            if fnmatch.fnmatch(name, pattern)
        ]


class LocalFileSystem(TagFileSystem):
    """Mounted/NFS tag archives — and the unit-test double for ADLS."""

    def __init__(self, root: str = "/"):
        self.root = root

    def _full(self, path: str) -> str:
        return os.path.join(self.root, path.lstrip("/"))

    def ls(self, path: str) -> List[str]:
        full = self._full(path)
        return sorted(os.listdir(full)) if os.path.isdir(full) else []

    def exists(self, path: str) -> bool:
        return os.path.exists(self._full(path))

    def isdir(self, path: str) -> bool:
        return os.path.isdir(self._full(path))

    def open(self, path: str, mode: str = "rb") -> IO:
        return open(self._full(path), mode)


class ADLSGen1FileSystem(TagFileSystem):
    """Azure Data Lake Store gen1 over the ``azure-datalake-store`` SDK.

    Auth mirrors the reference: interactive device-code flow, or a
    service-principal string ``"tenant_id:client_id:client_secret"``
    (reference ``azure_utils`` auth modes).  Import-gated — constructing it
    without the SDK raises with the mounted-filesystem alternative.
    """

    def __init__(
        self,
        store_name: str = "dataplatformdlsprod",
        interactive: bool = False,
        dl_service_auth_str: Optional[str] = None,
    ):
        try:
            from azure.datalake.store import core, lib
        except ImportError as exc:
            raise ImportError(
                "ADLSGen1FileSystem requires the 'azure-datalake-store' SDK, "
                "which is not installed in this environment. Point "
                "DataLakeProvider at a LocalFileSystem over a mounted tag "
                "archive instead."
            ) from exc
        if dl_service_auth_str:
            tenant, client_id, client_secret = dl_service_auth_str.split(":", 2)
            token = lib.auth(
                tenant_id=tenant,
                client_id=client_id,
                client_secret=client_secret,
                resource="https://datalake.azure.net/",
            )
        elif interactive:
            token = lib.auth()
        else:
            raise ValueError(
                "ADLSGen1FileSystem needs interactive=True or a "
                "dl_service_auth_str ('tenant:client_id:client_secret')"
            )
        self._fs = core.AzureDLFileSystem(token, store_name=store_name)

    def ls(self, path: str) -> List[str]:
        if not self._fs.exists(path):
            return []
        return sorted(posixpath.basename(p) for p in self._fs.ls(path))

    def exists(self, path: str) -> bool:
        return self._fs.exists(path)

    def isdir(self, path: str) -> bool:
        return self._fs.info(path)["type"] == "DIRECTORY"

    def open(self, path: str, mode: str = "rb") -> IO:
        return self._fs.open(path, mode)


# ---------------------------------------------------------------------------
# Readers
# ---------------------------------------------------------------------------

_YEAR_RE = re.compile(r"_(\d{4})\.(csv|parquet)(\.gz)?$", re.IGNORECASE)


class NcsReader:
    """Per-tag yearly files under the NCS asset-directory convention.

    Layout (reference ``ncs_reader`` behavior)::

        <base_dir>/<asset>/<tag>/<tag>_<year>.csv[.gz]      # yearly parts
        <base_dir>/<asset>/<tag>/<tag>_<year>.parquet
        <base_dir>/<asset>/<tag>.csv                        # single file

    CSV columns: ``(time, value)``, header optional.  Parquet: datetime
    index or a ``time`` column, first remaining column is the value.

    **Year pruning**: only files whose ``_<year>`` suffix intersects the
    requested ``[from_ts, to_ts]`` window are opened — the load-bearing
    optimization for decade-deep archives.
    """

    def __init__(self, fs: TagFileSystem, base_dir: str, assets: Optional[Sequence[str]] = None):
        self.fs = fs
        self.base_dir = base_dir.rstrip("/")
        self.assets = list(assets) if assets else None
        # per-tag file listings are consulted by can_handle_tag AND read_tag
        # (often from the dispatch thread pool) — cache the remote ls once
        self._files_cache: dict = {}

    # -- dispatch ------------------------------------------------------------
    def can_handle_tag(self, tag: SensorTag) -> bool:
        return bool(tag.asset) and bool(self._tag_files(tag))

    def _asset_dir(self, tag: SensorTag) -> str:
        return posixpath.join(self.base_dir, str(tag.asset))

    @staticmethod
    def _is_tag_file(name: str, tag_name: str) -> bool:
        """Exact-name matching: ``<tag>.<ext>`` or ``<tag>_<year>.<ext>``.

        A glob like ``tag_*`` would also swallow OTHER tags whose names
        extend this one (``PUMP_A`` matching ``PUMP_A_SPEED_2017.csv``) and
        silently blend foreign sensors into the series — so match the tag
        name literally and the suffix strictly.
        """
        if not name.startswith(tag_name):
            return False
        rest = name[len(tag_name):]
        return bool(
            re.fullmatch(r"\.(csv|parquet)(\.gz)?", rest, re.IGNORECASE)
            or re.fullmatch(r"_\d{4}\.(csv|parquet)(\.gz)?", rest, re.IGNORECASE)
        )

    def _tag_files(self, tag: SensorTag) -> List[str]:
        """Every on-lake file holding this tag (yearly parts or single)."""
        key = (str(tag.asset), tag.name)
        cached = self._files_cache.get(key)
        if cached is not None:
            return cached
        tag_dir = posixpath.join(self._asset_dir(tag), tag.name)
        if self.fs.isdir(tag_dir):
            # strict-match only — no ls() fallback: a stray README/checksum
            # in a tag dir must never be parsed as sensor data (the whole
            # point of _is_tag_file's exact-name rule above)
            names = [
                n for n in self.fs.ls(tag_dir) if self._is_tag_file(n, tag.name)
            ]
            files = [posixpath.join(tag_dir, n) for n in sorted(names)]
        else:
            asset_dir = self._asset_dir(tag)
            files = [
                posixpath.join(asset_dir, n)
                for n in sorted(self.fs.ls(asset_dir))
                if self._is_tag_file(n, tag.name)
            ]
        self._files_cache[key] = files
        return files

    @staticmethod
    def _file_year(path: str) -> Optional[int]:
        m = _YEAR_RE.search(posixpath.basename(path))
        return int(m.group(1)) if m else None

    def files_in_window(
        self,
        tag: SensorTag,
        from_ts: pd.Timestamp,
        to_ts: pd.Timestamp,
        all_files: Optional[List[str]] = None,
    ) -> List[str]:
        """Year-pruned file list (un-yeared files always pass)."""
        out = []
        for path in (self._tag_files(tag) if all_files is None else all_files):
            year = self._file_year(path)
            if year is None or (from_ts.year <= year <= to_ts.year):
                out.append(path)
        return out

    # -- reading -------------------------------------------------------------
    def _read_file(self, path: str) -> pd.Series:
        lower = path.lower()
        if lower.endswith(".parquet"):
            with self.fs.open(path, "rb") as f:
                df = pd.read_parquet(io.BytesIO(f.read()))
            if "time" in df.columns:
                df = df.set_index("time")
            series = df.iloc[:, 0]
        else:
            compression = "gzip" if lower.endswith(".gz") else None
            with self.fs.open(path, "rb") as f:
                raw = f.read()
            head = pd.read_csv(
                io.BytesIO(raw), nrows=1, header=None, compression=compression
            )
            skip = (
                1
                if isinstance(head.iloc[0, 0], str)
                and head.iloc[0, 0].strip().lower().startswith(("time", "timestamp"))
                else 0
            )
            df = pd.read_csv(
                io.BytesIO(raw),
                header=None,
                names=["time", "value"],
                skiprows=skip,
                compression=compression,
            )
            series = df.set_index("time")["value"]
        series.index = pd.to_datetime(series.index, utc=True)
        return series.astype(float)

    def read_tag(
        self, tag: SensorTag, from_ts: pd.Timestamp, to_ts: pd.Timestamp
    ) -> pd.Series:
        all_files = self._tag_files(tag)
        files = self.files_in_window(tag, from_ts, to_ts, all_files=all_files)
        if not files:
            if all_files:
                # tag exists but nothing in the window: empty series = data
                # gap (the dataset layer reports it), not a missing tag
                return pd.Series(
                    dtype=float,
                    index=pd.DatetimeIndex([], tz="UTC", name="time"),
                    name=tag.name,
                )
            raise FileNotFoundError(
                f"No NCS files for tag {tag.name!r} (asset {tag.asset!r}) "
                f"under {self.base_dir}"
            )
        logger.debug(
            "NCS read %s: %d/%d files after year pruning",
            tag.name, len(files), len(all_files),
        )
        series = pd.concat([self._read_file(p) for p in files]).sort_index()
        series = series[(series.index >= from_ts) & (series.index < to_ts)]
        series.name = tag.name
        return series


class IrocLakeReader:
    """IROC bundle CSVs on a :class:`TagFileSystem`.

    Same parsing as ``providers.IrocBundleProvider`` (rows of
    ``tag,timestamp,value``), adapted to the lake filesystem so
    ``DataLakeProvider`` can dispatch IROC-asset tags to it.
    """

    def __init__(self, fs: TagFileSystem, base_dir: str):
        self.fs = fs
        self.base_dir = base_dir.rstrip("/")
        # one download+parse per ASSET, not per tag: a 50-tag load against
        # 20 bundle files must not fetch the same files 1000 times
        self._bundle_cache: dict = {}
        self._files_cache: dict = {}
        self._lock = threading.Lock()

    def _asset_files(self, asset: str) -> List[str]:
        cached = self._files_cache.get(asset)
        if cached is None:
            cached = self.fs.glob(posixpath.join(self.base_dir, asset), "*.csv")
            self._files_cache[asset] = cached
        return cached

    def can_handle_tag(self, tag: SensorTag) -> bool:
        return bool(tag.asset) and bool(self._asset_files(str(tag.asset)))

    def _asset_bundle(self, asset: str) -> pd.DataFrame:
        from gordo_tpu.dataset.data_provider.providers import IrocBundleProvider

        with self._lock:  # reads fan out over a pool; load each asset once
            cached = self._bundle_cache.get(asset)
            if cached is not None:
                return cached
            frames = []
            for path in self._asset_files(asset):
                with self.fs.open(path, "rb") as f:
                    frames.append(
                        IrocBundleProvider._read_bundle(io.BytesIO(f.read()))
                    )
            if not frames:
                raise FileNotFoundError(
                    f"No IROC bundles for asset {asset!r} under {self.base_dir}"
                )
            bundle = pd.concat(frames)
            self._bundle_cache[asset] = bundle
            return bundle

    def read_tag(
        self, tag: SensorTag, from_ts: pd.Timestamp, to_ts: pd.Timestamp
    ) -> pd.Series:
        bundle = self._asset_bundle(str(tag.asset))
        if tag.name not in set(bundle["tag"]):
            raise KeyError(
                f"Tag {tag.name!r} not present in IROC bundles for asset "
                f"{tag.asset!r}"
            )
        rows = bundle[
            (bundle["tag"] == tag.name)
            & (bundle["time"] >= from_ts)
            & (bundle["time"] < to_ts)
        ].sort_values("time")
        series = rows.set_index("time")["value"].astype(float)
        series.name = tag.name
        return series


def read_tags_concurrently(
    reader_for_tag,
    tags: Sequence[SensorTag],
    from_ts: pd.Timestamp,
    to_ts: pd.Timestamp,
    max_workers: int = 8,
) -> Iterable[pd.Series]:
    """Fan per-tag reads out over a thread pool, yielding in tag order.

    The reference reads lake tags in a thread pool the same way — per-tag
    files are independent and the bottleneck is store round-trips.
    """
    def one(tag: SensorTag) -> pd.Series:
        # dispatch (which itself probes the store) runs INSIDE the pool —
        # per-tag can_handle listings would otherwise serialize up front
        return reader_for_tag(tag).read_tag(tag, from_ts, to_ts)

    with ThreadPoolExecutor(max_workers=max_workers) as pool:
        futures = [pool.submit(one, tag) for tag in tags]
        for future in futures:
            yield future.result()
