from gordo_tpu.dataset.base import GordoBaseDataset  # noqa: F401
from gordo_tpu.dataset.datasets import RandomDataset, TimeSeriesDataset  # noqa: F401
from gordo_tpu.dataset.sensor_tag import SensorTag, normalize_sensor_tags  # noqa: F401
