from gordo_tpu.dataset.base import GordoBaseDataset  # noqa: F401
from gordo_tpu.dataset.datasets import (  # noqa: F401
    RandomDataset,
    TimeSeriesDataset,
    dataset_from_metadata,
)
from gordo_tpu.dataset.sensor_tag import SensorTag, normalize_sensor_tags  # noqa: F401
