"""Safe row-filter expression evaluation.

Reference equivalent: ``gordo_components/dataset/filter_rows.py`` —
``pandas_filter_rows(df, expr)``: numexpr-style boolean expressions over tag
columns (e.g. ``"`TAG-A` > 0 & `TAG-B` < 100"``) applied before training.

Safety: the expression comes from project YAML, so it is validated against a
conservative token policy before being handed to ``DataFrame.eval`` (python
engine, no ``@`` locals, no attribute access, no dunder names).
"""

from __future__ import annotations

import re
from typing import Union

import pandas as pd

_FORBIDDEN = re.compile(r"(__|@|\.\s*[A-Za-z_])")
_ALLOWED_FUNCS = {"abs", "sqrt", "exp", "log", "sin", "cos"}
_CALL = re.compile(r"([A-Za-z_][A-Za-z0-9_]*)\s*\(")


_BACKTICKED = re.compile(r"`[^`]*`")


def _validate(expr: str) -> None:
    # Tag names are free-form (dots are common in real sensor tags, e.g.
    # "1903.R-29LT1001.MA_Y"); backtick-quoted names are column references,
    # not expression syntax, so they are excluded from token validation.
    expr = _BACKTICKED.sub("COL", expr)
    if _FORBIDDEN.search(expr):
        raise ValueError(
            f"Row filter {expr!r} contains forbidden tokens "
            "(attribute access / dunder / locals are not allowed)"
        )
    for fn in _CALL.findall(expr):
        if fn not in _ALLOWED_FUNCS:
            raise ValueError(
                f"Row filter {expr!r} calls disallowed function {fn!r}; "
                f"allowed: {sorted(_ALLOWED_FUNCS)}"
            )


def pandas_filter_rows(
    df: pd.DataFrame, filter_str: Union[str, list], buffer_size: int = 0
) -> pd.DataFrame:
    """Keep rows where the expression(s) evaluate truthy.

    ``buffer_size`` drops that many rows *around* every filtered-out row as
    well (sensor transients straddle the offending sample) — reference's
    ``row_filter_buffer_size`` behavior.
    """
    expressions = [filter_str] if isinstance(filter_str, str) else list(filter_str)
    mask = pd.Series(True, index=df.index)
    for expr in expressions:
        _validate(expr)
        result = df.eval(expr, engine="python")
        mask &= pd.Series(result, index=df.index).astype(bool)
    if buffer_size > 0:
        bad = ~mask
        # widen every filtered-out sample by +-buffer_size rows
        widened = bad.rolling(2 * buffer_size + 1, center=True, min_periods=1).max()
        mask = ~widened.astype(bool)
    return df[mask]
