"""Sensor tag representation and normalization.

Reference equivalent: ``gordo_components/dataset/sensor_tag.py`` —
``SensorTag(name, asset)`` plus ``normalize_sensor_tags`` accepting the
config-surface spellings (plain strings, ``[name, asset]`` lists,
``{name:, asset:}`` dicts, SensorTag) and inferring assets when possible.
"""

from __future__ import annotations

from typing import List, NamedTuple, Optional, Union


class SensorTag(NamedTuple):
    name: str
    asset: Optional[str] = None

    def to_json(self) -> dict:
        return {"name": self.name, "asset": self.asset}


TagLike = Union[str, dict, list, tuple, SensorTag]


class SensorTagNormalizationError(ValueError):
    pass


def _normalize_one(tag: TagLike, asset: Optional[str]) -> SensorTag:
    if isinstance(tag, SensorTag):
        return tag if tag.asset or not asset else SensorTag(tag.name, asset)
    if isinstance(tag, str):
        return SensorTag(tag, asset)
    if isinstance(tag, dict):
        try:
            return SensorTag(tag["name"], tag.get("asset", asset))
        except KeyError:
            raise SensorTagNormalizationError(
                f"Sensor tag dict {tag!r} requires a 'name' key"
            )
    if isinstance(tag, (list, tuple)):
        if len(tag) == 2:
            return SensorTag(str(tag[0]), tag[1])
        if len(tag) == 1:
            return SensorTag(str(tag[0]), asset)
        raise SensorTagNormalizationError(
            f"Sensor tag list {tag!r} must be [name] or [name, asset]"
        )
    raise SensorTagNormalizationError(f"Cannot normalize sensor tag {tag!r}")


def normalize_sensor_tags(
    tags: List[TagLike], asset: Optional[str] = None
) -> List[SensorTag]:
    """Normalize every config spelling of a tag list to ``SensorTag``s."""
    return [_normalize_one(tag, asset) for tag in tags]


def to_list_of_strings(tags: List[SensorTag]) -> List[str]:
    return [tag.name for tag in tags]
