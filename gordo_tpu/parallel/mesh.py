"""Compatibility shim — mesh construction moved to :mod:`gordo_tpu.mesh`.

The placement plane (``gordo_tpu/mesh/``) is now the one owner of device
meshes and shardings; this module re-exports the original surface so
existing imports (``gordo_tpu.parallel.mesh.fleet_mesh`` etc.) keep
working.  New code should import from ``gordo_tpu.mesh`` directly.
"""

from __future__ import annotations

from gordo_tpu.mesh import (  # noqa: F401  (re-export surface)
    DATA_AXIS,
    MODEL_AXIS,
    Mesh,
    NamedSharding,
    PartitionSpec,
    fleet_mesh,
    global_fleet_mesh,
    model_sharding,
    pad_to_multiple,
    replicated_sharding,
)

__all__ = [
    "DATA_AXIS",
    "MODEL_AXIS",
    "Mesh",
    "NamedSharding",
    "PartitionSpec",
    "fleet_mesh",
    "global_fleet_mesh",
    "model_sharding",
    "pad_to_multiple",
    "replicated_sharding",
]
