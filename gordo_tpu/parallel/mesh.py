"""Device-mesh construction and sharding helpers.

The framework's canonical mesh has two axes:

- ``"models"`` — the fleet axis: independent machines' stacked models.  This
  replaces the reference's Argo pod-per-machine fan-out; collectives never
  cross it (pure map), so XLA partitions it for free.
- ``"data"`` — batch/row axis for data-parallel fitting of a single larger
  model (all-reduce of grads rides ICI).

On a v5e-64 slice the default is all 64 chips on ``"models"``; a single-chip
dev box gets a 1x1 mesh and every program still compiles identically.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

MODEL_AXIS = "models"
DATA_AXIS = "data"


def fleet_mesh(
    devices: Optional[Sequence[jax.Device]] = None,
    data_parallel: int = 1,
) -> Mesh:
    """Build the canonical ``("models", "data")`` mesh over ``devices``.

    ``data_parallel`` chips are grouped per model-shard; the rest of the
    devices spread the fleet axis.
    """
    devices = list(devices) if devices is not None else jax.devices()
    n = len(devices)
    if n % data_parallel != 0:
        raise ValueError(
            f"data_parallel={data_parallel} does not divide device count {n}"
        )
    grid = np.asarray(devices).reshape(n // data_parallel, data_parallel)
    return Mesh(grid, (MODEL_AXIS, DATA_AXIS))


def model_sharding(mesh: Mesh, extra_dims: int = 0) -> NamedSharding:
    """Sharding placing a leading ``models`` axis over the mesh fleet axis."""
    return NamedSharding(mesh, P(MODEL_AXIS, *([None] * extra_dims)))


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def pad_to_multiple(m: int, k: int) -> int:
    """Smallest multiple of ``k`` that is >= ``m``."""
    return -(-m // k) * k
