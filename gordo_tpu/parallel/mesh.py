"""Device-mesh construction and sharding helpers.

The framework's canonical mesh has two axes:

- ``"models"`` — the fleet axis: independent machines' stacked models.  This
  replaces the reference's Argo pod-per-machine fan-out; collectives never
  cross it (pure map), so XLA partitions it for free.
- ``"data"`` — batch/row axis for data-parallel fitting of a single larger
  model (all-reduce of grads rides ICI).

On a v5e-64 slice the default is all 64 chips on ``"models"``; a single-chip
dev box gets a 1x1 mesh and every program still compiles identically.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

MODEL_AXIS = "models"
DATA_AXIS = "data"


def fleet_mesh(
    devices: Optional[Sequence[jax.Device]] = None,
    data_parallel: int = 1,
) -> Mesh:
    """Build the canonical ``("models", "data")`` mesh over ``devices``.

    ``data_parallel`` chips are grouped per model-shard; the rest of the
    devices spread the fleet axis.
    """
    devices = list(devices) if devices is not None else jax.devices()
    n = len(devices)
    if n % data_parallel != 0:
        raise ValueError(
            f"data_parallel={data_parallel} does not divide device count {n}"
        )
    grid = np.asarray(devices).reshape(n // data_parallel, data_parallel)
    return Mesh(grid, (MODEL_AXIS, DATA_AXIS))


def global_fleet_mesh(data_parallel: int = 1) -> Mesh:
    """The canonical mesh over EVERY process's devices — the multi-host
    form of :func:`fleet_mesh` (``gordo_tpu.distributed.runtime``).

    Devices order by ``(process_index, device id)`` so each host's local
    devices are CONTIGUOUS along the ``"models"`` axis: a host feeds its
    shard of a stacked fleet array with one contiguous
    ``make_array_from_process_local_data`` block, and a per-host slice of
    the machine list maps onto a per-host slice of the mesh.  Requires a
    uniform local device count (true of any TPU slice and of the
    simulated launcher); raises otherwise rather than building a mesh
    whose process boundaries fall mid-row.
    """
    import collections

    devices = sorted(jax.devices(), key=lambda d: (d.process_index, d.id))
    per_proc = collections.Counter(d.process_index for d in devices)
    counts = set(per_proc.values())
    if len(counts) > 1:
        raise ValueError(
            "global_fleet_mesh needs a uniform local device count per "
            f"process, got {dict(per_proc)}"
        )
    if data_parallel > 1 and min(counts) % data_parallel != 0:
        # keep every ("models" row x "data" group) within one host: the
        # data axis carries grad all-reduces, which should ride ICI, not
        # straddle the host boundary onto DCN
        raise ValueError(
            f"data_parallel={data_parallel} does not divide the per-process "
            f"device count {min(counts)}; a data group must not span hosts"
        )
    return fleet_mesh(devices, data_parallel=data_parallel)


def model_sharding(mesh: Mesh, extra_dims: int = 0) -> NamedSharding:
    """Sharding placing a leading ``models`` axis over the mesh fleet axis."""
    return NamedSharding(mesh, P(MODEL_AXIS, *([None] * extra_dims)))


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def pad_to_multiple(m: int, k: int) -> int:
    """Smallest multiple of ``k`` that is >= ``m``."""
    return -(-m // k) * k
