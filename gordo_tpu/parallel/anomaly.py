"""Whole-fleet anomaly-detector builds as stacked device programs.

Reference equivalent: running ``gordo_components/builder/build_model.py``
once per machine in its own Argo pod, each doing sklearn
``cross_val_predict`` + threshold derivation + a final Keras fit
(``model/anomaly/diff.py::DiffBasedAnomalyDetector``).

Here the entire bucket of M homogeneous machines — scaler stats, K CV folds
PLUS the final fit (folds ride a second vmap axis as weight masks),
out-of-fold scoring, per-tag/aggregate threshold derivation — compiles into
a few jitted dispatches, sharded over the mesh ``"models"`` axis.  Output is
M individually fitted :class:`DiffBasedAnomalyDetector` objects, artifact-
and metadata-compatible with the single-machine path.

Equivalence contract (tests/test_fleet.py): in the default exact mode,
EVERY machine's result — CV-fold fits, fold metrics, thresholds, scaler
stats, final params — is numerically identical to the single-machine path
(same RNG derivation, same materialized fold rows, same per-fold batch
geometry and shuffle).  This is achieved by grouping machines by row count
inside each bucket: within a length-group, fold indices and batch geometry
are shared static values, so each fold is materialized exactly as
``train.cv.cross_validate`` would (gather fold rows → fit scalers on them →
window → pad to the fold's own ``steps × bs``), then vmapped over machines.
A ragged bucket simply yields several length-groups, each exact — no
weight-mask approximation anywhere.  The ONE exception is the opt-in
``pad_lengths`` mode (:func:`_padded_fleet_program`), which deliberately
trades that exactness for O(1) compiles on ragged buckets: rows are
weight-masked rather than dropped, and fold/batch geometry derives from
the padded length (see docs/fleet.md for the contract).

Fleetability is *checked, not assumed*: :func:`analyze_definition` inspects
a prototype built from the model-config definition and returns a spec only
for the supported shape — ``DiffBasedAnomalyDetector`` wrapping
``Pipeline([*pure-stats scalers, BaseJaxEstimator])`` — everything else
falls back to the per-machine builder.
"""

from __future__ import annotations

import dataclasses
import hashlib
import logging
import pickle
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from gordo_tpu import compile as compile_plane
from gordo_tpu.anomaly.diff import SMOOTHING_WINDOW, DiffBasedAnomalyDetector
from gordo_tpu.models.estimator import BaseJaxEstimator
from gordo_tpu.ops.scalers import (
    BaseTransform,
    MinMaxScaler,
    RobustScaler,
    StandardScaler,
)
from gordo_tpu.mesh import (
    MODEL_AXIS,
    Mesh,
    model_sharding,
    pad_to_multiple,
)
from gordo_tpu.ingest.plane import owned_stack_base, stack_live_slots
from gordo_tpu.parallel import fleet as fleet_mod
from gordo_tpu.pipeline import Pipeline
from gordo_tpu.registry import lookup_factory
from gordo_tpu.train.cv import build_splitter
from gordo_tpu.train.fit import TrainConfig, make_fit_fn
from gordo_tpu.utils.trees import to_host

logger = logging.getLogger(__name__)

#: scalers whose stats are computable by a static pure function (vmappable).
FLEETABLE_SCALERS = (MinMaxScaler, StandardScaler, RobustScaler)

METRIC_NAMES = (
    "explained_variance_score",
    "r2_score",
    "mean_squared_error",
    "mean_absolute_error",
)


# ---------------------------------------------------------------------------
# Definition analysis
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class FleetSpec:
    """Everything needed to run one homogeneous bucket as a fleet program."""

    detector_proto: DiffBasedAnomalyDetector
    scaler_protos: List[BaseTransform]      # pipeline scalers, in order
    estimator_proto: BaseJaxEstimator
    train_cfg: TrainConfig
    factory_kwargs: Dict[str, Any]
    seed: int

    @property
    def signature(self) -> Tuple:
        """Bucket key: machines with equal signatures share one program."""
        return (
            type(self.detector_proto).__name__,
            self.detector_proto.window,
            tuple(
                (type(s).__name__, tuple(sorted(s._stat_options().items())))
                for s in self.scaler_protos
            ),
            (
                type(self.detector_proto.scaler).__name__,
                tuple(sorted(self.detector_proto.scaler._stat_options().items())),
            ),
            type(self.estimator_proto).__name__,
            self.estimator_proto.kind,
            self.train_cfg,
            tuple(sorted(self.factory_kwargs.items())),
        )


def analyze_definition(model) -> Optional[FleetSpec]:
    """Return a :class:`FleetSpec` if ``model`` (a built-but-unfitted
    prototype) matches the fleetable shape, else None."""
    if not isinstance(model, DiffBasedAnomalyDetector):
        return None
    if not isinstance(model.scaler, FLEETABLE_SCALERS):
        return None

    base = model.base_estimator
    scalers: List[BaseTransform] = []
    if isinstance(base, Pipeline):
        for _, step in base.steps[:-1]:
            if not isinstance(step, FLEETABLE_SCALERS):
                return None
            scalers.append(step)
        est = base._final
    else:
        est = base
    if not isinstance(est, BaseJaxEstimator):
        return None
    if est.params_ is not None:  # already fitted — not a prototype
        return None

    cfg, factory_kwargs = TrainConfig.from_kwargs(dict(est.kwargs))
    seed = int(factory_kwargs.get("seed", 0) or 0)
    return FleetSpec(
        detector_proto=model,
        scaler_protos=scalers,
        estimator_proto=est,
        train_cfg=cfg,
        factory_kwargs=factory_kwargs,
        seed=seed,
    )


# ---------------------------------------------------------------------------
# Pure device-side pieces
# ---------------------------------------------------------------------------

def _trailing_rolling_min(err: jnp.ndarray, window: int) -> jnp.ndarray:
    """Trailing rolling-min with ``min_periods=1`` semantics, (N, F)->(N, F)
    (pandas ``rolling(window, min_periods=1).min()`` as a static-shape op)."""
    return -jax.lax.reduce_window(
        -err,
        -jnp.inf,
        jax.lax.max,
        window_dimensions=(window, 1),
        window_strides=(1, 1),
        padding=((window - 1, 0), (0, 0)),
    )


def _smoothed_max(err: jnp.ndarray, window: int) -> jnp.ndarray:
    """Max over rows of the trailing rolling-min of ``err``.

    Matches ``anomaly.diff._rolling_min_max`` (pandas ``rolling(window,
    min_periods=1).min()`` then ``max()``) as a pure static-shape function.
    ``err``: (N, F) — returns (F,).
    """
    return jnp.max(_trailing_rolling_min(err, window), axis=0)


def _masked_smoothed_max(err: jnp.ndarray, mask: jnp.ndarray) -> jnp.ndarray:
    """(N, F) errors, (N,) row validity -> (F,): like :func:`_smoothed_max`
    but rolling-min windows that END on a masked row are excluded from the
    max.  With suffix padding every window ending on a real row contains
    only real rows, so this is exact for the pad-up program."""
    sm = _trailing_rolling_min(err, SMOOTHING_WINDOW)
    sm = jnp.where(mask[:, None] > 0, sm, -jnp.inf)
    mx = jnp.max(sm, axis=0)
    return jnp.where(jnp.isfinite(mx), mx, 0.0)


def _make_scale_chain(scaler_opts):
    """``X_f (M, n, F) -> (stats_list, transformed)`` for the pipeline's
    scaler chain: step i's stats are computed on step i-1's output
    (pipeline semantics).  On NaN-padded rows the nan-aware stat
    reductions exclude padding, and NaN propagates through apply so later
    steps' stats exclude it too."""

    def scale_chain(X_f):
        stats_list = []
        cur = X_f
        for scaler_cls, opts in scaler_opts:
            st = jax.vmap(
                lambda xm: scaler_cls.compute_stats(xm, **dict(opts))
            )(cur)
            stats_list.append(st)
            cur = jax.vmap(scaler_cls.apply)(st, cur)
        return stats_list, cur

    return scale_chain


def _make_apply_chain(scaler_opts):
    def apply_chain(stats_list, X_f):
        cur = X_f
        for (scaler_cls, _), st in zip(scaler_opts, stats_list):
            cur = jax.vmap(scaler_cls.apply)(st, cur)
        return cur

    return apply_chain


def _make_windowize(window_mode: str, lookback: int):
    """Estimator windowing semantics on already-scaled inputs (see the
    estimator classes: "none"=row-wise, "ae"=reconstruct window end,
    "forecast"=t+1)."""
    from gordo_tpu.ops.windows import make_windows

    def windowize(Xt, y_f):
        if window_mode == "none":
            return Xt, y_f
        if window_mode == "ae":
            inputs = jax.vmap(lambda a: make_windows(a, lookback))(Xt)
            return inputs, y_f[:, lookback - 1:]
        if window_mode == "forecast":
            inputs = jax.vmap(lambda a: make_windows(a[:-1], lookback))(Xt)
            return inputs, y_f[:, lookback:]
        raise ValueError(f"Unknown window_mode {window_mode!r}")

    return windowize


def _model_axis_pad(m: int, mesh) -> int:
    """Pad target for the stacked machine axis: next power of two, then
    the mesh's ``models``-axis multiple.

    The fleet program is a pure vmap over machines, so dummy lanes are
    free parity-wise (``_assemble`` slices ``[:m]``) and nearly free on
    device — but every DISTINCT machine count is a fresh XLA lowering of
    the same program (~88s cold for the LSTM CV+fit).  Power-of-two
    padding collapses all counts onto log-many compiled shapes: a 10k-
    machine project's 272-machine tail chunk reuses the 512-chunk
    program, and warm re-runs with slightly different counts recompile
    nothing."""
    m_pad = 1 << max(m - 1, 0).bit_length() if m > 1 else 1
    if mesh is not None:
        m_pad = pad_to_multiple(m_pad, mesh.shape[MODEL_AXIS])
    return m_pad


def _stack_machine_axis(arrs: Sequence[np.ndarray]) -> np.ndarray:
    """``np.stack`` along a new leading machine axis — except when the
    arrays are, in order, a consecutive run of leading-axis slots of ONE
    ingest-owned stacked buffer (``gordo_tpu/ingest/plane.py``): then the
    buffer slice is adopted with no copy.  The ingest plane preallocates
    that buffer at model-axis capacity precisely so this stacking copy
    (and the padding copy in :meth:`_dispatch_group`) disappears; any
    deviation — a fallback-loaded machine in the group, dedup slots out
    of machine order, a foreign array — falls back to the copy."""
    base = owned_stack_base(arrs[0])
    if base is None or any(a.shape != base.shape[1:] for a in arrs):
        return np.stack(arrs)
    b0 = base.__array_interface__["data"][0]
    stride = base.strides[0]
    off = arrs[0].__array_interface__["data"][0] - b0
    if stride <= 0 or off % stride:
        return np.stack(arrs)
    s0 = off // stride
    if s0 + len(arrs) > base.shape[0]:
        return np.stack(arrs)
    for j, a in enumerate(arrs):
        if (
            owned_stack_base(a) is not base
            or a.strides != base.strides[1:]
            or a.__array_interface__["data"][0] != b0 + (s0 + j) * stride
        ):
            return np.stack(arrs)
    return base[s0 : s0 + len(arrs)]


def _pad_models_capacity(X: np.ndarray, m_pad: int) -> np.ndarray:
    """:func:`fleet._pad_models` without the copy when ``X`` is the FULL
    live prefix of an ingest-owned buffer with spare capacity: the dummy
    pad lanes (repeats of the last machine; results discarded) are
    written into the buffer's scratch rows in place.  Requiring ``X`` to
    start at slot 0 and cover every live slot guarantees no other
    machine's data occupies the rows being overwritten."""
    m = X.shape[0]
    base = owned_stack_base(X)
    if (
        base is not None
        and m_pad <= base.shape[0]
        and m == stack_live_slots(base)
        and X.strides == base.strides
        and X.__array_interface__["data"][0]
        == base.__array_interface__["data"][0]
    ):
        base[m:m_pad] = base[m - 1]
        return base[:m_pad]
    return fleet_mod._pad_models(X, m_pad)


def _stack_warm_params(params_list: Sequence[Any], m_pad: int):
    """Stack per-machine param pytrees into the fleet layout: leading
    machine axis, padded to ``m_pad`` by repeating the last machine (the
    padded lanes are dummies whose results ``_assemble`` discards).

    A length-group shares one module, so every tree must agree in
    structure and leaf shapes; a mismatch (a stale artifact predating a
    model-config change, say) raises ``ValueError`` so the caller can
    fall back to a cold build instead of feeding XLA garbage."""
    treedef0 = None
    leaves0: List[Any] = []
    flats: List[List[np.ndarray]] = []
    for i, params in enumerate(params_list):
        leaves, treedef = jax.tree.flatten(params)
        leaves = [np.asarray(leaf) for leaf in leaves]
        if treedef0 is None:
            treedef0, leaves0 = treedef, leaves
        elif treedef != treedef0 or any(
            a.shape != b.shape or a.dtype != b.dtype
            for a, b in zip(leaves, leaves0)
        ):
            raise ValueError(
                f"warm-start params for machine {i} break the group's "
                "shared leaf signature — the previous artifact predates "
                "a model-config change; rebuild cold"
            )
        flats.append(leaves)
    stacked = [
        fleet_mod._pad_models(
            np.stack([flat[j] for flat in flats]), m_pad
        )
        for j in range(len(leaves0))
    ]
    return jax.tree.unflatten(treedef0, stacked)


# ---------------------------------------------------------------------------
# The fleet builder
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class _GroupContext:
    """Static per-group program context shared by dispatch and warm."""

    folds: Tuple
    k_folds: int
    module: Any
    built_kwargs: Dict[str, Any]
    scaler_opts: Tuple
    det_scaler_opts: Tuple
    window_mode: str
    lookback: int
    offset: int


@dataclasses.dataclass
class _PendingGroup:
    """One length-group's in-flight device program + assembly context."""

    indices: List[int]
    out: Any                      # device-side result tree until collected
    m: int
    built_kwargs: Dict[str, Any]
    k_folds: int
    t0: float
    pad_built: bool = False
    fetch_seconds: float = 0.0
    assemble_seconds: float = 0.0
    #: fetched HOST result tree, kept after collect — the stacked arrays
    #: the per-machine detectors hold views into, re-exposed whole so a
    #: downstream consumer (fleet-health baseline scoring) can adopt them
    #: without re-stacking per-machine slices leaf by leaf
    host: Optional[Dict[str, Any]] = None


class PendingFleetBuild:
    """An in-flight fleet build: every group's program has been DISPATCHED
    (inputs staged async, device futures in hand) but nothing has been
    fetched — the build-plane analogue of ``FleetScorer.dispatch_all`` /
    ``FleetFitResult``.

    :meth:`collect` blocks on the device results, runs the (partial) D2H
    fetch and per-machine assembly, and caches the detectors — idempotent,
    so the drive loop can hold one of these per chunk and collect behind
    the next chunk's dispatch.  ``fetch_seconds``/``assemble_seconds``
    accumulate where collect time went (the pipeline's stage-attribution
    telemetry reads them).
    """

    def __init__(
        self,
        builder: "FleetDiffBuilder",
        n: int,
        groups: List[_PendingGroup],
    ):
        self._builder = builder
        self._n = n
        self._groups = groups
        self._detectors: Optional[List[DiffBasedAnomalyDetector]] = None
        self.fetch_seconds = 0.0
        self.assemble_seconds = 0.0

    def collect(self) -> List[DiffBasedAnomalyDetector]:
        """Fetch + assemble every dispatched group (blocking; an async XLA
        failure from dispatch surfaces here).  Returns detectors in the
        original ``Xs`` input order; repeat calls return the cached list."""
        if self._detectors is None:
            detectors: List[Optional[DiffBasedAnomalyDetector]] = (
                [None] * self._n
            )
            for g in self._groups:
                for i, det in zip(g.indices, self._builder._collect_group(g)):
                    detectors[i] = det
                self.fetch_seconds += g.fetch_seconds
                self.assemble_seconds += g.assemble_seconds
            self._detectors = detectors  # type: ignore[assignment]
        return self._detectors  # type: ignore[return-value]

    def prestacked(self, names: List[str]) -> Optional[Dict[str, Any]]:
        """The collected groups' stacked host arrays as a serving
        prestack hint (``FleetScorer.from_models(prestacked_hint=...)``).

        ``names`` lists the chunk's machine names in the original input
        order (``names[i]`` ↔ detector ``i``).  The returned dict carries
        one pack per dispatched group — pad rows sliced off, rows in
        group-dispatch order, ``"names"`` reordered to match — all
        zero-copy basic slices of the arrays the detectors already hold
        views into.  The fleet-health baseline scorer adopts it instead
        of re-stacking per-machine slices leaf by leaf (one tiny jitted
        stack dispatch per leaf otherwise — the dominant host cost of
        baseline sketching at bucket-512 scale).  Returns None before
        :meth:`collect` or when any group's host tree was not retained.
        """
        if self._detectors is None:
            return None
        packs: List[Tuple] = []
        thr_parts: List[np.ndarray] = []
        agg_parts: List[np.ndarray] = []
        order: List[int] = []
        for g in self._groups:
            host = g.host
            if host is None:
                return None
            m = g.m
            packs.append((
                jax.tree.map(lambda a: a[:m], host["final_params"]),
                tuple(
                    {k: v[:m] for k, v in step.items()}
                    for step in host["scaler_stats"]
                ),
                {k: v[:m] for k, v in host["det_scaler_stats"].items()},
            ))
            thr_parts.append(host["feature_thresholds"][:m])
            agg_parts.append(host["aggregate_threshold"][:m])
            order.extend(g.indices)
        return {
            "names": [names[i] for i in order],
            "packs": packs,
            "feature_thresholds": (
                thr_parts[0] if len(thr_parts) == 1
                else np.concatenate(thr_parts)
            ),
            "agg": np.asarray(
                agg_parts[0] if len(agg_parts) == 1
                else np.concatenate(agg_parts),
                np.float32,
            ).reshape(-1),
        }


class FleetDiffBuilder:
    """Build M homogeneous ``DiffBasedAnomalyDetector`` machines at once.

    One instance per bucket; ``build(Xs, ys)`` returns fitted detectors in
    input order.
    """

    def __init__(
        self,
        spec: FleetSpec,
        cv: Any = None,
        mesh: Optional[Mesh] = None,
        pad_lengths: Optional[int] = None,
    ):
        self.spec = spec
        self.splitter = build_splitter(cv)
        self.mesh = mesh
        #: pad-up mode: machines grouped by row count rounded UP to a
        #: multiple of this, padded with weight-masked rows — every real
        #: row trains, and a ragged bucket needs one program per ALIGNED
        #: length instead of one per distinct length.  See
        #: :func:`_padded_fleet_program` for the (documented) CV-semantics
        #: difference vs the exact per-length mode.
        self.pad_lengths = int(pad_lengths) if pad_lengths else None

    # -- host-side orchestration --------------------------------------------
    def _validate_inputs(self, Xs, ys, warm_params):
        """Length/shape validation + one-time host dtype normalization (so
        the dispatch window below never needs ``np.asarray``)."""
        if ys is not None and len(ys) != len(Xs):
            raise ValueError(
                f"Got {len(Xs)} input series but {len(ys)} target series"
            )
        if warm_params is not None and len(warm_params) != len(Xs):
            raise ValueError(
                f"Got {len(Xs)} input series but {len(warm_params)} "
                "warm-start param trees"
            )
        Xs = [np.asarray(x, np.float32) for x in Xs]
        if ys is not None:
            for i, (x, yy) in enumerate(zip(Xs, ys)):
                if len(yy) != len(x):
                    raise ValueError(
                        f"Target row count differs from input for machine {i}: "
                        f"{len(yy)} != {len(x)}"
                    )
            ys = [np.asarray(yy, np.float32) for yy in ys]
        return Xs, ys

    def build(
        self,
        Xs: Sequence[np.ndarray],
        ys: Optional[Sequence[np.ndarray]] = None,
        warm_params: Optional[Sequence[Any]] = None,
    ) -> List[DiffBasedAnomalyDetector]:
        """Build detectors for ``Xs`` in input order (dispatch + collect
        back to back — see :meth:`dispatch` for the async split).

        Machines are grouped by row count; each length-group runs the exact
        fold-materializing program, so every machine's result matches the
        single-machine path (not just the bucket-max ones).

        ``warm_params`` (one param pytree per machine, aligned with ``Xs``)
        switches every group onto the warm program variant: fits resume
        from the given weights instead of ``fleet_init`` — the incremental
        refresh path.  Callers pair it with a reduced-epoch
        :class:`~gordo_tpu.train.fit.TrainConfig` in the spec.
        """
        return self.dispatch(Xs, ys, warm_params=warm_params).collect()

    def dispatch(
        self,
        Xs: Sequence[np.ndarray],
        ys: Optional[Sequence[np.ndarray]] = None,
        warm_params: Optional[Sequence[Any]] = None,
    ) -> PendingFleetBuild:
        """Launch every length-group's device program and return a
        :class:`PendingFleetBuild` WITHOUT blocking on results.

        Inputs are staged through the mesh placement seam (async
        ``device_put``) and jax's async dispatch returns device futures,
        so this returns as soon as the programs are enqueued — the drive
        loop dispatches chunk k+1 here while chunk k's fetch/assembly/write
        run behind it.  This method and everything it calls form the
        lint-enforced dispatch window: no blocking D2H transfers
        (``scripts/lint.py``'s ``D2H_FORBIDDEN_SCOPES`` gate).
        """
        Xs, ys = self._validate_inputs(Xs, ys, warm_params)
        groups: List[_PendingGroup] = []
        if self.pad_lengths:
            self._dispatch_padded(Xs, ys, warm_params, groups)
            return PendingFleetBuild(self, len(Xs), groups)

        n_lengths = len({int(x.shape[0]) for x in Xs})
        if n_lengths > 1 and n_lengths > len(Xs) // 2:
            # Exact parity requires one program per distinct row count; a
            # bucket where most machines differ in length loses the fleet
            # vmap win and pays one XLA compile per length (still no worse
            # than the per-machine fallback, but worth surfacing).
            logger.warning(
                "Fleet bucket of %d machines has %d distinct row counts; "
                "each length compiles its own program — consider aligning "
                "train windows for fleet efficiency",
                len(Xs), n_lengths,
            )
        self._dispatch_exact_length_groups(
            Xs, ys, range(len(Xs)), groups, warm_params
        )
        return PendingFleetBuild(self, len(Xs), groups)

    def _dispatch_exact_length_groups(
        self, Xs, ys, idxs, groups: List[_PendingGroup], warm_params=None
    ) -> None:
        """Group ``idxs`` by row count and dispatch the exact program per
        length-group, appending the pending groups."""
        by_len: Dict[int, List[int]] = {}
        for i in idxs:
            by_len.setdefault(int(Xs[i].shape[0]), []).append(i)
        for group in by_len.values():
            X_g = _stack_machine_axis([Xs[i] for i in group])
            if ys is None or all(ys[i] is Xs[i] for i in group):
                # the ingest plane hands targets == inputs as the SAME
                # array object — one stacked buffer serves both
                y_g = X_g
            else:
                y_g = _stack_machine_axis([ys[i] for i in group])
            warm_g = (
                None
                if warm_params is None
                else [warm_params[i] for i in group]
            )
            g = self._dispatch_group(X_g, y_g, warm=warm_g)
            g.indices = list(group)
            groups.append(g)

    def _dispatch_padded(
        self,
        Xs: Sequence[np.ndarray],
        ys: Optional[Sequence[np.ndarray]],
        warm_params: Optional[Sequence[Any]],
        groups: List[_PendingGroup],
    ) -> None:
        """Pad-up mode: group by row count rounded UP to ``pad_lengths``,
        NaN-pad each machine's rows to the group length (NaN rows fall out
        of the nan-aware scaler stats; zero-weight rows fall out of the
        loss), and dispatch the masked program once per group.  Every real
        row trains; a 16-length ragged bucket compiles O(1) programs."""
        pad = self.pad_lengths
        offset = int(self.spec.estimator_proto.offset)
        by_pad: Dict[int, List[int]] = {}
        exact_fallback: List[int] = []
        for i, x in enumerate(Xs):
            n_pad = -(-x.shape[0] // pad) * pad
            by_pad.setdefault(n_pad, []).append(i)

        for n_pad, idxs in list(by_pad.items()):
            folds = [
                (list(tr), list(te))
                for tr, te in self.splitter.split(np.empty((n_pad, 1)))
            ]
            # The masked program's exactness rests on padding being a
            # SUFFIX after every fold gather — i.e. fold indices must be
            # sorted contiguous blocks (true for TimeSeriesSplit and
            # unshuffled KFold).  A shuffled/exotic splitter would
            # silently interleave pad rows into training windows, so the
            # whole group demotes to the exact path instead.
            contiguous = all(
                np.array_equal(idx, np.arange(idx[0], idx[-1] + 1))
                for tr, te in folds
                for idx in (tr, te)
            )
            if not contiguous:
                logger.warning(
                    "pad_lengths=%d: CV splitter %s yields non-contiguous "
                    "fold indices — pad-up mode requires contiguous blocks; "
                    "building the %d machine(s) at padded length %d through "
                    "the exact per-length path",
                    pad, type(self.splitter).__name__, len(idxs), n_pad,
                )
                exact_fallback.extend(idxs)
                del by_pad[n_pad]
                continue
            # Every fold's test block must contain real target rows for
            # every machine, or its thresholds/metrics would be computed on
            # nothing (0/0-guarded into silently-wrong zeros).  A machine
            # shorter than the last fold's start (plus window context) can't
            # satisfy that at this padded length — build it exactly instead.
            min_len = max(int(te[0]) for _, te in folds) + offset + 1
            short = [i for i in idxs if Xs[i].shape[0] < min_len]
            if short:
                logger.warning(
                    "pad_lengths=%d: %d machine(s) are shorter than %d rows "
                    "(their real rows would miss a CV test block at padded "
                    "length %d) — building them through the exact per-length "
                    "path instead",
                    pad, len(short), min_len, n_pad,
                )
                exact_fallback.extend(short)
                idxs = [i for i in idxs if i not in set(short)]
                if not idxs:
                    del by_pad[n_pad]
                    continue
                by_pad[n_pad] = idxs

        self._dispatch_exact_length_groups(
            Xs, ys, exact_fallback, groups, warm_params
        )

        for n_pad, idxs in by_pad.items():
            m = len(idxs)
            n_feat = Xs[idxs[0]].shape[1]
            n_out = n_feat if ys is None else ys[idxs[0]].shape[1]
            X = np.full((m, n_pad, n_feat), np.nan, np.float32)
            y = np.full((m, n_pad, n_out), np.nan, np.float32)
            lens = np.zeros((m,), np.int32)
            for j, i in enumerate(idxs):
                L = Xs[i].shape[0]
                lens[j] = L
                X[j, :L] = Xs[i]
                y[j, :L] = Xs[i] if ys is None else ys[i]
            warm_g = (
                None
                if warm_params is None
                else [warm_params[i] for i in idxs]
            )
            g = self._dispatch_group(X, y, lens=lens, warm=warm_g)
            g.indices = list(idxs)
            # distinguishes genuinely pad-built artifacts from the
            # exact-fallback ones above (fleet_build stamps metadata
            # from this marker, not from the request flag)
            g.pad_built = True
            groups.append(g)

    def _group_context(
        self, n_rows: int, n_features: int, n_out: int
    ) -> _GroupContext:
        """Everything static a group's program factory needs, derived from
        geometry alone — shared by :meth:`_dispatch_group` (real data) and
        :meth:`warm` (shape structs)."""
        spec = self.spec
        est_proto = spec.estimator_proto

        # Static fold indices — identical to what cross_validate would use.
        folds = tuple(
            (tuple(int(i) for i in tr), tuple(int(i) for i in te))
            for tr, te in self.splitter.split(np.empty((n_rows, 1)))
        )

        # Factory module for this bucket's shapes.
        factory = lookup_factory(est_proto.model_type, est_proto.kind)
        built_kwargs = dict(
            n_features=n_features, n_features_out=n_out, **spec.factory_kwargs
        )
        module = factory(**built_kwargs)

        scaler_opts = tuple(
            (type(s), tuple(sorted(s._stat_options().items())))
            for s in spec.scaler_protos
        )
        det_scaler_opts = (
            type(spec.detector_proto.scaler),
            tuple(sorted(spec.detector_proto.scaler._stat_options().items())),
        )

        # Windowing semantics as static flags (see estimator classes):
        # "none"=row-wise FF AE, "ae"=reconstruct window end, "forecast"=t+1.
        from gordo_tpu.models.estimator import LSTMAutoEncoder, LSTMForecast

        if isinstance(est_proto, LSTMForecast):
            window_mode, lookback = "forecast", est_proto.lookback_window
        elif isinstance(est_proto, LSTMAutoEncoder):
            window_mode, lookback = "ae", est_proto.lookback_window
        else:
            window_mode, lookback = "none", 1

        return _GroupContext(
            folds=folds,
            k_folds=len(folds),
            module=module,
            built_kwargs=built_kwargs,
            scaler_opts=scaler_opts,
            det_scaler_opts=det_scaler_opts,
            window_mode=window_mode,
            lookback=int(lookback),
            offset=int(est_proto.offset),
        )

    def _group_program(self, ctx: _GroupContext, padded: bool, warm: bool):
        fn = _padded_fleet_program if padded else _exact_fleet_program
        return fn(
            ctx.module,
            ctx.scaler_opts,
            ctx.det_scaler_opts,
            ctx.window_mode,
            ctx.lookback,
            ctx.offset,
            self.spec.train_cfg,
            ctx.folds,
            self.mesh,
            warm=warm,
        )

    def warm(
        self,
        m: int,
        n_rows: int,
        n_features: int,
        n_out: Optional[int] = None,
        padded: bool = False,
    ) -> float:
        """AOT pre-compile the fleet program for one group geometry from
        shape structs alone — no data, no execution (``Program.warm`` for
        the build plane).  Returns compile seconds, 0.0 on a cache hit.

        Cold programs only: the warm-start variant's ``params0`` signature
        depends on the previous generation's leaf layout, which isn't
        derivable from geometry.
        """
        n_out = int(n_out) if n_out is not None else int(n_features)
        ctx = self._group_context(int(n_rows), int(n_features), n_out)
        m_pad = _model_axis_pad(int(m), self.mesh)
        ms = model_sharding(self.mesh) if self.mesh is not None else None

        def aval(shape, dtype):
            if ms is not None:
                return jax.ShapeDtypeStruct(shape, dtype, sharding=ms)
            return jax.ShapeDtypeStruct(shape, dtype)

        X_av = aval((m_pad, int(n_rows), int(n_features)), jnp.float32)
        y_av = aval((m_pad, int(n_rows), n_out), jnp.float32)
        seeds_av = aval((m_pad,), jnp.uint32)
        program = self._group_program(ctx, padded=padded, warm=False)
        if padded:
            return program.warm(
                X_av, y_av, aval((m_pad,), jnp.int32), seeds_av
            )
        return program.warm(X_av, y_av, seeds_av)

    def _dispatch_group(
        self,
        X: np.ndarray,
        y: np.ndarray,
        lens: Optional[np.ndarray] = None,
        warm: Optional[Sequence[Any]] = None,
    ) -> _PendingGroup:
        """Launch one length-homogeneous group's device program and return
        WITHOUT blocking (``lens`` given: the masked pad-up program;
        ``warm`` given: the warm program resuming from stacked previous
        params).  Inputs go through the placement seam (async H2D) and the
        jitted call returns device futures; the blocking fetch lives in
        :meth:`_collect_group`.  Lint-enforced dispatch window: no
        blocking D2H here (scripts/lint.py)."""
        spec = self.spec
        t0 = time.time()
        m, n_rows = X.shape[:2]
        ctx = self._group_context(n_rows, X.shape[2], y.shape[2])

        # Pad the model axis (dummy copies; results discarded): next power
        # of two + mesh multiple, so distinct machine counts share one
        # compiled program per (module, length) — see _model_axis_pad.
        m_pad = _model_axis_pad(m, self.mesh)
        if m_pad != m:
            y_is_x = y is X
            X = _pad_models_capacity(X, m_pad)
            y = X if y_is_x else _pad_models_capacity(y, m_pad)
            if lens is not None:
                # host ints → int32 view (this scope's lint gate reserves
                # the np.asarray spelling for D2H misuse)
                lens = fleet_mod._pad_models(
                    lens.astype(np.int32, copy=False), m_pad
                )

        seeds = np.full((m_pad,), spec.seed, dtype=np.uint32)
        params0 = (
            _stack_warm_params(warm, m_pad) if warm is not None else None
        )
        program = self._group_program(
            ctx, padded=lens is not None, warm=params0 is not None
        )
        host_args = (X, y, seeds) if lens is None else (X, y, lens, seeds)
        args = fleet_mod.stage_inputs(host_args, self.mesh)
        if params0 is not None:
            params0 = fleet_mod.stage_inputs(params0, self.mesh)
            out = program(*args, params0)
        else:
            out = program(*args)

        return _PendingGroup(
            indices=[],
            out=out,
            m=m,
            built_kwargs=ctx.built_kwargs,
            k_folds=ctx.k_folds,
            t0=t0,
        )

    def _collect_group(
        self, g: _PendingGroup
    ) -> List[DiffBasedAnomalyDetector]:
        """Blocking side of the split: fetch the group's device results —
        partially, where less than the full tree is ever read — and
        assemble per-machine detectors.  An async XLA failure from
        dispatch surfaces here."""
        out = g.out
        t0 = time.time()
        host = {
            # fold axis: slot -1 is the final full-data fit — the only slot
            # _assemble reads, so slice on device and fetch (K+1)x fewer
            # bytes than the stacked per-fold stats
            "scaler_stats": [
                {stat: np.asarray(val[:, -1]) for stat, val in step.items()}
                for step in out["scaler_stats"]
            ],
            "det_scaler_stats": to_host(out["det_scaler_stats"]),
            "final_params": to_host(out["final_params"]),
            "final_history": np.asarray(out["final_history"]),
            "feature_thresholds": np.asarray(out["feature_thresholds"]),
            "aggregate_threshold": np.asarray(out["aggregate_threshold"]),
            "metrics": {
                name: np.asarray(v) for name, v in out["metrics"].items()
            },
        }
        g.out = None  # free the device buffers now, not at pending teardown
        g.host = host  # views of these back the detectors; no extra copy
        fleet_seconds = time.time() - g.t0
        g.fetch_seconds = time.time() - t0
        t1 = time.time()
        detectors = self._assemble(
            host, g.m, g.built_kwargs, fleet_seconds, g.k_folds
        )
        if g.pad_built:
            for det in detectors:
                det.pad_built_ = True
        g.assemble_seconds = time.time() - t1
        return detectors

    def _build_group(
        self,
        X: np.ndarray,
        y: np.ndarray,
        lens: Optional[np.ndarray] = None,
        warm: Optional[Sequence[Any]] = None,
    ) -> List[DiffBasedAnomalyDetector]:
        """One length-homogeneous group, dispatch + collect back to back —
        the synchronous seam the split grew out of."""
        return self._collect_group(
            self._dispatch_group(X, y, lens=lens, warm=warm)
        )

    # -- unpacking into per-machine detector objects ------------------------
    def _assemble(
        self,
        out: Dict[str, Any],
        m: int,
        built_kwargs: Dict[str, Any],
        fleet_seconds: float,
        k_folds: int,
    ) -> List[DiffBasedAnomalyDetector]:
        """Unpack one group's HOST result tree into per-machine detectors.

        Still O(M) Python, but deliberately thin: prototypes are cloned
        from one ``pickle.dumps`` per bucket (a ``pickle.loads`` per
        machine replaces the ``copy.deepcopy`` ×(2+scalers) chain), array
        leaves are handed out as zero-copy views of the stacked host
        arrays, and ``cv_metadata_`` floats come from whole-array
        ``tolist()``/axis reductions instead of per-fold Python ``float()``
        loops.  ``out["scaler_stats"]`` arrives pre-sliced to the final-fit
        fold slot (see :meth:`_collect_group`).
        """
        spec = self.spec
        final_params_leaves, treedef = jax.tree.flatten(out["final_params"])
        est_blob = pickle.dumps(spec.estimator_proto)
        scaler_blobs = [pickle.dumps(p) for p in spec.scaler_protos]
        det_scaler_blob = pickle.dumps(spec.detector_proto.scaler)
        wrap = bool(spec.scaler_protos) or isinstance(
            spec.detector_proto.base_estimator, Pipeline
        )
        per_machine_seconds = fleet_seconds / m

        metrics = out["metrics"]
        folds_by = {n_: metrics[n_][:m].tolist() for n_ in METRIC_NAMES}
        means = {n_: metrics[n_][:m].mean(axis=1) for n_ in METRIC_NAMES}
        stds = {n_: metrics[n_][:m].std(axis=1) for n_ in METRIC_NAMES}
        feat_rows = out["feature_thresholds"]
        feat_lists = feat_rows[:m].tolist()
        agg = out["aggregate_threshold"]
        agg_list = agg[:m].tolist()

        detectors: List[DiffBasedAnomalyDetector] = []
        for i in range(m):
            est = pickle.loads(est_blob)
            est.module_ = None
            est.params_ = jax.tree.unflatten(
                treedef, [leaf[i] for leaf in final_params_leaves]
            )
            est._factory_kwargs_built = dict(built_kwargs)
            est.history_ = out["final_history"][i]
            est.fit_seconds_ = per_machine_seconds

            steps = []
            for blob, stats in zip(scaler_blobs, out["scaler_stats"]):
                sc = pickle.loads(blob)
                sc.stats_ = {key: val[i] for key, val in stats.items()}
                steps.append(sc)
            base: Any = Pipeline([*steps, est]) if wrap else est

            det_scaler = pickle.loads(det_scaler_blob)
            det_scaler.stats_ = {
                key: val[i] for key, val in out["det_scaler_stats"].items()
            }

            det = DiffBasedAnomalyDetector(
                base_estimator=base,
                scaler=det_scaler,
                require_thresholds=spec.detector_proto.require_thresholds,
                window=spec.detector_proto.window,
            )
            det.feature_thresholds_ = feat_rows[i]
            det.aggregate_threshold_ = float(agg[i])
            det.cv_metadata_ = {
                "scores": {
                    name: {
                        "folds": folds_by[name][i],
                        "mean": float(means[name][i]),
                        "std": float(stds[name][i]),
                    }
                    for name in METRIC_NAMES
                },
                "feature_thresholds": feat_lists[i],
                "aggregate_threshold": agg_list[i],
                "fleet": {"bucket_size": m, "fleet_seconds": fleet_seconds},
            }
            detectors.append(det)
        return detectors


# ---------------------------------------------------------------------------
# The exact compiled program (cached across equal-signature length-groups)
# ---------------------------------------------------------------------------

# One jitted program per (module, scalers, windowing, cfg, folds, mesh) —
# the closure must be cached so repeat builds (bench warm runs, CV re-runs)
# hit jax's compile cache instead of re-tracing a fresh closure every call.
# The cache itself lives in the compile plane (`compile.cached_closure`):
# one LRU and one `gordo_compiled_programs` gauge across the whole stack,
# replacing the private _EXACT_PROGRAMS dict this module used to keep.


def _exact_fleet_program(
    module,
    scaler_opts,
    det_scaler_opts,
    window_mode: str,
    lookback: int,
    offset: int,
    cfg: TrainConfig,
    folds: Tuple[Tuple[Tuple[int, ...], Tuple[int, ...]], ...],
    mesh,
    warm: bool = False,
):
    """Return the jitted exact program ``(X, y, seeds) -> out`` for one
    length-group (``warm=True``: ``(X, y, seeds, params0) -> out``).

    Single-machine parity by construction: each CV fold (and the final fit)
    materializes exactly the rows ``train.cv.cross_validate`` would hand the
    cloned pipeline — gather fold rows, fit the scaler chain on them, window,
    pad to the fold's OWN ``steps x bs`` geometry, fit with the same derived
    RNG keys.  No weight-mask approximations; the only difference from M
    separate single fits is the vmap over machines.

    The warm variant is the incremental-refresh entry point: ``params0``
    arrives as a TRACED stacked pytree (the previous generation's weights,
    leading axis = padded machine count) instead of being derived from the
    init keys, so every fold and the final fit resume from the served
    model.  Machine-count/length geometry still keys the compile cache the
    same way — warm and cold programs cache independently (``warm`` is part
    of the key) but share XLA lowerings across refresh cycles.
    """
    # Fold indices are digested (they can be tens of thousands of ints —
    # storing them verbatim in every cache key would bloat the cache and
    # make each lookup re-hash the full tuples).
    folds_digest = hashlib.md5(repr(folds).encode()).hexdigest()
    key = (
        module,
        scaler_opts,
        det_scaler_opts,
        window_mode,
        lookback,
        offset,
        cfg,
        folds_digest,
        mesh,
        bool(warm),
    )

    from gordo_tpu.ops import metrics as jmetrics
    from gordo_tpu.train.fit import batch_geometry

    det_cls, det_opts = det_scaler_opts
    fold_idx = [
        (np.asarray(tr, np.int32), np.asarray(te, np.int32)) for tr, te in folds
    ]
    scale_chain = _make_scale_chain(scaler_opts)
    apply_chain = _make_apply_chain(scaler_opts)
    windowize = _make_windowize(window_mode, lookback)

    def one_fit(params0, inputs, targets, fit_keys):
        """vmapped fit with THIS fold's true batch geometry (exactly
        ``train.fit.fit``: pad to steps*bs, weight-mask the padding)."""
        m = inputs.shape[0]
        na = inputs.shape[1]
        steps, bs, n_pad = batch_geometry(na, cfg.batch_size)
        w = jnp.concatenate(
            [jnp.ones((na,), jnp.float32), jnp.zeros((n_pad,), jnp.float32)]
        )
        if n_pad:
            inputs = jnp.concatenate(
                [inputs, jnp.zeros((m, n_pad) + inputs.shape[2:], inputs.dtype)],
                axis=1,
            )
            targets = jnp.concatenate(
                [targets, jnp.zeros((m, n_pad) + targets.shape[2:], targets.dtype)],
                axis=1,
            )
        fit_fn = make_fit_fn(module, cfg, steps, bs)
        return jax.vmap(fit_fn, in_axes=(0, 0, 0, None, 0))(
            params0, inputs, targets, w, fit_keys
        )

    vapply = jax.vmap(lambda p, x: module.apply({"params": p}, x))

    def body(X, y, seeds, warm_params0):
        # X: (M, N, F) raw rows, y: (M, N, Fout) raw targets, seeds: (M,)
        init_keys, fit_keys = fleet_mod.fleet_keys(seeds)

        # Detector scaler: fit ONCE on the full raw target series
        # (cross_validate fits self.scaler before any fold).
        det_stats = jax.vmap(
            lambda ym: det_cls.compute_stats(ym, **dict(det_opts))
        )(y)

        # Final fit's scaler chain + windows (also provides the init shape).
        full_stats, Xt_full = scale_chain(X)
        inputs_full, targets_full = windowize(Xt_full, y)
        if warm_params0 is None:
            params0 = fleet_mod.fleet_init(
                module, init_keys, inputs_full[0, :1]
            )
        else:
            params0 = warm_params0

        per_step_stats: List[List[Any]] = [[] for _ in scaler_opts]
        feat_maxes, total_maxes = [], []
        metric_vals: Dict[str, List[Any]] = {n: [] for n in METRIC_NAMES}

        for tr, te in fold_idx:
            # Materialize the fold exactly as the single path would.
            X_tr, y_tr = jnp.take(X, tr, axis=1), jnp.take(y, tr, axis=1)
            stats_k, Xt = scale_chain(X_tr)
            inputs, targets = windowize(Xt, y_tr)
            params_k, _ = one_fit(params0, inputs, targets, fit_keys)

            # Out-of-fold predictions on the materialized test slice.
            X_te, y_te = jnp.take(X, te, axis=1), jnp.take(y, te, axis=1)
            te_inputs, _ = windowize(apply_chain(stats_k, X_te), y_te)
            pred = vapply(params_k, te_inputs)
            y_true = y_te[:, offset:]

            for name in METRIC_NAMES:
                metric_vals[name].append(
                    jax.vmap(getattr(jmetrics, name))(y_true, pred)
                )
            y_s = jax.vmap(det_cls.apply, in_axes=(0, 0))(det_stats, y_true)
            p_s = jax.vmap(det_cls.apply, in_axes=(0, 0))(det_stats, pred)
            tag_err = jnp.abs(p_s - y_s)
            total = jnp.linalg.norm(tag_err, axis=-1)
            feat_maxes.append(
                jax.vmap(lambda e: _smoothed_max(e, SMOOTHING_WINDOW))(tag_err)
            )
            total_maxes.append(
                jax.vmap(
                    lambda t: _smoothed_max(t[:, None], SMOOTHING_WINDOW)[0]
                )(total)
            )
            for j, st in enumerate(stats_k):
                per_step_stats[j].append(st)

        # Final full-data fit (fold index -1 in the stats layout).
        final_params, final_history = one_fit(
            params0, inputs_full, targets_full, fit_keys
        )
        for j, st in enumerate(full_stats):
            per_step_stats[j].append(st)

        out = {
            # per scaler step: {stat: (M, K+1, ...)}; fold -1 = final fit
            "scaler_stats": [
                {
                    stat: jnp.stack([s[stat] for s in fold_stats], axis=1)
                    for stat in fold_stats[0]
                }
                for fold_stats in per_step_stats
            ],
            "det_scaler_stats": det_stats,
            "final_params": final_params,
            "final_history": final_history,
            "feature_thresholds": jnp.mean(
                jnp.stack(feat_maxes, axis=1), axis=1
            ),
            "aggregate_threshold": jnp.mean(
                jnp.stack(total_maxes, axis=1), axis=1
            ),
            "metrics": {
                name: jnp.stack(v, axis=1) for name, v in metric_vals.items()
            },
        }
        if mesh is not None:
            out = jax.lax.with_sharding_constraint(out, model_sharding(mesh))
        return out

    if warm:
        def program(X, y, seeds, params0):
            return body(X, y, seeds, params0)
        name = "fleet.exact_warm"
    else:
        def program(X, y, seeds):
            return body(X, y, seeds, None)
        name = "fleet.exact"

    # closure construction above is cheap; on a cache hit the factory is
    # never called and the PREVIOUSLY built ClosureProgram (whose jit
    # trace cache AND warmed AOT executables are intact) is returned
    return compile_plane.cached_closure(
        key, lambda: compile_plane.closure_program(program, name=name)
    )


def _padded_fleet_program(
    module,
    scaler_opts,
    det_scaler_opts,
    window_mode: str,
    lookback: int,
    offset: int,
    cfg: TrainConfig,
    folds: Tuple[Tuple[Tuple[int, ...], Tuple[int, ...]], ...],
    mesh,
    warm: bool = False,
):
    """The pad-up program ``(X, y, lens, seeds) -> out`` — ragged fleets
    without data loss (``warm=True`` appends a traced ``params0`` stacked
    pytree, exactly as in :func:`_exact_fleet_program`).

    ``X``/``y`` arrive NaN-padded past each machine's true row count
    (``lens``).  Row padding is handled by masking, never by dropping:

    - scaler stats: computed on the NaN-padded series — every fleetable
      scaler's stats are nan-aware reductions, so padding simply falls out;
    - training: zero-filled padding rows carry zero loss weight (the
      weight-mask machinery of ``train.fit.make_loss_fn``);
    - CV metrics: row-weighted metric variants (``ops.metrics``);
    - thresholds: rolling-smoothed errors at padded rows are masked to
      ``-inf`` before the row-max (padding is a SUFFIX, so every window
      ending on a real row contains only real rows).

    Semantics difference vs the exact per-length mode (documented
    contract, ``docs/fleet.md``): CV fold boundaries and minibatch
    geometry derive from the PADDED length, so a machine whose true length
    differs from the group length sees slightly different fold membership
    and shuffle partitions than its single-machine build would.  For
    machines already at the aligned length the program is the exact one
    (all-ones masks) — ``tests/test_fleet.py`` pins that parity.  ``lens``
    is a traced argument: machine-length variation never recompiles; only
    the padded group length does.
    """
    folds_digest = hashlib.md5(repr(folds).encode()).hexdigest()
    key = (
        "padded",
        module,
        scaler_opts,
        det_scaler_opts,
        window_mode,
        lookback,
        offset,
        cfg,
        folds_digest,
        mesh,
        bool(warm),
    )

    from gordo_tpu.ops.metrics import WEIGHTED_METRICS
    from gordo_tpu.train.fit import batch_geometry, make_fit_fn

    det_cls, det_opts = det_scaler_opts
    fold_idx = [
        (np.asarray(tr, np.int32), np.asarray(te, np.int32)) for tr, te in folds
    ]
    # the shared scale-chain on NaN-padded rows: nan-aware stat reductions
    # exclude padding, and NaN propagates through apply so step i+1's
    # stats exclude it too; the transformed output is discarded (training
    # inputs are rebuilt from the zero-padded arrays)
    scale_chain = _make_scale_chain(scaler_opts)
    apply_chain = _make_apply_chain(scaler_opts)
    windowize = _make_windowize(window_mode, lookback)

    def one_fit(params0, inputs, targets, wv, fit_keys):
        """vmapped fit with PER-MACHINE weights: fold batch geometry from
        the padded length, real rows weighted 1, padding 0."""
        m = inputs.shape[0]
        na = inputs.shape[1]
        steps, bs, n_pad = batch_geometry(na, cfg.batch_size)
        if n_pad:
            inputs = jnp.concatenate(
                [inputs, jnp.zeros((m, n_pad) + inputs.shape[2:], inputs.dtype)],
                axis=1,
            )
            targets = jnp.concatenate(
                [targets, jnp.zeros((m, n_pad) + targets.shape[2:], targets.dtype)],
                axis=1,
            )
            wv = jnp.concatenate(
                [wv, jnp.zeros((m, n_pad), wv.dtype)], axis=1
            )
        fit_fn = make_fit_fn(module, cfg, steps, bs)
        return jax.vmap(fit_fn)(params0, inputs, targets, wv, fit_keys)

    vapply = jax.vmap(lambda p, x: module.apply({"params": p}, x))
    masked_smoothed_max = _masked_smoothed_max

    def body(X, y, lens, seeds, warm_params0):
        # X: (M, N, F) NaN-padded, y: (M, N, Fout) NaN-padded, lens: (M,)
        init_keys, fit_keys = fleet_mod.fleet_keys(seeds)
        n = X.shape[1]
        valid = (
            jnp.arange(n, dtype=jnp.int32)[None, :] < lens[:, None]
        ).astype(jnp.float32)                       # (M, N)
        Xz = jnp.where(jnp.isnan(X), 0.0, X)
        yz = jnp.where(jnp.isnan(y), 0.0, y)

        det_stats = jax.vmap(
            lambda ym: det_cls.compute_stats(ym, **dict(det_opts))
        )(y)                                        # nan-aware: pads fall out

        full_stats, _ = scale_chain(X)
        Xt_full = jnp.where(
            valid[..., None] > 0, apply_chain(full_stats, Xz), 0.0
        )
        inputs_full, targets_full = windowize(Xt_full, yz)
        wv_full = valid[:, offset:] if offset else valid
        if warm_params0 is None:
            params0 = fleet_mod.fleet_init(
                module, init_keys, inputs_full[0, :1]
            )
        else:
            params0 = warm_params0

        per_step_stats: List[List[Any]] = [[] for _ in scaler_opts]
        feat_maxes, feat_has = [], []
        total_maxes = []
        metric_vals: Dict[str, List[Any]] = {n_: [] for n_ in METRIC_NAMES}

        for tr, te in fold_idx:
            X_tr_nan = jnp.take(X, tr, axis=1)
            stats_k, _ = scale_chain(X_tr_nan)
            valid_tr = jnp.take(valid, tr, axis=1)
            Xt = jnp.where(
                valid_tr[..., None] > 0,
                apply_chain(stats_k, jnp.take(Xz, tr, axis=1)),
                0.0,
            )
            inputs, targets = windowize(Xt, jnp.take(yz, tr, axis=1))
            wv = valid_tr[:, offset:] if offset else valid_tr
            params_k, _ = one_fit(params0, inputs, targets, wv, fit_keys)

            valid_te = jnp.take(valid, te, axis=1)
            Xt_te = jnp.where(
                valid_te[..., None] > 0,
                apply_chain(stats_k, jnp.take(Xz, te, axis=1)),
                0.0,
            )
            y_te = jnp.take(yz, te, axis=1)
            te_inputs, _ = windowize(Xt_te, y_te)
            pred = vapply(params_k, te_inputs)
            y_true = y_te[:, offset:]
            wv_te = valid_te[:, offset:] if offset else valid_te

            for name in METRIC_NAMES:
                metric_vals[name].append(
                    jax.vmap(WEIGHTED_METRICS[name])(y_true, pred, wv_te)
                )
            y_s = jax.vmap(det_cls.apply, in_axes=(0, 0))(det_stats, y_true)
            p_s = jax.vmap(det_cls.apply, in_axes=(0, 0))(det_stats, pred)
            tag_err = jnp.abs(p_s - y_s)
            total = jnp.linalg.norm(tag_err, axis=-1)
            feat_maxes.append(jax.vmap(masked_smoothed_max)(tag_err, wv_te))
            total_maxes.append(
                jax.vmap(
                    lambda t, w: masked_smoothed_max(t[:, None], w)[0]
                )(total, wv_te)
            )
            feat_has.append((jnp.sum(wv_te, axis=1) > 0).astype(jnp.float32))
            for j, st in enumerate(stats_k):
                per_step_stats[j].append(st)

        final_params, final_history = one_fit(
            params0, inputs_full, targets_full, wv_full, fit_keys
        )
        for j, st in enumerate(full_stats):
            per_step_stats[j].append(st)

        # fold means weighted by "this machine had any valid test rows in
        # this fold" — _dispatch_padded demotes machines too short for the
        # fold layout to the exact path, so this is belt-and-braces against
        # a 0/0 NaN-ing the artifact
        has = jnp.stack(feat_has, axis=1)            # (M, K)
        denom = jnp.maximum(jnp.sum(has, axis=1), 1.0)
        out = {
            "scaler_stats": [
                {
                    stat: jnp.stack([s[stat] for s in fold_stats], axis=1)
                    for stat in fold_stats[0]
                }
                for fold_stats in per_step_stats
            ],
            "det_scaler_stats": det_stats,
            "final_params": final_params,
            "final_history": final_history,
            "feature_thresholds": jnp.sum(
                jnp.stack(feat_maxes, axis=1) * has[:, :, None], axis=1
            ) / denom[:, None],
            "aggregate_threshold": jnp.sum(
                jnp.stack(total_maxes, axis=1) * has, axis=1
            ) / denom,
            "metrics": {
                name: jnp.stack(v, axis=1) for name, v in metric_vals.items()
            },
        }
        if mesh is not None:
            out = jax.lax.with_sharding_constraint(out, model_sharding(mesh))
        return out

    if warm:
        def program(X, y, lens, seeds, params0):
            return body(X, y, lens, seeds, params0)
        name = "fleet.padded_warm"
    else:
        def program(X, y, lens, seeds):
            return body(X, y, lens, seeds, None)
        name = "fleet.padded"

    return compile_plane.cached_closure(
        key, lambda: compile_plane.closure_program(program, name=name)
    )
