"""Whole-fleet anomaly-detector builds as stacked device programs.

Reference equivalent: running ``gordo_components/builder/build_model.py``
once per machine in its own Argo pod, each doing sklearn
``cross_val_predict`` + threshold derivation + a final Keras fit
(``model/anomaly/diff.py::DiffBasedAnomalyDetector``).

Here the entire bucket of M homogeneous machines — scaler stats, K CV folds
PLUS the final fit (folds ride a second vmap axis as weight masks),
out-of-fold scoring, per-tag/aggregate threshold derivation — compiles into
a few jitted dispatches, sharded over the mesh ``"models"`` axis.  Output is
M individually fitted :class:`DiffBasedAnomalyDetector` objects, artifact-
and metadata-compatible with the single-machine path.

Equivalence contract (tests/test_fleet.py): for machines whose row count
equals the bucket maximum, the FINAL model (params, scaler stats, anomaly
scores) is bit-identical to the single-machine path — RNG derivation,
padding, and shuffle match ``train.fit.fit`` exactly.  Shorter machines in
a ragged bucket, and all CV-fold fits, are *statistically* equivalent but
not bit-identical: batch geometry/fold membership come from the bucket-wide
padded length, so the per-epoch shuffle permutes a different row count than
the materialized single-machine arrays would, changing minibatch
composition — same estimator, different sample of SGD noise (a few percent
on fold-averaged thresholds at small epoch counts).

Fleetability is *checked, not assumed*: :func:`analyze_definition` inspects
a prototype built from the model-config definition and returns a spec only
for the supported shape — ``DiffBasedAnomalyDetector`` wrapping
``Pipeline([*pure-stats scalers, BaseJaxEstimator])`` — everything else
falls back to the per-machine builder.
"""

from __future__ import annotations

import copy
import dataclasses
import time
from functools import partial
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from gordo_tpu.anomaly.diff import SMOOTHING_WINDOW, DiffBasedAnomalyDetector
from gordo_tpu.models.estimator import BaseJaxEstimator
from gordo_tpu.ops.metrics import MASKED_METRICS
from gordo_tpu.ops.scalers import (
    BaseTransform,
    MinMaxScaler,
    RobustScaler,
    StandardScaler,
)
from gordo_tpu.parallel import fleet as fleet_mod
from gordo_tpu.parallel.mesh import MODEL_AXIS, model_sharding, pad_to_multiple
from gordo_tpu.pipeline import Pipeline
from gordo_tpu.registry import lookup_factory
from gordo_tpu.train.cv import build_splitter
from gordo_tpu.train.fit import TrainConfig, make_fit_fn
from gordo_tpu.utils.trees import to_host

#: scalers whose stats are computable by a static pure function (vmappable).
FLEETABLE_SCALERS = (MinMaxScaler, StandardScaler, RobustScaler)

METRIC_NAMES = (
    "explained_variance_score",
    "r2_score",
    "mean_squared_error",
    "mean_absolute_error",
)


# ---------------------------------------------------------------------------
# Definition analysis
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class FleetSpec:
    """Everything needed to run one homogeneous bucket as a fleet program."""

    detector_proto: DiffBasedAnomalyDetector
    scaler_protos: List[BaseTransform]      # pipeline scalers, in order
    estimator_proto: BaseJaxEstimator
    train_cfg: TrainConfig
    factory_kwargs: Dict[str, Any]
    seed: int

    @property
    def signature(self) -> Tuple:
        """Bucket key: machines with equal signatures share one program."""
        return (
            type(self.detector_proto).__name__,
            self.detector_proto.window,
            tuple(
                (type(s).__name__, tuple(sorted(s._stat_options().items())))
                for s in self.scaler_protos
            ),
            (
                type(self.detector_proto.scaler).__name__,
                tuple(sorted(self.detector_proto.scaler._stat_options().items())),
            ),
            type(self.estimator_proto).__name__,
            self.estimator_proto.kind,
            self.train_cfg,
            tuple(sorted(self.factory_kwargs.items())),
        )


def analyze_definition(model) -> Optional[FleetSpec]:
    """Return a :class:`FleetSpec` if ``model`` (a built-but-unfitted
    prototype) matches the fleetable shape, else None."""
    if not isinstance(model, DiffBasedAnomalyDetector):
        return None
    if not isinstance(model.scaler, FLEETABLE_SCALERS):
        return None

    base = model.base_estimator
    scalers: List[BaseTransform] = []
    if isinstance(base, Pipeline):
        for _, step in base.steps[:-1]:
            if not isinstance(step, FLEETABLE_SCALERS):
                return None
            scalers.append(step)
        est = base._final
    else:
        est = base
    if not isinstance(est, BaseJaxEstimator):
        return None
    if est.params_ is not None:  # already fitted — not a prototype
        return None

    cfg, factory_kwargs = TrainConfig.from_kwargs(dict(est.kwargs))
    seed = int(factory_kwargs.get("seed", 0) or 0)
    return FleetSpec(
        detector_proto=model,
        scaler_protos=scalers,
        estimator_proto=est,
        train_cfg=cfg,
        factory_kwargs=factory_kwargs,
        seed=seed,
    )


# ---------------------------------------------------------------------------
# Pure device-side pieces
# ---------------------------------------------------------------------------

def _span_mask(row_mask: np.ndarray, offset: int, lengths: np.ndarray) -> np.ndarray:
    """Aligned-axis mask: aligned index j is on iff rows ``j..j+offset`` are
    ALL on in ``row_mask`` and row ``j+offset`` is a real (unpadded) row.

    Works for train masks (window+target fully inside the train rows) and
    test masks (prediction j only uses test rows) alike; host numpy, static
    shapes. ``row_mask``: (..., N) bool; returns (..., N - offset) bool.
    """
    n = row_mask.shape[-1]
    span = offset + 1
    c = np.concatenate(
        [np.zeros(row_mask.shape[:-1] + (1,), np.int64),
         np.cumsum(row_mask.astype(np.int64), axis=-1)],
        axis=-1,
    )
    full = (c[..., span:] - c[..., : n - offset]) == span  # (..., N - offset)
    valid = (np.arange(n - offset) + offset) < lengths[..., None]
    return full & valid


def _smoothed_masked_max(err: jnp.ndarray, mask: jnp.ndarray, window: int) -> jnp.ndarray:
    """Max over masked rows of the trailing rolling-min of ``err``.

    Matches pandas ``rolling(window, min_periods=1).min()`` then ``max()`` on
    the masked segment (DiffBasedAnomalyDetector threshold smoothing), as a
    pure static-shape function: off-mask entries become +inf before the
    rolling min (identity) and -inf before the max.
    ``err``: (N, F) — returns (F,).
    """
    big = jnp.where(mask[:, None], err, jnp.inf)
    neg = -jax.lax.reduce_window(
        -big,
        -jnp.inf,
        jax.lax.max,
        window_dimensions=(window, 1),
        window_strides=(1, 1),
        padding=((window - 1, 0), (0, 0)),
    )
    vals = jnp.where(mask[:, None], neg, -jnp.inf)
    return jnp.max(vals, axis=0)


# ---------------------------------------------------------------------------
# The fleet builder
# ---------------------------------------------------------------------------

class FleetDiffBuilder:
    """Build M homogeneous ``DiffBasedAnomalyDetector`` machines at once.

    One instance per bucket; ``build(Xs, ys)`` returns fitted detectors in
    input order.
    """

    def __init__(self, spec: FleetSpec, cv: Any = None, mesh: Optional[Mesh] = None):
        self.spec = spec
        self.splitter = build_splitter(cv)
        self.mesh = mesh

    # -- host-side orchestration --------------------------------------------
    def build(
        self,
        Xs: Sequence[np.ndarray],
        ys: Optional[Sequence[np.ndarray]] = None,
    ) -> List[DiffBasedAnomalyDetector]:
        spec = self.spec
        est_proto = spec.estimator_proto
        offset = est_proto.offset
        t0 = time.time()

        X, w_rows, lengths = fleet_mod.stack_rows(Xs)
        if ys is None:
            y = X
        else:
            if len(ys) != len(Xs):
                raise ValueError(
                    f"Got {len(Xs)} input series but {len(ys)} target series"
                )
            y, _, y_lengths = fleet_mod.stack_rows(ys)
            mismatched = [
                i for i, (a, b) in enumerate(zip(lengths, y_lengths)) if a != b
            ]
            if mismatched:
                raise ValueError(
                    "Target row counts differ from inputs for machines "
                    f"{mismatched}: row masks are derived from X, so shorter "
                    "targets would silently train on zero padding"
                )
        m, n = X.shape[:2]
        n_features = X.shape[2]
        n_out = y.shape[2]

        # CV fold row-masks, per machine (fold geometry depends on length).
        k_folds = self.splitter.get_n_splits()
        train_rows = np.zeros((m, k_folds, n), dtype=bool)
        test_rows = np.zeros((m, k_folds, n), dtype=bool)
        for i, length in enumerate(lengths):
            tr, te = fleet_mod.fold_masks(int(length), self.splitter)
            train_rows[i, :, : int(length)] = tr
            test_rows[i, :, : int(length)] = te

        # Aligned-axis weights: K CV folds + 1 final full fit.
        w_folds = _span_mask(train_rows, offset, lengths[:, None]).astype(np.float32)
        w_test = _span_mask(test_rows, offset, lengths[:, None]).astype(np.float32)
        w_full = _span_mask(
            w_rows.astype(bool)[:, None, :], offset, lengths[:, None]
        ).astype(np.float32)
        w_all = np.concatenate([w_folds, w_full], axis=1)  # (M, K+1, NA)

        # Row masks per fold for scaler fitting (single-machine parity: each
        # CV fold refits the pipeline scalers on ITS train rows only; the
        # final fit's scalers see every valid row).
        rows_all = np.concatenate(
            [train_rows, w_rows.astype(bool)[:, None, :]], axis=1
        )  # (M, K+1, N)

        # Factory module for this bucket's shapes.
        factory = lookup_factory(est_proto.model_type, est_proto.kind)
        built_kwargs = dict(
            n_features=n_features, n_features_out=n_out, **spec.factory_kwargs
        )
        module = factory(**built_kwargs)

        # Pad the model axis for the mesh.
        m_pad = m
        if self.mesh is not None:
            m_pad = pad_to_multiple(m, self.mesh.shape[MODEL_AXIS])
        if m_pad != m:
            X = fleet_mod._pad_models(X, m_pad)
            y = fleet_mod._pad_models(y, m_pad)
            rows_all = fleet_mod._pad_models(rows_all, m_pad)
            w_all = np.concatenate(
                [w_all, np.zeros((m_pad - m,) + w_all.shape[1:], np.float32)], axis=0
            )
            w_test = np.concatenate(
                [w_test, np.zeros((m_pad - m,) + w_test.shape[1:], np.float32)],
                axis=0,
            )

        na = w_all.shape[-1]
        bs = int(min(spec.train_cfg.batch_size, na))
        steps = -(-na // bs)
        na_pad = steps * bs - na

        scaler_opts = tuple(
            (type(s), tuple(sorted(s._stat_options().items())))
            for s in spec.scaler_protos
        )
        det_scaler_opts = (
            type(spec.detector_proto.scaler),
            tuple(sorted(spec.detector_proto.scaler._stat_options().items())),
        )

        # Windowing semantics as static flags (see estimator classes):
        # "none"=row-wise FF AE, "ae"=reconstruct window end, "forecast"=t+1.
        from gordo_tpu.models.estimator import LSTMAutoEncoder, LSTMForecast

        if isinstance(est_proto, LSTMForecast):
            window_mode, lookback = "forecast", est_proto.lookback_window
        elif isinstance(est_proto, LSTMAutoEncoder):
            window_mode, lookback = "ae", est_proto.lookback_window
        else:
            window_mode, lookback = "none", 1

        seeds = np.full((m_pad,), spec.seed, dtype=np.uint32)
        out = _fleet_diff_program(
            module,
            scaler_opts,
            det_scaler_opts,
            window_mode,
            lookback,
            int(offset),
            spec.train_cfg,
            steps,
            bs,
            na_pad,
            self.mesh,
            jnp.asarray(X),
            jnp.asarray(y),
            jnp.asarray(rows_all),
            jnp.asarray(w_all),
            jnp.asarray(w_test),
            jnp.asarray(seeds),
        )
        out = to_host(out)
        fleet_seconds = time.time() - t0

        return self._assemble(
            out, m, built_kwargs, fleet_seconds, k_folds
        )

    # -- unpacking into per-machine detector objects ------------------------
    def _assemble(
        self,
        out: Dict[str, Any],
        m: int,
        built_kwargs: Dict[str, Any],
        fleet_seconds: float,
        k_folds: int,
    ) -> List[DiffBasedAnomalyDetector]:
        spec = self.spec
        detectors: List[DiffBasedAnomalyDetector] = []
        final_params_leaves, treedef = jax.tree.flatten(out["final_params"])

        for i in range(m):
            est = copy.deepcopy(spec.estimator_proto)
            est.module_ = None
            est.params_ = jax.tree.unflatten(
                treedef, [leaf[i] for leaf in final_params_leaves]
            )
            est._factory_kwargs_built = dict(built_kwargs)
            est.history_ = np.asarray(out["final_history"][i])
            est.fit_seconds_ = fleet_seconds / m

            steps = []
            for j, proto in enumerate(spec.scaler_protos):
                sc = copy.deepcopy(proto)
                # fold axis: -1 is the final full-data fit's scaler stats
                sc.stats_ = {
                    key: np.asarray(val[i, -1])
                    for key, val in out["scaler_stats"][j].items()
                }
                steps.append(sc)
            base: Any = est
            if steps or isinstance(spec.detector_proto.base_estimator, Pipeline):
                base = Pipeline([*steps, est])

            det_scaler = copy.deepcopy(spec.detector_proto.scaler)
            det_scaler.stats_ = {
                key: np.asarray(val[i])
                for key, val in out["det_scaler_stats"].items()
            }

            det = DiffBasedAnomalyDetector(
                base_estimator=base,
                scaler=det_scaler,
                require_thresholds=spec.detector_proto.require_thresholds,
                window=spec.detector_proto.window,
            )
            det.feature_thresholds_ = np.asarray(out["feature_thresholds"][i])
            det.aggregate_threshold_ = float(out["aggregate_threshold"][i])
            det.cv_metadata_ = {
                "scores": {
                    name: {
                        "folds": [
                            float(out["metrics"][name][i, k]) for k in range(k_folds)
                        ],
                        "mean": float(np.mean(out["metrics"][name][i])),
                        "std": float(np.std(out["metrics"][name][i])),
                    }
                    for name in METRIC_NAMES
                },
                "feature_thresholds": [
                    float(v) for v in out["feature_thresholds"][i]
                ],
                "aggregate_threshold": float(out["aggregate_threshold"][i]),
                "fleet": {"bucket_size": m, "fleet_seconds": fleet_seconds},
            }
            detectors.append(det)
        return detectors


# ---------------------------------------------------------------------------
# The single compiled program (cached across equal-signature buckets)
# ---------------------------------------------------------------------------

@partial(
    jax.jit,
    static_argnames=(
        "module",
        "scaler_opts",
        "det_scaler_opts",
        "window_mode",
        "lookback",
        "offset",
        "cfg",
        "steps",
        "bs",
        "na_pad",
        "mesh",
    ),
)
def _fleet_diff_program(
    module,
    scaler_opts,
    det_scaler_opts,
    window_mode: str,
    lookback: int,
    offset: int,
    cfg: TrainConfig,
    steps: int,
    bs: int,
    na_pad: int,
    mesh,
    X,         # (M, N, F) raw stacked rows (zero-padded)
    y,         # (M, N, Fout) raw targets
    rows_all,  # (M, K+1, N) bool: each fold's scaler-fit rows (K = all valid)
    w_all,     # (M, K+1, NA) aligned train weights; fold K is the final fit
    w_test,    # (M, K, NA) aligned test-eval masks
    seeds,     # (M,) uint32
):
    """Scaler stats -> windows -> (K+1)-fold vmapped fits -> out-of-fold
    scoring -> thresholds, as ONE jitted program over the whole bucket."""
    m = X.shape[0]
    k_folds = w_test.shape[1]

    # 1. Pipeline scaler chain — stats PER FOLD on that fold's train rows
    #    (single-machine parity: every CV fold refits its scalers), then
    #    transform; stats of step i are computed on step i-1's output.
    X_nan = jnp.where(rows_all[:, :, :, None], X[:, None], jnp.nan)  # (M,K+1,N,F)
    scaler_stats = []
    X_scaled = jnp.broadcast_to(X[:, None], X_nan.shape)
    vv = lambda f: jax.vmap(jax.vmap(f))  # noqa: E731 — (models, folds) map
    for scaler_cls, opts in scaler_opts:
        stats = vv(lambda xm: scaler_cls.compute_stats(xm, **dict(opts)))(X_nan)
        scaler_stats.append(stats)
        X_scaled = vv(scaler_cls.apply)(stats, X_scaled)
        X_nan = vv(scaler_cls.apply)(stats, X_nan)

    # 2. Detector scaler stats on raw targets over ALL valid rows (the
    #    detector scaler is fit once on the full series, not per fold).
    det_cls, det_opts = det_scaler_opts
    y_nan = jnp.where(rows_all[:, -1, :, None], y, jnp.nan)
    det_stats = jax.vmap(lambda ym: det_cls.compute_stats(ym, **dict(det_opts)))(
        y_nan
    )

    # 3. Windowing (estimator semantics) on the scaled input.
    from gordo_tpu.ops.windows import make_windows

    if window_mode == "none":
        inputs, targets = X_scaled, y                      # (M, K+1, NA, ...)
    elif window_mode == "ae":
        inputs = vv(lambda a: make_windows(a, lookback))(X_scaled)
        targets = y[:, lookback - 1:]
    elif window_mode == "forecast":
        inputs = vv(lambda a: make_windows(a[:-1], lookback))(X_scaled)
        targets = y[:, lookback:]
    else:
        raise ValueError(f"Unknown window_mode {window_mode!r}")

    # Pad aligned rows to whole minibatches.
    if na_pad:
        inputs = jnp.concatenate(
            [inputs, jnp.zeros(inputs.shape[:2] + (na_pad,) + inputs.shape[3:], inputs.dtype)],
            axis=2,
        )
        targets = jnp.concatenate(
            [targets, jnp.zeros((m, na_pad) + targets.shape[2:], targets.dtype)],
            axis=1,
        )
        w_all = jnp.concatenate(
            [w_all, jnp.zeros((m, w_all.shape[1], na_pad), w_all.dtype)], axis=2
        )

    # 4. (K+1)-fold fits: vmapped over (models, folds); each fold sees its
    #    own scaled inputs but the shared raw-target series.
    init_keys, fit_keys = fleet_mod.fleet_keys(seeds)
    params0 = fleet_mod.fleet_init(module, init_keys, inputs[0, 0, :1])
    params0 = jax.tree.map(
        lambda leaf: jnp.broadcast_to(
            leaf[:, None], (m, k_folds + 1) + leaf.shape[1:]
        ),
        params0,
    )
    fit_fn = make_fit_fn(module, cfg, steps, bs)
    vfit = jax.vmap(  # models axis
        jax.vmap(fit_fn, in_axes=(0, 0, None, 0, None)),  # folds axis
        in_axes=(0, 0, 0, 0, 0),
    )
    params, history = vfit(params0, inputs, targets, w_all, fit_keys)

    # 5. Out-of-fold scoring on the K CV folds.
    vapply = jax.vmap(
        jax.vmap(lambda p, x: module.apply({"params": p}, x)),  # folds
        in_axes=(0, 0),
    )
    cv_params = jax.tree.map(lambda leaf: leaf[:, :k_folds], params)
    na = w_test.shape[2]
    preds = vapply(cv_params, inputs[:, :k_folds])[:, :, :na]  # (M, K, NA, Fout)
    y_al = targets[:, :na]

    def fold_scores(pred_k, y_m, mask_k, det_stats_m):
        y_s = det_cls.apply(det_stats_m, y_m)
        p_s = det_cls.apply(det_stats_m, pred_k)
        tag_err = jnp.abs(p_s - y_s)
        total = jnp.linalg.norm(tag_err, axis=-1)
        feat_max = _smoothed_masked_max(tag_err, mask_k > 0, SMOOTHING_WINDOW)
        total_max = _smoothed_masked_max(
            total[:, None], mask_k > 0, SMOOTHING_WINDOW
        )[0]
        metrics = {
            name: MASKED_METRICS[name](y_m, pred_k, mask_k)
            for name in METRIC_NAMES
        }
        return feat_max, total_max, metrics

    vscores = jax.vmap(  # models
        jax.vmap(fold_scores, in_axes=(0, None, 0, None)),  # folds
        in_axes=(0, 0, 0, 0),
    )
    feat_max, total_max, metrics = vscores(preds, y_al, w_test, det_stats)

    out = {
        "scaler_stats": scaler_stats,
        "det_scaler_stats": det_stats,
        "final_params": jax.tree.map(lambda leaf: leaf[:, -1], params),
        "final_history": history[:, -1],
        "feature_thresholds": jnp.mean(feat_max, axis=1),
        "aggregate_threshold": jnp.mean(total_max, axis=1),
        "metrics": metrics,
    }
    if mesh is not None:
        out = jax.lax.with_sharding_constraint(
            out, model_sharding(mesh)
        )
    return out
