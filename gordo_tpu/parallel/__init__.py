"""Fleet parallelism: many independent per-machine models as ONE XLA program.

Reference equivalent: the *orchestration-level* fan-out in
``gordo_components/workflow`` — an Argo DAG schedules one ``gordo build`` pod
per machine (SURVEY.md §2.3: "fleet parallel" is the reference's only real
parallelism strategy).  There is no in-process distributed training in the
reference at all.

TPU-native replacement: stack the M machines' tiny models into leading-axis
pytrees, ``vmap`` the entire jitted fit over the model axis, and shard that
axis over a ``jax.sharding.Mesh`` — one dispatch trains the whole fleet, with
XLA placing each shard's models on its chip and batching their little
matmuls into MXU-sized ones.  Cross-validation folds ride a second vmap axis
(fold-mask weights), so CV for the whole fleet is the same single program.
"""

from gordo_tpu.parallel.mesh import (
    fleet_mesh,
    global_fleet_mesh,
    model_sharding,
    replicated_sharding,
)
from gordo_tpu.parallel.fleet import (
    FleetFitResult,
    StagedFleetFit,
    fleet_fit,
    fleet_stage,
    fleet_dispatch,
    fleet_apply,
    fleet_init,
    stack_rows,
    fold_masks,
)
from gordo_tpu.parallel.anomaly import FleetDiffBuilder

__all__ = [
    "fleet_mesh",
    "global_fleet_mesh",
    "model_sharding",
    "replicated_sharding",
    "FleetFitResult",
    "StagedFleetFit",
    "fleet_fit",
    "fleet_stage",
    "fleet_dispatch",
    "fleet_apply",
    "fleet_init",
    "stack_rows",
    "fold_masks",
    "FleetDiffBuilder",
]
