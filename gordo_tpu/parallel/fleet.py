"""Stacked-model fleet training: vmap over models, shard over the mesh.

Reference equivalent: SURVEY.md §2.3 — the reference's only parallelism is
Argo scheduling one training pod per machine
(``gordo_components/workflow/`` + ``builder/build_model.py``).  Here the
same fan-out is a single XLA program:

- every machine's (tiny) dataset is padded to a common row count and stacked
  into ``(M, N, F)`` device arrays, with a ``(M, N)`` weight mask zeroing
  padding out of the loss;
- per-machine params are initialised vmapped into leading-axis-stacked
  pytrees;
- the WHOLE multi-epoch fit (``gordo_tpu.train.fit.make_fit_fn``) is vmapped
  over the model axis and jitted with the stacked axis sharded over the
  mesh's ``"models"`` axis — XLA places each chip's slice of the fleet
  locally; no collectives cross the model axis (pure map), so scaling to a
  v5e-64 is embarrassing in the good sense.

The MXU win: one 8-tag hourglass's ``(256, 8)·(8, 4)`` matmuls can never
fill a 128x128 systolic array; 10k of them stacked become effectively
batched GEMMs that can.
"""

from __future__ import annotations

import dataclasses
from typing import Any, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from gordo_tpu.parallel.mesh import (
    DATA_AXIS,
    MODEL_AXIS,
    model_sharding,
    pad_to_multiple,
)
from gordo_tpu.train.fit import TrainConfig, batch_geometry, make_fit_fn


# ---------------------------------------------------------------------------
# Host-side stacking
# ---------------------------------------------------------------------------

def stack_rows(
    arrays: Sequence[np.ndarray],
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Stack per-machine row-major arrays with row padding.

    Returns ``(stacked (M, N, ...), weights (M, N), lengths (M,))`` where
    ``N`` is the max row count and ``weights`` masks padded rows.
    """
    arrays = [np.asarray(a, dtype=np.float32) for a in arrays]
    trailing = {a.shape[1:] for a in arrays}
    if len(trailing) != 1:
        raise ValueError(
            f"stack_rows needs homogeneous feature shapes, got {sorted(trailing)}"
        )
    lengths = np.array([a.shape[0] for a in arrays], dtype=np.int32)
    n = int(lengths.max())
    m = len(arrays)
    out = np.zeros((m, n) + arrays[0].shape[1:], dtype=np.float32)
    w = np.zeros((m, n), dtype=np.float32)
    for i, a in enumerate(arrays):
        out[i, : a.shape[0]] = a
        w[i, : a.shape[0]] = 1.0
    return out, w, lengths


def fold_masks(n_rows: int, splitter) -> Tuple[np.ndarray, np.ndarray]:
    """CV folds as static-shape boolean masks ``(K, n_rows)``.

    Device-side CV cannot fancy-index per fold (shapes must be static under
    vmap); fold membership becomes a weight/selection mask instead.
    """
    k = splitter.get_n_splits()
    train = np.zeros((k, n_rows), dtype=bool)
    test = np.zeros((k, n_rows), dtype=bool)
    for i, (tr, te) in enumerate(splitter.split(np.empty((n_rows, 1)))):
        train[i, tr] = True
        test[i, te] = True
    return train, test


# ---------------------------------------------------------------------------
# Fleet fit
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class FleetFitResult:
    """Stacked fit output: leading-axis-``M`` params pytree + loss history."""

    params: Any              # pytree, every leaf (M, ...)
    history: np.ndarray      # (M, epochs)
    n_models: int            # models actually requested (before mesh padding)

    def unstack_params(self) -> List[Any]:
        """Split the stacked pytree into per-machine host pytrees."""
        leaves, treedef = jax.tree.flatten(jax.device_get(self.params))
        return [
            jax.tree.unflatten(treedef, [leaf[i] for leaf in leaves])
            for i in range(self.n_models)
        ]


def _pad_models(arr: np.ndarray, m_pad: int) -> np.ndarray:
    """Grow the leading model axis to ``m_pad`` by repeating the last entry
    (weights for padded models are zeroed separately)."""
    m = arr.shape[0]
    if m == m_pad:
        return arr
    reps = np.repeat(arr[-1:], m_pad - m, axis=0)
    return np.concatenate([arr, reps], axis=0)


def fleet_keys(seeds: np.ndarray) -> Tuple[jax.Array, jax.Array]:
    """Per-machine (init_key, fit_key) pairs, derived EXACTLY like the
    single-model path (``train.fit.fit``: split of ``PRNGKey(seed)``) so a
    fleet fit is bit-identical to M separate fits of the same shapes."""
    keys = jax.vmap(jax.random.PRNGKey)(jnp.asarray(seeds, dtype=jnp.uint32))
    split = jax.vmap(jax.random.split)(keys)  # (M, 2, 2)
    return split[:, 0], split[:, 1]


def fleet_init(module, init_keys: jax.Array, sample_x: np.ndarray):
    """vmapped param init: one rng per machine -> stacked params pytree."""
    return jax.vmap(lambda k: module.init(k, jnp.asarray(sample_x))["params"])(
        init_keys
    )


def fleet_fit(
    module,
    X: np.ndarray,
    y: np.ndarray,
    w: np.ndarray,
    cfg: TrainConfig,
    seeds: Optional[np.ndarray] = None,
    mesh: Optional[Mesh] = None,
    params: Optional[Any] = None,
) -> FleetFitResult:
    """Train ``M`` instances of ``module`` on stacked data in one dispatch.

    ``X``: (M, N, ...) inputs, ``y``: (M, N, ...) targets, ``w``: (M, N)
    row-validity weights.  With a mesh, the model axis is sharded over the
    mesh's ``"models"`` axis (M is padded up to a multiple of its size with
    zero-weight dummies); rows replicate within a model shard — the ``data``
    mesh axis serves :func:`fit_data_parallel` instead.
    """
    X = np.asarray(X, np.float32)
    y = np.asarray(y, np.float32)
    w = np.asarray(w, np.float32)
    m, n = X.shape[:2]

    # Pad rows to a whole number of minibatches (masked out of the loss).
    steps, bs, n_pad = batch_geometry(n, cfg.batch_size)
    if n_pad:
        X = np.concatenate([X, np.zeros((m, n_pad) + X.shape[2:], X.dtype)], axis=1)
        y = np.concatenate([y, np.zeros((m, n_pad) + y.shape[2:], y.dtype)], axis=1)
        w = np.concatenate([w, np.zeros((m, n_pad), w.dtype)], axis=1)

    # Pad the model axis to the mesh's fleet width.
    m_pad = m
    if mesh is not None:
        m_pad = pad_to_multiple(m, mesh.shape[MODEL_AXIS])
        if m_pad != m:
            X = _pad_models(X, m_pad)
            y = _pad_models(y, m_pad)
            w = np.concatenate(
                [w, np.zeros((m_pad - m, w.shape[1]), w.dtype)], axis=0
            )

    if seeds is None:
        seeds = np.arange(m_pad, dtype=np.uint32)
    else:
        seeds = _pad_models(np.asarray(seeds, np.uint32), m_pad)

    init_keys, fit_keys = fleet_keys(seeds)
    if params is None:
        params = fleet_init(module, init_keys, X[0, :1])

    fit_fn = make_fit_fn(module, cfg, steps, bs)
    vfit = jax.vmap(fit_fn)

    if mesh is not None:
        ms = model_sharding(mesh)
        fitted = jax.jit(
            vfit,
            in_shardings=(ms, ms, ms, ms, ms),
            out_shardings=(ms, ms),
        )
    else:
        fitted = jax.jit(vfit)

    out_params, history = fitted(
        params, jnp.asarray(X), jnp.asarray(y), jnp.asarray(w), fit_keys
    )
    return FleetFitResult(
        params=out_params,
        history=np.asarray(history)[:m],
        n_models=m,
    )


def fleet_apply(
    module,
    params: Any,
    X,
    mesh: Optional[Mesh] = None,
) -> jnp.ndarray:
    """vmapped forward pass: stacked params (M, ...) x inputs (M, N, ...)."""
    vapply = jax.vmap(lambda p, x: module.apply({"params": p}, x))
    if mesh is not None:
        ms = model_sharding(mesh)
        return jax.jit(vapply, in_shardings=(ms, ms), out_shardings=ms)(
            params, jnp.asarray(X)
        )
    return jax.jit(vapply)(params, jnp.asarray(X))


# ---------------------------------------------------------------------------
# Data-parallel single-model fit (the "data" mesh axis)
# ---------------------------------------------------------------------------

def fit_data_parallel(
    module,
    X: np.ndarray,
    y: np.ndarray,
    cfg: TrainConfig,
    mesh: Mesh,
    rng: Optional[jax.Array] = None,
) -> Tuple[Any, np.ndarray]:
    """Fit ONE model with rows sharded over the mesh ``"data"`` axis.

    For a single larger model (not the fleet case): params replicate, the
    batch axis shards, and XLA's grad all-reduce rides ICI — the TPU-native
    replacement for the `tf.distribute` capability the reference never used
    (SURVEY.md §6.8).
    """
    from jax.sharding import NamedSharding, PartitionSpec as P

    rng = rng if rng is not None else jax.random.PRNGKey(0)
    X = np.asarray(X, np.float32)
    y = np.asarray(y, np.float32)
    n = X.shape[0]
    steps, bs, n_pad = batch_geometry(n, cfg.batch_size)
    w = np.concatenate([np.ones(n, np.float32), np.zeros(n_pad, np.float32)])
    if n_pad:
        X = np.concatenate([X, np.zeros((n_pad,) + X.shape[1:], X.dtype)])
        y = np.concatenate([y, np.zeros((n_pad,) + y.shape[1:], y.dtype)])

    init_rng, rng = jax.random.split(rng)  # same derivation as train.fit.fit
    params = module.init(init_rng, jnp.asarray(X[:1]))["params"]
    fit_fn = make_fit_fn(module, cfg, steps, bs)

    rows = NamedSharding(mesh, P(DATA_AXIS))
    repl = NamedSharding(mesh, P())
    fitted = jax.jit(
        fit_fn,
        in_shardings=(repl, rows, rows, rows, repl),
        out_shardings=(repl, repl),
    )
    out_params, history = fitted(
        params, jnp.asarray(X), jnp.asarray(y), jnp.asarray(w), rng
    )
    return out_params, np.asarray(history)
