"""Stacked-model fleet training: vmap over models, shard over the mesh.

Reference equivalent: SURVEY.md §2.3 — the reference's only parallelism is
Argo scheduling one training pod per machine
(``gordo_components/workflow/`` + ``builder/build_model.py``).  Here the
same fan-out is a single XLA program:

- every machine's (tiny) dataset is padded to a common row count and stacked
  into ``(M, N, F)`` device arrays, with a ``(M, N)`` weight mask zeroing
  padding out of the loss;
- per-machine params are initialised vmapped into leading-axis-stacked
  pytrees;
- the WHOLE multi-epoch fit (``gordo_tpu.train.fit.make_fit_fn``) is vmapped
  over the model axis and jitted with the stacked axis sharded over the
  mesh's ``"models"`` axis — XLA places each chip's slice of the fleet
  locally; no collectives cross the model axis (pure map), so scaling to a
  v5e-64 is embarrassing in the good sense.

The MXU win: one 8-tag hourglass's ``(256, 8)·(8, 4)`` matmuls can never
fill a 128x128 systolic array; 10k of them stacked become effectively
batched GEMMs that can.
"""

from __future__ import annotations

import dataclasses
import warnings
from typing import Any, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from gordo_tpu.mesh import (
    MODEL_AXIS,
    Mesh,
    data_sharding,
    model_sharding,
    pad_to_multiple,
    place,
    replicated_sharding,
)
from gordo_tpu.train.fit import TrainConfig, batch_geometry, make_fit_fn

# The fleet program donates X/y/w/fit_keys alongside params.  Only params
# can alias an output (same shapes), so XLA reports the rest as "not
# usable" donations — but donating them is still the point: the staged
# input buffers free at their last use inside the program instead of
# surviving until the result fetch, which is what lets bucket N+1's
# staged arrays coexist with bucket N's compute without doubling device
# memory.  Silence exactly that advisory.
warnings.filterwarnings(
    "ignore", message="Some donated buffers were not usable"
)


# ---------------------------------------------------------------------------
# Host-side stacking
# ---------------------------------------------------------------------------

def stack_rows(
    arrays: Sequence[np.ndarray],
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Stack per-machine row-major arrays with row padding.

    Returns ``(stacked (M, N, ...), weights (M, N), lengths (M,))`` where
    ``N`` is the max row count and ``weights`` masks padded rows.
    """
    arrays = [np.asarray(a, dtype=np.float32) for a in arrays]
    trailing = {a.shape[1:] for a in arrays}
    if len(trailing) != 1:
        raise ValueError(
            f"stack_rows needs homogeneous feature shapes, got {sorted(trailing)}"
        )
    lengths = np.array([a.shape[0] for a in arrays], dtype=np.int32)
    n = int(lengths.max())
    m = len(arrays)
    out = np.zeros((m, n) + arrays[0].shape[1:], dtype=np.float32)
    w = np.zeros((m, n), dtype=np.float32)
    for i, a in enumerate(arrays):
        out[i, : a.shape[0]] = a
        w[i, : a.shape[0]] = 1.0
    return out, w, lengths


def fold_masks(n_rows: int, splitter) -> Tuple[np.ndarray, np.ndarray]:
    """CV folds as static-shape boolean masks ``(K, n_rows)``.

    Device-side CV cannot fancy-index per fold (shapes must be static under
    vmap); fold membership becomes a weight/selection mask instead.
    """
    k = splitter.get_n_splits()
    train = np.zeros((k, n_rows), dtype=bool)
    test = np.zeros((k, n_rows), dtype=bool)
    for i, (tr, te) in enumerate(splitter.split(np.empty((n_rows, 1)))):
        train[i, tr] = True
        test[i, te] = True
    return train, test


# ---------------------------------------------------------------------------
# Fleet fit
# ---------------------------------------------------------------------------

class FleetFitResult:
    """Stacked fit output: leading-axis-``M`` params pytree + loss history.

    ``history`` is LAZY: :func:`fleet_dispatch` returns while the device
    program is still running, holding the on-device ``(m_pad, epochs)``
    history array; the first ``.history`` access (or :meth:`collect`)
    performs the blocking D2H fetch and caches the ``(M, epochs)`` host
    slice.  Dispatching bucket N+1 therefore never waits on bucket N's
    history transfer.
    """

    def __init__(self, params: Any, n_models: int, history: Any = None):
        self.params = params     # pytree, every leaf (m_pad, ...)
        self.n_models = n_models  # models requested (before mesh padding)
        self._history = history  # device (m_pad, E) until first access

    @property
    def history(self) -> np.ndarray:
        """(M, epochs) loss history — blocking D2H on first access."""
        if self._history is not None and not isinstance(
            self._history, np.ndarray
        ):
            self._history = np.asarray(self._history)[: self.n_models]
        return self._history

    def collect(self) -> "FleetFitResult":
        """Block until the fit finished and the history is on host."""
        jax.block_until_ready(self.params)
        _ = self.history
        return self

    def unstack_params(self) -> List[Any]:
        """Split the stacked pytree into per-machine host pytrees."""
        leaves, treedef = jax.tree.flatten(jax.device_get(self.params))
        return [
            jax.tree.unflatten(treedef, [leaf[i] for leaf in leaves])
            for i in range(self.n_models)
        ]


def _pad_models(arr: np.ndarray, m_pad: int) -> np.ndarray:
    """Grow the leading model axis to ``m_pad`` by repeating the last entry
    (weights for padded models are zeroed separately)."""
    m = arr.shape[0]
    if m == m_pad:
        return arr
    reps = np.repeat(arr[-1:], m_pad - m, axis=0)
    return np.concatenate([arr, reps], axis=0)


def _pad_stacked(
    arr: np.ndarray, m_pad: int, n_total: int, repeat_last: bool = True
) -> np.ndarray:
    """Grow ``(m, n, ...)`` to ``(m_pad, n_total, ...)`` in ONE
    preallocated buffer: row padding is zeros (weight-masked out of the
    loss), model padding repeats the last machine (zero-weight dummies).

    Replaces the former row-``np.concatenate`` followed by a
    model-``np.concatenate``: the payload is copied once instead of
    twice, and the transient peak host footprint drops from ~2x the
    stacked bucket (old array + concatenated copy, twice over) to the
    final buffer alone.
    """
    m, n = arr.shape[:2]
    if m == m_pad and n == n_total:
        return arr
    out = np.zeros((m_pad, n_total) + arr.shape[2:], arr.dtype)
    out[:m, :n] = arr
    if repeat_last and m_pad != m:
        out[m:, :n] = arr[-1]
    return out


def stage_inputs(tree: Any, mesh: Optional[Mesh] = None) -> Any:
    """Asynchronously stage a pytree of stacked host arrays onto the
    device(s) through the placement seam: model-axis sharding when a mesh
    is given, default placement otherwise.  ``jax.device_put`` does not
    block, so the H2D copies overlap whatever the device is already
    running — dispatching bucket k+1's program on staged inputs never
    waits on bucket k.  Shared by :func:`fleet_stage` and the fleet
    builder's dispatch window (``parallel/anomaly.py``)."""
    ms = model_sharding(mesh) if mesh is not None else None
    return place(tree, ms)


def fleet_keys(seeds: np.ndarray) -> Tuple[jax.Array, jax.Array]:
    """Per-machine (init_key, fit_key) pairs, derived EXACTLY like the
    single-model path (``train.fit.fit``: split of ``PRNGKey(seed)``) so a
    fleet fit is bit-identical to M separate fits of the same shapes."""
    keys = jax.vmap(jax.random.PRNGKey)(jnp.asarray(seeds, dtype=jnp.uint32))
    split = jax.vmap(jax.random.split)(keys)  # (M, 2, 2)
    return split[:, 0], split[:, 1]


def fleet_init(module, init_keys: jax.Array, sample_x: np.ndarray):
    """vmapped param init: one rng per machine -> stacked params pytree."""
    return jax.vmap(lambda k: module.init(k, jnp.asarray(sample_x))["params"])(
        init_keys
    )


@dataclasses.dataclass
class StagedFleetFit:
    """One bucket's fleet-fit inputs, already padded and in flight to the
    device (``jax.device_put`` is asynchronous: constructing this does not
    block on the H2D copy).  Produced by :func:`fleet_stage`, consumed
    exactly once by :func:`fleet_dispatch` — dispatch donates every buffer
    to the device program, so a staged batch cannot be dispatched twice.
    """

    params: Any          # pytree, leaves (m_pad, ...)
    X: jax.Array         # (m_pad, n_total, ...)
    y: jax.Array         # (m_pad, n_total, ...)
    w: jax.Array         # (m_pad, n_total)
    fit_keys: jax.Array  # (m_pad, 2)
    n_models: int        # models requested (before mesh padding)
    steps: int
    bs: int
    consumed: bool = False


def _validate_fleet_params(params: Any, m: int, m_pad: int) -> None:
    """Caller-supplied params must already span the PADDED model axis —
    the program is traced at ``m_pad`` lanes, and a silent shape mismatch
    surfaces as an impenetrable vmap error deep inside XLA."""
    bad = sorted(
        {
            str(getattr(leaf, "shape", ())[:1])
            for leaf in jax.tree.leaves(params)
            if getattr(leaf, "ndim", 0) < 1 or leaf.shape[0] != m_pad
        }
    )
    if bad:
        raise ValueError(
            f"caller-supplied params must have leading model axis {m_pad} "
            f"({m} machine(s) padded to the fleet width), got leading "
            f"shape(s) {bad}; initialise with fleet_init over {m_pad} keys "
            "or pad each leaf (the padded lanes are zero-weight dummies)"
        )


def fleet_stage(
    module,
    X: np.ndarray,
    y: np.ndarray,
    w: np.ndarray,
    cfg: TrainConfig,
    seeds: Optional[np.ndarray] = None,
    mesh: Optional[Mesh] = None,
    params: Optional[Any] = None,
) -> StagedFleetFit:
    """Stage one bucket's stacked data onto the device(s), asynchronously.

    Host-side work happens here — single-copy row/model padding
    (:func:`_pad_stacked`), seed/params validation, key derivation — then
    ``jax.device_put`` starts the H2D transfer and returns immediately.
    Staging bucket N+1 while bucket N's dispatched program runs overlaps
    its transfer with device compute.
    """
    X = np.asarray(X, np.float32)
    y = np.asarray(y, np.float32)
    w = np.asarray(w, np.float32)
    m, n = X.shape[:2]

    steps, bs, n_pad = batch_geometry(n, cfg.batch_size)
    n_total = n + n_pad
    m_pad = m
    if mesh is not None:
        m_pad = pad_to_multiple(m, mesh.shape[MODEL_AXIS])

    Xp = _pad_stacked(X, m_pad, n_total)
    yp = _pad_stacked(y, m_pad, n_total)
    wp = _pad_stacked(w, m_pad, n_total, repeat_last=False)

    if seeds is None:
        seeds = np.arange(m_pad, dtype=np.uint32)
    else:
        seeds = np.asarray(seeds, np.uint32)
        if seeds.shape[0] not in (m, m_pad):
            raise ValueError(
                f"seeds must have one entry per machine ({m}; or {m_pad} "
                f"including mesh padding), got {seeds.shape[0]}"
            )
        seeds = _pad_models(seeds, m_pad)

    ms = model_sharding(mesh) if mesh is not None else None
    Xd, yd, wd = stage_inputs((Xp, yp, wp), mesh)

    init_keys, fit_keys = fleet_keys(seeds)
    if params is None:
        params = fleet_init(module, init_keys, Xd[0, :1])
    else:
        _validate_fleet_params(params, m, m_pad)
        # private copy: dispatch donates the staged leaves, and the
        # caller's pytree must stay usable afterwards
        params = jax.tree.map(jnp.array, params)
    if ms is not None:
        params = place(params, ms)

    return StagedFleetFit(
        params=params, X=Xd, y=yd, w=wd, fit_keys=fit_keys,
        n_models=m, steps=steps, bs=bs,
    )


# Jitted (and donation-annotated) fleet programs, keyed on the static
# trace inputs — without this cache every fleet_dispatch re-traced a
# fresh vmap closure (the pre-pipeline fleet_fit did exactly that).  The
# cache is the compile plane's shared closure LRU.
def _fleet_program(module, cfg: TrainConfig, steps: int, bs: int, mesh):
    from gordo_tpu import compile as compile_plane

    key = ("fleet.fit", module, cfg, steps, bs, mesh)

    def build():
        vfit = jax.vmap(make_fit_fn(module, cfg, steps, bs))
        # every argument is donated: out params alias the input params
        # buffers, and X/y/w/fit_keys free at their last device use
        # instead of outliving the program (see the module-level warning
        # filter)
        if mesh is not None:
            ms = model_sharding(mesh)
            return compile_plane.jit(
                vfit,
                name="fleet.fit_sharded",
                in_shardings=(ms, ms, ms, ms, ms),
                out_shardings=(ms, ms),
                donate_argnums=(0, 1, 2, 3, 4),
            )
        return compile_plane.jit(
            vfit, name="fleet.fit", donate_argnums=(0, 1, 2, 3, 4)
        )

    return compile_plane.cached_closure(key, build)


def fleet_dispatch(
    module,
    staged: StagedFleetFit,
    cfg: TrainConfig,
    mesh: Optional[Mesh] = None,
) -> FleetFitResult:
    """Launch the fleet program on a staged bucket; returns immediately.

    The staged buffers are DONATED to the program (freed at their last
    device use); the returned :class:`FleetFitResult` holds device arrays
    and fetches the history lazily — call :meth:`FleetFitResult.collect`
    (or read ``.history``) to block.
    """
    if staged.consumed:
        raise RuntimeError(
            "StagedFleetFit already dispatched: its buffers were donated "
            "to the device program; stage the data again"
        )
    staged.consumed = True
    fitted = _fleet_program(module, cfg, staged.steps, staged.bs, mesh)
    out_params, history = fitted(
        staged.params, staged.X, staged.y, staged.w, staged.fit_keys
    )
    return FleetFitResult(
        params=out_params, n_models=staged.n_models, history=history
    )


def fleet_fit(
    module,
    X: np.ndarray,
    y: np.ndarray,
    w: np.ndarray,
    cfg: TrainConfig,
    seeds: Optional[np.ndarray] = None,
    mesh: Optional[Mesh] = None,
    params: Optional[Any] = None,
) -> FleetFitResult:
    """Train ``M`` instances of ``module`` on stacked data in one dispatch.

    ``X``: (M, N, ...) inputs, ``y``: (M, N, ...) targets, ``w``: (M, N)
    row-validity weights.  With a mesh, the model axis is sharded over the
    mesh's ``"models"`` axis (M is padded up to a multiple of its size with
    zero-weight dummies); rows replicate within a model shard — the ``data``
    mesh axis serves :func:`fit_data_parallel` instead.

    This is the blocking convenience wrapper over the pipelined surface:
    :func:`fleet_stage` (async H2D) → :func:`fleet_dispatch` (async
    compute, donated buffers) → :meth:`FleetFitResult.collect`.  Callers
    building many buckets should drive the three stages themselves so
    bucket N+1 stages while bucket N computes.
    """
    staged = fleet_stage(
        module, X, y, w, cfg, seeds=seeds, mesh=mesh, params=params
    )
    return fleet_dispatch(module, staged, cfg, mesh=mesh).collect()


def fleet_apply(
    module,
    params: Any,
    X,
    mesh: Optional[Mesh] = None,
) -> jnp.ndarray:
    """vmapped forward pass: stacked params (M, ...) x inputs (M, N, ...)."""
    from gordo_tpu import compile as compile_plane

    key = ("fleet.apply", module, mesh)

    def build():
        vapply = jax.vmap(lambda p, x: module.apply({"params": p}, x))
        if mesh is not None:
            ms = model_sharding(mesh)
            return compile_plane.jit(
                vapply, name="fleet.apply_sharded",
                in_shardings=(ms, ms), out_shardings=ms,
            )
        return compile_plane.jit(vapply, name="fleet.apply")

    return compile_plane.cached_closure(key, build)(params, jnp.asarray(X))


# ---------------------------------------------------------------------------
# Data-parallel single-model fit (the "data" mesh axis)
# ---------------------------------------------------------------------------

def fit_data_parallel(
    module,
    X: np.ndarray,
    y: np.ndarray,
    cfg: TrainConfig,
    mesh: Mesh,
    rng: Optional[jax.Array] = None,
) -> Tuple[Any, np.ndarray]:
    """Fit ONE model with rows sharded over the mesh ``"data"`` axis.

    For a single larger model (not the fleet case): params replicate, the
    batch axis shards, and XLA's grad all-reduce rides ICI — the TPU-native
    replacement for the `tf.distribute` capability the reference never used
    (SURVEY.md §6.8).
    """
    rng = rng if rng is not None else jax.random.PRNGKey(0)
    X = np.asarray(X, np.float32)
    y = np.asarray(y, np.float32)
    n = X.shape[0]
    steps, bs, n_pad = batch_geometry(n, cfg.batch_size)
    w = np.concatenate([np.ones(n, np.float32), np.zeros(n_pad, np.float32)])
    if n_pad:
        X = np.concatenate([X, np.zeros((n_pad,) + X.shape[1:], X.dtype)])
        y = np.concatenate([y, np.zeros((n_pad,) + y.shape[1:], y.dtype)])

    init_rng, rng = jax.random.split(rng)  # same derivation as train.fit.fit
    params = module.init(init_rng, jnp.asarray(X[:1]))["params"]
    fit_fn = make_fit_fn(module, cfg, steps, bs)

    from gordo_tpu import compile as compile_plane

    rows = data_sharding(mesh)
    repl = replicated_sharding(mesh)
    fitted = compile_plane.cached_closure(
        ("fleet.data_parallel_fit", module, cfg, steps, bs, mesh),
        lambda: compile_plane.jit(
            fit_fn,
            name="fleet.data_parallel_fit",
            in_shardings=(repl, rows, rows, rows, repl),
            out_shardings=(repl, repl),
        ),
    )
    out_params, history = fitted(
        params, jnp.asarray(X), jnp.asarray(y), jnp.asarray(w), rng
    )
    return out_params, np.asarray(history)
