from gordo_tpu.models import factories  # noqa: F401  (registers factories)
from gordo_tpu.models.base import GordoBase  # noqa: F401
