"""Abstract model contract.

Reference equivalent: ``gordo_components/model/base.py::GordoBase`` — every
model must expose ``get_metadata()``, ``score()`` and ``get_params()`` beyond
the fit/predict estimator surface.
"""

from __future__ import annotations

import abc
from typing import Any, Dict, Optional


class GordoBase(abc.ABC):
    @abc.abstractmethod
    def fit(self, X, y=None, **kwargs):
        ...

    @abc.abstractmethod
    def predict(self, X):
        ...

    @abc.abstractmethod
    def get_metadata(self) -> Dict[str, Any]:
        """Build/model metadata dict merged into the machine metadata JSON."""

    @abc.abstractmethod
    def score(self, X, y=None, sample_weight: Optional[Any] = None) -> float:
        ...

    @abc.abstractmethod
    def get_params(self, deep: bool = False) -> Dict[str, Any]:
        ...
