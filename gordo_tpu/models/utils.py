"""Model-output frame assembly.

Reference equivalent: ``gordo_components/model/utils.py::
make_base_dataframe`` — the multi-level-column DataFrame convention shared
by the server views and the anomaly path: top-level keys ``model-input``,
``model-output`` (+ anomaly columns), second level the tag names, with
``start``/``end`` timestamp columns when a time index is known.
"""

from __future__ import annotations

from typing import List, Optional, Union

import numpy as np
import pandas as pd


def make_base_dataframe(
    tags: List[str],
    model_input: np.ndarray,
    model_output: np.ndarray,
    target_tag_list: Optional[List[str]] = None,
    index: Optional[pd.Index] = None,
    frequency: Optional[Union[str, pd.Timedelta]] = None,
) -> pd.DataFrame:
    """Assemble the canonical prediction frame.

    ``model_output`` may be shorter than ``model_input`` (LSTM lookback
    offset); rows are aligned to the *end* of the input, matching the
    reference's truncation convention.
    """
    tags = [str(t) for t in tags]
    out_tags = [str(t) for t in (target_tag_list or tags)]
    n_out = len(model_output)
    offset = len(model_input) - n_out
    model_input = model_input[offset:]

    data = {}
    for i, tag in enumerate(tags):
        data[("model-input", tag)] = np.asarray(model_input)[:, i]
    for i, tag in enumerate(out_tags[: model_output.shape[1]]):
        data[("model-output", tag)] = np.asarray(model_output)[:, i]

    frame = pd.DataFrame(data)
    frame.columns = pd.MultiIndex.from_tuples(frame.columns)

    if index is not None:
        index = pd.Index(index[offset:])
        frame.index = index
        if frequency is not None:
            delta = pd.Timedelta(frequency)
            frame[("start", "")] = index
            frame[("end", "")] = index + delta
    return frame
