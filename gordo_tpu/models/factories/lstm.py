"""LSTM autoencoder/forecast factories as Flax modules.

Reference equivalent:
``gordo_components/model/factories/lstm_autoencoder.py`` — ``lstm_model`` /
``lstm_symmetric`` / ``lstm_hourglass`` over ``(lookback, n_features)``
windows.

TPU-native design: recurrence is expressed with ``flax.linen.RNN`` (which
lowers to ``lax.scan`` — compiler-friendly sequential control flow, no
Python loops in the traced program).  The window axis is short (order 10^2)
so scan latency is fine; throughput comes from batching across windows *and*
across models in the fleet engine.  The head reads the final timestep state
and projects to the output features, matching the reference's 2D
``(batch, n_features)`` output contract.
"""

from __future__ import annotations

from typing import Sequence, Tuple, Union

import flax.linen as nn
import jax.numpy as jnp

from gordo_tpu.models.factories.feedforward import (
    _broadcast_funcs,
    resolve_activation,
    resolve_compute_dtype,
)
from gordo_tpu.models.factories.utils import hourglass_calc_dims
from gordo_tpu.registry import register_model_builder


class LSTMAutoEncoderModule(nn.Module):
    """Stacked LSTM layers over the window, final-step dense head.

    Recurrent compute runs in ``compute_dtype`` (bfloat16 by default —
    MXU-native, same mixed-precision scheme as the feedforward modules)
    with float32 params and a float32 output head.
    """

    dims: Tuple[int, ...]
    funcs: Tuple[Union[str], ...]
    out_dim: int
    out_func: str = "linear"
    #: class default is float32 — NOT bf16 — so artifacts pickled before
    #: this field existed unpickle to exactly the numerics they trained and
    #: calibrated thresholds with; factories always pass a resolved value
    compute_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        # x: (batch, lookback, n_features)
        squeeze = x.ndim == 2
        if squeeze:  # single window
            x = x[None]
        x = x.astype(self.compute_dtype)
        for i, (d, f) in enumerate(zip(self.dims, self.funcs)):
            x = nn.RNN(
                nn.OptimizedLSTMCell(int(d), dtype=self.compute_dtype),
                name=f"lstm_{i}",
            )(x)
            x = resolve_activation(f)(x)
        out = nn.Dense(self.out_dim, dtype=jnp.float32, name="out")(
            x[:, -1, :].astype(jnp.float32)
        )
        out = resolve_activation(self.out_func)(out)
        return out[0] if squeeze else out


@register_model_builder(type="LSTMAutoEncoder")
def lstm_model(
    n_features: int,
    n_features_out: int = None,
    lookback_window: int = 1,
    encoding_dim: Sequence[int] = (256, 128, 64),
    encoding_func: Sequence[str] = None,
    decoding_dim: Sequence[int] = (64, 128, 256),
    decoding_func: Sequence[str] = None,
    out_func: str = "linear",
    compute_dtype: str = "auto",
    **_ignored,
) -> nn.Module:
    """Encoder/decoder LSTM stack (reference: ``lstm_autoencoder.lstm_model``).

    ``lookback_window`` is consumed by the estimator for windowing; the module
    itself handles any window length (scan over time axis).
    ``compute_dtype="float32"`` opts out of mixed precision.
    """
    n_features_out = n_features_out or n_features
    enc = tuple(int(d) for d in encoding_dim)
    dec = tuple(int(d) for d in decoding_dim)
    funcs = _broadcast_funcs(encoding_func, len(enc)) + _broadcast_funcs(
        decoding_func, len(dec)
    )
    return LSTMAutoEncoderModule(
        dims=enc + dec,
        funcs=funcs,
        out_dim=int(n_features_out),
        out_func=out_func,
        compute_dtype=resolve_compute_dtype(compute_dtype),
    )


@register_model_builder(type="LSTMAutoEncoder")
def lstm_symmetric(
    n_features: int,
    n_features_out: int = None,
    lookback_window: int = 1,
    dims: Sequence[int] = (256, 128, 64),
    funcs: Sequence[str] = None,
    **kwargs,
) -> nn.Module:
    """Symmetric LSTM AE (reference: ``lstm_symmetric``)."""
    if not dims:
        raise ValueError("dims must be non-empty")
    dims = tuple(int(d) for d in dims)
    funcs = _broadcast_funcs(funcs, len(dims))
    return lstm_model(
        n_features,
        n_features_out,
        lookback_window=lookback_window,
        encoding_dim=dims,
        encoding_func=funcs,
        decoding_dim=dims[::-1],
        decoding_func=funcs[::-1],
        **kwargs,
    )


@register_model_builder(type="LSTMAutoEncoder")
def lstm_hourglass(
    n_features: int,
    n_features_out: int = None,
    lookback_window: int = 1,
    encoding_layers: int = 3,
    compression_factor: float = 0.5,
    func: str = "tanh",
    **kwargs,
) -> nn.Module:
    """Tapered LSTM AE (reference: ``lstm_autoencoder.lstm_hourglass``)."""
    dims = hourglass_calc_dims(compression_factor, encoding_layers, n_features)
    return lstm_symmetric(
        n_features,
        n_features_out,
        lookback_window=lookback_window,
        dims=dims,
        funcs=[func] * len(dims),
        **kwargs,
    )
