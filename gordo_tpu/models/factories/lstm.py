"""LSTM autoencoder/forecast factories as Flax modules.

Reference equivalent:
``gordo_components/model/factories/lstm_autoencoder.py`` — ``lstm_model`` /
``lstm_symmetric`` / ``lstm_hourglass`` over ``(lookback, n_features)``
windows.

TPU-native design: recurrence is expressed with ``flax.linen.RNN`` (which
lowers to ``lax.scan`` — compiler-friendly sequential control flow, no
Python loops in the traced program).  The window axis is short (order 10^2)
so scan latency is fine; throughput comes from batching across windows *and*
across models in the fleet engine.  The head reads the final timestep state
and projects to the output features, matching the reference's 2D
``(batch, n_features)`` output contract.
"""

from __future__ import annotations

from typing import Any, Sequence, Tuple, Union

import flax.linen as nn
import jax
import jax.numpy as jnp

from gordo_tpu.models.factories.feedforward import (
    _broadcast_funcs,
    resolve_activation,
    resolve_compute_dtype,
)
from gordo_tpu.models.factories.utils import hourglass_calc_dims
from gordo_tpu.registry import register_model_builder


class _GateParams(nn.Module):
    """One gate's Dense parameters, never applied directly.

    Mirrors ``flax.linen.recurrent.DenseParams`` so the param tree under an
    ``OptimizedLSTMCell_{k}`` scope is bit-compatible with artifacts trained
    on the flax cell (same names, shapes, initializers, and path-derived
    init RNG)."""

    features: int
    use_bias: bool
    kernel_init: Any

    @nn.compact
    def __call__(self, in_features: int):
        kernel = self.param(
            "kernel", self.kernel_init, (in_features, self.features),
            jnp.float32,
        )
        bias = (
            self.param(
                "bias", nn.initializers.zeros_init(), (self.features,),
                jnp.float32,
            )
            if self.use_bias
            else None
        )
        return kernel, bias


class _FusedLSTMCellParams(nn.Module):
    """Owns one LSTM layer's params under the exact OptimizedLSTMCell tree
    (gates concatenated in flax's ``i, f, g, o`` order)."""

    features: int

    @nn.compact
    def __call__(self, in_features: int):
        ks_i, ks_h, biases = [], [], []
        for c in "ifgo":
            k, _ = _GateParams(
                self.features, False, nn.initializers.lecun_normal(),
                name=f"i{c}",
            )(in_features)
            ks_i.append(k)
        for c in "ifgo":
            k, b = _GateParams(
                self.features, True, nn.initializers.orthogonal(),
                name=f"h{c}",
            )(self.features)
            ks_h.append(k)
            biases.append(b)
        return (
            jnp.concatenate(ks_i, axis=-1),   # (in, 4H)
            jnp.concatenate(ks_h, axis=-1),   # (H, 4H)
            jnp.concatenate(biases, axis=-1),  # (4H,)
        )


def _fused_lstm_layer(
    x: jnp.ndarray,
    kernel_i: jnp.ndarray,
    kernel_h: jnp.ndarray,
    bias: jnp.ndarray,
    features: int,
    compute_dtype,
) -> jnp.ndarray:
    """LSTM layer with the input projection hoisted OUT of the recurrence.

    ``nn.RNN(OptimizedLSTMCell)`` recomputes ``x_t @ W_i`` inside every scan
    step: T small ``(B, F) @ (F, 4H)`` matmuls that can't fill the MXU.
    Here all T input projections run as ONE ``(B·T, F) @ (F, 4H)`` GEMM
    before the scan (under the fleet vmap: a batched GEMM over machines —
    the MXU-shaped form), and each scan step only pays the unavoidable
    recurrent ``(B, H) @ (H, 4H)``.

    Step math mirrors ``OptimizedLSTMCell`` exactly (same concat order,
    same dtype promotion: gates in ``compute_dtype``, carries promoted to
    float32 by the elementwise ops), so results match the flax cell.
    """
    cd = compute_dtype
    xp = x.astype(cd) @ kernel_i.astype(cd)         # (B, T, 4H), one GEMM
    kernel_h = kernel_h.astype(cd)
    bias = bias.astype(cd)
    batch = x.shape[0]
    c0 = jnp.zeros((batch, features), jnp.float32)  # flax carries are f32
    h0 = jnp.zeros((batch, features), jnp.float32)

    def step(carry, xp_t):
        c, h = carry
        z = (h.astype(cd) @ kernel_h + bias) + xp_t  # dense_h + dense_i
        i, f, g, o = jnp.split(z, 4, axis=-1)
        i, f, o = nn.sigmoid(i), nn.sigmoid(f), nn.sigmoid(o)
        g = nn.tanh(g)
        c = f * c + i * g          # promotes to f32 against the f32 carry
        h = o * jnp.tanh(c)
        return (c, h), h

    # plain scan, no unroll: measured on the fleet build (8-machine CPU
    # sweep), unroll=4 was ~20% SLOWER warm and slower to compile — the
    # step body is already one fused matmul + elementwise
    _, hs = jax.lax.scan(step, (c0, h0), jnp.swapaxes(xp, 0, 1))
    return jnp.swapaxes(hs, 0, 1)                   # (B, T, H)


class LSTMAutoEncoderModule(nn.Module):
    """Stacked LSTM layers over the window, final-step dense head.

    Recurrent compute runs in ``compute_dtype`` (bfloat16 by default —
    MXU-native, same mixed-precision scheme as the feedforward modules)
    with float32 params and a float32 output head.  The recurrence is the
    fused scan of :func:`_fused_lstm_layer`; its param tree is identical to
    the ``nn.RNN(OptimizedLSTMCell)`` stack it replaced, so pre-existing
    artifacts load unchanged.
    """

    dims: Tuple[int, ...]
    funcs: Tuple[Union[str], ...]
    out_dim: int
    out_func: str = "linear"
    #: class default is float32 — NOT bf16 — so artifacts pickled before
    #: this field existed unpickle to exactly the numerics they trained and
    #: calibrated thresholds with; factories always pass a resolved value
    compute_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        # x: (batch, lookback, n_features)
        squeeze = x.ndim == 2
        if squeeze:  # single window
            x = x[None]
        x = x.astype(self.compute_dtype)
        for i, (d, f) in enumerate(zip(self.dims, self.funcs)):
            d = int(d)
            ki, kh, b = _FusedLSTMCellParams(
                d, name=f"OptimizedLSTMCell_{i}"
            )(x.shape[-1])
            x = _fused_lstm_layer(x, ki, kh, b, d, self.compute_dtype)
            x = resolve_activation(f)(x)
        out = nn.Dense(self.out_dim, dtype=jnp.float32, name="out")(
            x[:, -1, :].astype(jnp.float32)
        )
        out = resolve_activation(self.out_func)(out)
        return out[0] if squeeze else out


@register_model_builder(type="LSTMAutoEncoder")
def lstm_model(
    n_features: int,
    n_features_out: int = None,
    lookback_window: int = 1,
    encoding_dim: Sequence[int] = (256, 128, 64),
    encoding_func: Sequence[str] = None,
    decoding_dim: Sequence[int] = (64, 128, 256),
    decoding_func: Sequence[str] = None,
    out_func: str = "linear",
    compute_dtype: str = "auto",
    **_ignored,
) -> nn.Module:
    """Encoder/decoder LSTM stack (reference: ``lstm_autoencoder.lstm_model``).

    ``lookback_window`` is consumed by the estimator for windowing; the module
    itself handles any window length (scan over time axis).
    ``compute_dtype="float32"`` opts out of mixed precision.
    """
    n_features_out = n_features_out or n_features
    enc = tuple(int(d) for d in encoding_dim)
    dec = tuple(int(d) for d in decoding_dim)
    funcs = _broadcast_funcs(encoding_func, len(enc)) + _broadcast_funcs(
        decoding_func, len(dec)
    )
    return LSTMAutoEncoderModule(
        dims=enc + dec,
        funcs=funcs,
        out_dim=int(n_features_out),
        out_func=out_func,
        compute_dtype=resolve_compute_dtype(compute_dtype),
    )


@register_model_builder(type="LSTMAutoEncoder")
def lstm_symmetric(
    n_features: int,
    n_features_out: int = None,
    lookback_window: int = 1,
    dims: Sequence[int] = (256, 128, 64),
    funcs: Sequence[str] = None,
    **kwargs,
) -> nn.Module:
    """Symmetric LSTM AE (reference: ``lstm_symmetric``)."""
    if not dims:
        raise ValueError("dims must be non-empty")
    dims = tuple(int(d) for d in dims)
    funcs = _broadcast_funcs(funcs, len(dims))
    return lstm_model(
        n_features,
        n_features_out,
        lookback_window=lookback_window,
        encoding_dim=dims,
        encoding_func=funcs,
        decoding_dim=dims[::-1],
        decoding_func=funcs[::-1],
        **kwargs,
    )


@register_model_builder(type="LSTMAutoEncoder")
def lstm_hourglass(
    n_features: int,
    n_features_out: int = None,
    lookback_window: int = 1,
    encoding_layers: int = 3,
    compression_factor: float = 0.5,
    func: str = "tanh",
    **kwargs,
) -> nn.Module:
    """Tapered LSTM AE (reference: ``lstm_autoencoder.lstm_hourglass``)."""
    dims = hourglass_calc_dims(compression_factor, encoding_layers, n_features)
    return lstm_symmetric(
        n_features,
        n_features_out,
        lookback_window=lookback_window,
        dims=dims,
        funcs=[func] * len(dims),
        **kwargs,
    )
