from gordo_tpu.models.factories.feedforward import (  # noqa: F401
    feedforward_hourglass,
    feedforward_model,
    feedforward_symmetric,
)
from gordo_tpu.models.factories.lstm import (  # noqa: F401
    lstm_hourglass,
    lstm_model,
    lstm_symmetric,
)
from gordo_tpu.models.factories.utils import hourglass_calc_dims  # noqa: F401
