"""Feedforward autoencoder factories as Flax modules.

Reference equivalent:
``gordo_components/model/factories/feedforward_autoencoder.py`` —
``feedforward_model`` / ``feedforward_symmetric`` / ``feedforward_hourglass``
returning compiled Keras ``Sequential`` models.  Here each factory returns a
Flax ``nn.Module``; optimizer/loss selection lives in the estimator's train
config (``gordo_tpu.train.fit``), not baked into the network, because under
XLA the whole fit loop is one compiled program anyway.

MXU note: these nets are tiny (feature counts in the tens).  Single-model
matmuls cannot fill the 128x128 systolic array — throughput comes from the
fleet engine vmapping thousands of such models into one batched matmul
(``gordo_tpu.parallel.fleet``), which these modules are shaped for: pure
dense stacks, static shapes, no data-dependent control flow.
"""

from __future__ import annotations

from typing import Callable, Sequence, Tuple, Union

import flax.linen as nn
import jax.numpy as jnp

from gordo_tpu.models.factories.utils import hourglass_calc_dims
from gordo_tpu.registry import register_model_builder

ACTIVATIONS = {
    "tanh": nn.tanh,
    "relu": nn.relu,
    "sigmoid": nn.sigmoid,
    "elu": nn.elu,
    "selu": nn.selu,
    "softplus": nn.softplus,
    "leaky_relu": nn.leaky_relu,
    "gelu": nn.gelu,
    "linear": lambda x: x,
    None: lambda x: x,
}


def resolve_activation(name: Union[str, Callable, None]) -> Callable:
    if callable(name):
        return name
    try:
        return ACTIVATIONS[name]
    except KeyError:
        raise ValueError(
            f"Unknown activation {name!r}; available: {sorted(k for k in ACTIVATIONS if isinstance(k, str))}"
        )


def _broadcast_funcs(funcs, n: int) -> Tuple:
    if funcs is None:
        funcs = "tanh"
    if isinstance(funcs, (str,)) or callable(funcs):
        return tuple([funcs] * n)
    funcs = tuple(funcs)
    if len(funcs) != n:
        raise ValueError(f"Got {len(funcs)} activation funcs for {n} layers")
    return funcs


def resolve_compute_dtype(compute_dtype) -> jnp.dtype:
    """``"auto"`` → bfloat16 on TPU (MXU-native), float32 elsewhere (XLA
    CPU emulates bf16 ~3× slower — measured on the LSTM fleet build);
    concrete dtype names pass through for explicit control."""
    if compute_dtype == "auto":
        import jax

        return jnp.dtype(
            jnp.bfloat16 if jax.default_backend() == "tpu" else jnp.float32
        )
    return jnp.dtype(compute_dtype)


class FeedForwardAutoEncoder(nn.Module):
    """Dense stack: encoder dims -> decoder dims -> linear-ish output head.

    Hidden compute runs in ``compute_dtype`` (bfloat16 by default on TPU —
    MXU-native) with float32 params and a float32 output head.
    """

    dims: Tuple[int, ...]
    funcs: Tuple[Union[str, Callable], ...]
    out_dim: int
    out_func: Union[str, Callable, None] = "linear"
    #: class default is float32 — NOT bf16 — so artifacts pickled before
    #: this field existed unpickle to exactly the numerics they trained and
    #: calibrated thresholds with; factories always pass a resolved value
    compute_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        x = x.astype(self.compute_dtype)
        for i, (d, f) in enumerate(zip(self.dims, self.funcs)):
            x = nn.Dense(d, dtype=self.compute_dtype, name=f"dense_{i}")(x)
            x = resolve_activation(f)(x)
        x = nn.Dense(self.out_dim, dtype=jnp.float32, name="out")(x)
        return resolve_activation(self.out_func)(x.astype(jnp.float32))


@register_model_builder(type="AutoEncoder")
def feedforward_model(
    n_features: int,
    n_features_out: int = None,
    encoding_dim: Sequence[int] = (256, 128, 64),
    encoding_func: Sequence[str] = None,
    decoding_dim: Sequence[int] = (64, 128, 256),
    decoding_func: Sequence[str] = None,
    out_func: str = "linear",
    compute_dtype: str = "auto",
    **_ignored,
) -> nn.Module:
    """Fully parameterised encoder/decoder AE (reference:
    ``feedforward_autoencoder.feedforward_model``)."""
    n_features_out = n_features_out or n_features
    enc = tuple(int(d) for d in encoding_dim)
    dec = tuple(int(d) for d in decoding_dim)
    funcs = _broadcast_funcs(encoding_func, len(enc)) + _broadcast_funcs(
        decoding_func, len(dec)
    )
    return FeedForwardAutoEncoder(
        dims=enc + dec,
        funcs=funcs,
        out_dim=int(n_features_out),
        out_func=out_func,
        compute_dtype=resolve_compute_dtype(compute_dtype),
    )


@register_model_builder(type="AutoEncoder")
def feedforward_symmetric(
    n_features: int,
    n_features_out: int = None,
    dims: Sequence[int] = (256, 128, 64),
    funcs: Sequence[str] = None,
    **kwargs,
) -> nn.Module:
    """Symmetric AE: encoder ``dims``, decoder reversed (reference:
    ``feedforward_symmetric``)."""
    if not dims:
        raise ValueError("dims must be non-empty")
    dims = tuple(int(d) for d in dims)
    funcs = _broadcast_funcs(funcs, len(dims))
    return feedforward_model(
        n_features,
        n_features_out,
        encoding_dim=dims,
        encoding_func=funcs,
        decoding_dim=dims[::-1],
        decoding_func=funcs[::-1],
        **kwargs,
    )


@register_model_builder(type="AutoEncoder")
def feedforward_hourglass(
    n_features: int,
    n_features_out: int = None,
    encoding_layers: int = 3,
    compression_factor: float = 0.5,
    func: str = "tanh",
    **kwargs,
) -> nn.Module:
    """Geometrically tapered hourglass AE — the reference's default model
    (reference: ``feedforward_autoencoder.feedforward_hourglass``)."""
    dims = hourglass_calc_dims(compression_factor, encoding_layers, n_features)
    return feedforward_symmetric(
        n_features, n_features_out, dims=dims, funcs=[func] * len(dims), **kwargs
    )
