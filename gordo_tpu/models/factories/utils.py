"""Factory helpers.

Reference equivalent: ``gordo_components/model/factories/utils.py`` —
hourglass dimension computation shared by the feedforward and LSTM
hourglass factories.
"""

from __future__ import annotations

from typing import List


def hourglass_calc_dims(compression_factor: float, encoding_layers: int,
                        n_features: int) -> List[int]:
    """Layer sizes tapering linearly from ``n_features`` down to
    ``n_features * compression_factor`` over ``encoding_layers`` steps
    (reference semantics: evenly-sloped taper, smallest layer >= 1)."""
    if not (0 <= compression_factor <= 1):
        raise ValueError("compression_factor must be in [0, 1]")
    if encoding_layers < 1:
        raise ValueError("encoding_layers must be >= 1")
    smallest = max(min(round(n_features * compression_factor), n_features), 1)
    slope = (n_features - smallest) / encoding_layers
    dims = [round(n_features - i * slope) for i in range(1, encoding_layers + 1)]
    return [max(int(d), 1) for d in dims]
