"""sklearn-contract estimators wrapping Flax modules.

Reference equivalent: ``gordo_components/model/models.py`` —
``KerasBaseEstimator`` / ``KerasAutoEncoder`` / ``KerasLSTMAutoEncoder`` /
``KerasLSTMForecast``.  Same contract: construct with ``kind=<registered
factory name>`` plus kwargs; the network is built from ``X.shape`` at fit
time; fit/predict/score/get_params/get_metadata like any sklearn estimator;
pickling carries host-side weights (reference used HDF5-bytes
``__getstate__``; here params are a host numpy pytree).

TPU-native: fit is one jitted XLA program (``gordo_tpu.train.fit``),
predict is a jitted apply.  The estimator exposes its pure pieces
(``module_``, ``params_``) so the fleet engine and the serving scorer can
batch many estimators into single device programs.
"""

from __future__ import annotations

import functools
import time
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from gordo_tpu.models.base import GordoBase
from gordo_tpu.ops.scalers import as_float2d
from gordo_tpu.ops.metrics import explained_variance_score
from gordo_tpu.ops.windows import make_windows
from gordo_tpu.registry import lookup_factory
from gordo_tpu.train.fit import TrainConfig, fit as fit_model
from gordo_tpu.utils.args import ParamsMixin, capture_args
from gordo_tpu.utils.trees import param_count, to_host


@functools.lru_cache(maxsize=256)
def _predict_jit_for(module):
    """One jitted apply per structurally-distinct module (flax modules are
    frozen dataclasses: equal factory output hashes equal)."""
    from gordo_tpu import compile as compile_plane

    return compile_plane.jit(module.apply, name="estimator.predict")


class BaseJaxEstimator(ParamsMixin, GordoBase):
    """Common machinery; subclasses define windowing/targets."""

    model_type = "AutoEncoder"  # factory-registry type to resolve `kind` in

    @capture_args
    def __init__(self, kind: str = "feedforward_hourglass", **kwargs):
        self.kind = kind
        self.kwargs = kwargs
        self.params_: Optional[Any] = None
        self.module_: Optional[Any] = None
        self.history_: Optional[np.ndarray] = None
        self.fit_seconds_: Optional[float] = None
        self._predict_jit = None
        self._factory_kwargs_built: Dict[str, Any] = {}

    # -- windowing hooks -----------------------------------------------------
    #: rows of the input consumed before the first prediction row
    offset = 0

    def _make_inputs(self, X: jnp.ndarray) -> jnp.ndarray:
        return X

    def _make_targets(self, X: jnp.ndarray, y: Optional[jnp.ndarray]) -> jnp.ndarray:
        return X if y is None else y

    # -- estimator surface ---------------------------------------------------
    def fit(self, X, y=None, **fit_kwargs):
        t0 = time.time()
        X = as_float2d(X)
        y_arr = None if y is None else as_float2d(y)

        merged = {**self.kwargs, **fit_kwargs}
        checkpoint_dir = merged.pop("checkpoint_dir", None)
        checkpoint_every = int(merged.pop("checkpoint_every", 10) or 10)
        cfg, factory_kwargs = TrainConfig.from_kwargs(merged)
        inputs = self._make_inputs(X)
        targets = self._make_targets(X, y_arr)

        factory = lookup_factory(self.model_type, self.kind)
        built_kwargs = dict(
            n_features=int(X.shape[1]),
            n_features_out=int(targets.shape[-1]),
            **factory_kwargs,
        )
        self.module_ = factory(**built_kwargs)
        self._factory_kwargs_built = built_kwargs
        self._train_cfg = cfg

        seed = int(factory_kwargs.get("seed", 0) or 0)
        if checkpoint_dir:
            # mid-fit checkpoint/resume for long fits (SURVEY.md §6.4)
            from gordo_tpu.train.checkpoint import fit_checkpointed

            params, history = fit_checkpointed(
                self.module_, inputs, targets, cfg,
                ckpt_dir=checkpoint_dir,
                checkpoint_every=checkpoint_every,
                rng=jax.random.PRNGKey(seed),
            )
        else:
            params, history = fit_model(
                self.module_, inputs, targets, cfg, rng=jax.random.PRNGKey(seed)
            )
        self.params_ = params
        self.history_ = np.asarray(history)
        self._predict_jit = None
        self.fit_seconds_ = time.time() - t0
        return self

    def _rebuild_module(self):
        factory = lookup_factory(self.model_type, self.kind)
        self.module_ = factory(**self._factory_kwargs_built)

    def predict(self, X) -> np.ndarray:
        if self.params_ is None:
            raise RuntimeError(f"{type(self).__name__} is not fitted")
        if self.module_ is None:
            self._rebuild_module()
        inputs = self._make_inputs(as_float2d(X))
        if self._predict_jit is None:
            # shared across instances, keyed on the (hashable, structurally
            # equal) flax module — same reasoning as _fit_jit: a fleet of
            # same-architecture estimators must hit ONE traced program, not
            # re-trace and re-compile per instance (the Nth identical
            # XLA:CPU recompile also segfaulted jax 0.9 under accumulated
            # compile state)
            self._predict_jit = _predict_jit_for(self.module_)
        return np.asarray(self._predict_jit({"params": self.params_}, inputs))

    def score(self, X, y=None, sample_weight=None) -> float:
        """Explained variance of the model's output vs its targets
        (reference: ``KerasAutoEncoder.score``)."""
        X = as_float2d(X)
        y_arr = None if y is None else as_float2d(y)
        targets = self._make_targets(X, y_arr)
        pred = self.predict(X)
        return float(explained_variance_score(targets, pred))

    def get_metadata(self) -> Dict[str, Any]:
        meta: Dict[str, Any] = {
            "model_type": type(self).__name__,
            "kind": self.kind,
            "parameters": {**self.kwargs},
        }
        if self.params_ is not None:
            meta.update(
                {
                    "num_params": param_count(self.params_),
                    "fit_seconds": self.fit_seconds_,
                    "history": {
                        "loss": [
                            float(v)
                            for v in ([] if self.history_ is None else self.history_)
                        ],
                    },
                }
            )
        return meta

    # -- pickling (device-independent artifacts) ----------------------------
    def __getstate__(self):
        state = dict(self.__dict__)
        state["params_"] = to_host(state.get("params_"))
        state["module_"] = None  # rebuilt from factory on demand
        state["_predict_jit"] = None
        return state

    def __setstate__(self, state):
        self.__dict__.update(state)


class AutoEncoder(BaseJaxEstimator):
    """Feedforward reconstruction AE (reference: ``KerasAutoEncoder``).

    Target is the estimator's own (already pipeline-transformed) input;
    score is explained variance of the reconstruction.
    """

    model_type = "AutoEncoder"


class LSTMAutoEncoder(BaseJaxEstimator):
    """Windowed LSTM reconstruction (reference: ``KerasLSTMAutoEncoder``).

    Windows X into ``lookback_window``-length subsequences on device; the
    model reconstructs the window's final timestep, so predictions start at
    row ``lookback_window - 1`` of the input (``offset``).
    """

    model_type = "LSTMAutoEncoder"

    def __init__(self, kind: str = "lstm_hourglass", **kwargs):
        super().__init__(kind=kind, **kwargs)

    @property
    def lookback_window(self) -> int:
        return int(self.kwargs.get("lookback_window", 1))

    @property
    def offset(self) -> int:
        return self.lookback_window - 1

    def _make_inputs(self, X):
        return make_windows(X, self.lookback_window)

    def _make_targets(self, X, y):
        base = X if y is None else y
        return base[self.lookback_window - 1:]


class LSTMForecast(LSTMAutoEncoder):
    """Windowed LSTM one-step-ahead forecast (reference:
    ``KerasLSTMForecast``): window ``X[t-L:t]`` predicts ``X[t]``, so
    predictions start at row ``lookback_window`` of the input."""

    @property
    def offset(self) -> int:
        return self.lookback_window

    def _make_inputs(self, X):
        return make_windows(X[:-1], self.lookback_window)

    def _make_targets(self, X, y):
        base = X if y is None else y
        return base[self.lookback_window:]


# Parity aliases (reference class names).
KerasAutoEncoder = AutoEncoder
KerasLSTMAutoEncoder = LSTMAutoEncoder
KerasLSTMForecast = LSTMForecast
