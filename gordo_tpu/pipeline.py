"""Functional pipeline containers.

Reference equivalent: the sklearn containers gordo-components composes via
``serializer.pipeline_from_definition`` — ``sklearn.pipeline.Pipeline``,
``FeatureUnion``, ``sklearn.compose.TransformedTargetRegressor``,
``sklearn.multioutput.MultiOutputRegressor`` (aliased onto these classes by
the definition interpreter).

Same fit/transform/predict contract; the implementation difference is that
transforms here are stats+pure-function objects (``gordo_tpu.ops.scalers``)
whose application can be folded into jitted device programs by the serving
scorer and fleet engine rather than executed step-by-step through host numpy.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

import numpy as np

from gordo_tpu.utils.args import ParamsMixin, capture_args

StepLike = Union[Any, Tuple[str, Any], List]


def _normalize_steps(steps: Sequence[StepLike]) -> List[Tuple[str, Any]]:
    normalized = []
    for i, step in enumerate(steps):
        if isinstance(step, (tuple, list)) and len(step) == 2 and isinstance(step[0], str):
            normalized.append((step[0], step[1]))
        else:
            normalized.append((f"step_{i}", step))
    return normalized


class Pipeline(ParamsMixin):
    """Sequential transform chain ending in an estimator (or not)."""

    @capture_args
    def __init__(self, steps: Sequence[StepLike], memory: Optional[str] = None):
        self.steps = _normalize_steps(steps)
        self.memory = memory

    # -- helpers -------------------------------------------------------------
    @property
    def named_steps(self) -> Dict[str, Any]:
        return dict(self.steps)

    def __getitem__(self, idx):
        return self.steps[idx][1]

    @property
    def _final(self) -> Any:
        return self.steps[-1][1]

    @property
    def offset(self) -> int:
        """Input rows consumed before the first prediction row (LSTM lookback)."""
        return getattr(self._final, "offset", 0)

    def _transform_until_final(self, X):
        for _, step in self.steps[:-1]:
            X = step.transform(X)
        return X

    # -- sklearn-contract surface -------------------------------------------
    def fit(self, X, y=None, **fit_kwargs):
        for _, step in self.steps[:-1]:
            X = step.fit_transform(X, y)
        if hasattr(self._final, "fit"):
            self._final.fit(X, y, **fit_kwargs)
        return self

    def transform(self, X):
        X = self._transform_until_final(X)
        final = self._final
        if hasattr(final, "transform"):
            X = final.transform(X)
        return X

    def fit_transform(self, X, y=None):
        return self.fit(X, y).transform(X)

    def predict(self, X):
        X = self._transform_until_final(X)
        return self._final.predict(X)

    def inverse_transform(self, X):
        for _, step in reversed(self.steps):
            if hasattr(step, "inverse_transform"):
                X = step.inverse_transform(X)
        return X

    def score(self, X, y=None, sample_weight=None):
        Xt = self._transform_until_final(X)
        return self._final.score(Xt, y, sample_weight)

    def get_metadata(self) -> Dict[str, Any]:
        final = self._final
        if hasattr(final, "get_metadata"):
            return final.get_metadata()
        return {}

    def get_params(self, deep: bool = False):
        # Preserve custom step names through definition round-trips; emit the
        # reference's bare-object form when names are the auto-generated ones.
        if all(name == f"step_{i}" for i, (name, _) in enumerate(self.steps)):
            return {"steps": [obj for _, obj in self.steps]}
        return {"steps": [[name, obj] for name, obj in self.steps]}


class FeatureUnion(ParamsMixin):
    """Concatenate multiple transformers' outputs along the feature axis."""

    @capture_args
    def __init__(self, transformer_list: Sequence[StepLike], n_jobs: Optional[int] = None):
        self.transformer_list = _normalize_steps(transformer_list)

    def fit(self, X, y=None):
        for _, t in self.transformer_list:
            t.fit(X, y)
        return self

    def transform(self, X):
        outs = [t.transform(X) for _, t in self.transformer_list]
        return np.concatenate([np.asarray(o) for o in outs], axis=1)

    def fit_transform(self, X, y=None):
        return self.fit(X, y).transform(X)

    def get_params(self, deep: bool = False):
        if all(
            name == f"step_{i}"
            for i, (name, _) in enumerate(self.transformer_list)
        ):
            return {"transformer_list": [obj for _, obj in self.transformer_list]}
        return {"transformer_list": [[name, obj] for name, obj in self.transformer_list]}


class TransformedTargetRegressor(ParamsMixin):
    """Fit the regressor on transformed targets; predict in original units."""

    @capture_args
    def __init__(self, regressor=None, transformer=None):
        self.regressor = regressor
        self.transformer = transformer

    @property
    def offset(self) -> int:
        return getattr(self.regressor, "offset", 0)

    def fit(self, X, y=None, **fit_kwargs):
        y = np.asarray(X if y is None else y, dtype=np.float32)
        if self.transformer is not None:
            y_t = self.transformer.fit_transform(y)
        else:
            y_t = y
        self.regressor.fit(X, y_t, **fit_kwargs)
        return self

    def predict(self, X):
        pred = self.regressor.predict(X)
        if self.transformer is not None:
            pred = self.transformer.inverse_transform(pred)
        return np.asarray(pred)

    def score(self, X, y=None, sample_weight=None):
        from gordo_tpu.ops.metrics import explained_variance_score

        y = np.asarray(X if y is None else y, dtype=np.float32)
        pred = self.predict(X)
        offset = self.offset
        return float(explained_variance_score(y[offset:], pred))

    def get_metadata(self):
        if hasattr(self.regressor, "get_metadata"):
            return self.regressor.get_metadata()
        return {}


class MultiOutputRegressor(ParamsMixin):
    """One cloned estimator per output column."""

    @capture_args
    def __init__(self, estimator=None, n_jobs: Optional[int] = None):
        self.estimator = estimator
        self.estimators_: List[Any] = []

    @property
    def offset(self) -> int:
        if self.estimators_:
            return max(getattr(e, "offset", 0) for e in self.estimators_)
        return getattr(self.estimator, "offset", 0)

    def fit(self, X, y=None, **fit_kwargs):
        import copy

        y = np.asarray(X if y is None else y, dtype=np.float32)
        if y.ndim == 1:
            y = y[:, None]
        self.estimators_ = []
        for col in range(y.shape[1]):
            est = (
                self.estimator.clone()
                if hasattr(self.estimator, "clone")
                else copy.deepcopy(self.estimator)
            )
            est.fit(X, y[:, col:col + 1], **fit_kwargs)
            self.estimators_.append(est)
        return self

    def predict(self, X):
        # Sub-estimators with a lookback offset return fewer rows than
        # len(X); keep their own row count and column-stack.
        preds = [np.asarray(e.predict(X)) for e in self.estimators_]
        preds = [p.reshape(len(p), -1) for p in preds]
        return np.concatenate(preds, axis=1)

    def get_metadata(self):
        if self.estimators_ and hasattr(self.estimators_[0], "get_metadata"):
            return {"per_output": [e.get_metadata() for e in self.estimators_]}
        return {}
