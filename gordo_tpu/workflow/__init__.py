"""Project-config normalization and execution-plan generation.

Reference equivalent: ``gordo_components/workflow/`` — the layer that turns
a project YAML (``machines:`` + ``globals:``) into per-machine build specs
and an orchestration document (there: a Jinja2-rendered Argo ``Workflow``
fanning out one builder pod per machine; here: a TPU fleet execution plan,
with the Argo/Kubernetes YAML still emittable for cluster parity).
"""

from gordo_tpu.workflow.config import (
    DEFAULT_MODEL,
    Machine,
    NormalizedConfig,
    load_machine_config,
)
from gordo_tpu.workflow.generator import (
    build_plan,
    generate_workflow,
    unique_tags,
    workflow_to_yaml,
)

__all__ = [
    "DEFAULT_MODEL",
    "Machine",
    "NormalizedConfig",
    "load_machine_config",
    "build_plan",
    "generate_workflow",
    "unique_tags",
    "workflow_to_yaml",
]
