"""Project YAML → normalized machine list.

Reference equivalent:
``gordo_components/workflow/config_elements/normalized_config.py`` (+
``machine.py``): parse ``machines:``, overlay ``globals:`` onto per-machine
entries over the built-in defaults, inject the default model (scaler +
hourglass autoencoder wrapped in a DiffBasedAnomalyDetector), and enforce
DNS-safe machine names (machine names become k8s service names downstream).
"""

from __future__ import annotations

import copy
import re
from typing import Any, Dict, List, Optional, Union

import yaml

#: the reference's default machine model, in this framework's dotted paths
#: (reference-era sklearn/gordo_components paths also work via ALIASES).
DEFAULT_MODEL: Dict[str, Any] = {
    "gordo_tpu.anomaly.diff.DiffBasedAnomalyDetector": {
        "base_estimator": {
            "gordo_tpu.pipeline.Pipeline": {
                "steps": [
                    "gordo_tpu.ops.scalers.MinMaxScaler",
                    {
                        "gordo_tpu.models.estimator.AutoEncoder": {
                            "kind": "feedforward_hourglass"
                        }
                    },
                ]
            }
        }
    }
}

DEFAULT_EVALUATION: Dict[str, Any] = {
    "cv_mode": "full_build",
}

#: DNS-1123 label: machine names become endpoint path segments and k8s
#: service names (reference enforces the same rule).
_NAME_RE = re.compile(r"^[a-z0-9]([-a-z0-9]{0,61}[a-z0-9])?$")


def _deep_merge(base: Dict, overlay: Dict) -> Dict:
    """Recursive dict merge; overlay wins, nested dicts merge."""
    out = copy.deepcopy(base)
    for key, value in overlay.items():
        if (
            key in out
            and isinstance(out[key], dict)
            and isinstance(value, dict)
        ):
            out[key] = _deep_merge(out[key], value)
        else:
            out[key] = copy.deepcopy(value)
    return out


class Machine:
    """One machine (named tag group): the unit of model building/serving.

    Reference equivalent: ``workflow/config_elements/machine.py::Machine``.
    """

    def __init__(
        self,
        name: str,
        dataset: Dict[str, Any],
        model: Optional[Dict[str, Any]] = None,
        metadata: Optional[Dict[str, Any]] = None,
        evaluation: Optional[Dict[str, Any]] = None,
        runtime: Optional[Dict[str, Any]] = None,
        project_name: Optional[str] = None,
    ):
        if not _NAME_RE.match(name or ""):
            raise ValueError(
                f"Invalid machine name {name!r}: must be a lowercase DNS-1123 "
                "label (a-z, 0-9, '-', max 63 chars, no leading/trailing '-')"
            )
        if not dataset:
            raise ValueError(f"Machine {name!r} has no dataset config")
        self.name = name
        self.dataset = dataset
        self.model = model or copy.deepcopy(DEFAULT_MODEL)
        self.metadata = metadata or {}
        self.evaluation = evaluation or copy.deepcopy(DEFAULT_EVALUATION)
        self.runtime = runtime or {}
        self.project_name = project_name

    @classmethod
    def from_config(
        cls,
        config: Dict[str, Any],
        project_name: Optional[str] = None,
        config_globals: Optional[Dict[str, Any]] = None,
    ) -> "Machine":
        g = config_globals or {}
        return cls(
            name=config.get("name"),
            dataset=_deep_merge(g.get("dataset", {}), config.get("dataset", {})),
            model=config.get("model") or g.get("model"),
            metadata=_deep_merge(
                g.get("metadata", {}), config.get("metadata", {})
            ),
            evaluation=_deep_merge(
                g.get("evaluation", {}), config.get("evaluation", {})
            ),
            runtime=_deep_merge(g.get("runtime", {}), config.get("runtime", {})),
            project_name=project_name,
        )

    def to_dict(self) -> Dict[str, Any]:
        return {
            "name": self.name,
            "dataset": self.dataset,
            "model": self.model,
            "metadata": self.metadata,
            "evaluation": self.evaluation,
            "runtime": self.runtime,
        }

    def __repr__(self) -> str:
        return f"Machine({self.name!r})"


class NormalizedConfig:
    """Parsed project config: globals overlaid onto every machine entry.

    Reference equivalent: ``NormalizedConfig`` — the single source of truth
    the builder fan-out, workflow generator, and watchman all consume.
    """

    def __init__(self, config: Dict[str, Any], project_name: str = "project"):
        if not isinstance(config, dict) or "machines" not in config:
            raise ValueError("Project config must be a mapping with 'machines'")
        self.project_name = project_name
        self.config_globals = config.get("globals", {}) or {}
        self.machines: List[Machine] = [
            Machine.from_config(m, project_name, self.config_globals)
            for m in config["machines"]
        ]
        names = [m.name for m in self.machines]
        dupes = {n for n in names if names.count(n) > 1}
        if dupes:
            raise ValueError(f"Duplicate machine names: {sorted(dupes)}")


def load_machine_config(source: Union[str, Dict]) -> Dict[str, Any]:
    """YAML text, file path, or dict → raw project-config dict."""
    if isinstance(source, dict):
        return source
    text = source
    if "\n" not in source and source.endswith((".yml", ".yaml")):
        with open(source) as f:
            text = f.read()
    loaded = yaml.safe_load(text)
    if not isinstance(loaded, dict):
        raise ValueError("Project config did not parse to a mapping")
    return loaded
