"""Project YAML → normalized machine list.

Reference equivalent:
``gordo_components/workflow/config_elements/normalized_config.py`` (+
``machine.py``): parse ``machines:``, overlay ``globals:`` onto per-machine
entries over the built-in defaults, inject the default model (scaler +
hourglass autoencoder wrapped in a DiffBasedAnomalyDetector), and enforce
DNS-safe machine names (machine names become k8s service names downstream).
"""

from __future__ import annotations

import copy
import hashlib
import json
import os
import re
from collections import Counter
from typing import Any, Dict, List, Optional, Union

import yaml

try:  # the C loader parses a 10k-machine project YAML ~5x faster
    from yaml import CSafeLoader as _SafeLoader
except ImportError:  # pragma: no cover - libyaml-less interpreter
    from yaml import SafeLoader as _SafeLoader

#: directory for the content-hash config-normalization cache (opt-in;
#: see NormalizedConfig.from_source and docs/configuration.md)
ENV_CONFIG_CACHE = "GORDO_INGEST_CONFIG_CACHE"
_CACHE_VERSION = 1

#: the reference's default machine model, in this framework's dotted paths
#: (reference-era sklearn/gordo_components paths also work via ALIASES).
DEFAULT_MODEL: Dict[str, Any] = {
    "gordo_tpu.anomaly.diff.DiffBasedAnomalyDetector": {
        "base_estimator": {
            "gordo_tpu.pipeline.Pipeline": {
                "steps": [
                    "gordo_tpu.ops.scalers.MinMaxScaler",
                    {
                        "gordo_tpu.models.estimator.AutoEncoder": {
                            "kind": "feedforward_hourglass"
                        }
                    },
                ]
            }
        }
    }
}

DEFAULT_EVALUATION: Dict[str, Any] = {
    "cv_mode": "full_build",
}

#: DNS-1123 label: machine names become endpoint path segments and k8s
#: service names (reference enforces the same rule).
_NAME_RE = re.compile(r"^[a-z0-9]([-a-z0-9]{0,61}[a-z0-9])?$")


def _deep_merge(base: Dict, overlay: Dict) -> Dict:
    """Recursive dict merge; overlay wins, nested dicts merge."""
    if not base:
        return copy.deepcopy(overlay) if overlay else {}
    if not overlay:
        return copy.deepcopy(base)
    out = copy.deepcopy(base)
    for key, value in overlay.items():
        if (
            key in out
            and isinstance(out[key], dict)
            and isinstance(value, dict)
        ):
            out[key] = _deep_merge(out[key], value)
        else:
            out[key] = copy.deepcopy(value)
    return out


class Machine:
    """One machine (named tag group): the unit of model building/serving.

    Reference equivalent: ``workflow/config_elements/machine.py::Machine``.
    """

    def __init__(
        self,
        name: str,
        dataset: Dict[str, Any],
        model: Optional[Dict[str, Any]] = None,
        metadata: Optional[Dict[str, Any]] = None,
        evaluation: Optional[Dict[str, Any]] = None,
        runtime: Optional[Dict[str, Any]] = None,
        project_name: Optional[str] = None,
    ):
        if not _NAME_RE.match(name or ""):
            raise ValueError(
                f"Invalid machine name {name!r}: must be a lowercase DNS-1123 "
                "label (a-z, 0-9, '-', max 63 chars, no leading/trailing '-')"
            )
        if not dataset:
            raise ValueError(f"Machine {name!r} has no dataset config")
        self.name = name
        self.dataset = dataset
        self.model = model or copy.deepcopy(DEFAULT_MODEL)
        self.metadata = metadata or {}
        self.evaluation = evaluation or copy.deepcopy(DEFAULT_EVALUATION)
        self.runtime = runtime or {}
        self.project_name = project_name

    @classmethod
    def from_config(
        cls,
        config: Dict[str, Any],
        project_name: Optional[str] = None,
        config_globals: Optional[Dict[str, Any]] = None,
    ) -> "Machine":
        g = config_globals or {}
        return cls(
            name=config.get("name"),
            dataset=_deep_merge(g.get("dataset", {}), config.get("dataset", {})),
            model=config.get("model") or g.get("model"),
            metadata=_deep_merge(
                g.get("metadata", {}), config.get("metadata", {})
            ),
            evaluation=_deep_merge(
                g.get("evaluation", {}), config.get("evaluation", {})
            ),
            runtime=_deep_merge(g.get("runtime", {}), config.get("runtime", {})),
            project_name=project_name,
        )

    def to_dict(self) -> Dict[str, Any]:
        return {
            "name": self.name,
            "dataset": self.dataset,
            "model": self.model,
            "metadata": self.metadata,
            "evaluation": self.evaluation,
            "runtime": self.runtime,
        }

    def __repr__(self) -> str:
        return f"Machine({self.name!r})"

    @classmethod
    def _from_normalized(
        cls, d: Dict[str, Any], project_name: Optional[str] = None
    ) -> "Machine":
        """Fast constructor for ALREADY-normalized, already-validated
        machine dicts (the :meth:`NormalizedConfig.from_source` cache-hit
        path): globals were merged and names DNS-validated when the cache
        entry was written, so neither repeats here."""
        self = cls.__new__(cls)
        self.name = d["name"]
        self.dataset = d["dataset"]
        self.model = d["model"]
        self.metadata = d.get("metadata") or {}
        self.evaluation = d.get("evaluation") or copy.deepcopy(
            DEFAULT_EVALUATION
        )
        self.runtime = d.get("runtime") or {}
        self.project_name = project_name
        return self


class NormalizedConfig:
    """Parsed project config: globals overlaid onto every machine entry.

    Reference equivalent: ``NormalizedConfig`` — the single source of truth
    the builder fan-out, workflow generator, and watchman all consume.
    """

    def __init__(self, config: Dict[str, Any], project_name: str = "project"):
        if not isinstance(config, dict) or "machines" not in config:
            raise ValueError("Project config must be a mapping with 'machines'")
        self.project_name = project_name
        self.config_globals = config.get("globals", {}) or {}
        self.machines: List[Machine] = [
            Machine.from_config(m, project_name, self.config_globals)
            for m in config["machines"]
        ]
        counts = Counter(m.name for m in self.machines)
        dupes = {n for n, c in counts.items() if c > 1}
        if dupes:
            raise ValueError(f"Duplicate machine names: {sorted(dupes)}")

    @classmethod
    def from_source(
        cls,
        source: Union[str, Dict],
        project_name: str = "project",
        cache_dir: Optional[str] = None,
    ) -> "NormalizedConfig":
        """The config fast path: YAML text/path/dict → NormalizedConfig
        through the C YAML loader plus an optional content-hash cache of
        the NORMALIZED output.

        ``cache_dir`` (default: ``GORDO_INGEST_CONFIG_CACHE`` env, off
        when unset) holds one JSON file per sha256 of the raw config text
        + project name; a hit skips both the YAML parse and the
        globals-merge normalization — re-planning an unchanged
        10k-machine project drops from seconds to a file read.  Entries
        are written atomically and only when the normalized output
        round-trips JSON exactly (a YAML-date-bearing config simply never
        caches), so a hit is byte-equivalent to the cold path.
        """
        if cache_dir is None:
            cache_dir = os.environ.get(ENV_CONFIG_CACHE) or None
        text: Optional[str] = None
        if isinstance(source, str):
            text = source
            if "\n" not in source and source.endswith((".yml", ".yaml")):
                with open(source) as f:
                    text = f.read()
        path = None
        if cache_dir and text is not None:
            digest = hashlib.sha256(
                f"v{_CACHE_VERSION}\x00{project_name}\x00".encode()
                + text.encode()
            ).hexdigest()
            path = os.path.join(cache_dir, f"config-{digest}.json")
            try:
                with open(path) as f:
                    payload = json.load(f)
            except (OSError, ValueError):
                payload = None
            if payload is not None and payload.get("version") == _CACHE_VERSION:
                self = cls.__new__(cls)
                self.project_name = payload["project_name"]
                self.config_globals = payload["globals"]
                self.machines = [
                    Machine._from_normalized(d, self.project_name)
                    for d in payload["machines"]
                ]
                return self
        cfg = cls(
            load_machine_config(text if text is not None else source),
            project_name,
        )
        if path is not None:
            payload = {
                "version": _CACHE_VERSION,
                "project_name": project_name,
                "globals": cfg.config_globals,
                "machines": [m.to_dict() for m in cfg.machines],
            }
            try:
                blob = json.dumps(payload)
            except (TypeError, ValueError):
                return cfg  # non-JSON values (YAML dates, ...): don't cache
            if json.loads(blob) != payload:
                return cfg  # lossy round-trip (non-str keys, ...): ditto
            os.makedirs(cache_dir, exist_ok=True)
            tmp = f"{path}.tmp{os.getpid()}"
            with open(tmp, "w") as f:
                f.write(blob)
            os.replace(tmp, path)
        return cfg


def load_machine_config(source: Union[str, Dict]) -> Dict[str, Any]:
    """YAML text, file path, or dict → raw project-config dict."""
    if isinstance(source, dict):
        return source
    text = source
    if "\n" not in source and source.endswith((".yml", ".yaml")):
        with open(source) as f:
            text = f.read()
    loaded = yaml.load(text, Loader=_SafeLoader)
    if not isinstance(loaded, dict):
        raise ValueError("Project config did not parse to a mapping")
    return loaded
