"""Workflow generation: project config → orchestration documents.

Reference equivalent: ``gordo_components/workflow/workflow_generator/
workflow_generator.py`` + ``resources/argo-workflow.yml.template`` — a
Jinja2-rendered Argo ``Workflow`` fanning out **one model-builder pod per
machine**, then per-machine ml-server Deployments/Services with Ambassador
route annotations and a watchman Deployment.

TPU-native redesign: the unit of training orchestration is no longer one
pod per machine — it is ONE builder job per project that runs the fleet
engine (``gordo_tpu.builder.fleet_build``) on a TPU slice, training whole
buckets of machines as single sharded XLA programs.  So this generator
emits:

- a **build plan**: machines bucketed by fleet signature (model-config
  shape x feature width), with cache keys — the document the fleet
  builder executes and the thing tests assert on (the reference's
  per-machine DAG assertions map to per-bucket assertions here);
- **kubernetes manifests** for deploy parity: builder Job (TPU nodepool),
  one ml-server Deployment/Service hosting every machine, watchman
  Deployment/Service, and per-machine Ambassador-style route Mappings so
  the reference's per-machine URLs keep working.

Documents are built as Python dicts and serialized with ``yaml.dump`` —
no string templating to escape-bug.
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional, Tuple

import yaml

from gordo_tpu.builder.build_model import calculate_model_key
from gordo_tpu.ingest.fingerprint import dataset_fingerprint
from gordo_tpu.workflow.config import Machine, NormalizedConfig

API_PREFIX = "/gordo/v0"
DEFAULT_IMAGE = "gordo-tpu"
DEFAULT_SERVER_PORT = 5555
DEFAULT_WATCHMAN_PORT = 5556
#: jax.distributed coordination-service port on process 0 of a multi-host
#: builder Job (the conventional jax coordinator port)
DEFAULT_COORDINATOR_PORT = 8476
#: where the shared persistent XLA compilation cache mounts in builder and
#: server pods — one PVC per project, so a restarted server (or any worker
#: of a --multihost Indexed Job) loads executables its peers already
#: compiled instead of re-paying every cold compile
COMPILE_CACHE_MOUNT = "/compile-cache"


def unique_tags(machines: List[Machine]) -> List[str]:
    """Sorted distinct tag names across the project (reference:
    ``workflow unique-tags``)."""
    tags = set()
    for machine in machines:
        for t in machine.dataset.get("tag_list") or machine.dataset.get("tags") or []:
            tags.add(t["name"] if isinstance(t, dict) else str(t))
    return sorted(tags)


def _fleet_signature(machine: Machine) -> str:
    """Static bucketing signature: machines whose model-config (minus
    per-machine irrelevancies) and tag width match can train as one
    stacked XLA program.  A cheap host-side proxy for
    ``parallel.anomaly.analyze_definition`` — the builder re-verifies with
    a real prototype at run time and falls back per machine if needed."""
    n_tags = len(
        machine.dataset.get("tag_list") or machine.dataset.get("tags") or []
    )
    return json.dumps({"model": machine.model, "n_tags": n_tags}, sort_keys=True)


#: measured per-distinct-row-count XLA compile cost of the fleet CV+fit
#: program (docs/perf.md "Ragged-length fleets": 218.9s cold for 16
#: lengths ≈ 13.7s each, CPU jax; TPU compiles are comparable)
COMPILE_SECONDS_PER_LENGTH = 13.7


def _ragged_length_estimate(members: List[Machine]) -> int:
    """Config-level upper estimate of DISTINCT train-row-counts in one
    bucket — each distinct length compiles its own fleet program.

    Machines without a row filter share a length whenever their
    (train window, resolution) agree; a machine WITH a ``row_filter``
    drops an unpredictable number of rows, so each one must be assumed a
    distinct length (that unpredictability is exactly why raggedness is
    the production norm)."""
    windows = set()
    filtered = 0
    for m in members:
        ds = m.dataset
        if ds.get("row_filter"):
            filtered += 1
        else:
            windows.add((
                str(ds.get("train_start_date")),
                str(ds.get("train_end_date")),
                str(ds.get("resolution")),
            ))
    return len(windows) + filtered


def build_plan(
    config: NormalizedConfig,
    max_bucket_size: int = 512,
    mesh: Optional[Dict[str, int]] = None,
    align_lengths: Optional[int] = None,
    pad_lengths: Optional[int] = None,
) -> Dict[str, Any]:
    """Bucketed fleet build plan for the project.

    ``align_lengths`` / ``pad_lengths`` must match the value the build
    will run with: they are part of fleet-built machines' cache identity,
    so plan keys computed without them would never match the registry
    entries an aligned/padded ``build_project`` writes.  (Like the
    bucketing itself, the keys are the fleet-path prediction: a machine
    the builder demotes to the single path at run time keys without the
    component there.)

    When NEITHER is set and the configs predict multiple distinct train
    lengths per bucket, the plan carries a ``ragged_compile_warning``
    with the estimated per-distinct-length compile bill — explicit, not
    silent: a 1000-machine filtered project that forgets the flag would
    otherwise discover the cost an hour into its build."""
    if align_lengths and pad_lengths:
        raise ValueError(
            "align_lengths and pad_lengths are mutually exclusive"
        )
    key_extra = None
    if align_lengths:
        key_extra = {"align_lengths": align_lengths}
    elif pad_lengths:
        key_extra = {"pad_lengths": pad_lengths}
    buckets: Dict[str, List[Machine]] = {}
    for machine in config.machines:
        buckets.setdefault(_fleet_signature(machine), []).append(machine)

    plan_buckets = []
    for i, (_, members) in enumerate(sorted(buckets.items())):
        for start in range(0, len(members), max_bucket_size):
            chunk = members[start : start + max_bucket_size]
            plan_buckets.append(
                {
                    "bucket": f"bucket-{i:03d}-{start // max_bucket_size:03d}",
                    "n_machines": len(chunk),
                    "machines": [m.name for m in chunk],
                    "model_config": chunk[0].model,
                    "cache_keys": {
                        m.name: calculate_model_key(
                            m.name, m.model, m.dataset, m.metadata,
                            extra=key_extra,
                        )
                        for m in chunk
                    },
                }
            )
    plan = {
        "project-name": config.project_name,
        "mesh": mesh or {"models": -1, "data": 1},  # -1: all available chips
        "n_machines": len(config.machines),
        "n_buckets": len(plan_buckets),
        "buckets": plan_buckets,
        # artifact volume layout: the generated builder writes format v2,
        # so the models PVC holds ~one pack per planned chunk (plus the
        # index) instead of one directory per machine
        "artifact_format": "v2",
        "artifact_packs_estimate": len(plan_buckets),
    }
    # ingest-plane projection: one provider fetch per distinct dataset
    # fingerprint (gordo_tpu/ingest/fingerprint.py) — the plan surfaces
    # the dedup the build will get, so a replicated fleet's operator
    # sees the fetch bill up front in `workflow plan`
    fingerprints = {
        dataset_fingerprint(dict(m.dataset)) for m in config.machines
    }
    n_machines = len(config.machines)
    dedup_hits = n_machines - len(fingerprints)
    plan["ingest"] = {
        "distinct_dataset_fingerprints": len(fingerprints),
        "dedup_hits": dedup_hits,
        "fetch_dedup_ratio": round(
            dedup_hits / n_machines, 4
        ) if n_machines else 0.0,
    }
    if align_lengths:
        plan["align_lengths"] = int(align_lengths)
    if pad_lengths:
        plan["pad_lengths"] = int(pad_lengths)
    if key_extra is None:
        est_lengths = sum(
            _ragged_length_estimate(members) for members in buckets.values()
        )
        extra = est_lengths - len(buckets)  # 1 compile/bucket is the floor
        if extra > 0:
            plan["ragged_compile_warning"] = {
                "estimated_distinct_lengths": est_lengths,
                "estimated_extra_compiles": extra,
                "estimated_extra_compile_seconds": round(
                    extra * COMPILE_SECONDS_PER_LENGTH, 1
                ),
                "hint": (
                    "Exact mode compiles one fleet program per distinct "
                    "train-row-count (~"
                    f"{COMPILE_SECONDS_PER_LENGTH:g}s each, measured). "
                    "Set align_lengths (truncate down, exact parity on "
                    "the truncated data) or pad_lengths (zero data loss, "
                    "padded fold geometry) to collapse them."
                ),
            }
    return plan


# ---------------------------------------------------------------------------
# kubernetes manifests
# ---------------------------------------------------------------------------

def _labels(project: str, component: str) -> Dict[str, str]:
    return {
        "app.kubernetes.io/part-of": "gordo-tpu",
        "app.kubernetes.io/instance": project,
        "app.kubernetes.io/component": component,
    }


def _scrape_annotations(port: int) -> Dict[str, str]:
    """Prometheus discovery annotations for a pod exposing ``/metrics``
    (the de-facto prometheus.io convention most cluster scrape configs
    key on).  Emitted by default on the server and watchman pod
    templates; ``--no-scrape-annotations`` opts out for clusters using
    ServiceMonitors or a different discovery scheme."""
    return {
        "prometheus.io/scrape": "true",
        "prometheus.io/port": str(port),
        "prometheus.io/path": "/metrics",
    }


def _multihost_builder_docs(
    project: str,
    image: str,
    tpu_resources: Dict[str, Any],
    num_processes: int,
    serve_dtype: Optional[str] = None,
) -> List[Dict]:
    """Indexed builder Job (one pod per process) + the headless Service
    that gives process 0 a stable coordinator DNS name.

    Env wiring is the ``GORDO_*`` contract of
    ``gordo_tpu.distributed.runtime``: every pod gets the same
    ``GORDO_COORDINATOR`` (pod 0's stable hostname) and its own
    ``GORDO_PROCESS_ID`` from the index kubernetes injects as
    ``JOB_COMPLETION_INDEX``.  ``gordo build-project`` picks the env
    contract up with no extra flags, shards the machine list
    deterministically, and barriers at the build edges — a pod that dies
    exits its peers with the resumable code, and the Job's retry
    (``backoffLimit``) re-runs into cache hits plus the dead shard's
    remainder."""
    job_name = f"gordo-builder-{project}"
    svc_name = f"gordo-builder-{project}"
    job = _builder_job(project, image, tpu_resources, serve_dtype=serve_dtype)
    spec = job["spec"]
    spec["completions"] = num_processes
    spec["parallelism"] = num_processes
    spec["completionMode"] = "Indexed"
    pod_spec = spec["template"]["spec"]
    # Indexed pods get hostname {job}-{index}; the headless subdomain
    # makes {job}-0.{svc} resolvable as the coordinator address
    pod_spec["subdomain"] = svc_name
    container = pod_spec["containers"][0]
    container["env"].extend(
        [
            {
                "name": "GORDO_COORDINATOR",
                "value": (
                    f"{job_name}-0.{svc_name}:{DEFAULT_COORDINATOR_PORT}"
                ),
            },
            {"name": "GORDO_NUM_PROCESSES", "value": str(num_processes)},
            # JOB_COMPLETION_INDEX is injected by kubernetes for Indexed
            # Jobs; dependent-env expansion turns it into the process id
            {"name": "GORDO_PROCESS_ID", "value": "$(JOB_COMPLETION_INDEX)"},
        ]
    )
    headless = {
        "apiVersion": "v1",
        "kind": "Service",
        "metadata": {
            "name": svc_name,
            "labels": _labels(project, "model-builder"),
        },
        "spec": {
            "clusterIP": "None",  # headless: per-pod DNS, no VIP
            "selector": _labels(project, "model-builder"),
            "ports": [
                {
                    "port": DEFAULT_COORDINATOR_PORT,
                    "targetPort": DEFAULT_COORDINATOR_PORT,
                }
            ],
        },
    }
    return [job, headless]


def _compile_cache_volume(project: str) -> Dict:
    return {
        "name": "compile-cache",
        "persistentVolumeClaim": {
            "claimName": f"gordo-compile-cache-{project}"
        },
    }


def _compile_cache_env() -> Dict[str, str]:
    return {"name": "GORDO_COMPILE_CACHE_DIR", "value": COMPILE_CACHE_MOUNT}


def _serve_dtype_env(serve_dtype: Optional[str]) -> List[Dict[str, str]]:
    """``GORDO_SERVE_DTYPE`` env entries for a pod template.  Stamped on
    BOTH the builder (so the warmup manifest records the precision and
    warmup compiles for it) and the server (so dispatch matches) — the
    serving-precision plane's one-config contract.  Validated here so a
    typo fails manifest GENERATION, not a pod at 3am."""
    if serve_dtype is None:
        return []
    from gordo_tpu.serve.precision import canonical

    return [{"name": "GORDO_SERVE_DTYPE", "value": canonical(serve_dtype)}]


def _evict_after_env() -> Dict[str, str]:
    """``GORDO_WATCHMAN_EVICT_AFTER`` for the watchman pod: a target
    replica failing this many consecutive index scrapes is marked
    ``down`` in the status doc (clients then skip it when bootstrapping
    their shard table and when choosing failover candidates).  Stamped
    explicitly (3 is also the library default) so the manifest documents
    the knob where operators tune it."""
    return {"name": "GORDO_WATCHMAN_EVICT_AFTER", "value": "3"}


def _reload_watch_env() -> Dict[str, str]:
    """``GORDO_RELOAD_WATCH_SECONDS`` for server pods: poll the artifact
    index's generation sidecar (one tiny file read off the models PVC)
    so a builder Job's generation stamp hot-reloads only the changed
    machines into the running replicas — no pod restart, no recompile.
    Stamped explicitly (even though 5 is also the library default) so
    the manifest documents the knob where operators tune it."""
    return {"name": "GORDO_RELOAD_WATCH_SECONDS", "value": "5"}


def _builder_job(
    project: str,
    image: str,
    tpu_resources: Dict[str, Any],
    serve_dtype: Optional[str] = None,
) -> Dict:
    return {
        "apiVersion": "batch/v1",
        "kind": "Job",
        "metadata": {
            "name": f"gordo-builder-{project}",
            "labels": _labels(project, "model-builder"),
        },
        "spec": {
            "backoffLimit": 3,  # idempotent: cache-hit machines skip
            "template": {
                "metadata": {"labels": _labels(project, "model-builder")},
                "spec": {
                    "restartPolicy": "OnFailure",
                    "containers": [
                        {
                            "name": "model-builder",
                            "image": image,
                            "command": ["gordo", "build-project"],
                            "args": [
                                "--machine-config", "/config/project.yaml",
                                "--output-dir", "/models",
                                "--model-register-dir", "/models/.register",
                            ],
                            "env": [
                                {"name": "PROJECT_NAME", "value": project},
                                # artifact format v2 (one mmap-able pack
                                # per fleet chunk, the server's zero-copy
                                # load path) is the library default; set
                                # GORDO_ARTIFACT_FORMAT=v1 here only for
                                # tooling that needs per-machine dirs
                                # shared persistent XLA compile cache: a
                                # retried Job (and every worker of a
                                # --multihost Indexed Job, which extends
                                # this template) reuses peers' compiles
                                _compile_cache_env(),
                                *_serve_dtype_env(serve_dtype),
                            ],
                            "resources": tpu_resources,
                            "volumeMounts": [
                                {"name": "models", "mountPath": "/models"},
                                {"name": "project-config", "mountPath": "/config"},
                                {"name": "compile-cache",
                                 "mountPath": COMPILE_CACHE_MOUNT},
                            ],
                        }
                    ],
                    "volumes": [
                        {
                            "name": "models",
                            "persistentVolumeClaim": {
                                "claimName": f"gordo-models-{project}"
                            },
                        },
                        {
                            "name": "project-config",
                            "configMap": {"name": f"gordo-config-{project}"},
                        },
                        _compile_cache_volume(project),
                    ],
                },
            },
        },
    }


def _validate_cron_schedule(schedule: str) -> str:
    """Reject obviously-malformed CronJob schedules at manifest
    GENERATION (the same fail-early posture as ``_serve_dtype_env``):
    kubernetes cron is five whitespace-separated fields."""
    fields = str(schedule).split()
    if len(fields) != 5:
        raise ValueError(
            f"--refresh-cron schedule {schedule!r} is not a 5-field cron "
            f"expression (minute hour day-of-month month day-of-week), "
            f"got {len(fields)} field(s)"
        )
    allowed = set("0123456789*/,-")
    for field in fields:
        if not field or not set(field) <= allowed:
            raise ValueError(
                f"--refresh-cron schedule {schedule!r}: field {field!r} "
                f"contains characters outside [0-9*/,-]"
            )
    return " ".join(fields)


def _refresh_cronjob(
    project: str,
    image: str,
    schedule: str,
    builder_job: Dict[str, Any],
) -> Dict:
    """A ``batch/v1`` CronJob running ``gordo refresh --once`` on
    ``schedule`` — the drift-driven incremental rebuild face of the
    builder (docs/operations.md "Incremental refresh").

    The pod template mirrors the builder Job's volumes and env (models
    PVC, project-config ConfigMap, shared compile cache, GORDO_* wiring)
    so the refresh cycle sees exactly the artifacts and config the full
    build produced — refused when the builder template carries no models
    volume, because a refresh with nowhere to read the previous
    generation from (or publish the next one to) can only rebuild cold
    into the void."""
    import copy

    schedule = _validate_cron_schedule(schedule)
    builder_spec = builder_job["spec"]["template"]["spec"]
    volume_names = {v.get("name") for v in builder_spec.get("volumes", [])}
    if "models" not in volume_names:
        raise ValueError(
            "--refresh-cron requires the builder template to mount a "
            "'models' volume (the artifact dir the refresh warm-starts "
            "from and publishes to); this builder configuration has "
            f"volumes {sorted(volume_names)}"
        )
    pod_spec = copy.deepcopy(builder_spec)
    container = pod_spec["containers"][0]
    container["name"] = "model-refresh"
    container["command"] = ["gordo", "refresh"]
    container["args"] = [
        "--machine-config", "/config/project.yaml",
        "--output-dir", "/models",
        "--model-register-dir", "/models/.register",
        "--once",
    ]
    # health comes off the rollup files under /models (no HTTP from the
    # cron pod); selection knobs documented where operators tune them
    container.setdefault("env", []).extend([
        {"name": "GORDO_REFRESH_HYSTERESIS", "value": "2"},
        {"name": "GORDO_REFRESH_COOLDOWN_SECONDS", "value": "900"},
    ])
    return {
        "apiVersion": "batch/v1",
        "kind": "CronJob",
        "metadata": {
            "name": f"gordo-refresh-{project}",
            "labels": _labels(project, "model-refresh"),
        },
        "spec": {
            "schedule": schedule,
            # a slow warm rebuild must not pile up concurrent cycles
            # racing the artifact index; the selector state file makes
            # skipped runs harmless (streaks persist)
            "concurrencyPolicy": "Forbid",
            "jobTemplate": {
                "spec": {
                    "backoffLimit": 2,  # idempotent: delta publish retries
                    "template": {
                        "metadata": {
                            "labels": _labels(project, "model-refresh")
                        },
                        "spec": pod_spec,
                    },
                },
            },
        },
    }


def _backfill_job(
    project: str,
    image: str,
    start: str,
    end: str,
    shards: int,
    builder_job: Dict[str, Any],
) -> Dict:
    """An Indexed ``batch/v1`` Job running ``gordo backfill`` over
    ``[start, end)`` — the offline backfill plane fanned out across
    ``shards`` pods (docs/batch.md "Sharded backfill").

    The pod template mirrors the builder Job's volumes and env (models
    PVC, project-config ConfigMap, shared compile cache, GORDO_* wiring)
    so each shard scores with exactly the artifacts the build produced
    and archives next to them.  Shard identity rides the same
    ``JOB_COMPLETION_INDEX`` dependent-env wiring as the multihost
    builder: ``GORDO_BACKFILL_SHARD_INDEX`` is the pod's completion
    index and ``GORDO_BACKFILL_NUM_SHARDS`` the fan-out, which
    ``batch.runner.resolve_shard`` consumes with no extra flags.
    Refused when the builder template carries no models volume — a
    backfill with no artifacts to load can only score the void."""
    import copy

    import pandas as pd

    try:
        ts_start = pd.Timestamp(start)
        ts_end = pd.Timestamp(end)
    except (TypeError, ValueError) as exc:
        raise ValueError(
            f"--backfill range ({start!r}, {end!r}) does not parse as "
            f"timestamps: {exc}"
        )
    if ts_start.tz_localize(None) >= ts_end.tz_localize(None):
        raise ValueError(
            f"--backfill start {start!r} must precede end {end!r} "
            f"(the range is half-open [start, end))"
        )
    builder_spec = builder_job["spec"]["template"]["spec"]
    volume_names = {v.get("name") for v in builder_spec.get("volumes", [])}
    if "models" not in volume_names:
        raise ValueError(
            "--backfill requires the builder template to mount a "
            "'models' volume (the artifact dir the backfill loads models "
            "from and archives scores under); this builder configuration "
            f"has volumes {sorted(volume_names)}"
        )
    pod_spec = copy.deepcopy(builder_spec)
    container = pod_spec["containers"][0]
    container["name"] = "backfill"
    container["command"] = ["gordo", "backfill"]
    container["args"] = [
        "--model-dir", "/models",
        "--start", str(start),
        "--end", str(end),
    ]
    container.setdefault("env", []).extend([
        # JOB_COMPLETION_INDEX is injected by kubernetes for Indexed
        # Jobs; the pair below is the env spelling of --shard i/N
        {"name": "GORDO_BACKFILL_SHARD_INDEX",
         "value": "$(JOB_COMPLETION_INDEX)"},
        {"name": "GORDO_BACKFILL_NUM_SHARDS", "value": str(shards)},
    ])
    return {
        "apiVersion": "batch/v1",
        "kind": "Job",
        "metadata": {
            "name": f"gordo-backfill-{project}",
            "labels": _labels(project, "backfill"),
        },
        "spec": {
            "completions": shards,
            "parallelism": shards,
            "completionMode": "Indexed",
            # exit 75 (EX_TEMPFAIL) = archived progress, not finished;
            # the retry resumes from completion records into byte-
            # identical segments, so a generous backoffLimit is cheap
            "backoffLimit": 6,
            "template": {
                "metadata": {"labels": _labels(project, "backfill")},
                "spec": pod_spec,
            },
        },
    }


def _server_deployment(
    project: str,
    image: str,
    replicas: int,
    server_args: Optional[List[str]] = None,
    scrape_annotations: bool = True,
    serve_dtype: Optional[str] = None,
    shard: Optional[Any] = None,
) -> Dict:
    """``shard`` (a ``serve.shard.ShardSpec``): emit one shard replica's
    Deployment of a fleet-sharded serving tier — its own name/labels (so
    per-shard Services select only it) and ``GORDO_SERVE_SHARD=i/N``
    stamped in the pod env, which makes the server load, warm, and make
    device-resident ONLY its shard's artifacts."""
    component = "ml-server" if shard is None else f"ml-server-shard-{shard.index}"
    name = f"gordo-server-{project}" + (
        "" if shard is None else f"-shard-{shard.index}"
    )
    shard_env = (
        []
        if shard is None
        else [{"name": "GORDO_SERVE_SHARD", "value": str(shard)}]
    )
    template_meta: Dict[str, Any] = {
        "labels": _labels(project, component),
    }
    if scrape_annotations:
        template_meta["annotations"] = _scrape_annotations(
            DEFAULT_SERVER_PORT
        )
    return {
        "apiVersion": "apps/v1",
        "kind": "Deployment",
        "metadata": {
            "name": name,
            "labels": _labels(project, component),
        },
        "spec": {
            "replicas": replicas,
            "selector": {"matchLabels": _labels(project, component)},
            "template": {
                "metadata": template_meta,
                "spec": {
                    "containers": [
                        {
                            "name": "ml-server",
                            "image": image,
                            "command": ["gordo", "run-server"],
                            "args": [
                                "--model-dir", "/models",
                                "--project", project,
                                "--port", str(DEFAULT_SERVER_PORT),
                                # warmup by default + the /ready-gated
                                # readinessProbe below: pods receive no
                                # traffic until their programs are compiled
                                "--warmup",
                                *(server_args or []),
                            ],
                            # the warmup loads executables the builder (or
                            # a previous server incarnation) already put in
                            # the shared compile cache — a rescheduled pod
                            # goes ready in cache-load time, not compile
                            # time
                            "env": [
                                _compile_cache_env(),
                                _reload_watch_env(),
                                *shard_env,
                                *_serve_dtype_env(serve_dtype),
                            ],
                            "ports": [{"containerPort": DEFAULT_SERVER_PORT}],
                            "readinessProbe": {
                                # /ready returns 503 until the startup
                                # warmup finishes compiling, so a
                                # rescheduled pod only receives traffic
                                # with warm programs
                                "httpGet": {
                                    "path": f"{API_PREFIX}/{project}/ready",
                                    "port": DEFAULT_SERVER_PORT,
                                },
                            },
                            "volumeMounts": [
                                {"name": "models", "mountPath": "/models",
                                 "readOnly": True},
                                {"name": "compile-cache",
                                 "mountPath": COMPILE_CACHE_MOUNT},
                            ],
                        }
                    ],
                    "volumes": [
                        {
                            "name": "models",
                            "persistentVolumeClaim": {
                                "claimName": f"gordo-models-{project}"
                            },
                        },
                        _compile_cache_volume(project),
                    ],
                },
            },
        },
    }


#: Service-level idle-timeout annotation for components that carry
#: long-lived SSE connections (the streaming plane): cloud LB defaults
#: (AWS ELB: 60s) would sever a healthy stream between events; an hour
#: keeps the connection while the server's keepalive comments (default
#: every 15s) prove liveness far inside it.
_SSE_SERVICE_ANNOTATIONS = {
    "service.beta.kubernetes.io/aws-load-balancer-connection-idle-timeout":
        "3600",
}


def _service(
    project: str,
    component: str,
    port: int,
    annotations: Optional[Dict[str, str]] = None,
) -> Dict:
    metadata: Dict[str, Any] = {
        "name": f"gordo-{component}-{project}",
        "labels": _labels(project, component),
    }
    if annotations:
        metadata["annotations"] = dict(annotations)
    return {
        "apiVersion": "v1",
        "kind": "Service",
        "metadata": metadata,
        "spec": {
            "selector": _labels(project, component),
            "ports": [{"port": port, "targetPort": port}],
        },
    }


def _machine_mapping(
    project: str, machine: str, component: str = "ml-server"
) -> Dict:
    """Ambassador-style route: per-machine URL → the owning server service
    (the reference annotated one Mapping per machine Service; machines now
    share one server — or, sharded, one replica — the outward URL contract
    is identical).  With a sharded tier, ``component`` is the OWNING
    shard's service, computed with the same shard function the servers
    load with: ingress-level machine-affinity routing, no lookup hop."""
    return {
        "apiVersion": "getambassador.io/v2",
        "kind": "Mapping",
        "metadata": {
            "name": f"gordo-mapping-{project}-{machine}",
            "labels": _labels(project, "route"),
        },
        "spec": {
            "prefix": f"{API_PREFIX}/{project}/{machine}/",
            "rewrite": f"{API_PREFIX}/{project}/{machine}/",
            "service": f"gordo-{component}-{project}:{DEFAULT_SERVER_PORT}",
        },
    }


def _stream_mapping(
    project: str,
    name: str,
    prefix: str,
    rewrite: str,
    component: str,
    port: int = DEFAULT_SERVER_PORT,
) -> Dict:
    """Route Mapping for the streaming plane (``serve/stream.py``).

    SSE subscriptions are long-lived by design; Ambassador's default
    per-request timeout (3s) and Envoy's idle timeout would sever a
    healthy stream between events.  The stream routes pin
    ``timeout_ms: 0`` (no request ceiling) and a day-long
    ``idle_timeout_ms`` — the server's keepalive comments
    (``GORDO_STREAM_KEEPALIVE``, default 15s) tick far inside it, so a
    dead peer is still reaped by TCP, not by a proxy guessing."""
    return {
        "apiVersion": "getambassador.io/v2",
        "kind": "Mapping",
        "metadata": {
            "name": name,
            "labels": _labels(project, "route"),
        },
        "spec": {
            "prefix": prefix,
            "rewrite": rewrite,
            "service": f"gordo-{component}-{project}:{port}",
            "timeout_ms": 0,
            "idle_timeout_ms": 86400000,
        },
    }


def _server_hpa(
    project: str, shard: Any, max_replicas: int = 4
) -> Dict:
    """HorizontalPodAutoscaler for one shard's Deployment, driven by the
    queue-wait-vs-service-time telemetry the coalescer already exports:
    ``gordo_coalesce_wait_service_ratio`` (p99 queue wait / median
    service time, refreshed at scrape time on ``/metrics``).  The target
    averageValue of 2 sits at HALF the coalescer's stand-down ratio (4):
    the tier scales out while batching still wins, well before replicas
    start shedding with 429.  Requires a prometheus adapter exposing the
    gauge as a Pods metric — the scrape annotations are already stamped.
    Scaling a shard Deployment adds replicas OF THAT SHARD (same machine
    subset, load-balanced by its Service); the shard count itself is
    static config, rendered at generation time."""
    name = f"gordo-server-{project}-shard-{shard.index}"
    return {
        "apiVersion": "autoscaling/v2",
        "kind": "HorizontalPodAutoscaler",
        "metadata": {
            "name": name,
            "labels": _labels(project, f"ml-server-shard-{shard.index}"),
        },
        "spec": {
            "scaleTargetRef": {
                "apiVersion": "apps/v1",
                "kind": "Deployment",
                "name": name,
            },
            "minReplicas": 1,
            "maxReplicas": max_replicas,
            "metrics": [
                {
                    "type": "Pods",
                    "pods": {
                        "metric": {
                            "name": "gordo_coalesce_wait_service_ratio"
                        },
                        "target": {
                            "type": "AverageValue",
                            "averageValue": "2",
                        },
                    },
                }
            ],
        },
    }


def _watchman_deployment(
    project: str,
    image: str,
    machines: List[str],
    scrape_annotations: bool = True,
    targets: Optional[List[str]] = None,
) -> Dict:
    template_meta: Dict[str, Any] = {
        "labels": _labels(project, "watchman"),
    }
    if scrape_annotations:
        # watchman's /metrics is the FLEET scrape surface (it merges every
        # target server's exposition under instance labels), so clusters
        # that only scrape one target per project point here
        template_meta["annotations"] = _scrape_annotations(
            DEFAULT_WATCHMAN_PORT
        )
    return {
        "apiVersion": "apps/v1",
        "kind": "Deployment",
        "metadata": {
            "name": f"gordo-watchman-{project}",
            "labels": _labels(project, "watchman"),
        },
        "spec": {
            "replicas": 1,
            "selector": {"matchLabels": _labels(project, "watchman")},
            "template": {
                "metadata": template_meta,
                "spec": {
                    "containers": [
                        {
                            "name": "watchman",
                            "image": image,
                            "command": ["gordo", "run-watchman"],
                            "args": [
                                "--project", project,
                                "--machines", ",".join(machines),
                                # one --target per serving service: the
                                # whole tier when sharded (watchman polls
                                # every replica and republishes each one's
                                # shard index + fleet generation)
                                *(
                                    arg
                                    for target in (
                                        targets
                                        or [
                                            f"http://gordo-ml-server-{project}"
                                            f":{DEFAULT_SERVER_PORT}"
                                        ]
                                    )
                                    for arg in ("--target", target)
                                ),
                                "--port", str(DEFAULT_WATCHMAN_PORT),
                            ],
                            "env": [_evict_after_env()],
                            "ports": [{"containerPort": DEFAULT_WATCHMAN_PORT}],
                        }
                    ],
                },
            },
        },
    }


def generate_workflow(
    config: NormalizedConfig,
    image: str = DEFAULT_IMAGE,
    server_replicas: int = 1,
    tpu_resources: Optional[Dict[str, Any]] = None,
    include_plan: bool = True,
    server_args: Optional[List[str]] = None,
    multihost: Optional[int] = None,
    scrape_annotations: bool = True,
    serve_dtype: Optional[str] = None,
    serve_shards: Optional[int] = None,
    hpa_max_replicas: int = 4,
    refresh_cron: Optional[str] = None,
    backfill: Optional[Tuple[str, str]] = None,
    backfill_shards: int = 1,
) -> List[Dict[str, Any]]:
    """Project config → list of k8s manifest dicts (+ the build plan as a
    ConfigMap so the cluster state carries the bucketing decision).

    ``server_args``: extra ``gordo run-server`` flags for the ml-server
    Deployment (e.g. ``["--coalesce-ms", "2"]`` or ``["--model-parallel"]``
    on a slice-backed node pool).

    ``multihost``: emit the builder as an N-process Indexed Job (one pod
    per process, ``jax.distributed`` wired via ``GORDO_*`` env) instead of
    a single-pod Job.  Refused when N exceeds the plan's machine-shard
    count — the extra pods would have empty shards yet still hold every
    barrier, so the spec is a config error, not a scheduling preference.

    ``scrape_annotations`` (default on): stamp ``prometheus.io/*``
    discovery annotations on the server and watchman pod templates so a
    conventionally-configured Prometheus scrapes their ``/metrics``
    without extra config; disable for clusters using ServiceMonitors.

    ``serve_dtype`` (e.g. ``"bfloat16"``): stamp ``GORDO_SERVE_DTYPE`` on
    the builder AND server pod templates — the build's warmup manifest
    then records the precision, warmup compiles for it, and dispatch
    matches (the serving-precision plane's one-config contract).  Only
    set this after the fp32 parity suite passes for the project's model
    family (docs/perf.md "Serving precision").

    ``serve_shards`` N>1: emit a fleet-sharded serving tier — one
    Deployment + Service per shard index (``GORDO_SERVE_SHARD=i/N`` in
    each pod env, so every replica loads only its shard's artifacts), an
    HPA per shard driven by the coalescer's queue-wait/service-time
    ratio gauge, per-machine Mappings routed to the OWNING shard's
    service (the same shard function everywhere — docs/serving.md
    "Sharded serving tier"), and the watchman polling every shard
    service.  Refused when N exceeds the machine count, mirroring the
    ``--multihost`` rule: machines are the atoms of the partition.

    ``refresh_cron`` (a 5-field cron schedule): additionally emit a
    CronJob running ``gordo refresh --once`` against the same models
    PVC and project config as the builder — the drift-driven
    incremental rebuild loop (docs/operations.md "Incremental
    refresh").  Refused when the builder template has no models volume
    to warm-start from, or when the schedule is malformed.

    ``backfill`` (a ``(start, end)`` timestamp pair): additionally emit
    an Indexed Job running ``gordo backfill`` over the half-open range
    against the same models PVC as the builder, fanned out across
    ``backfill_shards`` pods via the ``GORDO_BACKFILL_SHARD_INDEX`` /
    ``GORDO_BACKFILL_NUM_SHARDS`` env pair (docs/batch.md).  Refused
    when the range is malformed, when the builder has no models volume,
    or when ``backfill_shards`` exceeds the machine count — machines
    are the atoms of the backfill partition.
    """
    project = config.project_name
    machines = [m.name for m in config.machines]
    if serve_shards is not None:
        if serve_shards < 1:
            raise ValueError(
                f"serve_shards must be >= 1, got {serve_shards}"
            )
        if serve_shards > len(machines):
            raise ValueError(
                f"--serve-shards {serve_shards} exceeds the project's "
                f"machine count ({len(machines)}): machines are the atoms "
                f"of the serving partition, so extra replicas would own "
                f"empty shards. Use --serve-shards <= {len(machines)}."
            )
    if multihost is not None:
        if multihost < 1:
            raise ValueError(f"multihost must be >= 1, got {multihost}")
        from gordo_tpu.distributed.partition import max_processes

        shard_count = max_processes(config.machines)
        if multihost > shard_count:
            raise ValueError(
                f"--multihost {multihost} exceeds the plan's machine-shard "
                f"count ({shard_count}): machines are the atoms of the "
                f"process partition, so processes beyond that would idle "
                f"while holding every barrier. Use --multihost <= "
                f"{shard_count}, or grow the project."
            )
    tpu_resources = tpu_resources or {
        "limits": {"google.com/tpu": 8},
        "requests": {"google.com/tpu": 8},
    }
    if multihost is not None and multihost > 1:
        builder_docs = _multihost_builder_docs(
            project, image, tpu_resources, multihost,
            serve_dtype=serve_dtype,
        )
    else:
        builder_docs = [
            _builder_job(
                project, image, tpu_resources, serve_dtype=serve_dtype
            )
        ]
    if refresh_cron is not None:
        # mirror the single-pod builder template even under --multihost:
        # the refresh subset is small by construction, so one process is
        # the right shape regardless of how the FULL build fans out
        template = _builder_job(
            project, image, tpu_resources, serve_dtype=serve_dtype
        )
        builder_docs.append(
            _refresh_cronjob(project, image, refresh_cron, template)
        )
    if backfill is not None:
        start, end = backfill
        if backfill_shards < 1:
            raise ValueError(
                f"backfill_shards must be >= 1, got {backfill_shards}"
            )
        if backfill_shards > len(machines):
            raise ValueError(
                f"--backfill-shards {backfill_shards} exceeds the "
                f"project's machine count ({len(machines)}): machines are "
                f"the atoms of the backfill partition, so extra pods "
                f"would own empty shards. Use --backfill-shards <= "
                f"{len(machines)}."
            )
        # same single-pod template shape as the refresh CronJob: each
        # backfill shard is one process staging its own fleet subset
        template = _builder_job(
            project, image, tpu_resources, serve_dtype=serve_dtype
        )
        builder_docs.append(
            _backfill_job(
                project, image, start, end, backfill_shards, template
            )
        )
    sharded = serve_shards is not None and serve_shards > 1
    if sharded:
        from gordo_tpu.serve.shard import ShardSpec, shard_map

        specs = [ShardSpec(i, serve_shards) for i in range(serve_shards)]
        server_docs: List[Dict[str, Any]] = []
        for spec in specs:
            server_docs.append(
                _server_deployment(
                    project, image, server_replicas, server_args,
                    scrape_annotations=scrape_annotations,
                    serve_dtype=serve_dtype, shard=spec,
                )
            )
            server_docs.append(
                _service(
                    project, f"ml-server-shard-{spec.index}",
                    DEFAULT_SERVER_PORT,
                    annotations=_SSE_SERVICE_ANNOTATIONS,
                )
            )
            server_docs.append(
                _server_hpa(project, spec, max_replicas=hpa_max_replicas)
            )
        watchman_targets = [
            f"http://gordo-ml-server-shard-{i}-{project}:"
            f"{DEFAULT_SERVER_PORT}"
            for i in range(serve_shards)
        ]
        owner = shard_map(machines, serve_shards)
        mapping_component = {
            m: f"ml-server-shard-{owner[m]}" for m in machines
        }
    else:
        server_docs = [
            _server_deployment(
                project, image, server_replicas, server_args,
                scrape_annotations=scrape_annotations,
                serve_dtype=serve_dtype,
            ),
            _service(
                project, "ml-server", DEFAULT_SERVER_PORT,
                annotations=_SSE_SERVICE_ANNOTATIONS,
            ),
        ]
        watchman_targets = [
            f"http://gordo-ml-server-{project}:{DEFAULT_SERVER_PORT}"
        ]
        mapping_component = {m: "ml-server" for m in machines}
    docs: List[Dict[str, Any]] = [
        *builder_docs,
        *server_docs,
        _watchman_deployment(
            project, image, machines,
            scrape_annotations=scrape_annotations,
            targets=watchman_targets,
        ),
        _service(
            project, "watchman", DEFAULT_WATCHMAN_PORT,
            annotations=_SSE_SERVICE_ANNOTATIONS,
        ),
    ]
    docs.extend(
        _machine_mapping(project, m, mapping_component[m]) for m in machines
    )
    # streaming-plane routes (docs/serving.md "Streaming"): SSE-safe
    # Mappings with the per-request timeout disabled.  Sharded tiers get
    # one route per shard (ingest + subscribe against the replica that
    # OWNS the machines — streams are per-replica state) plus a merged
    # read-only route through the watchman relay's fan-in.
    if sharded:
        for spec in specs:
            docs.append(_stream_mapping(
                project,
                name=f"gordo-mapping-{project}-stream-shard-{spec.index}",
                prefix=f"{API_PREFIX}/{project}/shard-{spec.index}/stream",
                rewrite=f"{API_PREFIX}/{project}/stream",
                component=f"ml-server-shard-{spec.index}",
            ))
        docs.append(_stream_mapping(
            project,
            name=f"gordo-mapping-{project}-stream-merged",
            prefix=f"{API_PREFIX}/{project}/stream/merged",
            rewrite="/stream",
            component="watchman",
            port=DEFAULT_WATCHMAN_PORT,
        ))
    else:
        docs.append(_stream_mapping(
            project,
            name=f"gordo-mapping-{project}-stream",
            prefix=f"{API_PREFIX}/{project}/stream",
            rewrite=f"{API_PREFIX}/{project}/stream",
            component="ml-server",
        ))
    if include_plan:
        docs.append(
            {
                "apiVersion": "v1",
                "kind": "ConfigMap",
                "metadata": {
                    "name": f"gordo-build-plan-{project}",
                    "labels": _labels(project, "build-plan"),
                },
                "data": {"plan.yaml": yaml.safe_dump(build_plan(config))},
            }
        )
    return docs


def workflow_to_yaml(docs: List[Dict[str, Any]]) -> str:
    return yaml.safe_dump_all(docs, sort_keys=False)


# ---------------------------------------------------------------------------
# Argo shim
# ---------------------------------------------------------------------------

def generate_argo_workflow(
    config: NormalizedConfig,
    image: str = DEFAULT_IMAGE,
    max_bucket_size: int = 512,
    tpu_resources: Optional[Dict[str, Any]] = None,
    serve_dtype: Optional[str] = None,
) -> Dict[str, Any]:
    """Project config → one ``argoproj.io/v1alpha1 Workflow`` document.

    Reference equivalent: ``gordo_components/workflow`` rendered an Argo
    Workflow with one pod per machine.  The TPU-native build is the
    bucketed fleet program (one Job), so this shim exists for clusters
    whose tooling consumes Argo documents: a DAG with ONE task per fleet
    chunk (not per machine — a chunk is the unit that shares a stacked
    XLA program), each running ``gordo build-project --machines <chunk>``
    against the shared project ConfigMap and models PVC.  Chunk tasks are
    independent (no DAG edges): Argo schedules them with whatever
    parallelism the cluster allows, and the config-hash registry makes
    retries idempotent.
    """
    project = config.project_name
    plan = build_plan(config, max_bucket_size=max_bucket_size)
    tpu_resources = tpu_resources or {
        "limits": {"google.com/tpu": 8},
        "requests": {"google.com/tpu": 8},
    }
    tasks = [
        {
            "name": bucket["bucket"],
            "template": "build-chunk",
            "arguments": {
                "parameters": [
                    {
                        "name": "machines",
                        "value": ",".join(bucket["machines"]),
                    }
                ]
            },
        }
        for bucket in plan["buckets"]
    ]
    return {
        "apiVersion": "argoproj.io/v1alpha1",
        "kind": "Workflow",
        "metadata": {
            "generateName": f"gordo-build-{project}-",
            "labels": _labels(project, "model-builder"),
        },
        "spec": {
            "entrypoint": "build",
            "templates": [
                {"name": "build", "dag": {"tasks": tasks}},
                {
                    "name": "build-chunk",
                    "inputs": {"parameters": [{"name": "machines"}]},
                    "container": {
                        "name": "model-builder",
                        "image": image,
                        "command": ["gordo", "build-project"],
                        "args": [
                            "--machine-config", "/config/project.yaml",
                            "--output-dir", "/models",
                            "--model-register-dir", "/models/.register",
                            "--max-bucket-size", str(max_bucket_size),
                            "--machines",
                            "{{inputs.parameters.machines}}",
                        ],
                        "env": [
                            {"name": "PROJECT_NAME", "value": project},
                            # chunk tasks share one models PVC: each task
                            # writes its chunk's pack + an index merge
                            # (flock-serialized), not per-machine dirs —
                            # the v2 library default
                            *_serve_dtype_env(serve_dtype),
                        ],
                        "resources": tpu_resources,
                        "volumeMounts": [
                            {"name": "models", "mountPath": "/models"},
                            {
                                "name": "project-config",
                                "mountPath": "/config",
                            },
                        ],
                    },
                },
            ],
            "volumes": [
                {
                    "name": "models",
                    "persistentVolumeClaim": {
                        "claimName": f"gordo-models-{project}"
                    },
                },
                {
                    "name": "project-config",
                    "configMap": {"name": f"gordo-config-{project}"},
                },
            ],
        },
    }
