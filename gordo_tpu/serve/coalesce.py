"""Cross-request micro-batching: concurrent single-machine requests ride
one stacked device dispatch.

Reference equivalent: none — the reference's pod-per-model design gave
each request its own Flask worker and its own Keras predict; aggregate
throughput scaled only with pod count.  Here many machines share one chip,
and the per-request cost is DISPATCH (tiny program launch + transfer
latency), not compute: the measured single-machine HTTP route sustains
~600k samples/s while the stacked bulk route moves 3.1M on the same
hardware.  The coalescer closes that gap for clients that can't use the
bulk route: queued requests are grouped and scored through the SAME
vmapped fleet program the ``_bulk`` route uses, then sliced back per
request.

Batching policy (r6 — the r5 windowed drain lost 15% throughput and +48%
p99 at 64-way concurrency, BENCH_r05):

- **Continuous drain.**  The worker pulls the queue the moment it is free
  instead of idling through a fixed window; the previous dispatch's own
  service time is the accumulation window.  Under light load a lone
  request waits at most ``max_wait_s`` for a second rider; under heavy
  load nothing ever waits idle.
- **Knee cap.**  Effective batch size is capped at the measured
  throughput knee — the batch size past which a bigger dispatch no longer
  improves per-request amortization (it only stretches service time and
  p99).  ``knee_batch`` sets it explicitly; by default a short warmup
  sweep (:func:`estimate_knee`) measures it against the live fleet
  scorer, exercising the same gathered-subset and full-bucket dispatch
  paths production rounds use.
- **Assembly off the drain thread.**  The drain thread runs only the
  device dispatch (``FleetScorer.dispatch_all``); per-request result
  assembly and future resolution run on a separate finish pool, so
  response fan-out never serializes behind the next batch's gather.
- **Saturation stand-down.**  When queue wait runs away from service time
  (p99 wait > ``standdown_ratio`` × median service), batching is losing —
  new arrivals dispatch directly for ``standdown_cooldown_s`` while the
  queue drains, then coalescing resumes.  The combined path is never
  worse than direct for longer than one cooldown.

Semantics are identical to the per-machine path (same fused program
family, same padding rules, same per-machine error isolation).

Enabled via ``build_app(collection, coalesce_window_ms=...)`` /
``gordo run-server --coalesce-ms ...``; off by default.
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from gordo_tpu import compile as compile_plane
from gordo_tpu import telemetry

logger = logging.getLogger(__name__)

# -- telemetry instruments (docs/observability.md) --------------------------
_REQUESTS_TOTAL = telemetry.counter(
    "gordo_coalesce_requests_total",
    "Requests entering the coalescer (stacked and fallback-routed)",
)
_DISPATCHES_TOTAL = telemetry.counter(
    "gordo_coalesce_dispatches_total",
    "Stacked device dispatches run by the drain worker",
)
_BYPASSED_TOTAL = telemetry.counter(
    "gordo_coalesce_bypassed_total",
    "Requests routed direct instead of coalescing, by reason",
    labels=("reason",),
)
_BATCH_SIZE = telemetry.histogram(
    "gordo_coalesce_batch_size",
    "Requests drained per batch (before round-splitting)",
    buckets=telemetry.metrics.DEFAULT_SIZE_BUCKETS,
)
_QUEUE_WAIT_SECONDS = telemetry.histogram(
    "gordo_coalesce_queue_wait_seconds",
    "Per-request wait between enqueue and dispatch",
)
_DISPATCH_SECONDS = telemetry.histogram(
    "gordo_coalesce_dispatch_seconds",
    "Device service time of one stacked coalesced dispatch",
)
_STANDDOWNS_TOTAL = telemetry.counter(
    "gordo_coalesce_standdowns_total",
    "Saturation stand-downs (batching judged losing; routing direct)",
)
_KNEE_ESTIMATES_TOTAL = telemetry.counter(
    "gordo_coalesce_knee_estimates_total",
    "Knee-sweep runs by outcome",
    labels=("outcome",),
)
_QUEUE_DEPTH_GAUGE = telemetry.gauge(
    "gordo_coalesce_queue_depth", "Requests currently queued for a dispatch"
)
_INFLIGHT_GAUGE = telemetry.gauge(
    "gordo_coalesce_inflight",
    "In-flight single-machine anomaly requests (the bypass signal)",
)
_BATCH_CAP_GAUGE = telemetry.gauge(
    "gordo_coalesce_batch_cap", "Effective per-dispatch batch bound"
)
_STANDING_DOWN_GAUGE = telemetry.gauge(
    "gordo_coalesce_standing_down",
    "1 while the saturation stand-down routes requests direct",
)
_WAIT_SERVICE_RATIO_GAUGE = telemetry.gauge(
    "gordo_coalesce_wait_service_ratio",
    "Latest p99 queue wait over median service time (the overload and "
    "HPA signal; stand-down fires past standdown_ratio, shedding past "
    "the first cooldown doubling)",
)
_SHEDDING_GAUGE = telemetry.gauge(
    "gordo_coalesce_shedding",
    "1 while escalated saturation sheds new requests with 429",
)
_EXPIRED_TOTAL = telemetry.counter(
    "gordo_coalesce_expired_total",
    "Queued riders dropped before dispatch because their propagated "
    "deadline (X-Gordo-Deadline-Ms) expired while waiting",
)


class DeadlineExpired(Exception):
    """A queued rider's propagated deadline passed before its batch
    dispatched — the client upstream has already given up, so scoring it
    would spend device time on a dead response.  The handler maps this
    to 504."""


def export_gauges(coalescer: Optional["CoalescingScorer"]) -> None:
    """Refresh the point-in-time coalescer gauges (called by the server's
    ``/metrics`` handler at scrape time — gauges describe 'now')."""
    if coalescer is None:
        return
    _QUEUE_DEPTH_GAUGE.set(len(coalescer._queue))
    _INFLIGHT_GAUGE.set(coalescer.inflight)
    _BATCH_CAP_GAUGE.set(coalescer.batch_cap)
    _STANDING_DOWN_GAUGE.set(1.0 if coalescer.standing_down else 0.0)
    _WAIT_SERVICE_RATIO_GAUGE.set(coalescer.wait_service_ratio)
    _SHEDDING_GAUGE.set(
        1.0 if shed_retry_after(coalescer) is not None else 0.0
    )


#: consecutive stand-downs before the server starts SHEDDING (429 +
#: Retry-After) instead of routing direct: the first stand-down is a
#: transient probe (base cooldown); the second is the first cooldown
#: doubling — overload that persisted through a full cooldown, where
#: accepting more work only queues it to death
SHED_MIN_STREAK = 2
#: Retry-After ceiling: a shed client should probe again within the
#: stand-down's own escalation horizon, not minutes later
SHED_RETRY_MAX_S = 30.0


def shed_retry_after(
    coalescer: Optional["CoalescingScorer"],
) -> Optional[float]:
    """Seconds a shed request should wait before retrying, or None when
    the server should accept work.

    Shedding engages when the saturation stand-down has ESCALATED — at
    least :data:`SHED_MIN_STREAK` consecutive stand-downs, i.e. the
    cooldown has started doubling — and the suggested delay derives from
    what was OBSERVED, not a constant: at least the p99 queue wait that
    tripped the signal (a retry sooner than that lands in the same
    queue), at least the remaining cooldown (before it, batching is
    still stood down), floored at 1s (the header's second granularity)
    and capped at :data:`SHED_RETRY_MAX_S`."""
    if coalescer is None:
        return None
    if not coalescer.standing_down:
        return None
    if coalescer._standdown_streak < SHED_MIN_STREAK:
        return None
    remaining = coalescer._standdown_until - time.monotonic()
    suggest = max(coalescer.last_wait_p99, remaining, 1.0)
    return min(suggest, SHED_RETRY_MAX_S)


#: knee sweep acceptance: doubling the batch must improve throughput by at
#: least this factor to keep doubling (1.1 = 10% — below that the bigger
#: dispatch only stretches p99 for no amortization gain)
KNEE_MIN_GAIN = 1.1


def estimate_knee(
    fleet: Any,
    rows: int = 1024,
    max_batch: int = 512,
    min_gain: float = KNEE_MIN_GAIN,
) -> Optional[Dict[str, float]]:
    """Short warmup sweep for the batch-size throughput knee.

    Doubles the dispatch size (1, 2, 4, …) against the fleet scorer's
    largest bucket — subset-gather dispatches below the bucket size, the
    full stacked program at it — and stops when throughput(b) <
    ``min_gain`` × throughput(b/2), i.e. when a bigger batch stops paying
    for its longer service time.  Each size is timed as the MIN of two
    warm repetitions: a single noisy rep once mis-measured the knee at 1
    and strangled the coalescer into serialized micro-batches (r6 bench,
    −20% at 8-way).

    Returns ``{"knee": b, "amortization": t(1)·b / t(b)}`` — the
    amortization factor is how many single-dispatch service times b
    batched requests cost; ~b on a dispatch-dominated device (TPU tunnel:
    flat service curve), ~1 when service scales linearly with batch (CPU
    compute-bound), where batching cannot pay at ANY size.  None when the
    fleet has no stacked bucket (nothing to batch into).

    Cost: ~3 dispatches per size, log2(max_batch) sizes — seconds, and
    every dispatch doubles as program warmup for the sizes coalesced
    rounds will actually run at.
    """
    buckets = getattr(fleet, "buckets", None)
    if not buckets:
        return None
    bucket = max(buckets, key=lambda b: len(b.names))
    names = bucket.names
    n_feat = bucket.n_features or 1
    rows = max(int(rows), bucket.lookback + 1)
    X = np.zeros((rows, n_feat), np.float32)
    knee = 1
    t1: Optional[float] = None
    prev_t: Optional[float] = None
    size = 1
    limit = min(int(max_batch), len(names))
    while size <= limit:
        sub = {n: X for n in names[:size]}
        fleet.score_all(sub)  # compile/warm — excluded from the timing
        t = float("inf")
        for _ in range(2):  # min-of-2: timing noise only ever ADDS
            t0 = time.perf_counter()
            fleet.score_all(sub)
            t = min(t, time.perf_counter() - t0)
        if size == 1:
            t1 = t
        if prev_t is not None and t * min_gain > 2.0 * prev_t:
            break  # throughput gain from doubling fell under min_gain
        knee, prev_t = size, t
        size *= 2
    return {
        "knee": knee,
        "amortization": (t1 * knee / prev_t) if prev_t else 1.0,
    }


class CoalescingScorer:
    """Queue single-machine anomaly requests; a worker drains them
    continuously and runs one ``FleetScorer`` dispatch per drained batch.

    ``fleet_provider`` is called per batch (not cached) so a collection
    rescan's scorer reset takes effect on the next dispatch.
    """

    def __init__(
        self,
        fleet_provider: Callable[[], Any],
        max_wait_s: float = 0.002,
        max_batch: int = 512,
        min_concurrency: int = 2,
        knee_batch: int = 0,
        min_amortization: float = 2.0,
        standdown_ratio: float = 4.0,
        standdown_cooldown_s: float = 0.5,
        standdown_max_s: float = 8.0,
        signal_window: int = 64,
    ):
        self._provider = fleet_provider
        #: single-rider grace: a batch of 1 gains nothing from the stacked
        #: gather, so when peers are in flight the drain waits up to this
        #: long for a second rider.  This is the ONLY wait left from the
        #: r5 windowed design — a queue with >=2 entries dispatches
        #: immediately.
        self.max_wait_s = float(max_wait_s)
        self.max_batch = int(max_batch)
        #: adaptive bypass: coalescing only ever wins when requests overlap
        #: (≥2 riders share a dispatch); below this many in-flight
        #: single-machine requests the route scores directly, so an idle or
        #: lightly-loaded server pays neither the rider wait nor the
        #: gather-dispatch overhead (r4 driver bench: coalescing at low
        #: concurrency cost 23% throughput / +66% p99)
        self.min_concurrency = int(min_concurrency)
        #: explicit batch cap (0 = auto-estimate the knee on first use)
        self.knee_batch = int(knee_batch)
        #: batching must amortize at least this many single-dispatch
        #: service times at the knee, or the sweep DISABLES coalescing
        #: outright: an amortization of ~1 (service linear in batch — the
        #: CPU compute-bound regime) means sharing a dispatch saves
        #: nothing and queueing can only add latency.  An explicit
        #: ``knee_batch`` skips the sweep and this check.
        self.min_amortization = float(min_amortization)
        self._knee_no_gain = False
        self.standdown_ratio = float(standdown_ratio)
        #: first stand-down lasts this long; CONSECUTIVE ones double it up
        #: to ``standdown_max_s`` — a regime where batching structurally
        #: loses converges to ~all-direct with rare short probes, instead
        #: of spending half its time in losing re-probes
        self.standdown_cooldown_s = float(standdown_cooldown_s)
        self.standdown_max_s = float(standdown_max_s)
        self._standdown_streak = 0
        self.signal_window = int(signal_window)
        #: in-flight single-machine anomaly requests, maintained by the
        #: route handler on the event loop (single-threaded increments)
        self.inflight = 0
        self.n_bypassed = 0
        self.n_queue_full = 0
        self.n_standdowns = 0
        self._standdown_until = 0.0
        #: latest saturation-signal evaluation (drain-thread writes;
        #: scrape/shed reads): p99 queue wait, and its ratio over median
        #: service time — the overload/HPA telemetry and the observed
        #: basis of a shed response's Retry-After
        self.last_wait_p99 = 0.0
        self.wait_service_ratio = 0.0
        self._knee: Optional[int] = None
        self._knee_started = False
        self._cv = threading.Condition()
        #: (name, X, future, enqueue time, trace id) — the trace id rides
        #: the queue so dispatch spans can name every rider they carried
        self._queue: List[
            Tuple[str, np.ndarray, Future, float, Optional[str],
                  Optional[float]]
        ] = []
        self._closed = False
        self.n_dispatches = 0
        self.n_requests = 0
        self.n_fallback = 0
        #: saturation signal state (drain-thread writes, stats reads)
        self._waits: deque = deque(maxlen=self.signal_window)
        self._services: deque = deque(maxlen=32)
        # machines the fleet scorer can't stack run its slow host-side
        # fallback; they score HERE instead, so one slow machine can't
        # head-of-line-block the stacked batches on the worker thread
        self._fallback_pool = ThreadPoolExecutor(
            max_workers=4, thread_name_prefix="gordo-coalesce-fb"
        )
        #: result assembly + future resolution run here, NOT on the drain
        #: thread — the drain thread starts gathering the next batch the
        #: moment the device dispatch returns
        self._finish_pool = ThreadPoolExecutor(
            max_workers=2, thread_name_prefix="gordo-coalesce-fin"
        )
        self._thread = threading.Thread(
            target=self._run, name="gordo-coalescer", daemon=True
        )
        self._thread.start()

    #: pre-knee batch cap: until the sweep lands, dispatches are bounded
    #: here rather than at max_batch — the r5 64-way loss was exactly
    #: uncapped saturated dispatches, and the estimate arrives within the
    #: first seconds of load
    PRE_KNEE_CAP = 64

    # -- batching policy -----------------------------------------------------
    @property
    def batch_cap(self) -> int:
        """Effective per-dispatch batch bound: the explicit ``knee_batch``,
        else the estimated knee, else a conservative pre-knee cap."""
        cap = (
            self.knee_batch
            or self._knee
            or min(self.max_batch, self.PRE_KNEE_CAP)
        )
        return max(1, min(cap, self.max_batch))

    def ensure_knee(self, rows: int = 1024) -> Optional[int]:
        """Estimate the knee once (idempotent; safe from any thread).
        Called from the server's warmup task when warmup is enabled, from
        the replay harness's warmup phase, and lazily (in the background)
        on the first live dispatch otherwise.

        When the sweep finds no amortization (service time ~linear in
        batch size), coalescing is DISABLED for this scorer's lifetime:
        batching that saves nothing can only add queueing latency, so the
        honest adaptive answer is to get out of the way entirely."""
        if self.knee_batch or self._knee is not None or self._knee_no_gain:
            return self._knee
        self._knee_started = True
        try:
            est = estimate_knee(
                self._provider(), rows=rows, max_batch=self.max_batch
            )
        except Exception:
            _KNEE_ESTIMATES_TOTAL.inc(1.0, "failed")
            logger.exception(
                "Knee estimation failed; batch cap stays at the pre-knee "
                "bound"
            )
            return None
        if est is None:
            _KNEE_ESTIMATES_TOTAL.inc(1.0, "no_bucket")
            return None
        if est["amortization"] < self.min_amortization:
            self._knee_no_gain = True
            _KNEE_ESTIMATES_TOTAL.inc(1.0, "no_gain")
            # one structured line: batching saves nothing on this backend,
            # every future request routes direct for this scorer's lifetime
            telemetry.log_event(
                logger, "coalescer_knee_no_gain",
                amortization=round(est["amortization"], 2),
                min_amortization=self.min_amortization,
                knee=int(est["knee"]),
            )
            return None
        self._knee = int(est["knee"])
        _KNEE_ESTIMATES_TOTAL.inc(1.0, "estimated")
        telemetry.log_event(
            logger, "coalescer_knee_estimated", level=logging.INFO,
            knee=self._knee, amortization=round(est["amortization"], 2),
        )
        return self._knee

    def _note_dispatch_signal(self, waits: List[float], service: float) -> None:
        """Record queue waits + service time; stand down when p99 wait says
        batching is losing (requests queue faster than dispatches clear)."""
        self._waits.extend(waits)
        self._services.append(service)
        if (
            len(self._waits) < max(4, self.signal_window // 4)
            or len(self._services) < 4
        ):
            return
        wait_p99 = float(np.percentile(np.asarray(self._waits), 99))
        med_service = float(np.median(np.asarray(self._services)))
        self.last_wait_p99 = wait_p99
        self.wait_service_ratio = wait_p99 / max(med_service, 1e-6)
        if wait_p99 > self.standdown_ratio * max(med_service, 1e-6):
            cooldown = min(
                self.standdown_cooldown_s * (2 ** self._standdown_streak),
                self.standdown_max_s,
            )
            self._standdown_streak += 1
            self._standdown_until = time.monotonic() + cooldown
            self.n_standdowns += 1
            _STANDDOWNS_TOTAL.inc()
            # waits reset (they describe the regime we just left); service
            # times stay — they remain valid and let a post-cooldown probe
            # re-evaluate after only ~signal_window/4 fresh waits
            self._waits.clear()
            # one structured line per stand-down (the satellite contract:
            # these transitions were previously invisible at runtime)
            telemetry.log_event(
                logger, "coalescer_standdown",
                cooldown_s=round(cooldown, 2),
                wait_p99_ms=round(wait_p99 * 1e3, 1),
                service_median_ms=round(med_service * 1e3, 1),
                streak=self._standdown_streak,
            )
        else:
            # a healthy evaluation ends the escalation: the next
            # stand-down (if any) starts from the base cooldown again
            self._standdown_streak = 0

    @property
    def standing_down(self) -> bool:
        return time.monotonic() < self._standdown_until

    # -- producer side -------------------------------------------------------
    def should_coalesce(self) -> bool:
        """True when enough requests are in flight for a shared dispatch to
        pay for itself, the saturation signal isn't standing the coalescer
        down, AND the queue isn't already saturated; callers score
        directly otherwise (and count the bypass for the stats endpoint).

        The queue-depth backpressure is the per-request loss bound: once
        the queue holds two knee-capped dispatches' worth, a new rider
        would wait >= 2 service times with no amortization gain, so it
        dispatches direct instead — under saturation the combined path
        degrades to ~direct continuously, without waiting for the
        stand-down signal to accumulate."""
        if self._knee_no_gain or self.standing_down:
            self.n_bypassed += 1
            _BYPASSED_TOTAL.inc(
                1.0, "no_gain" if self._knee_no_gain else "standdown"
            )
            return False
        if compile_plane.warming():
            # startup warmup still compiling: queue behind it rather than
            # dispatch direct — a direct dispatch would block an executor
            # thread on its own cold compile of the very program the
            # warmup is about to land, while queued riders share ONE
            # compile when the drain gets to them
            return True
        if self.inflight < self.min_concurrency:
            self.n_bypassed += 1
            _BYPASSED_TOTAL.inc(1.0, "low_concurrency")
            return False
        # len() on the queue list is GIL-atomic; a stale read only shifts
        # one request between two correct paths
        if len(self._queue) >= 2 * self.batch_cap:
            self.n_queue_full += 1
            self.n_bypassed += 1
            _BYPASSED_TOTAL.inc(1.0, "queue_full")
            return False
        return True

    def reset_stats(self) -> None:
        """Zero the counters (requests/dispatches/bypasses) without
        touching the learned policy state (knee, no-gain flag, stand-down
        escalation) — benches call this after their warmup phase so the
        reported stats describe only the measured window."""
        self.n_requests = 0
        self.n_dispatches = 0
        self.n_fallback = 0
        self.n_bypassed = 0
        self.n_queue_full = 0
        self.n_standdowns = 0

    def submit(
        self, name: str, X: np.ndarray, trace_id: Optional[str] = None,
        deadline: Optional[float] = None,
    ) -> Future:
        """Enqueue one machine's rows; the Future resolves to the same
        arrays dict ``CompiledScorer.anomaly_arrays`` returns.
        ``trace_id`` (the request's propagated id) tags the dispatch span
        this request ends up riding.  ``deadline`` (a ``time.monotonic()``
        timestamp from the propagated budget) lets the drain drop this
        rider with :class:`DeadlineExpired` instead of dispatching work
        the client already abandoned."""
        fut: Future = Future()
        with self._cv:
            if self._closed:
                raise RuntimeError("CoalescingScorer is closed")
            self._queue.append(
                (name, X, fut, time.monotonic(), trace_id, deadline)
            )
            self._cv.notify()
        return fut

    def close(self) -> None:
        with self._cv:
            self._closed = True
            self._cv.notify()
        self._thread.join(timeout=5)
        # drain thread no longer submits; let in-flight assemblies resolve
        # their futures before the pool dies
        self._finish_pool.shutdown(wait=True)
        self._fallback_pool.shutdown(wait=False)

    # -- worker side ---------------------------------------------------------
    def _drain(
        self,
    ) -> List[Tuple[str, np.ndarray, Future, float, Optional[str],
                    Optional[float]]]:
        """Continuous drain: block for work, take what's queued (up to the
        knee cap) NOW.  The only wait is the single-rider grace — one
        queued request with peers still in flight holds ``max_wait_s`` for
        a second rider, because a batch of 1 cannot amortize anything.
        A rider carrying a propagated deadline caps the grace at its own
        remaining budget (deadline-aware admission: holding a request
        past the point its client gives up turns the grace into a 504)."""
        with self._cv:
            while not self._queue and not self._closed:
                self._cv.wait()
            if not self._queue:
                return []
            if (
                len(self._queue) == 1
                and self.inflight > 1
                and self.max_wait_s > 0
            ):
                deadline = time.monotonic() + self.max_wait_s
                rider_deadline = self._queue[0][5]
                if rider_deadline is not None:
                    deadline = min(deadline, rider_deadline)
                while len(self._queue) == 1 and not self._closed:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        break
                    self._cv.wait(remaining)
            # hand over at most batch_cap; the rest stays queued for the
            # IMMEDIATE next iteration (no idle window between dispatches)
            cap = self.batch_cap
            batch = self._queue[:cap]
            self._queue = self._queue[cap:]
            return batch

    def _run(self) -> None:
        while True:
            try:
                batch = self._drain()
                if not batch:
                    if self._closed:
                        return
                    continue
                t_dispatch = time.monotonic()
                # expired riders resolve with DeadlineExpired BEFORE the
                # dispatch: their clients already gave up, and dropping
                # them here frees the batch slot for live work
                live = []
                for item in batch:
                    dl = item[5]
                    if dl is not None and t_dispatch >= dl:
                        _EXPIRED_TOTAL.inc()
                        self._resolve(item[2], exc=DeadlineExpired(
                            f"rider for {item[0]!r} expired "
                            f"{t_dispatch - dl:.3f}s before dispatch"
                        ))
                    else:
                        live.append(item)
                batch = live
                if not batch:
                    continue
                waits = [
                    t_dispatch - t_enq for _, _, _, t_enq, _, _ in batch
                ]
                for w in waits:
                    _QUEUE_WAIT_SECONDS.observe(w)
                _BATCH_SIZE.observe(len(batch))
                # score_all keys by machine name, so duplicate-name requests
                # split into successive rounds (each round has unique names)
                rounds: List[
                    Dict[str, Tuple[np.ndarray, Future, Optional[str]]]
                ] = []
                for name, X, fut, _, tid, _ in batch:
                    for rnd in rounds:
                        if name not in rnd:
                            rnd[name] = (X, fut, tid)
                            break
                    else:
                        rounds.append({name: (X, fut, tid)})
                service = 0.0
                for rnd in rounds:
                    service += self._score_round(rnd)
                if service > 0:
                    self._note_dispatch_signal(waits, service)
            except Exception:
                # the worker must be unkillable: a dead worker would leave
                # every future unresolved and the route hanging forever
                logger.exception("Coalescer worker iteration failed")

    @staticmethod
    def _resolve(fut: Future, res: Any = None, exc: Optional[Exception] = None) -> None:
        """Resolve a future that a disconnecting client may cancel at any
        moment: set_running_or_notify_cancel() closes the PENDING->cancel
        race (a RUNNING future cannot be cancelled), and the InvalidState
        guard keeps the worker alive no matter what."""
        try:
            if not fut.set_running_or_notify_cancel():
                return  # cancelled before scoring completed
            if exc is not None:
                fut.set_exception(exc)
            else:
                fut.set_result(res)
        except Exception:
            logger.exception("Failed to resolve coalesced future")

    def _score_one(self, scorer: Any, name: str, X: np.ndarray, fut: Future) -> None:
        """Score a non-stackable machine on the fallback pool."""
        try:
            out = scorer.score_all({name: X})
        except Exception as exc:
            self._resolve(fut, exc=exc)
            return
        self._finish(name, fut, out)

    def _score_round(
        self, rnd: Dict[str, Tuple[np.ndarray, Future, Optional[str]]]
    ) -> float:
        """Dispatch one unique-name round; returns the device service time
        (0.0 when nothing reached a stacked dispatch)."""
        self.n_requests += len(rnd)
        _REQUESTS_TOTAL.inc(len(rnd))
        try:
            scorer = self._provider()
        except Exception as exc:
            for _, fut, _ in rnd.values():
                self._resolve(fut, exc=exc)
            return 0.0
        if not self._knee_started and not self.knee_batch:
            # lazy knee estimation off the drain thread: until it lands the
            # cap is max_batch (the r5 behavior); the sweep doubles as
            # subset-program warmup.  Row hint: this round's request shape.
            self._knee_started = True
            rows = max(x.shape[0] for x, _, _ in rnd.values())
            self._fallback_pool.submit(self.ensure_knee, rows)
        # machines outside the stacked buckets run FleetScorer's host-side
        # fallback (potentially 100s of ms each) — push those off the
        # worker so they can't head-of-line-block the fast stacked batch
        stacked = {}
        for name, (X, fut, tid) in rnd.items():
            if name in scorer.machine_bucket or name not in scorer.models:
                stacked[name] = (X, fut, tid)  # unknown names error in-slot
            else:
                self.n_fallback += 1
                self._fallback_pool.submit(
                    self._score_one, scorer, name, X, fut
                )
        if not stacked:
            return 0.0
        rnd = stacked
        self.n_dispatches += 1
        _DISPATCHES_TOTAL.inc()
        t0 = time.monotonic()
        # the dispatch span carries every rider's propagated trace id, so
        # a request's timeline can be followed INTO the shared dispatch
        riders = sorted(
            {tid for _, _, tid in rnd.values() if tid is not None}
        )
        with telemetry.span(
            "coalesce.dispatch", batch=len(rnd), traces=riders
        ):
            try:
                # dispatch_all runs the device work (stack → dispatch →
                # device_get) and defers per-machine assembly; scorers
                # without the split API (tests, exotic providers) do both
                # here
                dispatch = getattr(scorer, "dispatch_all", None)
                X_map = {n: x for n, (x, _, _) in rnd.items()}
                pending = dispatch(X_map) if dispatch is not None else (
                    scorer.score_all(X_map)
                )
            except Exception as exc:  # whole-dispatch failure: fail futures
                logger.exception("Coalesced dispatch failed")
                for _, fut, _ in rnd.values():
                    self._resolve(fut, exc=exc)
                service = time.monotonic() - t0
                _DISPATCH_SECONDS.observe(service)
                return service
        service = time.monotonic() - t0
        _DISPATCH_SECONDS.observe(service)
        # per-request result assembly + future resolution run on the
        # finish pool: the drain thread is free to gather the next batch
        self._finish_pool.submit(self._finish_round, rnd, pending)
        return service

    def _finish_round(
        self,
        rnd: Dict[str, Tuple[np.ndarray, Future, Optional[str]]],
        pending: Any,
    ) -> None:
        """Assemble per-machine results (host-side numpy slicing) and
        resolve the round's futures — off the drain thread.

        This stays the NON-columnar ``assemble``: a coalesced round
        fans out to many single-machine responses, each negotiated and
        encoded for its own requester, so the per-machine split happens
        here regardless of wire format.  The GSB1 columnar path
        (``assemble_columnar`` + ``encode_columnar``) belongs to the
        ``_bulk`` route, which bypasses the coalescer entirely — one
        requester consumes the whole stacked result."""
        try:
            assemble = getattr(pending, "assemble", None)
            out = assemble() if assemble is not None else pending
        except Exception as exc:
            logger.exception("Coalesced result assembly failed")
            for _, fut, _ in rnd.values():
                self._resolve(fut, exc=exc)
            return
        for name, (_, fut, _) in rnd.items():
            self._finish(name, fut, out)

    def _finish(self, name: str, fut: Future, out: Dict[str, Any]) -> None:
        res = out.get(name)
        if res is None:
            self._resolve(
                fut, exc=RuntimeError(f"No result for machine {name!r}")
            )
        elif "error" in res and "model-output" not in res:
            # same exception surface as the per-machine scorer path:
            # client-input problems raise ValueError (-> HTTP 400),
            # everything else RuntimeError (-> 500)
            exc_cls = (
                ValueError if res.get("client-error") else RuntimeError
            )
            self._resolve(fut, exc=exc_cls(str(res["error"])))
        else:
            self._resolve(fut, res=res)


def stats(coalescer: Optional[CoalescingScorer]) -> Dict[str, Any]:
    if coalescer is None:
        return {"enabled": False}
    stacked = coalescer.n_requests - coalescer.n_fallback
    return {
        "enabled": True,
        "requests": coalescer.n_requests,
        "fallback_requests": coalescer.n_fallback,
        "bypassed_requests": coalescer.n_bypassed,
        "min_concurrency": coalescer.min_concurrency,
        "dispatches": coalescer.n_dispatches,
        # amortization of the STACKED path only — fallback-routed requests
        # never ride a dispatch and must not inflate the ratio
        "mean_batch": (
            round(stacked / coalescer.n_dispatches, 2)
            if coalescer.n_dispatches
            else None
        ),
        # r6 adaptive policy state
        "batch_cap": coalescer.batch_cap,
        "knee_batch": coalescer.knee_batch or None,
        "knee_estimated": coalescer._knee,
        "knee_no_gain": coalescer._knee_no_gain,
        "queue_full_bypassed": coalescer.n_queue_full,
        "standdowns": coalescer.n_standdowns,
        "standing_down": coalescer.standing_down,
        "shedding": shed_retry_after(coalescer) is not None,
        "wait_service_ratio": round(coalescer.wait_service_ratio, 2),
    }
