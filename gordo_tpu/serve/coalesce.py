"""Cross-request micro-batching: concurrent single-machine requests ride
one stacked device dispatch.

Reference equivalent: none — the reference's pod-per-model design gave
each request its own Flask worker and its own Keras predict; aggregate
throughput scaled only with pod count.  Here many machines share one chip,
and the per-request cost is DISPATCH (tiny program launch + transfer
latency), not compute: the measured single-machine HTTP route sustains
~600k samples/s while the stacked bulk route moves 3.1M on the same
hardware.  The coalescer closes that gap for clients that can't use the
bulk route: requests arriving within a small window are grouped and scored
through the SAME vmapped fleet program the ``_bulk`` route uses, then
sliced back per request.

Semantics are identical to the per-machine path (same fused program
family, same padding rules, same per-machine error isolation); only
latency changes — by at most ``max_wait_s`` under light load, negative
under heavy load (queueing beats serial dispatch).

Enabled via ``build_app(collection, coalesce_window_ms=...)`` /
``gordo run-server --coalesce-ms ...``; off by default.
"""

from __future__ import annotations

import logging
import threading
import time
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

logger = logging.getLogger(__name__)


class CoalescingScorer:
    """Queue single-machine anomaly requests; a worker drains them in
    windows and runs one ``FleetScorer.score_all`` per drained batch.

    ``fleet_provider`` is called per batch (not cached) so a collection
    rescan's scorer reset takes effect on the next dispatch.
    """

    def __init__(
        self,
        fleet_provider: Callable[[], Any],
        max_wait_s: float = 0.002,
        max_batch: int = 512,
        min_concurrency: int = 2,
    ):
        self._provider = fleet_provider
        self.max_wait_s = float(max_wait_s)
        self.max_batch = int(max_batch)
        #: adaptive bypass: coalescing only ever wins when requests overlap
        #: (≥2 riders share a dispatch); below this many in-flight
        #: single-machine requests the route scores directly, so an idle or
        #: lightly-loaded server pays neither the window wait nor the
        #: gather-dispatch overhead (r4 driver bench: coalescing at low
        #: concurrency cost 23% throughput / +66% p99)
        self.min_concurrency = int(min_concurrency)
        #: in-flight single-machine anomaly requests, maintained by the
        #: route handler on the event loop (single-threaded increments)
        self.inflight = 0
        self.n_bypassed = 0
        self._cv = threading.Condition()
        self._queue: List[Tuple[str, np.ndarray, Future]] = []
        self._closed = False
        self.n_dispatches = 0
        self.n_requests = 0
        self.n_fallback = 0
        # machines the fleet scorer can't stack run its slow host-side
        # fallback; they score HERE instead, so one slow machine can't
        # head-of-line-block the stacked batches on the worker thread
        self._fallback_pool = ThreadPoolExecutor(
            max_workers=4, thread_name_prefix="gordo-coalesce-fb"
        )
        self._thread = threading.Thread(
            target=self._run, name="gordo-coalescer", daemon=True
        )
        self._thread.start()

    # -- producer side -------------------------------------------------------
    def should_coalesce(self) -> bool:
        """True when enough requests are in flight for a shared dispatch to
        pay for its window wait; callers score directly otherwise (and count
        the bypass for the stats endpoint)."""
        if self.inflight >= self.min_concurrency:
            return True
        self.n_bypassed += 1
        return False

    def submit(self, name: str, X: np.ndarray) -> Future:
        """Enqueue one machine's rows; the Future resolves to the same
        arrays dict ``CompiledScorer.anomaly_arrays`` returns."""
        fut: Future = Future()
        with self._cv:
            if self._closed:
                raise RuntimeError("CoalescingScorer is closed")
            self._queue.append((name, X, fut))
            self._cv.notify()
        return fut

    def close(self) -> None:
        with self._cv:
            self._closed = True
            self._cv.notify()
        self._thread.join(timeout=5)
        self._fallback_pool.shutdown(wait=False)

    # -- worker side ---------------------------------------------------------
    def _drain(self) -> List[Tuple[str, np.ndarray, Future]]:
        """Block for work, then collect arrivals for up to ``max_wait_s``."""
        with self._cv:
            while not self._queue and not self._closed:
                self._cv.wait()
            if not self._queue:
                return []
            if len(self._queue) < self.max_batch:
                # normal operation: gather arrivals for one window.  Under
                # overload (a full batch already queued) dispatch NOW —
                # the leftovers of a burst must not sit through an extra
                # idle window each round.
                deadline = time.monotonic() + self.max_wait_s
                while len(self._queue) < self.max_batch:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0 or self._closed:
                        break
                    self._cv.wait(remaining)
            # hand over at most max_batch; the rest stays queued for the
            # next iteration instead of one unbounded mega-batch
            batch = self._queue[: self.max_batch]
            self._queue = self._queue[self.max_batch:]
            return batch

    def _run(self) -> None:
        while True:
            try:
                batch = self._drain()
                if not batch:
                    if self._closed:
                        return
                    continue
                # score_all keys by machine name, so duplicate-name requests
                # split into successive rounds (each round has unique names)
                rounds: List[Dict[str, Tuple[np.ndarray, Future]]] = []
                for name, X, fut in batch:
                    for rnd in rounds:
                        if name not in rnd:
                            rnd[name] = (X, fut)
                            break
                    else:
                        rounds.append({name: (X, fut)})
                for rnd in rounds:
                    self._score_round(rnd)
            except Exception:
                # the worker must be unkillable: a dead worker would leave
                # every future unresolved and the route hanging forever
                logger.exception("Coalescer worker iteration failed")

    @staticmethod
    def _resolve(fut: Future, res: Any = None, exc: Optional[Exception] = None) -> None:
        """Resolve a future that a disconnecting client may cancel at any
        moment: set_running_or_notify_cancel() closes the PENDING->cancel
        race (a RUNNING future cannot be cancelled), and the InvalidState
        guard keeps the worker alive no matter what."""
        try:
            if not fut.set_running_or_notify_cancel():
                return  # cancelled before scoring completed
            if exc is not None:
                fut.set_exception(exc)
            else:
                fut.set_result(res)
        except Exception:
            logger.exception("Failed to resolve coalesced future")

    def _score_one(self, scorer: Any, name: str, X: np.ndarray, fut: Future) -> None:
        """Score a non-stackable machine on the fallback pool."""
        try:
            out = scorer.score_all({name: X})
        except Exception as exc:
            self._resolve(fut, exc=exc)
            return
        self._finish(name, fut, out)

    def _score_round(self, rnd: Dict[str, Tuple[np.ndarray, Future]]) -> None:
        self.n_requests += len(rnd)
        try:
            scorer = self._provider()
        except Exception as exc:
            for _, fut in rnd.values():
                self._resolve(fut, exc=exc)
            return
        # machines outside the stacked buckets run FleetScorer's host-side
        # fallback (potentially 100s of ms each) — push those off the
        # worker so they can't head-of-line-block the fast stacked batch
        stacked = {}
        for name, (X, fut) in rnd.items():
            if name in scorer.machine_bucket or name not in scorer.models:
                stacked[name] = (X, fut)  # unknown names error in-slot
            else:
                self.n_fallback += 1
                self._fallback_pool.submit(
                    self._score_one, scorer, name, X, fut
                )
        if not stacked:
            return
        rnd = stacked
        self.n_dispatches += 1
        try:
            out = scorer.score_all({n: x for n, (x, _) in rnd.items()})
        except Exception as exc:  # whole-dispatch failure: fail each future
            logger.exception("Coalesced dispatch failed")
            for _, fut in rnd.values():
                self._resolve(fut, exc=exc)
            return
        for name, (_, fut) in rnd.items():
            self._finish(name, fut, out)

    def _finish(self, name: str, fut: Future, out: Dict[str, Any]) -> None:
        res = out.get(name)
        if res is None:
            self._resolve(
                fut, exc=RuntimeError(f"No result for machine {name!r}")
            )
        elif "error" in res and "model-output" not in res:
            # same exception surface as the per-machine scorer path:
            # client-input problems raise ValueError (-> HTTP 400),
            # everything else RuntimeError (-> 500)
            exc_cls = (
                ValueError if res.get("client-error") else RuntimeError
            )
            self._resolve(fut, exc=exc_cls(str(res["error"])))
        else:
            self._resolve(fut, res=res)


def stats(coalescer: Optional[CoalescingScorer]) -> Dict[str, Any]:
    if coalescer is None:
        return {"enabled": False}
    stacked = coalescer.n_requests - coalescer.n_fallback
    return {
        "enabled": True,
        "requests": coalescer.n_requests,
        "fallback_requests": coalescer.n_fallback,
        "bypassed_requests": coalescer.n_bypassed,
        "min_concurrency": coalescer.min_concurrency,
        "dispatches": coalescer.n_dispatches,
        # amortization of the STACKED path only — fallback-routed requests
        # never ride a dispatch and must not inflate the ratio
        "mean_batch": (
            round(stacked / coalescer.n_dispatches, 2)
            if coalescer.n_dispatches
            else None
        ),
    }
