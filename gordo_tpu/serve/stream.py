"""Streaming scoring plane: push-based online anomaly detection.

The third workload (after request/response serving and offline batch):
long-lived sessions where sensor rows arrive one at a time and anomaly
verdicts are PUSHED to subscribers instead of polled.

Three pieces live here:

* **The incremental-window step program** (``serve.stream_step``, owned
  by the compile plane).  The fused request path re-scores the whole
  request series per poll — a 1-row update costs a bucket-padded
  O(lookback-series) dispatch plus smoothing over the full history.
  Here the carried state (a fixed ``offset + smooth_window`` raw-input
  ring plus the row count) lives as device-resident leaves threaded
  through the program, so one arriving row pays an O(1) state shift
  plus ONE tiny fixed-shape dispatch.  At steady state the fp32 verdict
  is byte-identical to the full-window program over the same trailing
  rows (:func:`reference_verdict` is the oracle; ``tests/test_stream.py``
  pins it at every step, across a generation flip) — the fixed state
  shape means XLA lowers the same kernels every step, and the math is
  stage-for-stage the request path's.

* **Per-machine stream state** (:class:`MachineStream`).  Carries the
  device leaves plus a small host mirror of the raw input ring.  When a
  delta hot-reload (r15) swaps the underlying :class:`ModelEntry`, the
  stream re-primes by replaying the mirrored rows through the NEW
  model's step program — subscribers keep their session and the first
  post-flip verdict is already byte-equal to a full re-score under the
  new generation.

* **The hub** (:class:`StreamHub`): a monotonic event log with a bounded
  replay ring, fan-out to per-subscriber bounded queues, and the SSE /
  long-poll transport.  Event ids are hub-global and strictly
  increasing; a client that reconnects with ``Last-Event-ID`` replays
  everything it missed from the ring (no verdict lost or duplicated —
  the chaos suite pins this).  Slow consumers are DISCONNECTED on queue
  overflow rather than silently dropped-from: the client notices,
  resumes by id, and the ring bridges the gap.

Event types pushed: ``verdict`` (per valid scored row), ``threshold``
(total-score crossings of the model's aggregate threshold, transitions
only), ``drift`` (fleet-health status transitions, evaluated every
:data:`DRIFT_CHECK_EVERY` verdicts against the r14 sketches).

Env knobs (docs/configuration.md "Streaming"): ``GORDO_STREAM_REPLAY``
(replay-ring events, default 4096), ``GORDO_STREAM_QUEUE``
(per-subscriber queue depth, default 256), ``GORDO_STREAM_KEEPALIVE``
(SSE keepalive comment interval seconds, default 15),
``GORDO_STREAM_POLL_TIMEOUT`` (long-poll max wait seconds, default 25).

Fault seams: ``stream.ingest`` (pre-state-mutation, so an injected
failure never half-applies a row) and ``stream.push`` (per-event in the
SSE writer; ``disconnect`` kills the transport mid-event,
``slow_consumer`` stalls the writer until its queue overflows).
"""

from __future__ import annotations

import asyncio
import collections
import json
import logging
import os
import threading
import time
from typing import Any, Dict, Iterable, List, Optional, Set, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from gordo_tpu import compile as compile_plane
from gordo_tpu import faults, telemetry
from gordo_tpu.anomaly.diff import scores_fn
from gordo_tpu.ops.windows import make_windows
from gordo_tpu.serve import precision

logger = logging.getLogger(__name__)

__all__ = [
    "MachineStream",
    "StreamHub",
    "Subscriber",
    "EventRing",
    "StreamUnsupported",
    "warm_stream_program",
    "reference_verdict",
    "sse_format",
    "run_sse",
    "poll_events",
    "replay_ring_size",
    "queue_depth",
    "keepalive_seconds",
    "poll_timeout_seconds",
]

# -- env knobs (read live, like fleet_health's thresholds) ------------------


def replay_ring_size() -> int:
    """``GORDO_STREAM_REPLAY``: events the hub retains for by-id resume."""
    return int(os.environ.get("GORDO_STREAM_REPLAY", "4096"))


def queue_depth() -> int:
    """``GORDO_STREAM_QUEUE``: per-subscriber queue bound; overflow
    disconnects the subscriber (it resumes by Last-Event-ID)."""
    return int(os.environ.get("GORDO_STREAM_QUEUE", "256"))


def keepalive_seconds() -> float:
    """``GORDO_STREAM_KEEPALIVE``: SSE comment interval keeping idle
    connections alive through ingress idle timeouts."""
    return float(os.environ.get("GORDO_STREAM_KEEPALIVE", "15"))


def poll_timeout_seconds() -> float:
    """``GORDO_STREAM_POLL_TIMEOUT``: long-poll fallback max wait."""
    return float(os.environ.get("GORDO_STREAM_POLL_TIMEOUT", "25"))


#: evaluate the machine's fleet-health drift status every N valid
#: verdicts — a sketch comparison per row would tax the O(1) hot path
DRIFT_CHECK_EVERY = 16

# -- telemetry (docs/observability.md "Streaming") --------------------------

_SUBSCRIBERS = telemetry.gauge(
    "gordo_stream_subscribers",
    "Live stream subscribers (SSE + long-poll) on this replica",
)
_EVENTS_PUSHED = telemetry.counter(
    "gordo_stream_events_pushed_total",
    "Stream events published to the hub, by event type",
    labels=("type",),
)
_PUSH_SECONDS = telemetry.histogram(
    "gordo_stream_push_seconds",
    "Detection-to-push latency: ingest scoring to SSE frame write",
    buckets=(0.001, 0.005, 0.02, 0.1, 0.5, 2.0),
)
_DROPPED = telemetry.counter(
    "gordo_stream_dropped_total",
    "Stream subscriber disconnects/drops, by reason "
    "(slow_consumer = queue overflow, replay_gap = resume id aged out "
    "of the replay ring)",
    labels=("reason",),
)
_INGESTED = telemetry.counter(
    "gordo_stream_ingest_rows_total",
    "Rows accepted by the streaming ingest path",
)


class StreamUnsupported(ValueError):
    """Model cannot serve the streaming plane (needs the fused anomaly
    chain: pure-stats scalers + BaseJaxEstimator + diff detector)."""


# ---------------------------------------------------------------------------
# The incremental step program
# ---------------------------------------------------------------------------


def _mode_offset(mode: str, lookback: int) -> int:
    """Rows consumed before the first output row — identical to the
    fused path's ``X.shape[0] - pred.shape[0]``."""
    if mode == "ae":
        return lookback - 1
    if mode == "forecast":
        return lookback
    return 0


def _stream_step_fn(
    module,
    scaler_classes,
    mode,
    lookback,
    det_cls,
    smooth_window,
    dtype,
    with_confidence,
    scaler_stats,
    params,
    det_stats,
    agg_threshold,
    rows,
    count,
    x,
):
    """One arriving row -> (new state, verdict arrays).

    State leaves (device-resident, threaded through every call):

    * ``rows``  (H, F) f32 — raw input ring, newest last, where
      ``H = offset + W`` (W = max(smooth_window, 1)): exactly enough
      rows to window the newest sample AND recompute the W raw scores
      its trailing rolling median covers
    * ``count`` ()  i32 — total rows ever ingested (drives the
      min_periods=1 validity mask, so early-stream medians match the
      full path's NaN-padded windows, and warm-up garbage in the ring
      never reaches a verdict)

    The math is stage-for-stage the request path's ``_score_program_fn``
    over the ring: cast, scaler chain, the W newest model windows,
    detector |diff| + L2, masked nanmedian standing in for the trailing
    rolling median at the newest row.  Because the ring has a FIXED
    shape, XLA lowers the exact same kernels every step — at steady
    state (count >= H) the fp32 verdict is byte-identical to running
    the full-window program over the same trailing rows.  (The ring is
    also deliberately raw input, not carried scores: it is
    model-independent, so a generation flip keeps the state and the
    first post-flip verdict is already exact under the new params.)
    """
    offset = _mode_offset(mode, lookback)
    w = max(smooth_window, 1)
    rows = jnp.concatenate([rows[1:], x[None, :]], axis=0)
    count = count + 1

    Xc = precision.cast_input(rows, dtype)
    scaler_stats = precision.cast_params(scaler_stats, dtype)
    params = precision.cast_params(params, dtype)
    det_stats = precision.cast_params(det_stats, dtype)

    Xs = Xc
    for cls, stats in zip(scaler_classes, scaler_stats):
        Xs = cls.apply(stats, Xs)

    if mode == "none":
        inputs = Xs                              # (W, F)
    elif mode == "ae":
        inputs = make_windows(Xs, lookback)      # (W, lookback, F)
    else:  # forecast
        inputs = make_windows(Xs[:-1], lookback)

    pred = module.apply({"params": params}, inputs)  # (W, n_out)
    y_al = Xc[offset:]                               # (W, F)
    tag_raw, tot_raw = scores_fn(det_cls, det_stats, y_al, pred)
    tag_raw = tag_raw.astype(jnp.float32)
    tot_raw = tot_raw.astype(jnp.float32)

    # min_periods=1 reconstructed from the row count: the newest
    # n_valid raw scores are real, older slots cover ring positions the
    # stream has not filled yet — masked to NaN exactly where the full
    # path's rolling window would hold its NaN padding
    n_valid = jnp.clip(count - offset, 0, w)
    mask = jnp.arange(w) >= (w - n_valid)
    if smooth_window > 1:
        tag = jnp.nanmedian(
            jnp.where(mask[:, None], tag_raw, jnp.nan), axis=0
        )
        tot = jnp.nanmedian(jnp.where(mask, tot_raw, jnp.nan))
    else:
        tag = tag_raw[-1]
        tot = tot_raw[-1]

    out = {
        "rows": rows,
        "count": count,
        "valid": count > offset,
        "tag-anomaly-scores": tag.astype(jnp.float32),
        "total-anomaly-score": tot.astype(jnp.float32),
    }
    if with_confidence:
        out["anomaly-confidence"] = out["total-anomaly-score"] / jnp.maximum(
            agg_threshold.astype(jnp.float32), 1e-12
        )
    return out


#: the per-machine incremental program, owned by the compile plane —
#: warmed per fleet signature at server startup (compile/warmup.py), so
#: the first streamed row of any machine never traces
_stream_program = compile_plane.program(
    "serve.stream_step",
    _stream_step_fn,
    static_argnames=(
        "module", "scaler_classes", "mode", "lookback", "det_cls",
        "smooth_window", "dtype", "with_confidence",
    ),
)


def _stream_args(
    c: Dict[str, Any], dtype: str, state: Dict[str, Any], x
) -> Tuple:
    """The ONE assembly of ``_stream_program`` arguments — dispatch,
    replay, and AOT warmup must agree on statics and pytree layout."""
    det = c["detector"]
    with_confidence = det["feature_thresholds"] is not None
    return (
        c["module"],
        tuple(cls for cls, _ in c["scalers"]),
        c["mode"],
        c["lookback"],
        det["scaler_cls"],
        max(int(det["window"] or 0), 1),
        dtype,
        with_confidence,
        tuple(stats for _, stats in c["scalers"]),
        c["params"],
        det["scaler_stats"],
        np.float32(det["aggregate_threshold"]) if with_confidence else None,
        state["rows"],
        state["count"],
        x,
    )


def warm_stream_program(
    scorer, n_features: int, dtype: Optional[str] = None
) -> List[Tuple[str, float]]:
    """AOT-compile the stream step for one machine's chain — shape
    structs only.  Returns ``[("serve.stream_step", compile_seconds)]``
    (0.0 = cached), or ``[]`` when the model can't stream."""
    c = scorer.chain
    if not c or not c.get("detector"):
        return []
    det = c["detector"]
    if det["feature_thresholds"] is None and det["require_thresholds"]:
        return []
    dtype = precision.canonical(dtype) if dtype else scorer.dtype
    w = max(int(det["window"] or 0), 1)
    h = _mode_offset(c["mode"], c["lookback"]) + w
    f = int(n_features)
    state = {
        "rows": jax.ShapeDtypeStruct((h, f), jnp.float32),
        "count": jax.ShapeDtypeStruct((), jnp.int32),
    }
    x = jax.ShapeDtypeStruct((f,), jnp.float32)
    args = _stream_args(c, dtype, state, x)
    return [("serve.stream_step", _stream_program.warm(*args))]


def reference_verdict(
    scorer, rows: np.ndarray, dtype: Optional[str] = None
) -> Dict[str, np.ndarray]:
    """The parity oracle: the request path's full-window program
    (``serve.score``) over ``rows`` at its EXACT shape — no bucket
    padding — returning the newest row's verdict arrays.

    ``tests/test_stream.py`` pins the streaming step byte-identical
    (fp32) to this at every steady-state step: both paths then lower
    fixed input shapes, so XLA picks identical kernels and the only
    question is the math — which is stage-for-stage the same.  (The
    production ``anomaly_arrays`` surface pads requests to row buckets;
    kernel selection varies with batch shape at the last ulp, which is
    why the oracle dispatches unpadded.)
    """
    from gordo_tpu.serve import scorer as scorer_mod

    c = scorer.chain
    det = c["detector"]
    with_confidence = det["feature_thresholds"] is not None
    X = jnp.asarray(np.asarray(rows, np.float32))
    dtype = precision.canonical(dtype) if dtype else scorer.dtype
    args, kw = scorer_mod._program_args(
        c, X, True, 0, dtype, with_confidence
    )
    out = scorer_mod._score_program(*args, **kw)
    verdict = {
        "tag-anomaly-scores": np.asarray(out["tag-anomaly-scores"])[-1],
        "total-anomaly-score": np.asarray(out["total-anomaly-score"])[-1],
    }
    if with_confidence:
        verdict["anomaly-confidence"] = np.asarray(
            out["anomaly-confidence"]
        )[-1]
    return verdict


# ---------------------------------------------------------------------------
# Per-machine carried state
# ---------------------------------------------------------------------------


class MachineStream:
    """One machine's streaming session: device ring + host row mirror.

    The carried state is the raw-input ring (plus the running count) —
    deliberately model-INdependent, so ``rebind(scorer)`` after a delta
    hot-reload (r15) keeps the session: when the new model shares the
    old one's window geometry the device ring survives untouched and
    the first post-flip verdict is already byte-equal to a full
    re-score under the new generation; when geometry changed, the host
    mirror re-primes a fresh ring from whatever history still fits.
    """

    def __init__(self, name: str, scorer, dtype: Optional[str] = None):
        self.name = name
        self.count = 0
        self.exceeding = False
        self.drift_status: Optional[str] = None
        self._state: Optional[Dict[str, Any]] = None
        self._bound = None  # AOT fast path, resolved on first dispatch
        self._rows: "collections.deque[np.ndarray]" = collections.deque()
        self._scorer = None
        self.state_rows = 0
        self.rebind(scorer, dtype)

    # -- model binding -------------------------------------------------------

    def rebind(self, scorer, dtype: Optional[str] = None) -> None:
        """(Re)attach to ``scorer``, carrying the session state across."""
        c = scorer.chain
        if not c or not c.get("detector"):
            raise StreamUnsupported(
                f"machine {self.name!r} has no fused anomaly chain; "
                "the streaming plane needs pure-stats scalers, a jax "
                "estimator, and a diff-based detector"
            )
        det = c["detector"]
        if det["feature_thresholds"] is None and det["require_thresholds"]:
            raise StreamUnsupported(
                f"machine {self.name!r} requires thresholds but "
                "cross_validate() never derived them"
            )
        prior_rows = self.state_rows
        self._scorer = scorer
        self._bound = None  # statics changed with the generation
        self.chain = c
        self.dtype = precision.canonical(dtype) if dtype else scorer.dtype
        self.offset = _mode_offset(c["mode"], c["lookback"])
        self.window = max(int(det["window"] or 0), 1)
        self.state_rows = self.offset + self.window
        self.with_confidence = det["feature_thresholds"] is not None
        if self.state_rows != prior_rows:
            # window geometry changed: re-prime a fresh ring from the
            # host mirror.  The device count is capped at the mirrored
            # depth so the min_periods mask treats unfillable older
            # slots as warm-up — verdicts equal a cold start over the
            # retained history (self.count keeps the true position for
            # event numbering).
            mirror = list(self._rows)[-self.state_rows:]
            self._rows = collections.deque(mirror, maxlen=self.state_rows)
            self._state = None
            if mirror:
                f = mirror[0].shape[0]
                ring = np.zeros((self.state_rows, f), np.float32)
                if len(mirror):
                    ring[self.state_rows - len(mirror):] = np.stack(mirror)
                self._state = {
                    "rows": jnp.asarray(ring),
                    "count": jnp.asarray(
                        min(self.count, len(mirror)), jnp.int32
                    ),
                }

    @property
    def scorer(self):
        return self._scorer

    def _init_state(self, n_features: int, count: int = 0) -> None:
        self._state = {
            "rows": jnp.zeros((self.state_rows, n_features), jnp.float32),
            "count": jnp.asarray(count, jnp.int32),
        }

    # -- the hot path --------------------------------------------------------

    def _advance(self, x: np.ndarray) -> Dict[str, Any]:
        args = _stream_args(self.chain, self.dtype, self._state, x)
        # the ring's shape is fixed by construction, so the call
        # signature never varies between rebinds: resolve the AOT
        # executable once and skip the registry's per-call keying —
        # it otherwise costs more than the device step itself
        if self._bound is None:
            self._bound = _stream_program.bind(*args)
        out = (
            self._bound(*args) if self._bound is not None
            else _stream_program(*args)
        )
        self._state = {k: out[k] for k in ("rows", "count")}
        return out

    def ingest(self, x: np.ndarray) -> Optional[Dict[str, Any]]:
        """Score one arriving row; returns the verdict arrays (fp32) for
        a valid (post-warmup) row, else None."""
        x = np.asarray(x, np.float32).reshape(-1)
        if self._state is None:
            self._init_state(x.shape[0], count=self.count)
        self._rows.append(x)
        out = self._advance(x)
        self.count += 1
        if not bool(out["valid"]):
            return None
        verdict = {
            "tag-anomaly-scores": np.asarray(out["tag-anomaly-scores"]),
            "total-anomaly-score": np.asarray(out["total-anomaly-score"]),
        }
        if "anomaly-confidence" in out:
            verdict["anomaly-confidence"] = np.asarray(
                out["anomaly-confidence"]
            )
        # the same per-verdict fold the request path does: streamed
        # totals feed the r14 health sketches (which feed r17 refresh)
        telemetry.FLEET_HEALTH.record(
            self.name, verdict["total-anomaly-score"].reshape(1)
        )
        return verdict


# ---------------------------------------------------------------------------
# Event log + subscribers
# ---------------------------------------------------------------------------


class EventRing:
    """Bounded in-memory event log with hub-global monotonic ids."""

    def __init__(self, maxlen: Optional[int] = None):
        self._events: "collections.deque[Dict[str, Any]]" = (
            collections.deque(maxlen=maxlen or replay_ring_size())
        )
        self.last_id = 0

    def append(self, etype: str, data: Dict[str, Any]) -> Dict[str, Any]:
        self.last_id += 1
        ev = {"id": self.last_id, "type": etype, "data": data}
        self._events.append(ev)
        return ev

    def since(
        self, after: int, machines: Optional[Set[str]] = None
    ) -> Tuple[List[Dict[str, Any]], bool]:
        """Events with id > ``after`` (filtered), plus a gap flag: True
        when ids between ``after`` and the oldest retained event have
        been trimmed — the subscriber missed events it can never replay."""
        oldest = self._events[0]["id"] if self._events else self.last_id + 1
        gap = after + 1 < oldest and after < self.last_id
        out = [
            ev for ev in self._events
            if ev["id"] > after
            and (machines is None or ev["data"].get("machine") in machines)
        ]
        return out, gap


class Subscriber:
    """One live consumer: a bounded queue the hub fans into."""

    def __init__(
        self,
        machines: Optional[Set[str]] = None,
        maxsize: Optional[int] = None,
    ):
        self.machines = machines
        self.queue: "asyncio.Queue[Dict[str, Any]]" = asyncio.Queue(
            maxsize=maxsize or queue_depth()
        )
        self.dead = False

    def wants(self, ev: Dict[str, Any]) -> bool:
        return self.machines is None or (
            ev["data"].get("machine") in self.machines
        )


class StreamHub:
    """The per-replica streaming hub: machine streams, event ring,
    subscriber fan-out.

    Loop-confined by design: ingest handlers, the SSE writers, and the
    watchman relay all run on the serving event loop, so fan-out needs
    no locking beyond the ring's (which also serves sync callers like
    bench's in-process replay).  A hub with ``collection=None`` is a
    pure relay (watchman re-fans upstream events through one).
    """

    def __init__(self, collection=None, ring_size: Optional[int] = None):
        self.collection = collection
        self.ring = EventRing(ring_size)
        self.streams: Dict[str, MachineStream] = {}
        self._subscribers: Set[Subscriber] = set()
        self._lock = threading.Lock()

    # -- subscriptions -------------------------------------------------------

    def subscribe(
        self,
        machines: Optional[Iterable[str]] = None,
        maxsize: Optional[int] = None,
    ) -> Subscriber:
        sub = Subscriber(
            set(machines) if machines is not None else None, maxsize
        )
        with self._lock:
            self._subscribers.add(sub)
            _SUBSCRIBERS.set(float(len(self._subscribers)))
        return sub

    def unsubscribe(self, sub: Subscriber) -> None:
        with self._lock:
            self._subscribers.discard(sub)
            _SUBSCRIBERS.set(float(len(self._subscribers)))

    @property
    def n_subscribers(self) -> int:
        return len(self._subscribers)

    # -- publishing ----------------------------------------------------------

    def publish(self, etype: str, data: Dict[str, Any]) -> Dict[str, Any]:
        """Append to the ring and fan out; slow consumers (full queue)
        are marked dead — their transport closes and they resume by id."""
        with self._lock:
            ev = self.ring.append(etype, data)
            subs = list(self._subscribers)
        _EVENTS_PUSHED.inc(1.0, etype)
        for sub in subs:
            if sub.dead or not sub.wants(ev):
                continue
            try:
                sub.queue.put_nowait(ev)
            except asyncio.QueueFull:
                sub.dead = True
                _DROPPED.inc(1.0, "slow_consumer")
        return ev

    # -- ingest --------------------------------------------------------------

    def stream_for(self, name: str, scorer, dtype=None) -> MachineStream:
        """The machine's stream, rebound when a hot reload swapped the
        scorer object underneath it (entry identity IS the generation)."""
        ms = self.streams.get(name)
        if ms is None:
            ms = self.streams[name] = MachineStream(name, scorer, dtype)
        elif ms.scorer is not scorer:
            ms.rebind(scorer, dtype)
        return ms

    def ingest_rows(
        self,
        name: str,
        scorer,
        X: np.ndarray,
        dtype: Optional[str] = None,
    ) -> List[Dict[str, Any]]:
        """Feed rows for one machine; returns the events published.

        The ``stream.ingest`` fault seam fires BEFORE any state
        mutation, so an injected failure never half-applies a row and a
        client retry is safe.
        """
        if faults.enabled():
            faults.check("stream.ingest", machine=name)
        ms = self.stream_for(name, scorer, dtype)
        X = np.asarray(X, np.float32)
        if X.ndim == 1:
            X = X[None, :]
        events: List[Dict[str, Any]] = []
        for row in X:
            verdict = ms.ingest(row)
            _INGESTED.inc(1.0)
            if verdict is None:
                continue
            events.extend(self._emit(ms, verdict))
        return events

    def _emit(
        self, ms: MachineStream, verdict: Dict[str, Any]
    ) -> List[Dict[str, Any]]:
        now = time.time()
        total = float(verdict["total-anomaly-score"])
        data = {
            "machine": ms.name,
            "step": ms.count,
            "time": now,
            "total-anomaly-score": total,
            "tag-anomaly-scores": [
                float(v) for v in verdict["tag-anomaly-scores"]
            ],
        }
        if "anomaly-confidence" in verdict:
            data["anomaly-confidence"] = float(verdict["anomaly-confidence"])
        events = [self.publish("verdict", data)]

        det = ms.chain["detector"]
        if det["feature_thresholds"] is not None:
            threshold = float(det["aggregate_threshold"])
            exceeding = total > threshold
            if exceeding != ms.exceeding:
                ms.exceeding = exceeding
                events.append(self.publish("threshold", {
                    "machine": ms.name,
                    "direction": "above" if exceeding else "below",
                    "total-anomaly-score": total,
                    "threshold": threshold,
                    "time": now,
                }))

        if ms.count % DRIFT_CHECK_EVERY == 0:
            doc = telemetry.FLEET_HEALTH.doc(machines=[ms.name])
            status = doc["machines"][ms.name]["status"]
            if status != ms.drift_status:
                was, ms.drift_status = ms.drift_status, status
                if was is not None:
                    events.append(self.publish("drift", {
                        "machine": ms.name,
                        "status": status,
                        "was": was,
                        "drift": doc["machines"][ms.name]["drift"],
                        "time": now,
                    }))
        return events


# ---------------------------------------------------------------------------
# Transport: SSE framing + long-poll
# ---------------------------------------------------------------------------


def sse_format(ev: Dict[str, Any]) -> bytes:
    """One event as an SSE frame: ``id`` / ``event`` / ``data`` lines."""
    payload = json.dumps(ev["data"], separators=(",", ":"))
    return (
        f"id: {ev['id']}\nevent: {ev['type']}\ndata: {payload}\n\n"
    ).encode()


async def run_sse(response, hub: StreamHub, sub: Subscriber, after: int
                  ) -> None:
    """Drive one SSE connection: replay from ``after``, then live fan-out
    with keepalive comments.  Returns when the subscriber dies (slow
    consumer), the fault plane disconnects it, or the peer goes away.

    The ``stream.push`` seam fires per frame: ``disconnect`` aborts the
    transport mid-event (a partial frame hits the wire — the client's
    parser must resync on reconnect), ``slow_consumer`` stalls the
    writer until the hub marks the queue overflowed.
    """
    replayed, gap = hub.ring.since(after, sub.machines)
    if gap:
        _DROPPED.inc(1.0, "replay_gap")
        await response.write(
            b": replay-gap - events before this id were trimmed\n\n"
        )
    # the caller subscribed BEFORE this replay (so nothing lands in the
    # window between the two), which means events published during that
    # window sit in BOTH the replay batch and the queue — the id cursor
    # below filters the queued copies
    sent = replayed[-1]["id"] if replayed else after
    try:
        for ev in replayed:
            await response.write(sse_format(ev))
        while not sub.dead:
            try:
                ev = await asyncio.wait_for(
                    sub.queue.get(), timeout=keepalive_seconds()
                )
            except asyncio.TimeoutError:
                await response.write(b": keepalive\n\n")
                continue
            if ev["id"] <= sent:
                continue
            sent = ev["id"]
            if faults.enabled():
                try:
                    faults.check(
                        "stream.push", machine=ev["data"].get("machine", ""),
                        event_id=ev["id"],
                    )
                except faults.InjectedFault as exc:
                    if exc.mode == "slow_consumer":
                        # stall until the bounded queue overflows and the
                        # hub marks us dead — the real pathology (capped
                        # so a quiet hub can't wedge the writer forever)
                        stall_until = time.monotonic() + 10.0
                        while not sub.dead and time.monotonic() < stall_until:
                            await asyncio.sleep(0.005)
                        break
                    # mid-event disconnect: leak a partial frame, then die
                    await response.write(
                        f"id: {ev['id']}\nevent: {ev['type']}\n".encode()
                    )
                    raise
            if "time" in ev["data"]:
                _PUSH_SECONDS.observe(max(time.time() - ev["data"]["time"], 0.0))
            await response.write(sse_format(ev))
    finally:
        hub.unsubscribe(sub)


async def poll_events(
    hub: StreamHub,
    machines: Optional[Set[str]],
    after: int,
    timeout: Optional[float] = None,
) -> Dict[str, Any]:
    """Long-poll fallback: wait up to ``timeout`` for at least one event
    past ``after``, then return the batch + resume cursor as one doc."""
    timeout = poll_timeout_seconds() if timeout is None else timeout
    deadline = time.monotonic() + timeout
    # subscribe BEFORE the ring check so an event landing between the
    # two can't slip through the wait (the queue wakes us, the ring
    # re-read below is what actually returns it — ids dedup naturally)
    sub = hub.subscribe(machines)
    try:
        events, gap = hub.ring.since(after, machines)
        if not events and timeout > 0:
            remaining = deadline - time.monotonic()
            if remaining > 0:
                try:
                    await asyncio.wait_for(sub.queue.get(), timeout=remaining)
                except asyncio.TimeoutError:
                    pass
            events, gap = hub.ring.since(after, machines)
    finally:
        hub.unsubscribe(sub)
    return {
        "events": events,
        "last-event-id": events[-1]["id"] if events else after,
        "replay-gap": gap,
    }
