"""Async ML server.

Reference equivalent: ``gordo_components/server/server.py`` (Flask
``build_app``/``run_server`` behind gunicorn) and
``server/views/base.py``/``views/anomaly.py`` (the
``/gordo/v0/<project>/<machine>/...`` routes, payload validation against
model metadata, download-model).

Differences by design:
- aiohttp event loop instead of gunicorn worker forks: device dispatches run
  in a thread-pool executor so the loop keeps accepting while XLA computes.
- one process serves MANY machines (``ModelCollection``) — the reference
  runs one pod per machine; the per-machine route shape is preserved so
  clients cannot tell the difference.
- scoring goes through :class:`gordo_tpu.serve.scorer.CompiledScorer` — one
  fused jitted program per shape bucket instead of sklearn-transform hops.
"""

from __future__ import annotations

import asyncio
import json
import logging
import os
import threading
import time
from typing import Any, Dict, List, Optional

import numpy as np
import pandas as pd
from aiohttp import web

import gordo_tpu
from gordo_tpu import artifacts, faults, serializer, telemetry
from gordo_tpu.telemetry.fleet_health import drift_top_k
from gordo_tpu.serve import codec
from gordo_tpu.serve import coalesce as coalesce_mod
from gordo_tpu.serve import stream as stream_mod
from gordo_tpu.serve.scorer import CompiledScorer

logger = logging.getLogger(__name__)

API_PREFIX = "/gordo/v0"

# -- telemetry instruments (see docs/observability.md for the catalog) ------
_REQUEST_SECONDS = telemetry.histogram(
    "gordo_server_request_seconds",
    "End-to-end request handling time by route pattern and response codec",
    labels=("route", "codec"),
)
_REQUESTS_TOTAL = telemetry.counter(
    "gordo_server_requests_total",
    "Requests served by route pattern and HTTP status",
    labels=("route", "status"),
)
_MACHINES_GAUGE = telemetry.gauge(
    "gordo_server_machines",
    "Machines currently loaded in this server's collection",
)
_SHED_TOTAL = telemetry.counter(
    "gordo_server_shed_total",
    "Requests shed with 429 + Retry-After (coalescer stand-down escalated)",
)
_SHARD_INDEX_GAUGE = telemetry.gauge(
    "gordo_server_shard_index",
    "This replica's shard index (absent when serving unsharded)",
)
_SHARD_COUNT_GAUGE = telemetry.gauge(
    "gordo_server_shard_count",
    "Shard count of the serving tier this replica belongs to",
)
_FLEET_GENERATION_GAUGE = telemetry.gauge(
    "gordo_fleet_generation",
    "Artifact generation this replica is serving (set at scrape time)",
)
_RELOADS_TOTAL = telemetry.counter(
    "gordo_server_reloads_total",
    "Completed artifact reloads by kind (delta = O(changed-machines) "
    "restack; full = complete scorer rebuild)",
    labels=("kind",),
)
_QUARANTINED_GAUGE = telemetry.gauge(
    "gordo_machines_quarantined",
    "Machines this replica refuses with 503 because their pack failed "
    "validation (heals when a good generation flips)",
)

#: Prometheus exposition content type (text format 0.0.4)
METRICS_CONTENT_TYPE = "text/plain"


def _codec_label(content_type: Optional[str]) -> str:
    if content_type == codec.COLUMNAR_CONTENT_TYPE:
        return "columnar"
    if content_type == codec.MSGPACK_CONTENT_TYPE:
        return "msgpack"
    if content_type == "application/json":
        return "json"
    return "other"


@web.middleware
async def telemetry_middleware(request: web.Request, handler):
    """Per-request observability: a trace id from the ``X-Gordo-Trace-Id``
    header (minted when absent) binds to the handler's context and echoes
    back on the response; every request lands in the per-route/per-codec
    request histogram and the route/status counter.  Route label is the
    matched ROUTE PATTERN (``{machine}`` stays a placeholder), so
    cardinality is bounded by the route table, not the fleet."""
    trace_id = request.headers.get(telemetry.TRACE_HEADER) or (
        telemetry.new_trace_id()
    )
    telemetry.set_trace_id(trace_id)
    t0 = time.perf_counter()
    status = 500
    codec_label = "other"
    try:
        resp = await handler(request)
        status = resp.status
        codec_label = _codec_label(resp.content_type)
        resp.headers[telemetry.TRACE_HEADER] = trace_id
        return resp
    except web.HTTPException as exc:
        status = exc.status
        exc.headers[telemetry.TRACE_HEADER] = trace_id
        raise
    finally:
        resource = request.match_info.route.resource
        route = resource.canonical if resource is not None else "unmatched"
        _REQUEST_SECONDS.observe(
            time.perf_counter() - t0, route, codec_label
        )
        _REQUESTS_TOTAL.inc(1.0, route, str(status))

#: per-request absolute monotonic deadline (set by deadline_middleware
#: from the propagated X-Gordo-Deadline-Ms budget; absent = no deadline)
DEADLINE_KEY = "gordo-deadline"


def _deadline_expired_response(detail: str) -> web.Response:
    return web.json_response(
        {"error": f"deadline expired: {detail}"}, status=504
    )


@web.middleware
async def deadline_middleware(request: web.Request, handler):
    """Deadline propagation ingress + the ``server.request`` fault seam.

    The ``X-Gordo-Deadline-Ms`` header carries the client's REMAINING
    budget in milliseconds (wall clocks don't cross machines — only
    durations do); it converts here to an absolute ``time.monotonic()``
    deadline stored on the request for the handlers and the coalescer.
    A request arriving already expired is refused with 504 before any
    body parse or dispatch — the client upstream has given up, so every
    cycle spent on it is pure waste."""
    if faults.enabled():
        try:
            faults.check("server.request", path=request.path)
        except faults.InjectedFault as exc:
            if exc.mode == "reset":
                # drop the connection mid-request, as a crashing worker
                # would — the client sees a reset, not a status line
                if request.transport is not None:
                    request.transport.close()
                raise web.HTTPInternalServerError(text=str(exc))
            status = 503 if exc.mode == "http_503" else 500
            return web.json_response({"error": str(exc)}, status=status)
    raw = request.headers.get(telemetry.DEADLINE_HEADER)
    if raw is not None:
        try:
            ms = int(raw)
        except ValueError:
            ms = None
        if ms is not None:
            if ms <= 0:
                return _deadline_expired_response(
                    "budget exhausted on arrival"
                )
            request[DEADLINE_KEY] = time.monotonic() + ms / 1000.0
    return await handler(request)


COLLECTION_KEY: "web.AppKey[ModelCollection]" = web.AppKey(
    "collection", object
)
COALESCER_KEY: "web.AppKey[object]" = web.AppKey("coalescer", object)
WARMUP_TASK_KEY: "web.AppKey[object]" = web.AppKey("warmup_task", object)
STREAM_HUB_KEY: "web.AppKey[object]" = web.AppKey("stream_hub", object)


class ModelEntry:
    """One served machine, loaded through the artifact plane — a v1
    per-machine directory or a slot of a v2 pack, behind one surface.

    ``serve_dtype``: the collection's serving precision, threaded into
    this entry's scorer (``None`` resolves ``GORDO_SERVE_DTYPE`` per
    call — the bench/test compatibility path)."""

    def __init__(
        self, name: str, directory: str, serve_dtype: Optional[str] = None
    ):
        # v1-dir compatibility constructor (tests/bench build entries
        # straight from a dumped artifact dir)
        self._init_from(
            artifacts.ArtifactRef(name, "dir", directory, directory=directory),
            serve_dtype=serve_dtype,
        )

    @classmethod
    def from_artifact(
        cls, ref: "artifacts.ArtifactRef", serve_dtype: Optional[str] = None
    ) -> "ModelEntry":
        entry = cls.__new__(cls)
        entry._init_from(ref, serve_dtype=serve_dtype)
        return entry

    def _init_from(
        self, ref: "artifacts.ArtifactRef", serve_dtype: Optional[str] = None
    ) -> None:
        self.name = ref.name
        self.directory = ref.ref
        self.model = ref.load_model()
        self.metadata = ref.load_metadata()
        # machine= wires the single-machine scoring route into the
        # fleet-health plane: every response's total scores fold into
        # this machine's live sketch
        self.scorer = CompiledScorer(
            self.model, dtype=serve_dtype, machine=self.name
        )
        self.mtime, self.size = ref.stat()
        #: the artifact generation whose bytes this entry serves.  Pack
        #: rows written but not yet stamped carry ``gen = active + 1``;
        #: clamping to the store's published id makes a pending-loaded
        #: entry reload once when its stamp lands (bytes identical —
        #: harmless) instead of silently skipping the flip.  v1 dirs
        #: have no generations and stay at 0.
        if ref.kind == "pack" and ref._store is not None:
            self.generation = min(
                ref._store.row_generation(ref.name),
                ref._store.generation,
            )
        else:
            self.generation = 0

    @property
    def tags(self) -> List[str]:
        tag_list = self.metadata.get("dataset", {}).get("tag_list") or []
        return [t["name"] if isinstance(t, dict) else str(t) for t in tag_list]

    @property
    def resolution(self) -> Optional[str]:
        """The artifact's training resample resolution (pandas offset), used
        as the row-duration fallback when a request's index is too short to
        derive steps from."""
        return self.metadata.get("dataset", {}).get("resolution")


class ModelCollection:
    """All machines this server hosts: ``{name: ModelEntry}``.

    ``from_directory`` accepts either a single machine's artifact dir or a
    project output dir containing one artifact dir per machine (the layout
    ``build_project`` writes).
    """

    def __init__(
        self,
        entries: Dict[str, ModelEntry],
        project: str = "project",
        source_dir: Optional[str] = None,
        serve_mesh=None,
        pack_store=None,
        serve_dtype: Optional[str] = None,
        shard=None,
        fleet_machines: Optional[List[str]] = None,
        shard_owner: Optional[Dict[str, int]] = None,
        quarantined: Optional[Dict[str, Dict[str, Any]]] = None,
    ):
        from gordo_tpu.serve import precision

        self.entries = entries
        #: machines this replica owns but refuses to serve because their
        #: pack (or their individual load) failed validation:
        #: ``{name: {"error": str, "ts": epoch}}``.  The 503 surface,
        #: the ``gordo_machines_quarantined`` gauge, and the
        #: ``quarantined`` status in /fleet-health all read this; a
        #: rescan rebuilds it from scratch, so a good generation flip
        #: heals a machine the moment its pack validates again.
        self.quarantined: Dict[str, Dict[str, Any]] = dict(quarantined or {})
        #: most recent reload/quarantine failure, ``{"error", "ts"}`` —
        #: surfaced by /healthz so an operator sees WHY a fleet shrank
        #: without grepping logs
        self.last_error: Optional[Dict[str, Any]] = None
        if self.quarantined:
            worst = sorted(self.quarantined)[0]
            self.last_error = {
                "error": (
                    f"{len(self.quarantined)} machine(s) quarantined "
                    f"(e.g. {worst}: "
                    f"{self.quarantined[worst]['error']})"
                ),
                "ts": time.time(),
            }
        self.project = project
        self.source_dir = source_dir
        #: this replica's ShardSpec in a fleet-sharded tier (None when the
        #: process serves the whole project)
        self.shard = shard
        #: the FULL project machine list (sharded replicas serve a subset
        #: but must still answer "who owns machine X" — the 421 surface
        #: and the client/watchman shard-table source)
        self.fleet_machines = sorted(
            fleet_machines if fleet_machines is not None else entries
        )
        #: name → owning shard index, from the one shared shard function
        #: (``from_directory`` passes its already-computed table so a 10k-
        #: machine shard startup doesn't partition the fleet twice)
        if shard_owner is None and shard is not None:
            from gordo_tpu.serve.shard import shard_map

            shard_owner = shard_map(self.fleet_machines, shard.count)
        self.shard_owner: Dict[str, int] = shard_owner or {}
        #: optional ("models","data") fleet mesh: stacked serving dispatches
        #: shard their machine axis over it (multi-chip serving)
        self.serve_mesh = serve_mesh
        #: the v2 artifacts.PackStore these entries came from (None for a
        #: v1 directory layout): lets the fleet scorer ship each pack's
        #: stacked tensors to the device as ONE transfer
        self.pack_store = pack_store
        #: the ONE serving precision for this collection (env >
        #: build-manifest dtype > float32; resolved by from_directory) —
        #: per-machine mixing would make responses depend on bucketing
        self.serve_dtype = precision.canonical(serve_dtype) if (
            serve_dtype
        ) else precision.serve_dtype()
        self._fleet_scorer = None
        #: the published artifact generation these entries serve (0 for
        #: v1 layouts / pre-generation indexes) — the value the watch
        #: loop compares the on-disk GENERATION sidecar against
        self.artifact_generation: int = (
            int(getattr(pack_store, "generation", 0)) if pack_store else 0
        )
        #: True while a generation flip is being absorbed (entry rebuild
        #: + delta restack in an executor thread).  Scoring NEVER blocks
        #: on this — the old scorer keeps serving until the swap — but
        #: /healthz surfaces it so rollout tooling can see a reload in
        #: flight.
        self.reloading: bool = False
        # guards the (entries, _fleet_scorer) pair: the background rescan
        # swaps both from an executor thread while bulk requests lazily
        # build the scorer from other executor threads
        self._lock = threading.Lock()
        # adopt the build-time residual baselines riding the artifact
        # metadata — the reference distribution the drift signal (and
        # `gordo refresh`, eventually) compares live sketches against
        telemetry.FLEET_HEALTH.load_baselines(
            {name: e.metadata for name, e in entries.items()}
        )

    @property
    def fleet_scorer(self):
        """Stacked multi-machine scorer (built lazily on first bulk call)."""
        with self._lock:
            if self._fleet_scorer is None:
                from gordo_tpu.serve.fleet_scorer import FleetScorer

                self._fleet_scorer = FleetScorer.from_models(
                    {name: e.model for name, e in self.entries.items()},
                    mesh=self.serve_mesh,
                    pack_store=self.pack_store,
                    dtype=self.serve_dtype,
                )
            return self._fleet_scorer

    @classmethod
    def from_directory(
        cls, path: str, project: str = "project", serve_mesh=None,
        shard=None,
    ) -> "ModelCollection":
        """Load every artifact under ``path`` — a v2 pack index, v1
        per-machine dirs, a mixed output, or one machine's artifact dir.

        A failing pack quarantines ONLY its machines (they 503 with a
        ``quarantined`` detail and heal when a good generation flips)
        while the rest of the fleet loads and serves; it is never a
        silent shrink — the quarantine set rides /healthz, the project
        index, /fleet-health and the ``gordo_machines_quarantined``
        gauge.  Only when NOTHING loads does startup still die loudly
        (:class:`gordo_tpu.artifacts.PackCorruptError` — a server with
        zero machines serves nobody).  A single broken v1 dir only loses
        that machine, as before.

        ``shard`` (a :class:`gordo_tpu.serve.shard.ShardSpec`, default
        ``GORDO_SERVE_SHARD`` from the environment): load ONLY this
        replica's shard of the fleet — the partition is computed over the
        discovered machine list with the one shared shard function, so
        only the owned machines' models (and, pack-aligned, typically
        only the owned packs' bytes) are loaded, warmed, and made device-
        resident.  Per-replica time-to-ready scales as ~1/N.

        The serving dtype resolves here: ``GORDO_SERVE_DTYPE`` when set,
        else the build's warmup-manifest dtype (the precision decision
        travels with the artifacts), else float32."""
        from gordo_tpu.compile import load_warmup_manifest
        from gordo_tpu.serve import precision
        from gordo_tpu.serve.shard import ShardSpec, shard_map

        store, refs = artifacts.discover(path, quarantine=True)
        if shard is None:
            shard = ShardSpec.from_env()
        quarantined_errors: Dict[str, str] = dict(
            getattr(store, "quarantined_machines", None) or {}
        )
        # quarantined machines stay IN the fleet list: clients must keep
        # routing them to their owner (which answers 503 with the why),
        # and dropping them would shift the positional shard table
        fleet_machines = sorted(
            {r.name for r in refs} | set(quarantined_errors)
        )
        shard_owner: Optional[Dict[str, int]] = None
        if shard is not None:
            shard_owner = shard_map(fleet_machines, shard.count)
            refs = [
                r for r in refs
                if shard_owner.get(r.name) == shard.index
            ]
            # only this shard's quarantined machines are ours to report
            quarantined_errors = {
                n: e for n, e in quarantined_errors.items()
                if shard_owner.get(n) == shard.index
            }
            if not refs and not quarantined_errors and fleet_machines:
                raise FileNotFoundError(
                    f"Shard {shard} owns no machines of the "
                    f"{len(fleet_machines)}-machine fleet under {path!r} "
                    f"(shard count exceeds the machine count?)"
                )
            logger.info(
                "Serving shard %s: %d of %d machines",
                shard, len(refs), len(fleet_machines),
            )
        source_dir: Optional[str] = (
            None if artifacts.is_artifact_dir(path) else path
        )
        manifest_dtype = None
        if source_dir is not None:
            manifest = load_warmup_manifest(source_dir)
            manifest_dtype = (manifest or {}).get("dtype")
        serve_dtype = precision.serve_dtype(default=manifest_dtype)
        entries: Dict[str, ModelEntry] = {}
        for ref in refs:
            if ref.kind == "pack":
                try:
                    entries[ref.name] = ModelEntry.from_artifact(
                        ref, serve_dtype=serve_dtype
                    )
                except Exception as exc:
                    # pack-slot load failure (corrupt segment, injected
                    # read fault): quarantine just this machine — the
                    # pack's healthy siblings keep serving
                    logger.exception(
                        "quarantining %s: load failed", ref.name
                    )
                    quarantined_errors[ref.name] = str(exc)
                continue
            try:
                entries[ref.name] = ModelEntry.from_artifact(
                    ref, serve_dtype=serve_dtype
                )
            except Exception:
                logger.exception("Failed to load artifact %s", ref.ref)
        if not entries:
            if quarantined_errors:
                detail = "; ".join(
                    f"{n}: {e}" for n, e in
                    sorted(quarantined_errors.items())[:3]
                )
                raise artifacts.PackCorruptError(
                    f"every machine under {path!r} is quarantined "
                    f"({detail})"
                )
            raise FileNotFoundError(f"No model artifacts under {path!r}")
        now = time.time()
        return cls(
            entries,
            project=project,
            source_dir=source_dir,
            serve_mesh=serve_mesh,
            pack_store=store,
            serve_dtype=serve_dtype,
            shard=shard,
            fleet_machines=fleet_machines,
            shard_owner=shard_owner,
            quarantined={
                n: {"error": e, "ts": now}
                for n, e in quarantined_errors.items()
            },
        )

    def get(self, name: str) -> Optional[ModelEntry]:
        return self.entries.get(name)

    @property
    def generation(self) -> int:
        """Fleet-generation stamp: for v2 packs with a generations layer,
        the REAL published artifact generation id (small monotone ints —
        what ``client.wait_for_generation`` converges on and watchman
        republishes per target).  Layouts predating the generations layer
        fall back to the old change-detector integers: the pack index's
        mtime-in-ms, else the newest loaded artifact's — still monotone
        enough for rollout visibility, never confusable with real ids
        (ms timestamps are 13 digits, generation ids start at 1)."""
        if self.artifact_generation > 0:
            return self.artifact_generation
        if self.pack_store is not None:
            return int(self.pack_store.index_stat[0] * 1000)
        return int(
            max((e.mtime for e in self.entries.values()), default=0.0)
            * 1000
        )

    def maybe_delta_reload(self) -> Dict[str, List[str]]:
        """The generation watch loop's poll: read the tiny ``GENERATION``
        sidecar (one small file, no index parse, no pack validation) and
        run a rescan only when the published id advanced past what this
        collection serves.  Nothing blocks scoring either way."""
        unchanged = {"added": [], "reloaded": [], "removed": []}
        if self.source_dir is None:
            return unchanged
        try:
            gen = artifacts.read_generation(self.source_dir)
        except Exception:
            logger.exception("generation poll failed")
            return unchanged
        if gen <= self.artifact_generation:
            return unchanged
        return self.rescan()

    def rescan(self) -> Dict[str, List[str]]:
        """Pick up artifacts dumped/rebuilt/removed after startup.

        The reference got this "for free" from its pod-per-model design (a
        new machine = a new pod); one process serving a whole project must
        instead watch its artifact dir.  New artifacts load, vanished ones
        drop, changed ones reload — v1 dirs on (mtime, size) of model.pkl,
        v2 pack slots on the flock-serialized index GENERATION: a pack
        machine reloads iff its row's generation is newer than its entry's
        and no newer than the published id.  Pack mtimes are NOT a signal
        (``delta_write`` mutates pack bytes in place, so mtime ticks while
        a write is still torn; the generation flips only after the bytes
        are fsync'd) and pending rows (``gen > published``) are invisible
        until their build stamps.  When every change is a generation-gated
        pack reload, the fleet scorer is rebuilt by ``delta_restack`` —
        O(changed machines), one device transfer per touched pack, zero
        compiles — and swapped under the lock while the old scorer keeps
        serving; structural changes fall back to the full restack.  The
        entries dict is replaced atomically so in-flight requests keep a
        consistent view.
        """
        if self.source_dir is None or not os.path.isdir(self.source_dir):
            return {"added": [], "reloaded": [], "removed": []}
        try:
            store, refs = artifacts.discover(
                self.source_dir, quarantine=True
            )
        except Exception as exc:
            # a mid-write index (builder racing the rescan) must not take
            # down the serving loop — keep the current view, retry later
            logger.exception("Artifact discovery failed during rescan")
            self.last_error = {
                "error": f"rescan discovery failed: {exc}",
                "ts": time.time(),
            }
            return {"added": [], "reloaded": [], "removed": []}
        # this scan's quarantine view, rebuilt from scratch every rescan:
        # a machine whose new generation validates simply stops appearing
        # here — that IS the heal
        scan_quarantined: Dict[str, str] = dict(
            getattr(store, "quarantined_machines", None) or {}
        )
        fleet_machines = sorted(
            {r.name for r in refs} | set(scan_quarantined)
        )
        shard_owner: Dict[str, int] = {}
        if self.shard is not None:
            # re-partition over the CURRENT fleet: machines built after
            # startup land on their owning shard, and only that replica
            # loads them (every replica recomputes the same partition)
            from gordo_tpu.serve.shard import shard_map

            shard_owner = shard_map(fleet_machines, self.shard.count)
            refs = [
                r for r in refs
                if shard_owner.get(r.name) == self.shard.index
            ]
            scan_quarantined = {
                n: e for n, e in scan_quarantined.items()
                if shard_owner.get(n) == self.shard.index
            }
        if (
            store is not None
            and self.pack_store is not None
            and store.index_stat == self.pack_store.index_stat
        ):
            # unchanged index: keep the already-mapped store so entry
            # views and the fleet scorer's prestacking stay one object
            store = self.pack_store
            for ref in refs:
                if ref.kind == "pack":
                    ref._store = store
        store_generation = int(getattr(store, "generation", 0) or 0)
        flip = (
            store is not None
            and store_generation != self.artifact_generation
        )
        if flip:
            self.reloading = True
        try:
            added, reloaded, reloaded_dirs = [], [], []
            new_entries: Dict[str, ModelEntry] = {}
            for ref in refs:
                current = self.entries.get(ref.name)
                stale = False
                if current is not None:
                    if ref.kind == "pack" and store_generation > 0:
                        # generation gating — the torn-write-safe signal:
                        # delta_write rewrites pack bytes in place, so a
                        # stat-based compare can reload mid-write; the
                        # index generation flips only after fsync.  Rows
                        # newer than the published id are pending (a
                        # build still running) and must NOT load yet.
                        row_gen = store.row_generation(ref.name)
                        stale = (
                            current.generation < row_gen <= store_generation
                            # a restored/rolled-back index publishes an
                            # OLDER id than the entry serves: adopt it
                            or current.generation > store_generation
                        )
                    elif ref.kind == "pack":
                        # pre-generation index (never stamped): the old
                        # whole-store signals — an index swap remaps
                        # every pack, and (mtime, size) drift reloads
                        stale = store is not self.pack_store or (
                            ref.stat() != (current.mtime, current.size)
                        )
                    else:
                        # (mtime, size) inequality, not mtime>: a rebuild
                        # can land with an equal-or-older mtime (cache
                        # copies, clock skew) and must still reload.
                        # Known blind spot: an mtime-preserving copy
                        # (cp -p) of a same-size artifact is
                        # indistinguishable without hashing content.
                        stale = ref.stat() != (current.mtime, current.size)
                try:
                    if current is None:
                        new_entries[ref.name] = ModelEntry.from_artifact(
                            ref, serve_dtype=self.serve_dtype
                        )
                        added.append(ref.name)
                    elif stale:
                        new_entries[ref.name] = ModelEntry.from_artifact(
                            ref, serve_dtype=self.serve_dtype
                        )
                        reloaded.append(ref.name)
                        if ref.kind != "pack":
                            reloaded_dirs.append(ref.name)
                    else:
                        new_entries[ref.name] = current
                except Exception as exc:
                    logger.exception(
                        "Failed to (re)load artifact %s", ref.ref
                    )
                    if current is not None:  # keep serving the old model
                        new_entries[ref.name] = current
                    elif ref.kind == "pack":
                        # nothing to keep serving: the machine joins the
                        # quarantine set instead of silently vanishing
                        scan_quarantined[ref.name] = str(exc)
            removed = sorted(set(self.entries) - set(new_entries))
            # quarantine refresh + heal: the set is rebuilt from THIS
            # scan, so a machine whose new generation validates drops out
            # (heal) and a newly-corrupt one joins; a persisting error
            # keeps its original timestamp
            new_quarantined: Dict[str, Dict[str, Any]] = {}
            for name, err in scan_quarantined.items():
                prev = self.quarantined.get(name)
                new_quarantined[name] = (
                    prev if prev is not None and prev["error"] == err
                    else {"error": err, "ts": time.time()}
                )
            healed = sorted(
                n for n in self.quarantined if n not in new_quarantined
            )
            if healed:
                logger.info(
                    "quarantine healed for %s (generation %d)",
                    healed, store_generation,
                )
            newly_quarantined = sorted(
                set(new_quarantined) - set(self.quarantined)
            )
            if newly_quarantined:
                worst = newly_quarantined[0]
                self.last_error = {
                    "error": (
                        f"quarantined {newly_quarantined} "
                        f"({worst}: {new_quarantined[worst]['error']})"
                    ),
                    "ts": time.time(),
                }
            self.quarantined = new_quarantined
            if added or reloaded or removed or flip:
                logger.info(
                    "Collection rescan: +%s ~%s -%s (generation %d -> %d)",
                    added, reloaded, removed,
                    self.artifact_generation, store_generation,
                )
                # while the successor scorer builds, the OLD one keeps
                # serving — nothing below blocks a request until the
                # quick swap under the lock
                with self._lock:
                    old_scorer = self._fleet_scorer
                new_scorer = None
                if (
                    old_scorer is not None
                    and store is not None
                    and not added and not removed and not reloaded_dirs
                ):
                    try:
                        new_scorer = old_scorer.delta_restack(
                            {n: e.model for n, e in new_entries.items()},
                            store,
                            reloaded,
                            mesh=self.serve_mesh,
                        )
                    except Exception:
                        # a failed delta restack falls back to the lazy
                        # full rebuild — never to a stale scorer
                        logger.exception("delta restack failed")
                        new_scorer = None
                with self._lock:  # swap entries + scorer atomically
                    self.entries = new_entries
                    self.pack_store = store
                    self._fleet_scorer = new_scorer
                    self.artifact_generation = store_generation
                _RELOADS_TOTAL.inc(
                    1.0, "delta" if new_scorer is not None else "full"
                )
                # refresh drift baselines for (re)loaded artifacts — a
                # rebuilt machine's NEW training distribution is the one
                # its live window must be compared against from now on
                telemetry.FLEET_HEALTH.load_baselines(
                    {
                        name: new_entries[name].metadata
                        for name in added + reloaded
                        if name in new_entries
                    }
                )
        finally:
            self.reloading = False
        # fleet view refreshes even when this shard's entries didn't
        # change: a machine added to ANOTHER shard must still 421-route
        # (not 404) from here, and the shard table must agree fleet-wide
        self.fleet_machines = fleet_machines
        if self.shard is not None:
            self.shard_owner = shard_owner
        return {"added": added, "reloaded": reloaded, "removed": removed}


# ---------------------------------------------------------------------------
# payload parsing / response shaping
# ---------------------------------------------------------------------------

def parse_X(payload: Any, tags: List[str]) -> np.ndarray:
    """``{"X": ...}`` JSON → float32 matrix.  Accepts a list-of-lists or a
    list of records keyed by tag name (reference ``server/utils.py``
    ``@extract_X_y`` behaviors)."""
    if not isinstance(payload, dict) or "X" not in payload:
        raise ValueError("Payload must be a JSON object with an 'X' key")
    X = payload["X"]
    if isinstance(X, list) and X and isinstance(X[0], dict):
        if not tags:
            raise ValueError("Record-style X requires model tag metadata")
        try:
            X = [[rec[t] for t in tags] for rec in X]
        except KeyError as exc:
            raise ValueError(f"Record missing tag {exc}")
    try:
        arr = np.asarray(X, dtype=np.float32)
    except (TypeError, ValueError) as exc:
        # e.g. JSON nulls / non-numeric entries — a client error, not a 500
        raise ValueError(f"X is not a numeric matrix: {exc}")
    if arr.ndim == 1:
        arr = arr[:, None]
    if arr.ndim != 2:
        raise ValueError(f"X must be 2-dimensional, got shape {arr.shape}")
    return arr


#: bodies above this decode+parse in the executor: a 3 MB JSON request
#: costs ~20-30ms of json.loads + np.asarray — enough that at 64-way
#: concurrency the event loop itself was the serving bottleneck
_OFFLOAD_BYTES = 64 * 1024


def _decode_payload(raw: bytes, is_msgpack: bool) -> Any:
    """Bytes → payload dict; ValueError on malformed input (→ 400), 415
    for a body carrying an array dtype the wire doesn't speak (a media
    problem, not a malformed payload).  Pure function so handlers can run
    it on or off the event loop."""
    if is_msgpack:
        try:
            return codec.unpackb(raw)
        except codec.UnsupportedWireDtype as exc:
            raise web.HTTPUnsupportedMediaType(
                text=json.dumps({"error": str(exc)}),
                content_type="application/json",
            )
        except Exception as exc:
            raise ValueError(f"Invalid msgpack body: {exc}")
    # json.JSONDecodeError is a ValueError — same 400 surface as before
    return json.loads(raw)


async def _read_payload(request: web.Request) -> Any:
    """Request body → payload dict; msgpack bodies (the bundled client's
    bulk fast path) decode through the binary codec, anything else parses
    as JSON.  Large bodies decode in the executor so the accept loop
    stays responsive under concurrent load."""
    raw = await request.read()
    is_msgpack = request.content_type == codec.MSGPACK_CONTENT_TYPE
    if len(raw) > _OFFLOAD_BYTES:
        return await asyncio.get_running_loop().run_in_executor(
            None, _decode_payload, raw, is_msgpack
        )
    return _decode_payload(raw, is_msgpack)


async def _read_and_parse_single(request: web.Request, entry: "ModelEntry"):
    """Read → decode → parse for the single-machine routes, off-loop for
    large bodies (one executor hop covers decode AND the list→ndarray
    conversion, both loop-hostile at 2048-row request sizes).

    Returns ``(X, index, y)``; raises ValueError for client errors."""
    raw = await request.read()
    is_msgpack = request.content_type == codec.MSGPACK_CONTENT_TYPE

    def work():
        payload = _decode_payload(raw, is_msgpack)
        X = parse_X(payload, entry.tags)
        _validate_width(X, entry)
        index = parse_index(payload, X.shape[0])
        y = (
            parse_X({"X": payload["y"]}, entry.tags)
            if isinstance(payload, dict) and payload.get("y") is not None
            else None
        )
        return X, index, y

    if len(raw) > _OFFLOAD_BYTES:
        return await asyncio.get_running_loop().run_in_executor(None, work)
    return work()


async def _respond(
    request: web.Request, obj: Any, status: int = 200
) -> web.Response:
    """Encode a scoring response: GSB1 columnar blocks when the client
    lists ``Accept: application/x-gordo-columnar`` (the bulk route hands
    this path a still-stacked ``ColumnarResult`` — zero per-machine
    splitting on either end of the wire), msgpack when the client asks
    (``Accept: application/x-msgpack`` — raw array buffers, memcpy speed),
    JSON otherwise with ndarray
    leaves encoded by the native fastjson kernel (~13x stdlib json, which
    was the measured HTTP serving ceiling — see ``serve/codec.py``).
    An ``Accept`` ``dtype=`` media parameter selects the wire float
    precision (``application/x-msgpack;dtype=bfloat16`` halves bulk
    response bytes); an unknown dtype name is a 415, not a 500.
    Encoding runs in the executor: a large bulk body takes ~100ms even
    natively, which must not stall the accept loop."""
    try:
        encode, content_type = codec.negotiate(
            request.headers.get("Accept", "")
        )
    except codec.UnsupportedWireDtype as exc:
        raise web.HTTPUnsupportedMediaType(
            text=json.dumps({"error": str(exc)}),
            content_type="application/json",
        )
    body = await asyncio.get_running_loop().run_in_executor(
        None, encode, obj
    )
    return web.Response(body=body, status=status, content_type=content_type)


def parse_index(payload: Any, n_rows: int) -> Optional[pd.DatetimeIndex]:
    """Optional per-row timestamps riding with X (reference server-views
    behavior: requests carrying time info get time info back)."""
    idx = payload.get("index") if isinstance(payload, dict) else None
    if idx is None:
        return None
    if not isinstance(idx, list) or len(idx) != n_rows:
        got = len(idx) if isinstance(idx, list) else type(idx).__name__
        raise ValueError(
            f"index must list one timestamp per X row ({n_rows}), got {got}"
        )
    try:
        return pd.DatetimeIndex(pd.to_datetime(idx, utc=True))
    except Exception as exc:
        raise ValueError(f"index is not parseable as timestamps: {exc}")


def time_columns(
    index: pd.DatetimeIndex, n_out: int, resolution: Optional[str] = None
) -> Dict[str, List[str]]:
    """Per-output-row ``start``/``end`` (reference ``make_base_dataframe``
    columns): start = the input row's timestamp (offset rows consumed at the
    front), end = the NEXT row's timestamp — per-row diffs, so irregular
    indices get their true row spans (a median step would mislabel every row
    around a gap).  The last row extends by its preceding step; 1-row
    requests (no step to derive) fall back to the artifact's training
    ``resolution``, then to zero."""
    start = index[len(index) - n_out:]
    if len(index) >= 2:
        deltas = index[1:] - index[:-1]
        end_all = index[1:].append(
            pd.DatetimeIndex([index[-1] + deltas[-1]])
        )
        end = end_all[len(index) - n_out:]
    else:
        res_delta = pd.Timedelta(0)
        if resolution:
            try:
                res_delta = pd.Timedelta(
                    pd.tseries.frequencies.to_offset(resolution)
                )
            except (ValueError, TypeError):
                pass
        end = start + res_delta
    return {
        "start": [t.isoformat() for t in start],
        "end": [t.isoformat() for t in end],
    }


# ---------------------------------------------------------------------------
# handlers
# ---------------------------------------------------------------------------

def _misdirected(collection: "ModelCollection", name: str) -> Optional[str]:
    """When ``name`` is a real fleet machine owned by ANOTHER shard,
    the human-readable misroute message (else None).  Clients computing
    the shard table locally never hit this; it exists so a stale or
    hand-built client fails loudly with the owner's identity instead of
    a 404 that reads like 'machine was deleted'."""
    if collection.shard is None:
        return None
    owner = collection.shard_owner.get(name)
    if owner is None or owner == collection.shard.index:
        return None
    return (
        f"Machine {name!r} belongs to serving shard "
        f"{owner}/{collection.shard.count}; this replica serves shard "
        f"{collection.shard}"
    )


def _entry_or_404(request: web.Request) -> ModelEntry:
    return _resolve_entry(
        request.app[COLLECTION_KEY], request.match_info["machine"]
    )


def _resolve_entry(collection: "ModelCollection", name: str) -> ModelEntry:
    """``name`` -> entry, with the one quarantine/misroute/404 contract
    shared by the path-routed handlers and the streaming plane (whose
    machine names arrive in payloads and query strings, not the path)."""
    entry = collection.get(name)
    if entry is None:
        info = collection.quarantined.get(name)
        if info is not None:
            # 503, not 404: the machine EXISTS and will heal when a good
            # generation flips — clients should treat this as transient
            raise web.HTTPServiceUnavailable(
                text=json.dumps({
                    "error": (
                        f"Machine {name!r} is quarantined: "
                        f"{info['error']}"
                    ),
                    "quarantined": True,
                    "since": info["ts"],
                }),
                content_type="application/json",
            )
        misroute = _misdirected(collection, name)
        if misroute is not None:
            # 421 Misdirected Request: the machine exists, this replica
            # just isn't its owner — a routing bug, not a missing model
            # (and a non-retryable client error on the bundled client)
            raise web.HTTPMisdirectedRequest(
                text=json.dumps({
                    "error": misroute,
                    "shard": collection.shard_owner[name],
                    "shard-count": collection.shard.count,
                }),
                content_type="application/json",
            )
        raise web.HTTPNotFound(text=f"Machine {name!r} not found")
    return entry


def _shed_response(request: web.Request) -> Optional[web.Response]:
    """Overload shedding: once the coalescer's saturation stand-down has
    ESCALATED (consecutive stand-downs doubling the cooldown — not the
    first transient one), new scoring work is refused with 429 +
    ``Retry-After`` derived from the observed queue wait, instead of
    queueing toward a timeout.  The bundled client honors Retry-After on
    its retryable-status path, so a shed request comes back exactly when
    the server predicted it could be served."""
    coalescer = request.app.get(COALESCER_KEY)
    if coalescer is None:
        return None
    retry_after = coalesce_mod.shed_retry_after(coalescer)
    if retry_after is None:
        return None
    _SHED_TOTAL.inc()
    return web.json_response(
        {
            "error": (
                "server overloaded (queue wait escalated past service "
                "time); retry after the indicated delay"
            ),
            "retry-after-seconds": retry_after,
        },
        status=429,
        headers={"Retry-After": str(max(1, int(round(retry_after))))},
    )


async def healthcheck(request: web.Request) -> web.Response:
    _entry_or_404(request)
    return web.json_response({"gordo-server-version": gordo_tpu.__version__})


async def metadata(request: web.Request) -> web.Response:
    entry = _entry_or_404(request)
    return web.json_response(
        {
            "endpoint-metadata": {"model-name": entry.name},
            "metadata": entry.metadata,
        },
        dumps=_json_dumps,
    )


async def prediction(request: web.Request) -> web.Response:
    entry = _entry_or_404(request)
    t0 = time.perf_counter()
    try:
        X, index, _ = await _read_and_parse_single(request, entry)
    except ValueError as exc:
        return web.json_response({"error": str(exc)}, status=400)
    loop = asyncio.get_running_loop()
    try:
        with telemetry.span(
            "server.predict", machine=entry.name, rows=X.shape[0]
        ):
            out = await loop.run_in_executor(None, entry.scorer.predict, X)
    except ValueError as exc:  # client-input problem (e.g. short rows)
        return web.json_response({"error": str(exc)}, status=400)
    except Exception as exc:
        logger.exception("Prediction failed for %s", entry.name)
        return web.json_response({"error": str(exc)}, status=500)
    data: Dict[str, Any] = {"model-output": out}
    if index is not None:
        data.update(time_columns(index, out.shape[0], entry.resolution))
    return await _respond(
        request,
        {
            "data": data,
            "time-seconds": round(time.perf_counter() - t0, 6),
        },
    )


async def anomaly_prediction(request: web.Request) -> web.Response:
    entry = _entry_or_404(request)
    shed = _shed_response(request)
    if shed is not None:
        # refused before the body is even read: shedding exists to stop
        # spending on work that will queue to death anyway
        return shed
    if not entry.scorer.is_anomaly:
        return web.json_response(
            {
                "error": "Model is not an AnomalyDetector; use /prediction"
            },
            status=422,
        )
    t0 = time.perf_counter()
    try:
        X, index, y = await _read_and_parse_single(request, entry)
    except ValueError as exc:
        return web.json_response({"error": str(exc)}, status=400)
    deadline = request.get(DEADLINE_KEY)
    if deadline is not None and time.monotonic() >= deadline:
        # the budget ran out while the body was read/parsed — refuse
        # before dispatch rather than scoring into a dead socket
        return _deadline_expired_response("before dispatch")
    loop = asyncio.get_running_loop()
    coalescer = request.app.get(COALESCER_KEY)
    score_span = telemetry.span(
        "server.anomaly", machine=entry.name, rows=X.shape[0]
    )
    try:
        with score_span:
            if coalescer is not None and y is None:
                # handlers run on the single-threaded event loop, so the
                # inflight counter needs no lock; it counts EVERY in-flight
                # single-machine anomaly request (direct or coalesced) —
                # the concurrency signal the adaptive bypass keys on
                coalescer.inflight += 1
                try:
                    if coalescer.should_coalesce():
                        # concurrent requests across machines merge into
                        # one stacked dispatch (the _bulk route's program
                        # family)
                        out = await asyncio.wrap_future(
                            coalescer.submit(
                                entry.name,
                                X,
                                trace_id=telemetry.current_trace_id(),
                                deadline=deadline,
                            )
                        )
                    else:  # too few riders: direct dispatch wins — bypass
                        out = await loop.run_in_executor(
                            None, entry.scorer.anomaly_arrays, X, None
                        )
                finally:
                    coalescer.inflight -= 1
            else:
                out = await loop.run_in_executor(
                    None, entry.scorer.anomaly_arrays, X, y
                )
    except ValueError as exc:  # client-input problem (e.g. short rows)
        return web.json_response({"error": str(exc)}, status=400)
    except coalesce_mod.DeadlineExpired as exc:
        # the coalescer dropped this rider pre-dispatch: its propagated
        # budget expired while queued
        return _deadline_expired_response(str(exc))
    except Exception as exc:
        logger.exception("Anomaly scoring failed for %s", entry.name)
        return web.json_response({"error": str(exc)}, status=500)
    data = dict(out)
    if index is not None:
        data.update(
            time_columns(index, len(data["model-output"]), entry.resolution)
        )
    return await _respond(
        request,
        {
            "data": data,
            "time-seconds": round(time.perf_counter() - t0, 6),
        },
    )


async def bulk_anomaly_prediction(request: web.Request) -> web.Response:
    """Score MANY machines in one request via the stacked fleet scorer
    (one vmapped device program per structure bucket).  Payload:
    ``{"X": {"<machine>": [[...rows...]], ...}}``."""
    collection: ModelCollection = request.app[COLLECTION_KEY]
    t0 = time.perf_counter()
    try:
        payload = await _read_payload(request)
        if not isinstance(payload, dict) or not isinstance(payload.get("X"), dict):
            raise ValueError(
                "Payload must be {'X': {machine: rows}} for bulk scoring"
            )
    except ValueError as exc:
        return web.json_response({"error": str(exc)}, status=400)
    # per-machine validation: one bad machine reports in ITS result slot and
    # must not 400 the rest of the fleet.  The whole parse loop (dozens of
    # list->ndarray conversions) runs in the executor — at fleet request
    # sizes it is far too much work for the event loop.
    indices = payload.get("index") or {}

    def _parse_machines():
        X_by: Dict[str, np.ndarray] = {}
        idx_by: Dict[str, pd.DatetimeIndex] = {}
        errors: Dict[str, Dict[str, str]] = {}
        # bulk clients replay one fetch window across the fleet, so the
        # per-machine index lists are usually IDENTICAL — parse each
        # distinct list once (list equality is a C compare; re-running
        # pd.to_datetime per machine was the parse loop's hottest path)
        idx_cache: Dict[tuple, "tuple[list, pd.DatetimeIndex]"] = {}

        def parse_index_cached(raw: Any, n_rows: int):
            key = None
            if isinstance(raw, list) and raw and len(raw) == n_rows:
                key = (raw[0], raw[-1], len(raw))
                hit = idx_cache.get(key)
                if hit is not None and hit[0] == raw:
                    return hit[1]
            index = parse_index({"index": raw}, n_rows)
            if key is not None and index is not None:
                idx_cache[key] = (raw, index)
            return index

        for name, rows in payload["X"].items():
            entry = collection.get(name)
            try:
                if entry is None:
                    q = collection.quarantined.get(name)
                    if q is not None:
                        # in-slot, like every other per-machine bulk
                        # error: one quarantined machine must never tear
                        # the rest of the round's responses
                        raise ValueError(
                            f"Machine {name!r} is quarantined: "
                            f"{q['error']}"
                        )
                    # a foreign-shard machine reports its owner in-slot
                    # (scatter-gather clients route per shard and should
                    # never see this; a mis-split payload must say WHY)
                    raise ValueError(
                        _misdirected(collection, name)
                        or f"Unknown machine {name!r}"
                    )
                X = parse_X({"X": rows}, entry.tags)
                _validate_width(X, entry)
                if isinstance(indices, dict) and name in indices:
                    index = parse_index_cached(indices[name], X.shape[0])
                    if index is not None:
                        idx_by[name] = index
                X_by[name] = X
            except ValueError as exc:
                errors[name] = {"error": str(exc)}
        return X_by, idx_by, errors

    loop = asyncio.get_running_loop()
    X_by_name, index_by_name, machine_errors = await loop.run_in_executor(
        None, _parse_machines
    )
    if not X_by_name and machine_errors:
        return web.json_response(
            {"error": "No valid machines in payload",
             "data": machine_errors},
            status=400,
        )
    deadline = request.get(DEADLINE_KEY)
    if deadline is not None and time.monotonic() >= deadline:
        return _deadline_expired_response("before bulk dispatch")
    # a columnar client keeps the stacked dispatch output STACKED: decide
    # the assembly mode from Accept BEFORE dispatch so the hot path never
    # splits per machine just to re-glue the pieces at encode time
    columnar = codec.wants_columnar(request.headers.get("Accept"))
    try:
        # resolve the lazy scorer inside the executor too: first-call param
        # stacking for a large project must not stall the accept loop
        with telemetry.span("server.bulk", machines=len(X_by_name)):
            if columnar:
                col = await loop.run_in_executor(
                    None,
                    lambda: collection.fleet_scorer.dispatch_all(
                        X_by_name
                    ).assemble_columnar(),
                )
                out = col.rest
            else:
                col = None
                out = await loop.run_in_executor(
                    None, lambda: collection.fleet_scorer.score_all(X_by_name)
                )
    except Exception as exc:
        logger.exception("Bulk anomaly scoring failed")
        return web.json_response({"error": str(exc)}, status=500)
    # "client-error" is transport metadata (exception-type routing for the
    # coalescer), not response schema — strip it
    data = {
        name: {k: v for k, v in res.items() if k != "client-error"}
        for name, res in out.items()
    }
    # the parse loop dedupes equal indices to shared DatetimeIndex
    # objects, so one (index, n_out, resolution) rendering serves every
    # machine that shares the window — the per-machine isoformat loops
    # were, at fleet width, a bigger bill than the scoring itself
    tc_cache: Dict[tuple, Dict[str, List[str]]] = {}

    def cached_time_columns(name: str, n_out: int) -> Dict[str, List[str]]:
        entry = collection.get(name)
        resolution = entry.resolution if entry is not None else None
        index = index_by_name[name]
        key = (id(index), n_out, resolution)
        cols = tc_cache.get(key)
        if cols is None:
            cols = time_columns(index, n_out, resolution)
            tc_cache[key] = cols
        return cols

    for name, res in data.items():
        if name in index_by_name and "model-output" in res:
            res.update(cached_time_columns(name, len(res["model-output"])))
    if col is not None:
        # stacked machines never left the blocks; their time-column
        # partials ride the rest blob and merge client-side on decode
        for name in index_by_name:
            rows = col.rows(name)
            if rows:
                data.setdefault(name, {}).update(
                    cached_time_columns(name, rows)
                )
    data.update(machine_errors)
    if col is not None:
        col.rest = data
        payload_obj: Any = {
            "data": col,
            "time-seconds": round(time.perf_counter() - t0, 6),
        }
    else:
        payload_obj = {
            "data": data,
            "time-seconds": round(time.perf_counter() - t0, 6),
        }
    return await _respond(request, payload_obj)


async def download_model(request: web.Request) -> web.Response:
    entry = _entry_or_404(request)
    loop = asyncio.get_running_loop()
    # pickling a params pytree can take long enough to stall the accept loop
    body = await loop.run_in_executor(None, serializer.dumps, entry.model)
    return web.Response(body=body, content_type="application/octet-stream")


# -- streaming plane (serve/stream.py) --------------------------------------

def _stream_after(request: web.Request, hub) -> int:
    """The resume cursor: ``Last-Event-ID`` header (SSE reconnect), then
    ``?after=`` (long-poll / explicit replay), else the ring head — a
    fresh subscriber tails live events only."""
    raw = request.headers.get("Last-Event-ID") or request.query.get("after")
    if raw is None:
        return hub.ring.last_id
    try:
        return int(raw)
    except ValueError:
        raise web.HTTPBadRequest(
            text=json.dumps({"error": f"bad event id {raw!r}"}),
            content_type="application/json",
        )


async def stream_ingest(request: web.Request) -> web.Response:
    """``POST {project}/stream/ingest``: feed arriving rows into the
    per-machine streams; verdicts/crossings push to subscribers.

    Body forms: ``{"machine": m, "x": row-or-rows}`` or the bulk-shaped
    ``{"X": {machine: rows}}``.  Scoring BYPASSES the coalescer — a
    streamed row is one O(1) fixed-shape dispatch already, and queueing
    it behind a micro-batch window would tax exactly the latency the
    push model exists to minimize.  Returns the accepted row count and
    the hub's event cursor (a poller can resume from it directly).
    """
    collection: ModelCollection = request.app[COLLECTION_KEY]
    hub = request.app[STREAM_HUB_KEY]
    payload = await _read_payload(request)
    if not isinstance(payload, dict):
        raise web.HTTPBadRequest(
            text=json.dumps({"error": "body must be a JSON object"}),
            content_type="application/json",
        )
    try:
        if isinstance(payload.get("X"), dict):
            batches = [
                (name, rows) for name, rows in payload["X"].items()
            ]
        elif payload.get("machine"):
            batches = [(payload["machine"], payload.get("x"))]
        else:
            raise ValueError(
                'need {"machine": ..., "x": ...} or {"X": {machine: rows}}'
            )
        parsed = []
        for name, rows in batches:
            entry = _resolve_entry(collection, name)
            X = parse_X({"X": rows}, entry.tags)
            _validate_width(X, entry)
            parsed.append((name, entry, X))
    except ValueError as exc:
        raise web.HTTPBadRequest(
            text=json.dumps({"error": str(exc)}),
            content_type="application/json",
        )
    accepted = 0
    published = 0
    for name, entry, X in parsed:
        try:
            events = hub.ingest_rows(
                name, entry.scorer, X, dtype=collection.serve_dtype
            )
        except stream_mod.StreamUnsupported as exc:
            raise web.HTTPUnprocessableEntity(
                text=json.dumps({"error": str(exc)}),
                content_type="application/json",
            )
        except faults.InjectedFault as exc:
            # the stream.ingest seam fires BEFORE state mutation, so
            # the client may retry without double-applying the row
            if exc.mode == "reset":
                if request.transport is not None:
                    request.transport.close()
                raise web.HTTPInternalServerError(text=str(exc))
            status = 503 if exc.mode == "http_503" else 500
            return web.json_response({"error": str(exc)}, status=status)
        accepted += int(X.shape[0])
        published += len(events)
    return await _respond(request, {
        "accepted": accepted,
        "events": published,
        "last-event-id": hub.ring.last_id,
    })


async def stream_subscribe(request: web.Request) -> web.StreamResponse:
    """``GET {project}/stream``: the push surface.

    Default is SSE (``text/event-stream`` frames with hub-global
    monotonic ids; reconnect with ``Last-Event-ID`` to replay what was
    missed).  ``?mode=poll&after=N`` is the chunked long-poll fallback
    for clients that can't hold SSE: it waits up to ``?timeout=`` (capped
    at the server's poll budget) for events past ``N`` and returns them
    as one JSON batch with the next cursor.  ``?machines=a,b`` filters;
    every named machine is resolved through the quarantine/shard
    contract first, so a subscription for a foreign machine 421s with
    the owner shard identified (clients split subscriptions per shard).
    """
    collection: ModelCollection = request.app[COLLECTION_KEY]
    hub = request.app[STREAM_HUB_KEY]
    machines = None
    if request.query.get("machines"):
        machines = [
            m for m in request.query["machines"].split(",") if m
        ]
        for name in machines:
            _resolve_entry(collection, name)
    after = _stream_after(request, hub)

    if request.query.get("mode") == "poll":
        try:
            timeout = min(
                float(request.query.get("timeout", "1e9")),
                stream_mod.poll_timeout_seconds(),
            )
        except ValueError:
            timeout = stream_mod.poll_timeout_seconds()
        doc = await stream_mod.poll_events(
            hub, set(machines) if machines else None, after, timeout
        )
        return await _respond(request, doc)

    sub = hub.subscribe(machines)
    response = web.StreamResponse(
        status=200,
        headers={
            "Content-Type": "text/event-stream",
            "Cache-Control": "no-cache",
            # tells nginx-style proxies not to buffer the event stream
            "X-Accel-Buffering": "no",
        },
    )
    response.enable_chunked_encoding()
    await response.prepare(request)
    try:
        await stream_mod.run_sse(response, hub, sub, after)
    except faults.InjectedFault:
        # mid-event disconnect: kill the transport with the frame torn
        if request.transport is not None:
            request.transport.close()
    except (ConnectionResetError, ConnectionError, asyncio.CancelledError):
        pass  # peer went away / server shutdown — run_sse unsubscribed
    return response


async def readiness(request: web.Request) -> web.Response:
    """Readiness endpoint for orchestrators: 503 while a startup warmup is
    still compiling, 200 once it finishes (or when warmup is off).  The
    generated k8s Deployment points its readinessProbe here so a
    rescheduled pod only receives traffic once its programs are compiled.
    """
    fut = request.app.get(WARMUP_TASK_KEY)
    if fut is not None and not fut.done():
        return web.json_response(
            {"ready": False, "reason": "warmup in progress"}, status=503
        )
    return web.json_response({"ready": True})


async def healthz(request: web.Request) -> web.Response:
    """Liveness + warmup-state surface: 200 always (the process is up),
    with ``state`` reporting ``warming`` while the startup warmup is
    still pre-compiling serving programs and ``ready`` after — what
    ``gordo warmup --url`` polls, and the human-readable twin of the
    ``/ready`` readiness gate (which speaks HTTP status for kubernetes).
    """
    fut = request.app.get(WARMUP_TASK_KEY)
    state = "warming" if (fut is not None and not fut.done()) else "ready"
    collection = request.app.get(COLLECTION_KEY)
    if state == "ready" and collection is not None and collection.reloading:
        # a generation flip is being absorbed in the background; the OLD
        # scorer keeps serving throughout, so this state never gates
        # traffic — it is rollout visibility, not readiness
        state = "reloading"
    doc: Dict[str, Any] = {
        "state": state,
        "gordo-server-version": gordo_tpu.__version__,
    }
    if collection is not None:
        doc["fleet-generation"] = collection.generation
        if collection.quarantined:
            doc["quarantined"] = sorted(collection.quarantined)
        if collection.last_error is not None:
            # the most recent reload/quarantine failure (string +
            # timestamp): an operator probing a shrunken fleet sees WHY
            # here instead of grepping logs
            doc["last-error"] = dict(collection.last_error)
    if state == "ready" and fut is not None:
        # a FAILED warmup still goes ready (the pod can serve; programs
        # compile lazily) but says so, so the init-container gate can tell
        exc = None if fut.cancelled() else fut.exception()
        if exc is not None:
            doc["warmup_error"] = str(exc)
        elif fut.done():
            res = fut.result()
            doc["warmup_errors"] = int(res.get("errors", 0)) if isinstance(
                res, dict
            ) else 0
    return web.json_response(doc)


async def metrics_endpoint(request: web.Request) -> web.Response:
    """Prometheus scrape surface (mounted at ``/metrics``, where every
    scraper looks by default).  Point-in-time gauges (collection size,
    coalescer queue/policy state) refresh at scrape time — they describe
    "now", so sampling them on the read side is both cheaper and more
    honest than pushing every transition."""
    collection = request.app.get(COLLECTION_KEY)
    if collection is not None:
        _MACHINES_GAUGE.set(len(collection.entries))
        _QUARANTINED_GAUGE.set(float(len(collection.quarantined)))
        _FLEET_GENERATION_GAUGE.set(float(collection.generation))
        if collection.shard is not None:
            _SHARD_INDEX_GAUGE.set(collection.shard.index)
            _SHARD_COUNT_GAUGE.set(collection.shard.count)
        # fleet-health gauges refresh at scrape time too: top-K by drift
        # only (bounded cardinality on a 10k-machine fleet; the full
        # per-machine set lives at /gordo/v0/<p>/fleet-health)
        telemetry.FLEET_HEALTH.export_gauges(
            machines=sorted(collection.entries)
        )
    coalesce_mod.export_gauges(request.app.get(COALESCER_KEY))
    return web.Response(
        text=telemetry.render(), content_type=METRICS_CONTENT_TYPE
    )


async def fleet_health(request: web.Request) -> web.Response:
    """The full per-machine fleet-health document for THIS replica's
    machines: live score sketch, build-time baseline, drift score and
    status each, plus the top-K drift ranking (``?top=N`` overrides the
    default).  Sharded replicas report their shard identity so
    watchman's ``/fleet-health`` can merge N of these into one fleet
    view (sketches merge exactly — see telemetry/fleet_health.py)."""
    collection: ModelCollection = request.app[COLLECTION_KEY]
    try:
        top = int(request.query.get("top", "") or drift_top_k())
    except ValueError:
        return web.json_response(
            {"error": "top must be an integer"}, status=400
        )
    doc = telemetry.FLEET_HEALTH.doc(
        machines=sorted(collection.entries), top=top
    )
    doc["project-name"] = collection.project
    if collection.quarantined:
        # quarantined machines carry a `quarantined` status in the doc:
        # they have no live sketch (nothing scores them) but MUST NOT
        # read as merely "no data" — the fleet view has to show them red
        machines_doc = doc.setdefault("machines", {})
        for name, info in sorted(collection.quarantined.items()):
            slot = machines_doc.setdefault(name, {})
            slot["status"] = "quarantined"
            slot["quarantine-error"] = info["error"]
            slot["quarantine-since"] = info["ts"]
        doc["quarantined"] = sorted(collection.quarantined)
    if collection.shard is not None:
        doc["serve-shard"] = {
            "index": collection.shard.index,
            "count": collection.shard.count,
        }
    return web.json_response(doc)


async def scores_aggregate(request: web.Request) -> web.Response:
    """Aggregation pushdown over the score archive: per-machine,
    per-period summaries (count / mean / max / exceedance / sketch
    percentiles) computed server-side by scanning the mmap columns of
    ``.gordo-scores/`` under this collection's artifact dir — a
    fleet-year dashboard query returns kilobytes of summaries instead
    of the ~84M raw samples ``client.score_history`` would ship.

    Query: ``?machines=a,b&start=...&end=...&stats=count,p99&period=7d
    &threshold=1.0`` (all optional; defaults: full roster, the archive
    plan's span, the standard stat set, 1d, 1.0).  The response rides
    whatever the ``Accept`` header negotiates — the GSB1 columnar wire
    ships each stat as ONE contiguous ``[n_machines, n_periods]`` block
    (the bundled client's default); JSON/msgpack split per machine.
    The scan runs in the executor: a fleet-year pass takes ~100ms-class
    time that must not stall the accept loop."""
    collection: ModelCollection = request.app[COLLECTION_KEY]
    from gordo_tpu.batch import archive as score_archive

    root = collection.source_dir
    if root is None or not os.path.isdir(score_archive.archive_root(root)):
        return web.json_response(
            {"error": "no score archive under this server's artifact "
                      "dir (run gordo backfill first)"},
            status=404,
        )
    q = request.query
    machines = [m for m in (q.get("machines") or "").split(",") if m]
    stats = [s for s in (q.get("stats") or "").split(",") if s]
    period = (
        q.get("period")
        or os.environ.get("GORDO_SCORES_AGG_PERIOD", "")
        or "1d"
    )
    try:
        threshold = float(q.get("threshold", "") or 1.0)
    except ValueError:
        return web.json_response(
            {"error": "threshold must be a number"}, status=400
        )
    arch = score_archive.ScoreArchive(root)

    def scan() -> Dict[str, Any]:
        return arch.aggregate(
            machines or None,
            q.get("start") or None,
            q.get("end") or None,
            stats=stats or None,
            period=period,
            threshold=threshold,
        )

    try:
        doc = await asyncio.get_running_loop().run_in_executor(None, scan)
    except (ValueError, score_archive.ArchiveError) as exc:
        return web.json_response({"error": str(exc)}, status=400)
    # each stat matrix ships as one contiguous GSB1 block; the machine
    # map hands every machine its row view, so the JSON/msgpack
    # fallbacks split into per-machine dicts via the same one rule
    stat_arrays = doc.pop("stats")
    blocks = [np.ascontiguousarray(a) for a in stat_arrays.values()]
    entry_map = {
        name: {
            stat: (bi, mi, None)
            for bi, stat in enumerate(stat_arrays)
        }
        for mi, name in enumerate(doc["machines"])
    }
    envelope = dict(doc)
    envelope["stats"] = list(stat_arrays)
    envelope["data"] = codec.ColumnarResult(
        blocks=blocks, machines=entry_map
    )
    return await _respond(request, envelope)


def _mesh_doc(mesh) -> dict:
    """Wire-shape description of a serve mesh (``None`` = single-device)."""
    if mesh is None:
        return {"device-count": 1, "shape": None, "sharded": False}
    return {
        "device-count": int(mesh.devices.size),
        "shape": {str(k): int(v) for k, v in mesh.shape.items()},
        "sharded": True,
    }


async def project_index(request: web.Request) -> web.Response:
    collection: ModelCollection = request.app[COLLECTION_KEY]
    store = collection.pack_store
    doc = {
        "project-name": collection.project,
        "machines": sorted(collection.entries),
        "gordo-server-version": gordo_tpu.__version__,
        "coalescer": coalesce_mod.stats(request.app.get(COALESCER_KEY)),
        # client/watchman artifact discovery: which format backs this
        # collection, and how many packs when v2
        "artifact-format": "v2-packs" if store is not None else "v1-dirs",
        # the serving precision this collection dispatches at (the
        # serving-precision plane; clients reading bulk responses at
        # reduced wire dtypes can confirm what the compute side ran)
        "serving-dtype": collection.serve_dtype,
        # change-detector stamp for the artifacts backing this replica;
        # watchman republishes it per target (routing-topology surface)
        "fleet-generation": collection.generation,
        # placement plane: the device mesh this replica's stacked fleet
        # dispatches shard over (no mesh = single-device serving)
        "mesh": _mesh_doc(collection.serve_mesh),
    }
    if collection.quarantined:
        doc["quarantined"] = sorted(collection.quarantined)
    if collection.shard is not None:
        # the routing-topology surface: which shard this replica is, and
        # the FULL fleet list every client needs to compute the shard
        # table locally ("machines" stays this replica's served subset)
        doc["serve-shard"] = {
            "index": collection.shard.index,
            "count": collection.shard.count,
        }
        doc["fleet-machines"] = collection.fleet_machines
    if store is not None:
        doc["artifact-packs"] = len(store.packs)
        doc["artifact-pack-bytes"] = store.total_bytes()
        doc["artifact-generations-retained"] = len(store.generations)
    return web.json_response(doc)


def _validate_width(X: np.ndarray, entry: ModelEntry) -> None:
    tags = entry.tags
    if tags and X.shape[1] != len(tags):
        raise ValueError(
            f"X has {X.shape[1]} columns; model expects {len(tags)} tags"
        )


def _json_dumps(obj) -> str:
    import json

    return json.dumps(obj, default=str)


# ---------------------------------------------------------------------------
# app factory
# ---------------------------------------------------------------------------

def warmup_scorers(
    collection: ModelCollection,
    row_sizes: Optional[List[int]] = None,
) -> Dict[str, Any]:
    """Precompile the serving programs so early requests don't pay
    compilation (~20-40s cold on TPU).

    Delegates to the compile plane (:func:`gordo_tpu.compile.
    warmup_collection`): per structural bucket, per row bucket, the full
    stacked dispatch, the 1-machine subset gather, and the per-machine
    fused program are AOT-compiled (``lower(shapes).compile()`` — no
    input data, nothing executes).  Row buckets come from ``row_sizes``,
    else the build's warmup manifest under the collection's source dir,
    else the defaults (the minimum serving bucket and the 2048-row
    replayed-stream shape).  Errors are logged and counted, never raised:
    a warmup failure must not take down startup.
    """
    from gordo_tpu.compile import warmup_collection

    return warmup_collection(collection, row_sizes=row_sizes)


def build_app(
    collection: ModelCollection,
    rescan_interval: float = 0.0,
    coalesce_window_ms: float = 0.0,
    warmup: bool = False,
    coalesce_min_concurrency: int = 2,
    coalesce_knee_batch: int = 0,
    health_rollup_interval: float = 0.0,
    reload_watch_interval: float = 0.0,
) -> web.Application:
    """``rescan_interval > 0`` starts a background artifact-dir rescan so
    machines built after startup begin serving without a restart.
    ``health_rollup_interval > 0`` periodically appends this replica's
    fleet-health doc as one JSONL line under the artifact dir
    (``.gordo-fleet-health/``, size-capped keep-last-2 rotation) — the
    no-HTTP interface a ``gordo refresh`` loop (ROADMAP item 3) and
    ``gordo fleet-health --dir`` consume.
    ``coalesce_window_ms > 0`` micro-batches concurrent single-machine
    anomaly requests into stacked fleet dispatches (``serve/coalesce.py``):
    a continuous drain groups whatever is queued, capping each dispatch at
    the measured throughput knee and standing down to direct dispatch when
    the saturation signal says batching is losing.  ``coalesce_window_ms``
    bounds only the single-rider grace wait (one queued request holding
    for a second rider); requests below ``coalesce_min_concurrency`` in
    flight dispatch directly (adaptive bypass), so an idle or
    lightly-loaded server keeps uncoalesced latency.
    ``coalesce_knee_batch`` pins the batch cap explicitly (0 = estimate
    it from a short warmup sweep on first use).
    ``reload_watch_interval > 0`` starts the generation watch: a cheap
    poll of the artifact index's ``GENERATION`` sidecar that triggers a
    delta hot reload the moment a build (or ``delta_write``) stamps a
    new generation — O(changed machines), zero compiles, the old scorer
    serving until the swap.  It complements (does not replace) the
    coarser full ``rescan_interval`` sweep, which also covers v1 dirs
    and fleet membership changes.
    ``warmup`` precompiles the serving programs in a background executor
    task at startup (``warmup_scorers``) — the server accepts traffic
    immediately; an early request races the warmup at worst."""
    from gordo_tpu.utils.compile_cache import enable_persistent_compile_cache

    enable_persistent_compile_cache()
    app = web.Application(
        client_max_size=256 * 1024 * 1024,
        middlewares=[telemetry_middleware, deadline_middleware],
    )
    app[COLLECTION_KEY] = collection
    app[STREAM_HUB_KEY] = stream_mod.StreamHub(collection)

    if warmup:
        from gordo_tpu import compile as compile_plane

        async def _warmup(app: web.Application):
            # a DAEMON thread, not the loop's executor: compiles can't be
            # interrupted, and a non-daemon worker (incl. any
            # ThreadPoolExecutor's) would be joined at interpreter exit —
            # Ctrl-C during a multi-minute TPU warmup must still exit
            # promptly
            loop = asyncio.get_running_loop()
            fut: asyncio.Future = loop.create_future()
            # readiness() only checks fut.done() — consume a failure here
            # so GC doesn't log "Future exception was never retrieved"
            fut.add_done_callback(
                lambda f: None if f.cancelled() else f.exception()
            )

            def _resolve(setter):
                try:
                    loop.call_soon_threadsafe(
                        lambda: None if fut.done() else setter()
                    )
                except RuntimeError:
                    pass  # loop already closed — nothing to resolve into

            def runner():
                try:
                    res = warmup_scorers(collection)
                    coalescer = app.get(COALESCER_KEY)
                    if coalescer is not None:
                        # the knee sweep rides the warmup thread: it warms
                        # the subset programs coalesced rounds run at AND
                        # fixes the batch cap before real traffic arrives
                        res["coalesce_knee"] = coalescer.ensure_knee(
                            rows=2048
                        )
                except Exception as exc:  # warmup_scorers logs details
                    # bind now: CPython deletes the except-bound name when
                    # the block exits, before the scheduled callback runs
                    _resolve(lambda e=exc: fut.set_exception(e))
                else:
                    _resolve(lambda: fut.set_result(res))
                finally:
                    # /healthz flips to "ready" and the coalescer stops
                    # queueing riders behind the warmup
                    compile_plane.set_warming(False)

            compile_plane.set_warming(True)
            threading.Thread(
                target=runner, name="gordo-warmup", daemon=True
            ).start()
            app[WARMUP_TASK_KEY] = fut

        app.on_startup.append(_warmup)

    if coalesce_window_ms > 0:
        coalescer = coalesce_mod.CoalescingScorer(
            lambda: collection.fleet_scorer,
            max_wait_s=coalesce_window_ms / 1000.0,
            min_concurrency=coalesce_min_concurrency,
            knee_batch=coalesce_knee_batch,
        )
        app[COALESCER_KEY] = coalescer

        async def _close_coalescer(app: web.Application):
            await asyncio.get_running_loop().run_in_executor(
                None, coalescer.close
            )

        app.on_cleanup.append(_close_coalescer)

    if rescan_interval > 0 and collection.source_dir is not None:

        async def _rescan_loop(app: web.Application):
            loop = asyncio.get_running_loop()
            while True:
                await asyncio.sleep(rescan_interval)
                try:
                    # artifact loads unpickle params — keep the accept loop
                    # responsive by rescanning in the executor
                    await loop.run_in_executor(None, collection.rescan)
                except Exception:
                    logger.exception("Artifact rescan failed")

        async def _start(app: web.Application):
            app["_rescan_task"] = asyncio.get_running_loop().create_task(
                _rescan_loop(app)
            )

        async def _stop(app: web.Application):
            task = app.get("_rescan_task")
            if task is not None:
                task.cancel()
                try:
                    await task
                except asyncio.CancelledError:
                    pass

        app.on_startup.append(_start)
        app.on_cleanup.append(_stop)

    if reload_watch_interval > 0 and collection.source_dir is not None:

        async def _reload_watch_loop(app: web.Application):
            loop = asyncio.get_running_loop()
            while True:
                await asyncio.sleep(reload_watch_interval)
                try:
                    # the poll itself is one tiny file read; a detected
                    # flip runs the (heavier) delta reload in the
                    # executor so the accept loop never stalls
                    await loop.run_in_executor(
                        None, collection.maybe_delta_reload
                    )
                except Exception:
                    logger.exception("generation watch failed")

        async def _start_watch(app: web.Application):
            app["_reload_watch_task"] = (
                asyncio.get_running_loop().create_task(
                    _reload_watch_loop(app)
                )
            )

        async def _stop_watch(app: web.Application):
            task = app.get("_reload_watch_task")
            if task is not None:
                task.cancel()
                try:
                    await task
                except asyncio.CancelledError:
                    pass

        app.on_startup.append(_start_watch)
        app.on_cleanup.append(_stop_watch)

    if health_rollup_interval > 0 and collection.source_dir is not None:

        def _write_health_rollup() -> None:
            doc = telemetry.FLEET_HEALTH.doc(
                machines=sorted(collection.entries)
            )
            doc["project-name"] = collection.project
            if collection.shard is not None:
                doc["serve-shard"] = {
                    "index": collection.shard.index,
                    "count": collection.shard.count,
                }
            telemetry.write_rollup(
                collection.source_dir, doc, shard=collection.shard
            )

        async def _rollup_loop(app: web.Application):
            loop = asyncio.get_running_loop()
            while True:
                await asyncio.sleep(health_rollup_interval)
                try:
                    # the doc build walks every machine's sketch — off
                    # the accept loop like the rescan
                    await loop.run_in_executor(None, _write_health_rollup)
                except Exception:
                    logger.exception("fleet-health rollup failed")

        async def _start_rollup(app: web.Application):
            app["_health_rollup_task"] = (
                asyncio.get_running_loop().create_task(_rollup_loop(app))
            )

        async def _stop_rollup(app: web.Application):
            task = app.get("_health_rollup_task")
            if task is not None:
                task.cancel()
                try:
                    await task
                except asyncio.CancelledError:
                    pass
            # last-gasp rollup at shutdown so a clean drain leaves the
            # freshest doc on disk for the file-interface consumers
            try:
                await asyncio.get_running_loop().run_in_executor(
                    None, _write_health_rollup
                )
            except Exception:
                logger.exception("final fleet-health rollup failed")

        app.on_startup.append(_start_rollup)
        app.on_cleanup.append(_stop_rollup)

    # scrape surface at the conventional root path (no project segment:
    # one process = one scrape target, whatever it hosts)
    app.router.add_get("/metrics", metrics_endpoint)
    # liveness + warmup state at the conventional root path too
    app.router.add_get("/healthz", healthz)
    p = f"{API_PREFIX}/{{project}}"
    app.router.add_get(f"{p}/", project_index)
    app.router.add_get(f"{p}/ready", readiness)
    # the fleet-under-observation surface (per-machine drift/sketches);
    # registered before the {machine} routes like _bulk
    app.router.add_get(f"{p}/fleet-health", fleet_health)
    # registered before the {machine} routes so "_bulk" never resolves as a
    # machine name
    app.router.add_post(f"{p}/_bulk/anomaly/prediction", bulk_anomaly_prediction)
    # score-archive aggregation pushdown (r20): summaries over the
    # backfill plane's archive, served from this collection's source dir
    app.router.add_get(f"{p}/scores/aggregate", scores_aggregate)
    # streaming plane: also before {machine} ("stream" is a path segment,
    # not a machine name)
    app.router.add_post(f"{p}/stream/ingest", stream_ingest)
    app.router.add_get(f"{p}/stream", stream_subscribe)
    app.router.add_get(f"{p}/{{machine}}/healthcheck", healthcheck)
    app.router.add_get(f"{p}/{{machine}}/metadata", metadata)
    app.router.add_post(f"{p}/{{machine}}/prediction", prediction)
    app.router.add_post(f"{p}/{{machine}}/anomaly/prediction", anomaly_prediction)
    app.router.add_get(f"{p}/{{machine}}/download-model", download_model)
    return app


def run_server(
    model_dir: str,
    host: str = "0.0.0.0",
    port: int = 5555,
    project: str = "project",
    rescan_interval: float = 30.0,
    coalesce_window_ms: float = 0.0,
    coalesce_min_concurrency: int = 2,
    coalesce_knee_batch: int = 0,
    model_parallel: bool = False,
    mesh_devices: Optional[str] = None,
    warmup: bool = False,
    shard: Optional[str] = None,
    health_rollup_interval: Optional[float] = None,
    reload_watch_interval: Optional[float] = None,
) -> None:
    """Blocking entrypoint (reference: ``gordo run-server``).

    ``model_parallel=True`` shards every stacked serving dispatch over all
    visible devices (the ``"models"`` mesh axis) — one server process
    driving a whole slice instead of one chip. ``mesh_devices`` narrows
    the fleet-mesh width (``"all"``/``"auto"``/``"1"``/an integer N;
    default is the ``GORDO_MESH_DEVICES`` env var, else all devices).

    ``shard``: ``"i/N"`` (or a :class:`~gordo_tpu.serve.shard.ShardSpec`)
    — serve only shard i of an N-replica fleet-sharded tier; default is
    the ``GORDO_SERVE_SHARD`` env var (what the generated per-shard
    Deployments stamp), else unsharded.

    ``health_rollup_interval``: seconds between fleet-health JSONL
    rollup lines under the artifact dir (default: the
    ``GORDO_HEALTH_ROLLUP_SECONDS`` env var, else 60; 0 disables).

    ``reload_watch_interval``: seconds between generation-sidecar polls
    for the delta hot reload (default: the ``GORDO_RELOAD_WATCH_SECONDS``
    env var, else 5; 0 disables — the coarse rescan still reloads, just
    slower and via a full restack).
    """
    if health_rollup_interval is None:
        try:
            health_rollup_interval = float(
                os.environ.get("GORDO_HEALTH_ROLLUP_SECONDS", "") or 60.0
            )
        except ValueError:
            health_rollup_interval = 60.0
    if reload_watch_interval is None:
        try:
            reload_watch_interval = float(
                os.environ.get("GORDO_RELOAD_WATCH_SECONDS", "") or 5.0
            )
        except ValueError:
            reload_watch_interval = 5.0
    from gordo_tpu.serve.shard import ShardSpec

    if isinstance(shard, str):
        shard = ShardSpec.parse(shard)
    serve_mesh = None
    if model_parallel:
        from gordo_tpu.mesh import FleetMesh

        fm = FleetMesh.resolve(mesh_devices)  # honors GORDO_MESH_DEVICES
        if fm.is_sharded:
            serve_mesh = fm.mesh
            logger.info(
                "Model-parallel serving over %d devices", fm.n_devices
            )
        else:
            logger.warning(
                "--model-parallel requested but only 1 device is visible "
                "(%s) — serving single-device; check the TPU runtime/"
                "device visibility (or GORDO_MESH_DEVICES) if a slice "
                "was expected",
                fm.devices[0].platform,
            )
    # crash-safe writer audit before loading: sweep orphaned tmp files a
    # killed build left behind and re-publish a stale GENERATION sidecar;
    # unrepairable findings (truncated packs) are logged here and then
    # quarantined machine-by-machine by the collection load below
    try:
        report = artifacts.fsck(model_dir, repair=True)
        if report.get("findings"):
            logger.warning(
                "artifact fsck: %d finding(s), %d repaired — %s",
                len(report["findings"]),
                len(report.get("repaired", [])),
                report["findings"][:5],
            )
    except Exception:
        logger.exception("artifact fsck failed (continuing to load)")
    collection = ModelCollection.from_directory(
        model_dir, project=project, serve_mesh=serve_mesh, shard=shard
    )
    logger.info(
        "Serving %d machine(s)%s from %s on %s:%d",
        len(collection.entries),
        f" (shard {collection.shard})" if collection.shard else "",
        model_dir,
        host,
        port,
    )
    web.run_app(
        build_app(
            collection,
            rescan_interval=rescan_interval,
            coalesce_window_ms=coalesce_window_ms,
            coalesce_min_concurrency=coalesce_min_concurrency,
            coalesce_knee_batch=coalesce_knee_batch,
            warmup=warmup,
            health_rollup_interval=health_rollup_interval,
            reload_watch_interval=reload_watch_interval,
        ),
        host=host,
        port=port,
    )
