"""Model serving.

Reference equivalent: ``gordo_components/server/`` — Flask app exposing
``/gordo/v0/<project>/<machine>/{prediction, anomaly/prediction, metadata,
healthcheck, download-model}`` over a model loaded from ``MODEL_LOCATION``.

TPU-native design: the HTTP frontend is asyncio (aiohttp — Flask isn't in
this image and a blocking WSGI stack would serialize device dispatches);
the scoring hot path is :mod:`gordo_tpu.serve.scorer` — the whole
scaler→model→anomaly-math pipeline fused into one jitted device program
with request shapes padded onto a small set of compile buckets.  One server
process can host MANY machines (``ModelCollection``), unlike the
reference's pod-per-machine layout; the routes stay per-machine for parity.
"""

from gordo_tpu.serve import precision
from gordo_tpu.serve.scorer import CompiledScorer, compile_scorer
from gordo_tpu.serve.server import ModelCollection, build_app, run_server
from gordo_tpu.serve.shard import ShardRouter, ShardSpec

__all__ = [
    "CompiledScorer",
    "compile_scorer",
    "ModelCollection",
    "ShardRouter",
    "ShardSpec",
    "build_app",
    "precision",
    "run_server",
]
