"""Fleet-sharded serving: ONE shard function shared by every tier.

A project too big (or too hot) for one scoring process splits across N
replicas by machine — and the split is the SAME deterministic,
config-derived partition the multi-host builder uses
(:func:`gordo_tpu.distributed.partition.partition_machines`): disjoint,
exhaustive, independent of list order, computable by anyone holding the
machine-name list.  That property is the whole design: the server, the
client, the watchman and the workflow generator each compute the
partition locally, so a single-machine request routes straight to the
owning replica with ZERO extra hops — no lookup service, no consistent-
hash ring to rebalance, no routing table to distribute (Podracer's
sharded actor fleets and the TensorFlow-serving paper's replicated model
servers both land on this shape).

Serving shards partition on machine NAME only (one uniform signature
bucket): unlike the build partition — which keeps same-signature
machines together so they train as few stacked programs — the serving
tier's clients know names, not model configs, and the contract must be
computable from the project index alone.  Within that one bucket the
partition is ``partition_machines``'s contiguous name-sorted slices, so
shard boundaries line up with the name-sorted (signature, bucket) chunks
the v2 pack writer emits: a replica's shard is typically a run of whole
packs, each still ONE ``artifacts.to_device`` transfer.

``scripts/lint.py`` rejects any other shard computation on the serve
path (serve/, client/, watchman/, workflow/): two implementations that
drift by one machine silently misroute that machine forever.

Environment contract: ``GORDO_SERVE_SHARD=i/N`` (what the generated
per-shard Deployments stamp) makes a server load — and warm — only its
shard's artifacts.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Dict, Iterable, List, Optional, Sequence

#: the env var a sharded replica reads at startup (``"i/N"`` — shard
#: index i of N, zero-based)
ENV_SHARD = "GORDO_SERVE_SHARD"


@dataclass(frozen=True)
class ShardSpec:
    """One replica's identity in an N-way sharded serving tier."""

    index: int
    count: int

    def __post_init__(self):
        if self.count < 1:
            raise ValueError(f"shard count must be >= 1, got {self.count}")
        if not 0 <= self.index < self.count:
            raise ValueError(
                f"shard index {self.index} outside [0, {self.count})"
            )

    def __str__(self) -> str:
        return f"{self.index}/{self.count}"

    @classmethod
    def parse(cls, spec: str) -> "ShardSpec":
        """``"i/N"`` → ShardSpec (the ``GORDO_SERVE_SHARD`` /
        ``--shard`` wire format)."""
        try:
            index_s, count_s = str(spec).strip().split("/", 1)
            return cls(int(index_s), int(count_s))
        except (ValueError, TypeError) as exc:
            raise ValueError(
                f"shard spec must be 'i/N' with 0 <= i < N, got {spec!r}"
            ) from exc

    @classmethod
    def from_env(cls) -> Optional["ShardSpec"]:
        spec = os.environ.get(ENV_SHARD, "").strip()
        return cls.parse(spec) if spec else None


class _ServeAtom:
    """Name-only machine stand-in for :func:`partition_machines`: serving
    shards partition on name alone, so every atom carries the same
    precomputed empty ``fleet_signature`` (one bucket → contiguous
    name-sorted slices — and the partition never has to import the build
    plane's config-signature machinery into a serving process)."""

    __slots__ = ("name", "fleet_signature")

    def __init__(self, name: str):
        self.name = name
        self.fleet_signature = ""


def shard_slices(names: Iterable[str], count: int) -> List[List[str]]:
    """The full partition: ``count`` disjoint, exhaustive, name-sorted
    shards of ``names``, via the builder's :func:`partition_machines`.
    Deterministic in (set(names), count) — input order never matters."""
    from gordo_tpu.distributed.partition import partition_machines

    atoms = [_ServeAtom(n) for n in sorted(set(names))]
    return [
        [a.name for a in shard]
        for shard in partition_machines(atoms, count)
    ]


def shard_map(names: Iterable[str], count: int) -> Dict[str, int]:
    """``{machine name: owning shard index}`` for the whole fleet."""
    return {
        name: idx
        for idx, shard in enumerate(shard_slices(names, count))
        for name in shard
    }


def shard_of(name: str, names: Iterable[str], count: int) -> int:
    """The shard index owning ``name`` (KeyError when it isn't in the
    fleet list — an unknown machine has no owner to guess)."""
    return shard_map(names, count)[name]


def owned_names(names: Iterable[str], spec: ShardSpec) -> List[str]:
    """The machines shard ``spec.index`` of ``spec.count`` owns."""
    return shard_slices(names, spec.count)[spec.index]


class ShardRouter:
    """Client-side affinity routing over an N-replica serving tier.

    ``replica_urls`` is ordered by shard index (url ``i`` serves shard
    ``i/N``); ``names`` is the FULL fleet machine list (from watchman or
    a replica's project index — every replica reports it), never a
    request's subset: the partition is defined over the whole fleet, and
    a subset-derived table would route almost every machine wrong.
    """

    def __init__(self, names: Sequence[str], replica_urls: Sequence[str]):
        if not replica_urls:
            raise ValueError("ShardRouter needs at least one replica url")
        self.replica_urls = list(replica_urls)
        self._shard_of = shard_map(names, len(self.replica_urls))

    def url_for(self, name: str) -> str:
        """The owning replica's base url (KeyError for unknown machines —
        surfaced to the caller as a per-machine error, not a guess)."""
        return self.replica_urls[self._shard_of[name]]

    def split(self, names: Iterable[str]) -> Dict[str, List[str]]:
        """Scatter plan: ``{replica url: [its machines, in input order]}``
        — only replicas that own at least one requested machine appear.
        Input order is preserved per replica so gather-side reassembly in
        the ORIGINAL machine order is a plain dict merge."""
        out: Dict[str, List[str]] = {}
        for name in names:
            out.setdefault(self.url_for(name), []).append(name)
        return out
