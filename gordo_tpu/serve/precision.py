"""The serving-precision plane: ONE owner of the serving compute dtype.

Reference status: absent upstream — the reference served fp64 pandas
through sklearn and had no precision policy to own.  Here every serving
request runs through a fused XLA program, and reduced-precision batched
serving is the dominant TPU lever (PAPERS.md, the Gemma-on-TPU serving
comparison): bf16 halves parameter residency and H2D bytes and runs on
the MXU's native path.  This module is the single place that policy
lives; the scorer, the fleet scorer, the warmup path, the artifact
plane's ``to_device`` casts, and the workflow generator all resolve the
serving dtype HERE so they can never disagree.

Resolution order (``serve_dtype``): an explicit argument (tests,
embedding callers) > the ``GORDO_SERVE_DTYPE`` env var (what the
generated k8s manifests stamp on builder AND server pods) > the build's
warmup-manifest dtype (``default=``, so the decision travels with the
artifacts) > ``float32``.

Supported dtypes:

- ``float32`` — the parity reference; the default everywhere.
- ``bfloat16`` — params, scaler stats and all in-program compute run
  bf16; outputs are cast back to float32 before leaving the program so
  the response schema (and the codec) see exactly what fp32 serving
  emits, modulo the precision itself.  Gated by the fp32 parity suite
  (``tests/test_serving_precision.py``; per-machine error bounds —
  see docs/perf.md "Serving precision").
- ``int8`` — EXPERIMENTAL, behind the explicit ``GORDO_SERVE_INT8=1``
  opt-in: weight/stat tensors are fake-quantized to the symmetric
  127-level int8 grid in-program (per-leaf max-abs scale) and
  activations compute in bf16 — the numerics of int8 weight-only
  serving, measurable against the parity gate ahead of hardware int8
  kernels.  It is a precision probe, not (yet) a throughput lever.

The dtype is a STATIC argument of every serving program, so it lands in
the compile plane's executable cache keys and the warmup manifest —
a bf16 manifest warms bf16 executables, never fp32 ones.
"""

from __future__ import annotations

import os
from typing import Any, Optional

import numpy as np

#: the one env knob (stamped by the workflow generator on builder and
#: server pods so build-time manifests and serve-time dispatches agree)
SERVE_DTYPE_ENV = "GORDO_SERVE_DTYPE"
#: int8 is experimental quantization simulation — require a second,
#: explicit switch so nobody lands on it by typo or copy-paste
INT8_OPT_IN_ENV = "GORDO_SERVE_INT8"

#: accepted spellings → canonical names (the canonical name is what the
#: compile-plane cache keys and the warmup manifest carry)
_ALIASES = {
    "float32": "float32", "fp32": "float32", "f32": "float32",
    "bfloat16": "bfloat16", "bf16": "bfloat16",
    "int8": "int8", "i8": "int8",
}
SUPPORTED = ("float32", "bfloat16", "int8")


def canonical(name: str) -> str:
    """Canonical dtype name for any accepted spelling; ValueError on an
    unknown one (the loud-config contract: a typo'd dtype must fail the
    process, not silently serve fp32)."""
    resolved = _ALIASES.get(str(name).strip().lower())
    if resolved is None:
        raise ValueError(
            f"unknown serving dtype {name!r}; supported: "
            f"{', '.join(SUPPORTED)} (GORDO_SERVE_DTYPE)"
        )
    return resolved


def _int8_opted_in() -> bool:
    return os.environ.get(INT8_OPT_IN_ENV, "").strip().lower() in (
        "1", "true", "on", "yes",
    )


def serve_dtype(default: Optional[str] = None) -> str:
    """Resolve the serving dtype: ``GORDO_SERVE_DTYPE`` when set, else
    ``default`` (the warmup manifest's build-time dtype, when the caller
    has one), else ``float32``.  ``int8`` additionally requires the
    ``GORDO_SERVE_INT8=1`` opt-in — without it resolution raises, so a
    misconfigured deployment fails at startup/build, never mid-request.
    """
    raw = os.environ.get(SERVE_DTYPE_ENV, "").strip()
    if raw:
        name = canonical(raw)
    elif default:
        name = canonical(default)
    else:
        name = "float32"
    if name == "int8" and not _int8_opted_in():
        raise ValueError(
            "GORDO_SERVE_DTYPE=int8 is experimental (weight fake-quant, "
            "bf16 activations) and requires the explicit opt-in "
            f"{INT8_OPT_IN_ENV}=1"
        )
    return name


def storage_np_dtype(name: str):
    """The numpy dtype device-resident float tensors are STORED in for a
    serving dtype: bf16 for both bf16 and int8 serving (int8 fake-quant
    happens in-program; shipping bf16 already halves residency and the
    pack ``to_device`` transfer), float32 otherwise.  Returns None for
    float32 so callers can skip the cast entirely and keep the v2 pack
    load zero-copy."""
    if canonical(name) == "float32":
        return None
    import ml_dtypes

    return np.dtype(ml_dtypes.bfloat16)


# ---------------------------------------------------------------------------
# in-program casts (traced inside the fused serving programs)
# ---------------------------------------------------------------------------

def compute_dtype(name: str):
    """The jnp dtype in-program activations compute in."""
    import jax.numpy as jnp

    return jnp.float32 if canonical(name) == "float32" else jnp.bfloat16


def _fake_quant_int8(a):
    """Symmetric per-tensor fake quantization to the 127-level int8 grid
    (round-to-nearest, per-leaf max-abs scale) — the numerics of int8
    weight-only serving without hardware int8 kernels."""
    import jax.numpy as jnp

    scale = jnp.maximum(jnp.max(jnp.abs(a)), 1e-12) / 127.0
    q = jnp.clip(jnp.round(a / scale), -127.0, 127.0)
    return (q * scale).astype(jnp.bfloat16)


def cast_params(tree: Any, name: str) -> Any:
    """Cast a parameter/stats pytree's float leaves for in-program
    compute: identity for float32, bf16 cast for bfloat16, fake-quant →
    bf16 for int8.  No-op on leaves already stored reduced."""
    name = canonical(name)
    if name == "float32":
        return tree
    import jax
    import jax.numpy as jnp

    if name == "int8":
        fn = lambda a: (  # noqa: E731
            _fake_quant_int8(a)
            if jnp.issubdtype(jnp.asarray(a).dtype, jnp.floating) else a
        )
    else:
        fn = lambda a: (  # noqa: E731
            jnp.asarray(a).astype(jnp.bfloat16)
            if jnp.issubdtype(jnp.asarray(a).dtype, jnp.floating) else a
        )
    return jax.tree.map(fn, tree)


def cast_input(x: Any, name: str) -> Any:
    """Cast the request matrix to the compute dtype (activations: bf16
    for both bf16 and int8 serving — inputs are data, not weights, so
    they are never fake-quantized)."""
    if canonical(name) == "float32":
        return x
    import jax.numpy as jnp

    return jnp.asarray(x).astype(jnp.bfloat16)


def cast_storage(tree: Any, name: str) -> Any:
    """Cast an already-stacked device/host pytree's float leaves to the
    STORAGE dtype (see :func:`storage_np_dtype`); identity for f32."""
    st = storage_np_dtype(name)
    if st is None:
        return tree
    import jax
    import jax.numpy as jnp

    return jax.tree.map(
        lambda a: (
            jnp.asarray(a).astype(jnp.bfloat16)
            if jnp.issubdtype(jnp.asarray(a).dtype, jnp.floating) else a
        ),
        tree,
    )
